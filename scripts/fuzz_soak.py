#!/usr/bin/env python
"""Extended differential fuzz soak (beyond the unit suite's 40 seeds).

Random schemas x random data through EVERY backend vs the Python
oracle: native VM decode+encode each seed, device decode+encode on a
sampled subset (XLA compiles are the cost), truncation robustness on
the VM. Run on CPU with the axon site hook scrubbed:

    PYTHONPATH= JAX_PLATFORMS=cpu python scripts/fuzz_soak.py \
        [first_seed] [n_schemas]

The round-4 soak ran seeds 100..349 (250 schemas): 0 failures.
"""

from __future__ import annotations

import sys
import traceback

sys.path.insert(0, ".")


def main() -> int:
    from pyruhvro_tpu.fallback.decoder import decode_to_record_batch
    from pyruhvro_tpu.fallback.io import MalformedAvro
    from pyruhvro_tpu.hostpath import NativeHostCodec
    from pyruhvro_tpu.ops import UnsupportedOnDevice
    from pyruhvro_tpu.ops.arrow_build import build_record_batch
    from pyruhvro_tpu.ops.decode import DeviceDecoder
    from pyruhvro_tpu.ops.encode import DeviceEncoder
    from pyruhvro_tpu.schema.cache import get_or_parse_schema
    from pyruhvro_tpu.utils.datagen import random_datums, random_schema

    first = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    count = int(sys.argv[2]) if len(sys.argv) > 2 else 250
    fails = 0
    for seed in range(first, first + count):
        try:
            schema = random_schema(seed)
            e = get_or_parse_schema(schema)
            datums = random_datums(e.ir, 40, seed=seed + 9000)
            want = decode_to_record_batch(datums, e.ir, e.arrow_schema)
            vm = NativeHostCodec(e.ir, e.arrow_schema)
            got = vm.decode(datums)
            assert got.equals(want), "VM decode mismatch"
            assert [bytes(x) for x in vm.encode(want)] == datums, "VM encode"
            if seed % 5 == 0:  # device paths: XLA compile per schema
                dd = DeviceDecoder(e.ir)
                host, n, meta = dd.decode_to_columns(datums)
                gd = build_record_batch(e.ir, e.arrow_schema, host, n, meta)
                assert gd.equals(want), "device decode mismatch"
                try:
                    de = DeviceEncoder(e.ir, e.arrow_schema)
                    assert [
                        bytes(x) for x in de.encode(want).to_pylist()
                    ] == datums, "device encode"
                except UnsupportedOnDevice:
                    pass
            for d in datums[:4]:  # truncation must error or agree
                if not d:
                    continue
                cut = d[: len(d) // 2]
                try:
                    g2 = vm.decode([cut])
                    w2 = decode_to_record_batch([cut], e.ir, e.arrow_schema)
                    assert g2.equals(w2), "truncation divergence"
                except MalformedAvro:
                    pass
            if seed % 25 == 0:
                print(f"seed {seed} ok", flush=True)
        except Exception as ex:  # noqa: BLE001 — report and count
            fails += 1
            print(f"SEED {seed} FAILED: {ex!r}", flush=True)
            traceback.print_exc()
            if fails > 3:
                return 1
    print(f"soak complete: {count} schemas, {fails} failures")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
