#!/usr/bin/env python
"""Audit soak: the differential-audit plane (ISSUE 18) under sustained
production-shaped traffic, run as a standalone gate for the slow CI
perf-artifacts job.

Decodes 100k kafka-style rows through the routed API in many calls at
a 5% audit budget, and additionally arms the plane's force-next latch
at a fixed cadence so dozens of calls shadow through the pure-Python
oracle regardless of the measured cost ratio (at 5% the natural period
on this workload spaces audits wider than a 200-call run — the pacing
math itself is covered by bench.py and the unit tests; the soak's job
is volume on the COMPARISON path). Asserts the steady-state contract:

  * **zero mismatches** — every audited call's per-column digests agree
    between the serving tier and the independent oracle re-execution
    (a mismatch here is a real cross-tier correctness bug, not flake:
    the digests are slice/chunk/layout-invariant by construction);
  * **real coverage** — audits actually fired (audited > 0) and the
    age-decayed coverage gauge is positive;
  * **bounded caller cost** — the plane's own accounting keeps the
    amortized shadow fraction (cost_ratio / period) within the
    configured budget;
  * **clean error ledger** — no shadow errors (nothing chaotic is
    injected here; a shadow crash under clean traffic is a bug).

Writes ``AUDIT_REPORT.json`` (atomic) with the final audit section,
the rendered audit-report text and the pass/fail verdict per
invariant, so CI uploads an inspectable artifact.

Usage::

    JAX_PLATFORMS=cpu python scripts/audit_soak.py [--rows 100000]
        [--rows-per-call 500] [--budget 0.05] [--out AUDIT_REPORT.json]

Exit 1 on any invariant violation.
"""

from __future__ import annotations

import argparse
import faulthandler
import os
import sys
import time

sys.path.append(".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")

WATCHDOG_S = 600


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--rows-per-call", type=int, default=500)
    ap.add_argument("--budget", type=float, default=0.05)
    ap.add_argument("--out", default="AUDIT_REPORT.json")
    args = ap.parse_args()

    faulthandler.dump_traceback_later(WATCHDOG_S, exit=True)
    os.environ["PYRUHVRO_TPU_AUDIT_BUDGET"] = str(args.budget)

    import pyruhvro_tpu as p
    from pyruhvro_tpu.runtime import audit, metrics, telemetry
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
    )

    calls = max(1, args.rows // args.rows_per_call)
    print(f"[audit-soak] {calls} calls x {args.rows_per_call} rows "
          f"at budget {args.budget}", flush=True)

    # a few distinct corpora so schema/dictionary caches behave like
    # production, and both decode and encode lanes see audits
    corpora = [kafka_style_datums(args.rows_per_call, seed=s)
               for s in range(8)]
    batches = [p.deserialize_array(c, KAFKA_SCHEMA_JSON,
                                   backend="host") for c in corpora]
    t0 = time.perf_counter()
    rows = 0
    for i in range(calls):
        if i % 4 == 0:
            audit.force_next()  # fixed-cadence shadow volume (the
            # latch is consumed by the NEXT eligible call, so this
            # lands on every call shape in the mix below over time)
        if i % 5 == 4:
            p.serialize_record_batch(batches[i % len(batches)],
                                     KAFKA_SCHEMA_JSON, 2,
                                     backend="host")
        elif i % 3 == 2:
            p.deserialize_array_threaded(corpora[i % len(corpora)],
                                         KAFKA_SCHEMA_JSON, 2,
                                         backend="host")
        else:
            p.deserialize_array(corpora[i % len(corpora)],
                                KAFKA_SCHEMA_JSON, backend="host")
        rows += args.rows_per_call
    wall_s = time.perf_counter() - t0

    snap = telemetry.snapshot()
    aud = snap.get("audit") or {}
    counters = metrics.snapshot()
    period = max(1, int(aud.get("period") or 1))
    amortized = float(aud.get("cost_ratio") or 0.0) / period

    checks = {
        "zero_mismatches": int(aud.get("mismatches") or 0) == 0,
        "audits_fired": int(aud.get("audited") or 0) > 0,
        "coverage_positive": float(aud.get("coverage") or 0.0) > 0.0,
        "no_shadow_errors": int(aud.get("shadow_errors") or 0) == 0,
        "amortized_within_budget": amortized <= args.budget + 0.005,
    }
    ok = all(checks.values())

    report = {
        "rows": rows,
        "calls": calls,
        "budget": args.budget,
        "wall_s": round(wall_s, 3),
        "amortized_shadow_frac": round(amortized, 6),
        "checks": checks,
        "ok": ok,
        "audit": aud,
        "mismatch_counters": {k: v for k, v in counters.items()
                              if k.startswith("audit.mismatch")},
        "rendered": audit.render_audit_report(snap),
    }
    from pyruhvro_tpu.runtime import fsio

    fsio.atomic_write_json(args.out, report, indent=2)

    print(report["rendered"], flush=True)
    for name, passed in checks.items():
        print(f"[audit-soak] {'PASS' if passed else 'FAIL'} {name}",
              flush=True)
    print(f"[audit-soak] {'OK' if ok else 'FAILED'}: {rows} rows, "
          f"{aud.get('audited')} audited, "
          f"{aud.get('mismatches')} mismatches, "
          f"coverage {aud.get('coverage')}, wall {wall_s:.1f}s "
          f"-> {args.out}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
