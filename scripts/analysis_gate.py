#!/usr/bin/env python
"""The static-analysis CI gate (ISSUE 11): one command, exit non-zero
on any finding.

Passes:

1. cross-language contract checker (``pyruhvro_tpu/analysis/contracts``)
   — opcode/coltype/error enums, profiler slots, aux tags and the
   specializer's embedded tables must agree across Python and C++;
2. AST invariant lints (``pyruhvro_tpu/analysis/lints``) — knob reads
   outside the registry, signal-unsafe metrics/locks, non-atomic JSON
   writes, uncounted fault-seam swallows;
3. the concurrency-correctness pass (ISSUE 14,
   ``pyruhvro_tpu/analysis/concurrency``) — lock-order inversion
   cycles in the acquired-while-held graph, locks held across blocking
   seams, and the guarded-by discipline over ``runtime/`` module
   globals; the lock inventory, edge list and audited waiver list land
   in the report;
4. README knob-table drift — the table between the
   ``<!-- knob-table:start/end -->`` markers must equal
   ``knobs.render_markdown_table()`` (``--fix-knob-table`` rewrites it);
   the metric-key registry table between the
   ``<!-- metric-keys:start/end -->`` markers is held to the same
   standard against the statically-extracted key registry
   (``--fix-metric-keys`` rewrites it);
4b. optionally (``--ir``, ISSUE 15) the IR verification plane
   (``pyruhvro_tpu/analysis/irverify``): abstract interpretation over
   the compiled hostpath opcode programs — type/effect discipline,
   wire-progress/termination, int32/int64 overflow lanes vs anchored
   native guards, and generic<->specialized effect-trace equivalence —
   driven across the full schema-construct lattice with a seeded
   mutation self-test; writes ``IR_VERIFY_REPORT.json`` (per-point
   verdicts, 100%% lattice coverage asserted, mutation verdicts);
5. optionally (``--sanitize``) the native differential suites under
   ASan+UBSan: the host-codec/extractor/fused-decode modules rebuild
   with ``-fsanitize=address,undefined`` (separate cache flavor,
   ``runtime/native/build.py``) and the differential + quick
   malformed-fuzz suites must pass with zero sanitizer reports. Each
   suite failure is retried ONCE in a fresh interpreter (the PR 8
   isolated-rerun convention, lifted to suite granularity) so ASan's
   2-4x memory/time overhead cannot turn container-load flakes into
   red gates; a failure that reproduces isolated is the verdict;
6. optionally (``--tsan``) the same differential suites PLUS the
   threaded legs of ``tests/test_concurrency.py`` against the
   ThreadSanitizer flavor (``.tsan`` cache key, ``PYRUHVRO_TPU_TSAN``)
   under the libtsan preload, gating on zero data-race reports — the
   dynamic complement of the static lock-graph pass. Same
   isolated-rerun deflake rule; a real TSan report is never retried.

Always writes ``ANALYSIS_REPORT.json`` (per-pass findings, the full
knob inventory, sanitizer summary) — CI uploads it as an artifact next
to the perf-gate snapshot.
"""

from __future__ import annotations

import argparse
import os
import re
import shutil
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pyruhvro_tpu.analysis import Finding  # noqa: E402
from pyruhvro_tpu.analysis import concurrency  # noqa: E402
from pyruhvro_tpu.analysis.contracts import check_contracts  # noqa: E402
from pyruhvro_tpu.analysis.lints import run_lints  # noqa: E402
from pyruhvro_tpu.runtime import fsio, knobs  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TABLE_START = "<!-- knob-table:start -->"
_TABLE_END = "<!-- knob-table:end -->"

# the sanitizer leg: native differential suites + quick malformed-fuzz
# seeds (the not-slow half; CI's perf job owns the full sweep)
_SAN_SUITES = (
    ("tests/test_native_extract.py", ()),
    ("tests/test_fused_decode.py", ()),
    ("tests/test_fuzz_malformed.py", ()),
)

# the TSan leg (ISSUE 14): the native differentials again — this time
# hunting data races, not memory bugs — plus the explicitly-threaded
# legs of the concurrency suite (concurrent native decode/encode over
# the GIL-released VM) and, since r17, the shard-runner differential
# suite — the in-native thread pool fanning one call across per-shard
# arenas is exactly the surface a race would hide in
_TSAN_SUITES = (
    ("tests/test_native_extract.py", ()),
    ("tests/test_fused_decode.py", ()),
    ("tests/test_concurrency.py", ("-k", "threaded")),
    ("tests/test_shard_runner.py", ()),
)


# ---------------------------------------------------------------------------
# README knob-table drift
# ---------------------------------------------------------------------------


def check_knob_table(root: str, fix: bool = False):
    """The README table between the markers must match the registry
    rendering exactly — docs generated from code cannot drift."""
    findings = []
    path = os.path.join(root, "README.md")
    rel = "README.md"
    with open(path, encoding="utf-8") as f:
        text = f.read()
    want = knobs.render_markdown_table()
    m = re.search(re.escape(_TABLE_START) + r"\n(.*?)" + re.escape(_TABLE_END),
                  text, flags=re.S)
    if m is None:
        findings.append(Finding(
            "docs.knob-table", rel,
            f"knob-table markers missing ({_TABLE_START} ... "
            f"{_TABLE_END}) — the README table is generated from "
            "runtime/knobs.py"))
        return findings
    if m.group(1) != want:
        if fix:
            new = (text[: m.start(1)] + want + text[m.end(1):])
            with open(path, "w", encoding="utf-8") as f:
                f.write(new)
            print("analysis_gate: rewrote the README knob table from "
                  "the registry")
        else:
            findings.append(Finding(
                "docs.knob-table", rel,
                "knob table drifted from runtime/knobs.py — run "
                "scripts/analysis_gate.py --fix-knob-table",
                text[: m.start(1)].count("\n") + 1))
    return findings


# ---------------------------------------------------------------------------
# sanitizer leg
# ---------------------------------------------------------------------------


def _runtime_libs(names):
    gxx = shutil.which("g++")
    if not gxx:
        return None
    libs = []
    for lib in names:
        p = subprocess.run([gxx, "-print-file-name=" + lib],
                           capture_output=True, text=True).stdout.strip()
        if not p or p == lib or not os.path.exists(p):
            return None
        libs.append(p)
    return libs


def _san_runtime_paths():
    return _runtime_libs(("libasan.so", "libubsan.so"))


_SAN_REPORT_RE = re.compile(
    r"AddressSanitizer|UndefinedBehaviorSanitizer|runtime error:|"
    r"LeakSanitizer|heap-buffer-overflow|heap-use-after-free")

_TSAN_REPORT_RE = re.compile(
    r"WARNING: ThreadSanitizer|ThreadSanitizer: data race|"
    r"ThreadSanitizer: reported \d+ warnings")


def _run_one_suite(suite, env: dict, timeout: int,
                   report_re=_SAN_REPORT_RE):
    path, extra = suite if isinstance(suite, tuple) else (suite, ())
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", path, *extra, "-q", "-m",
             "not slow", "-p", "no:cacheprovider", "-p", "no:randomly"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout,
        )
        rc, out = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as e:
        # a wedged suite is a red result, not a gate crash: the
        # remaining suites still run and the report still writes
        rc = -1
        out = ((e.stdout or "") if isinstance(e.stdout, str) else ""
               ) + f"\n[analysis_gate] suite timed out after {timeout}s"
    return {
        "suite": " ".join((path,) + tuple(extra)),
        "returncode": rc,
        "seconds": round(time.monotonic() - t0, 1),
        "sanitizer_report": bool(report_re.search(out)),
        "tail": out.splitlines()[-8:],
    }


def run_sanitizer_suites(timeout_per_suite: int = 1800):
    """Run the differential suites against the ASan+UBSan native build.
    Returns (summary dict, findings). A red suite re-runs once in a
    fresh interpreter (suite-level PR 8 isolated-rerun guard)."""
    findings = []
    libs = _san_runtime_paths()
    if libs is None:
        return ({"ran": False,
                 "skipped": "no g++/libasan/libubsan on this host"},
                [Finding("sanitize.toolchain", "scripts/analysis_gate.py",
                         "sanitizer runtimes unavailable — the "
                         "sanitizer leg cannot run")])
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYRUHVRO_TPU_NATIVE_SAN="1",
        # the interpreter VM serves; the spec cache is flavor-blind
        PYRUHVRO_TPU_NO_SPECIALIZE="1",
        LD_PRELOAD=" ".join(libs),
        # CPython "leaks" interned objects by design; link-order check
        # off because the runtime arrives via LD_PRELOAD, not ld
        ASAN_OPTIONS="detect_leaks=0:verify_asan_link_order=0:"
                     "abort_on_error=1",
        UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1",
    )
    summary = {"ran": True, "preload": libs, "suites": []}
    findings.extend(_drive_suites(_SAN_SUITES, env, timeout_per_suite,
                                  summary, "sanitize", "ASan/UBSan",
                                  _SAN_REPORT_RE))
    return summary, findings


def _drive_suites(suites, env, timeout_per_suite, summary, tag, what,
                  report_re):
    """Shared suite driver for the ASan and TSan legs: run each suite,
    apply the PR 8 isolated-rerun deflake rule (a genuine sanitizer
    report is NEVER retried), collect findings."""
    findings = []
    for suite in suites:
        res = _run_one_suite(suite, env, timeout_per_suite, report_re)
        res["isolated_rerun"] = False
        if res["returncode"] != 0 and not res["sanitizer_report"]:
            # PR 8 deflake convention at suite granularity: sanitizer
            # overhead on a loaded container can trip wall-clock
            # assertions — an isolated fresh-interpreter rerun is the
            # verdict; a real sanitizer report is NEVER retried
            retry = _run_one_suite(suite, env, timeout_per_suite,
                                   report_re)
            retry["isolated_rerun"] = True
            res = retry
        summary["suites"].append(res)
        status = ("clean" if res["returncode"] == 0
                  and not res["sanitizer_report"] else "RED")
        print(f"analysis_gate: {tag} {res['suite']}: {status} "
              f"({res['seconds']}s"
              + (", isolated rerun" if res["isolated_rerun"] else "")
              + ")")
        if res["returncode"] != 0 or res["sanitizer_report"]:
            findings.append(Finding(
                f"{tag}.suite", res["suite"],
                ("sanitizer report in output" if res["sanitizer_report"]
                 else f"suite failed (rc={res['returncode']}) under "
                      f"{what}")
                + " — tail: " + " | ".join(res["tail"][-3:])))
    return findings


def run_tsan_suites(timeout_per_suite: int = 1800):
    """Run the native differential + threaded suites against the
    ThreadSanitizer flavor (``.tsan`` cache key) under the libtsan
    preload, gating on zero data-race reports. Structure mirrors
    :func:`run_sanitizer_suites` including the isolated-rerun rule."""
    findings = []
    libs = _runtime_libs(("libtsan.so",))
    if libs is None:
        return ({"ran": False,
                 "skipped": "no g++/libtsan on this host"},
                [Finding("tsan.toolchain", "scripts/analysis_gate.py",
                         "ThreadSanitizer runtime unavailable — the "
                         "TSan leg cannot run")])
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYRUHVRO_TPU_TSAN="1",
        # the interpreter VM serves; the spec cache is flavor-blind
        PYRUHVRO_TPU_NO_SPECIALIZE="1",
        LD_PRELOAD=" ".join(libs),
        # keep going on a report (the grep is the gate, and one red
        # suite must not hide the others); history_size buys deeper
        # stacks on the second access of a reported race; the
        # suppressions file scopes out UNINSTRUMENTED third-party
        # allocators (pyarrow's mimalloc) whose raw-atomic
        # synchronization the runtime cannot see — each entry carries
        # its audit note in scripts/tsan.supp
        TSAN_OPTIONS="halt_on_error=0:history_size=4:suppressions="
                     + os.path.join(REPO, "scripts", "tsan.supp"),
    )
    summary = {"ran": True, "preload": libs, "suites": []}
    findings.extend(_drive_suites(_TSAN_SUITES, env, timeout_per_suite,
                                  summary, "tsan", "ThreadSanitizer",
                                  _TSAN_REPORT_RE))
    return summary, findings


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--report", default=os.path.join(REPO,
                                                     "ANALYSIS_REPORT.json"),
                    help="where to write the findings/inventory report")
    ap.add_argument("--fix-knob-table", action="store_true",
                    help="rewrite the README knob table from the "
                         "registry instead of failing on drift")
    ap.add_argument("--fix-metric-keys", action="store_true",
                    help="rewrite the README metric-key registry table "
                         "from the extracted keys instead of failing "
                         "on drift")
    ap.add_argument("--ir", action="store_true",
                    help="run the IR verification plane (abstract "
                         "interpretation over the opcode programs + "
                         "lattice coverage + mutation self-test)")
    ap.add_argument("--ir-report",
                    default=os.path.join(REPO, "IR_VERIFY_REPORT.json"),
                    help="where --ir writes the lattice/mutation "
                         "verdicts")
    ap.add_argument("--sanitize", action="store_true",
                    help="also run the native differential suites under "
                         "ASan+UBSan (rebuilds the .san flavor)")
    ap.add_argument("--tsan", action="store_true",
                    help="also run the native differential + threaded "
                         "suites under ThreadSanitizer (rebuilds the "
                         ".tsan flavor, preloads libtsan)")
    ap.add_argument("--skip-generative", action="store_true",
                    help="skip the import-based specializer-table check "
                         "(pure-parse contract checks only)")
    args = ap.parse_args(argv)

    passes = {}
    contracts = check_contracts(REPO, generative=not args.skip_generative)
    passes["contracts"] = contracts
    lints = run_lints(REPO, fix_metric_keys=args.fix_metric_keys)
    passes["lints"] = lints
    conc_findings, conc_info = concurrency.analyze(REPO)
    passes["concurrency"] = conc_findings
    passes["knob_table"] = check_knob_table(REPO, fix=args.fix_knob_table)

    ir_summary = {"ran": False}
    if args.ir:
        from pyruhvro_tpu.analysis.irverify import run_ir_verification

        ir_findings, ir_report = run_ir_verification(REPO)
        passes["ir"] = ir_findings
        fsio.atomic_write_json(args.ir_report, ir_report, indent=1)
        cov = ir_report["lattice"]["coverage"]
        ir_summary = {
            "ran": True,
            "report": os.path.relpath(args.ir_report, REPO),
            "coverage_pct": cov["coverage_pct"],
            "constructible": cov["constructible"],
            "verified": cov["verified"],
            "mutation_all_caught": ir_report["mutation"]["all_caught"],
        }
        print(f"analysis_gate: ir lattice {cov['verified']}/"
              f"{cov['constructible']} verified "
              f"({cov['coverage_pct']}%), mutation self-test "
              + ("all caught" if ir_report["mutation"]["all_caught"]
                 else "ESCAPES"))

    sanitizer = {"ran": False}
    if args.sanitize:
        sanitizer, san_findings = run_sanitizer_suites()
        passes["sanitize"] = san_findings
    tsan = {"ran": False}
    if args.tsan:
        tsan, tsan_findings = run_tsan_suites()
        passes["tsan"] = tsan_findings

    all_findings = [f for fs in passes.values() for f in fs]
    report = {
        "schema_version": 1,
        "generated_by": "scripts/analysis_gate.py",
        "time": time.time(),
        "passes": {name: {"count": len(fs),
                          "findings": [f.to_dict() for f in fs]}
                   for name, fs in passes.items()},
        "finding_count": len(all_findings),
        "knobs": knobs.inventory(),
        "sanitizer": sanitizer,
        "tsan": tsan,
        "ir": ir_summary,
        # the lock-graph evidence (ISSUE 14): inventory, the
        # acquired-while-held edges, guarded-global declarations and
        # the audited waiver list
        "concurrency": conc_info,
    }
    fsio.atomic_write_json(args.report, report, indent=1)

    for f in all_findings:
        print(f)
    print(f"analysis_gate: {len(all_findings)} finding(s); report -> "
          f"{os.path.relpath(args.report, REPO)}")
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())
