#!/bin/bash
# TPU tunnel watcher (round 5). The axon tunnel wedges for hours at a
# time; this loop probes it cheaply (subprocess + timeout, so a wedged
# probe can't wedge the watcher) and, the moment a probe succeeds, runs
# the full capture sequence and commits the artifacts. Rationale:
# VERDICT r04 "Next round #1" — get a device-labeled bench row on the
# record while the tunnel is alive, whenever that happens to be.
set -u
cd /root/repo
mkdir -p tpu_capture
LOG=tpu_capture/watch.log
say() { echo "[$(date +%H:%M:%S)] $*" >> "$LOG"; }

say "watcher started (pid $$)"
while true; do
  if timeout 100 python -c "import jax; print(jax.devices())" \
      > tpu_capture/probe.out 2>&1; then
    say "TUNNEL ALIVE: $(tail -1 tpu_capture/probe.out)"
    break
  fi
  say "probe timed out/failed; sleeping 180s"
  sleep 180
done

# --- capture sequence (tunnel alive) ---------------------------------
say "running full bench (device phases) ..."
timeout 5400 python bench.py \
  > tpu_capture/bench_stdout.log 2> tpu_capture/bench_stderr.log
rc=$?
say "bench rc=$rc headline=$(tail -1 tpu_capture/bench_stdout.log)"
cp -f BENCH_DETAILS.json tpu_capture/BENCH_DETAILS_device.json 2>/dev/null

say "running ab_pallas (hardware Mosaic compile) ..."
timeout 1800 python scripts/ab_pallas.py --rows 10000 \
  > tpu_capture/ab_pallas.log 2>&1
say "ab_pallas rc=$?"

say "running north-star single10m on device routing ..."
PYRUHVRO_TPU_FORCE_DEVICE=1 timeout 3600 python scripts/north_star.py \
  --mode single10m > tpu_capture/north_star.log 2>&1
say "north_star rc=$?"

git add -A tpu_capture BENCH_DETAILS.json NORTH_STAR.json 2>/dev/null
git commit -q -m "Capture live-TPU bench/pallas/north-star artifacts" \
  2>/dev/null && say "committed capture" || say "nothing to commit"
say "capture complete; watcher exiting"
