#!/usr/bin/env python
"""North-star configs from BASELINE.md, demonstrated end to end.

Config A (``--mode single10m``): 10M kafka-style rows decoded and
encoded on one node. BASELINE.md framed this as "one v5e chip"; the
measured transport model (BENCH_NOTES.md) routes it to the fastest
attached backend via ``backend="auto"`` — the point of the config is
the 10M-row scale, which exercises the BatchTooLarge splitting, int32
offset guards, and streaming memory behavior.

Config B (``--mode roundtrip100m``): the 100M-row serialize+deserialize
round trip in 8 chunks. Run chunk-by-chunk (12.5M rows each, distinct
per-chunk generator seed; rows within a chunk tile a 50k-unique pool —
the same replication scheme as ``bench.py``'s workload) so peak memory
stays bounded: decode chunk → serialize → byte-compare against the
chunk's original datums → drop.

Config C (``--mode mesh``): sharded-mesh correctness — the 8-device
``shard_map`` decode+encode on the spoofed CPU mesh, differentially
verified (the scale knob is CPU-XLA-bound; multi-chip perf economics
are covered in BENCH_NOTES.md).

Results are printed as one JSON line each, and appended to
``NORTH_STAR.json`` at the repo root.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pyruhvro_tpu.runtime import fsio  # noqa: E402  (after sys.path)

BASELINE_DECODE = 10_000 / 1.17e-3
BASELINE_ENCODE = 10_000 / 1.40e-3


def _log(m):
    print(m, file=sys.stderr, flush=True)


def _gen(rows: int, unique: int = 50_000, seed: int = 7):
    from pyruhvro_tpu.utils.datagen import kafka_style_datums

    base = kafka_style_datums(min(rows, unique), seed=seed)
    if rows <= len(base):
        return base[:rows]
    reps = -(-rows // len(base))
    return (base * reps)[:rows]


def _record(result: dict) -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "NORTH_STAR.json")
    try:
        existing = json.load(open(path))
    except Exception:
        existing = {}
    existing[result["mode"]] = result
    fsio.atomic_write_json(path, existing, indent=2)
    print(json.dumps(result), flush=True)


def _warm_routing() -> None:
    """Resolve backend routing OUTSIDE the timed sections: the backend
    probe and the interconnect RTT probe are watchdogged (a wedged
    device tunnel costs their full timeouts once per process, memoized)
    — a tiny decode+encode here eats both so the timed numbers measure
    the codec, not the probes."""
    from pyruhvro_tpu import deserialize_array, serialize_record_batch

    warm = _gen(8, seed=1)
    batch = deserialize_array(warm, _schema())
    serialize_record_batch(batch, _schema(), 1)


def single10m(rows: int) -> None:
    from pyruhvro_tpu import deserialize_array_threaded, serialize_record_batch
    import pyarrow as pa

    _warm_routing()
    datums = _gen(rows)
    _log(f"[north-star] {rows:,} rows, {sum(map(len, datums)):,} bytes")
    t0 = time.perf_counter()
    batches = deserialize_array_threaded(datums, _schema(), 8)
    dt_de = time.perf_counter() - t0
    n = sum(b.num_rows for b in batches)
    assert n == rows, (n, rows)
    _log(f"[north-star] decode: {dt_de:.2f}s = {rows/dt_de:,.0f} rec/s")

    whole = pa.Table.from_batches(batches).combine_chunks().to_batches()[0]
    t0 = time.perf_counter()
    arrays = serialize_record_batch(whole, _schema(), 8)
    dt_en = time.perf_counter() - t0
    assert sum(len(a) for a in arrays) == rows
    _log(f"[north-star] encode: {dt_en:.2f}s = {rows/dt_en:,.0f} rec/s")
    from pyruhvro_tpu.runtime import metrics as _metrics

    snap = _metrics.snapshot()
    f_hit = int(snap.get("decode.fused", 0))
    f_fb = int(snap.get("decode.fused_fallback", 0))
    _record({
        "mode": "single10m", "rows": rows,
        "decode_s": round(dt_de, 3),
        "decode_rec_s": round(rows / dt_de, 1),
        "decode_vs_baseline": round(rows / dt_de / BASELINE_DECODE, 4),
        "encode_s": round(dt_en, 3),
        "encode_rec_s": round(rows / dt_en, 1),
        "encode_vs_baseline": round(rows / dt_en / BASELINE_ENCODE, 4),
        # absolute rec/s only compares within one machine class: carry
        # the recording box's shape + the fused-decode coverage so a
        # slower box's honest reseed never reads as a codec regression
        "machine": {"cpus": os.cpu_count()},
        **({"fused_decode": {
            "fused": f_hit, "fallback": f_fb,
            "hit_rate": round(f_hit / (f_hit + f_fb), 4),
        }} if (f_hit or f_fb) else {}),
        # one-call native shard runner (ISSUE 17): >0 ⇒ the chunked
        # host calls above went through the single-native-call fan-out
        "shard_native_calls": int(snap.get("shard.native", 0)),
    })


def host_shard_1m(rows: int, chunks: int = 8) -> None:
    """The shard-runner headline (ISSUE 17): kafka rows × ``chunks``
    through the host tier's ONE-CALL native fan-out — the wall the PR 9
    serial per-chunk loop is compared against. Records the runner's own
    drained busy/wall counters as ``chunk_efficiency`` (the figure
    BENCH_NOTES.md says to quote) and how many native shard calls
    actually served the run (0 ⇒ the path degraded; the number is then
    NOT a shard-runner number)."""
    from pyruhvro_tpu import deserialize_array_threaded, serialize_record_batch
    import pyarrow as pa

    from pyruhvro_tpu.runtime import metrics as _metrics

    _warm_routing()
    datums = _gen(rows)
    deserialize_array_threaded(datums[:4096], _schema(), chunks,
                               backend="host")  # warm the arm
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        batches = deserialize_array_threaded(datums, _schema(), chunks,
                                             backend="host")
        walls.append(time.perf_counter() - t0)
        assert sum(b.num_rows for b in batches) == rows
    walls.sort()
    dt_de = walls[len(walls) // 2]
    snap = _metrics.snapshot()
    whole = pa.Table.from_batches(batches).combine_chunks().to_batches()[0]
    t0 = time.perf_counter()
    arrays = serialize_record_batch(whole, _schema(), chunks,
                                    backend="host")
    dt_en = time.perf_counter() - t0
    assert sum(len(a) for a in arrays) == rows
    eff = None
    effs = snap.get("pool.eff_fanouts", 0)
    if effs:
        eff = round(snap.get("pool.chunk_efficiency", 0.0) / effs, 4)
    _record({
        "mode": "host_shard_1m", "rows": rows, "chunks": chunks,
        "decode_s": round(dt_de, 3),
        "decode_rec_s": round(rows / dt_de, 1),
        "decode_vs_baseline": round(rows / dt_de / BASELINE_DECODE, 4),
        "encode_s": round(dt_en, 3),
        "encode_rec_s": round(rows / dt_en, 1),
        "shard_native_calls": int(snap.get("shard.native", 0)),
        "shard_fallbacks": int(snap.get("shard.fallback", 0)),
        **({"chunk_efficiency": eff} if eff is not None else {}),
        "machine": {"cpus": os.cpu_count()},
    })


def roundtrip100m(rows: int, chunks: int = 8) -> None:
    from pyruhvro_tpu import deserialize_array_threaded, serialize_record_batch

    _warm_routing()
    per = rows // chunks
    # inner chunking (~1M rows each) drives the library's own parallel
    # API per piece — the per-chunk cache-resident execution the codec
    # uses at scale (BENCH_NOTES.md "Scale behavior")
    inner = max(1, per // 1_000_000)
    t_de = t_en = 0.0
    checked = 0
    for c in range(chunks):
        base = _gen(per, seed=7 + c)  # distinct data per chunk
        t0 = time.perf_counter()
        batches = deserialize_array_threaded(base, _schema(), inner)
        t_de += time.perf_counter() - t0
        assert sum(b.num_rows for b in batches) == per
        t0 = time.perf_counter()
        arrays = [
            a for b in batches
            for a in serialize_record_batch(b, _schema(), 1)
        ]
        t_en += time.perf_counter() - t0
        # byte-exact round trip for the whole chunk
        flat = _pa().concat_arrays(arrays)
        assert len(flat) == per
        assert flat.equals(
            _pa().array([bytes(d) for d in base], _pa().binary())
        )
        checked += per
        _log(f"[north-star] chunk {c + 1}/{chunks}: {checked:,} rows "
             f"round-tripped byte-exact")
    _record({
        "mode": "roundtrip100m", "rows": checked, "chunks": chunks,
        "unique_rows_per_chunk": 50_000,
        "decode_s": round(t_de, 2),
        "decode_rec_s": round(checked / t_de, 1),
        "encode_s": round(t_en, 2),
        "encode_rec_s": round(checked / t_en, 1),
        "byte_exact": True,
    })


def mesh(rows: int) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the capacity planner may persist its learned rungs (ISSUE 10) so
    # later runs on this machine start warm
    os.environ.setdefault("PYRUHVRO_TPU_CAPACITY_PERSIST", "1")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    from pyruhvro_tpu.fallback.decoder import decode_to_record_batch
    from pyruhvro_tpu.parallel import ShardedDecoder, ShardedEncoder, chunk_mesh
    from pyruhvro_tpu.runtime import metrics
    from pyruhvro_tpu.schema.cache import get_or_parse_schema

    e = get_or_parse_schema(_schema())
    m = chunk_mesh(n_devices=8)
    datums = _gen(rows)
    sd = ShardedDecoder(e.ir, mesh=m)
    t0 = time.perf_counter()
    batches = sd.decode(datums, e.ir, e.arrow_schema)
    cold_s = time.perf_counter() - t0
    oracle = decode_to_record_batch(datums, e.ir, e.arrow_schema)
    row = 0
    for b in batches:
        assert b.equals(oracle.slice(row, b.num_rows)), row
        row += b.num_rows
    # steady state (ISSUE 10): the cold call above paid the one-time
    # XLA compile (device.compile_s below); with the capacity planner
    # there are no retry-ladder recompiles, so every later call is a
    # pure pack→h2d→launch→d2h pipeline — the wall a long-running mesh
    # consumer actually sees. decode_s is the warm median; the pre-PR-10
    # 30.8 s figure was a cold call stacked with retry-rung recompiles.
    snap0 = metrics.snapshot()
    warm_walls = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = sd.decode(datums, e.ir, e.arrow_schema)
        warm_walls.append(time.perf_counter() - t0)
        assert sum(b.num_rows for b in out) == rows
    warm_walls.sort()
    warm_s = warm_walls[len(warm_walls) // 2]
    snap = metrics.snapshot()

    def delta(key):
        return snap.get(key, 0.0) - snap0.get(key, 0.0)

    pipeline_s = delta("device.pipeline_s")
    overlap_s = delta("device.overlap_s")
    phases = {
        "pack_s": round(delta("decode.pack_s") / 5, 5),
        "h2d_s": round(delta("decode.h2d_s") / 5, 5),
        "launch_s": round(delta("device.launch_s") / 5, 5),
        "d2h_s": round(delta("decode.d2h_s") / 5, 5),
        # host pack/h2d seconds spent while shard transfers/launches
        # were in flight, over the pipeline wall (> 0 = overlapping)
        "overlap_frac": round(overlap_s / pipeline_s, 4)
        if pipeline_s > 0 else 0.0,
    }
    warm_retries = int(delta("device.retries"))
    arrays = ShardedEncoder(e.ir, e.arrow_schema, mesh=m).encode(oracle)
    assert [bytes(x) for a in arrays for x in a] == [bytes(d) for d in datums]
    _record({
        "mode": "mesh", "rows": rows, "devices": 8,
        "decode_s": round(warm_s, 3),
        "decode_cold_s": round(cold_s, 2),
        "compile_s": round(snap0.get("device.compile_s", 0.0), 2),
        "warm_reps": len(warm_walls),
        "warm_retries": warm_retries,
        "jit_cache_hits": int(delta("device.jit_cache.hits")),
        "phases": phases,
        "machine": {"cpus": os.cpu_count()},
        "verified": "decode==oracle per shard; "
        "encode wire-exact per shard",
    })


def _schema():
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON

    return KAFKA_SCHEMA_JSON


def _pa():
    import pyarrow

    return pyarrow


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("single10m", "host_shard_1m",
                                       "roundtrip100m", "mesh"),
                    required=True)
    ap.add_argument("--rows", type=int, default=None)
    a = ap.parse_args()
    if a.mode == "single10m":
        single10m(a.rows or 10_000_000)
    elif a.mode == "host_shard_1m":
        host_shard_1m(a.rows or 1_000_000)
    elif a.mode == "roundtrip100m":
        roundtrip100m(a.rows or 100_000_000)
    else:
        mesh(a.rows or 20_000)
