#!/usr/bin/env python
"""Malformed-bytes soak (bounded): the mutation fuzz of
tests/test_fuzz_malformed.py scaled up and run as a standalone gate for
the wheel-build CI job.

Random schemas x mutated corpora (truncate / bit-flip / splice) through
the native VM vs the pure-Python oracle:

  * crash-freedom — every record either decodes or raises a
    ValueError-family error (MalformedAvro/ArrowInvalid), never
    anything else, never memory-unsafely;
  * accept-vs-reject agreement per record, equal decodes on accepts;
  * on_error="skip" parity — fallback and native tiers return
    byte-identical surviving rows and identical quarantine indices.

Usage::

    JAX_PLATFORMS=cpu python scripts/malformed_soak.py [first_seed] [n]

Iterations are bounded (default 40 schemas x 40 records x ~3 mutations
each); exit 1 on any divergence.
"""

from __future__ import annotations

import sys
import traceback

# APPEND (not insert): when a wheel is installed the soak must exercise
# THAT build's compiled extensions (the CI wheel job's whole point) —
# the checkout only backs imports that aren't installed (the tests
# package, or a source-tree run with no wheel present).
sys.path.append(".")
sys.path.append("tests")


def main() -> int:
    from test_fuzz_malformed import _check_schema_seed

    from pyruhvro_tpu.hostpath import native_available
    from pyruhvro_tpu.utils.datagen import random_schema

    if not native_available():
        print("native toolchain unavailable; soak skipped")
        return 0
    first = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    count = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    fails = 0
    for seed in range(first, first + count):
        try:
            _check_schema_seed(random_schema(seed), seed)
            if seed % 10 == 0:
                print(f"seed {seed} ok", flush=True)
        except Exception as ex:  # noqa: BLE001 — report and count
            fails += 1
            print(f"SEED {seed} FAILED: {ex!r}", flush=True)
            traceback.print_exc()
            if fails > 3:
                return 1
    print(f"malformed soak complete: {count} schemas, {fails} failures")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
