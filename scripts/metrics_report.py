#!/usr/bin/env python
"""Render a telemetry phase-breakdown report (tier-1-safe surface).

Thin wrapper over ``python -m pyruhvro_tpu.telemetry`` so the report
path is exercised by the unit suite (``tests/test_telemetry.py`` runs it
against a checked-in sample snapshot) and can never bit-rot unnoticed.

Usage::

    python scripts/metrics_report.py report BENCH_DETAILS.json
    python scripts/metrics_report.py report snapshot.json
    python scripts/metrics_report.py prom snapshot.json
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pyruhvro_tpu.runtime.telemetry import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
