#!/usr/bin/env python
"""Sustained-overload serving soak (ISSUE 19), run as a standalone gate
for the slow CI perf-artifacts job.

Measures the plane's single-process capacity, then offers a sustained
multiple of it from two tenants — one well-behaved, one flooding —
with ``serve_worker`` chaos injected, and asserts the overload
contract end to end:

  * **shed, never die** — at >= 2x capacity the plane sheds the excess
    with structured :class:`Overloaded` rejections; the process never
    crashes, deadlocks, or wedges (faulthandler watchdog);
  * **admitted traffic stays fast** — the e2e p99 of ADMITTED requests
    stays within the soak SLO even while the queues are saturated
    (admission control is doing its job: latency is bounded by queue
    depth, not offered load);
  * **tenant isolation** — the flood tenant cannot push the
    well-behaved tenant's admitted p99 past 2x its solo baseline;
  * **brownout ladder engages** — sustained pressure walks the rungs
    (audit -> sampling -> explore -> tenant) and every engagement is
    counted with occupancy recorded;
  * **zero-loss mid-load drain** — a drain issued while requests are
    still in flight resolves EVERY accepted request exactly once
    (result or structured error): none lost, none double-answered.

Writes ``SERVE_SOAK.json`` (atomic) with per-phase latency summaries,
shed accounting, brownout occupancy and the drain verdict, so CI
uploads an inspectable artifact.

Usage::

    JAX_PLATFORMS=cpu python scripts/serve_soak.py
        [--duration 8] [--overload 8] [--fault-rate 0.05]
        [--out SERVE_SOAK.json]

Exit 1 on any invariant violation.
"""

from __future__ import annotations

import argparse
import faulthandler
import os
import sys
import threading
import time

sys.path.append(".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# soak defaults: bounded queues small enough to saturate quickly, shed
# policy so overload is visible as rejections (not blocking callers),
# and a brownout ladder that engages within the run
os.environ.setdefault("PYRUHVRO_TPU_SERVE_POLICY", "shed")
os.environ.setdefault("PYRUHVRO_TPU_SERVE_QUEUE", "32")
os.environ.setdefault("PYRUHVRO_TPU_SERVE_WORKERS", "2")
os.environ.setdefault("PYRUHVRO_TPU_SERVE_BROWNOUT", "0.5")
os.environ.setdefault("PYRUHVRO_TPU_SERVE_BROWNOUT_SUSTAIN", "2")
os.environ.setdefault("PYRUHVRO_TPU_SERVE_COALESCE_S", "0.001")

WATCHDOG_S = 420
ROWS_PER_REQ = 32
SLO_P99_S = 1.5       # admitted traffic must beat this even overloaded
ISOLATION_FACTOR = 2.0  # wb overload p99 <= factor * wb solo p99 (floored)
ISOLATION_FLOOR_S = 0.5


def pct(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def summary(lat):
    return {
        "count": len(lat),
        "p50_s": pct(lat, 0.50),
        "p90_s": pct(lat, 0.90),
        "p99_s": pct(lat, 0.99),
        "max_s": max(lat) if lat else None,
    }


class TenantLoad:
    """One tenant's open-loop submission thread at a fixed offered
    rate; every outcome is accounted (admitted future / shed)."""

    def __init__(self, plane, tenant, rate_rps, data, schema):
        from pyruhvro_tpu.serving import Overloaded

        self._Overloaded = Overloaded
        self.plane = plane
        self.tenant = tenant
        self.rate = rate_rps
        self.data = data
        self.schema = schema
        self.futures = []
        self.latencies = []
        self.shed = 0
        self.submit_errors = 0
        self.submitted = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"soak-{tenant}")

    def _run(self):
        period = 1.0 / self.rate
        next_t = time.monotonic()
        while not self._stop.is_set():
            now = time.monotonic()
            if now < next_t:
                time.sleep(min(period, next_t - now))
                continue
            if now - next_t > 10 * period:
                # fell behind (GIL/scheduler): drop the missed ticks
                # rather than replaying them as a burst — an open-loop
                # client with a bounded send buffer does the same
                next_t = now
            next_t += period
            self.submitted += 1
            t0 = time.monotonic()
            try:
                f = self.plane.submit(
                    "decode", self.data, self.schema, timeout_s=10.0,
                    tenant=self.tenant)
            except self._Overloaded:
                self.shed += 1
                continue
            except Exception:  # noqa: BLE001 — drain racing submit
                self.submit_errors += 1
                continue
            f.add_done_callback(
                lambda fut, t=t0: self.latencies.append(
                    time.monotonic() - t)
                if fut.exception() is None else None)
            self.futures.append(f)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)

    def account(self):
        """(results, structured_failures, unresolved) over admitted."""
        res = fail = pending = 0
        for f in self.futures:
            if not f.done():
                pending += 1
            elif f.exception() is None:
                res += 1
            else:
                fail += 1
        return res, fail, pending


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds per load phase (default 8)")
    ap.add_argument("--overload", type=float, default=8.0,
                    help="offered load as a multiple of measured "
                         "capacity (default 8; the closed-loop probe "
                         "understates coalesced throughput, so a high "
                         "multiple is needed to genuinely saturate)")
    ap.add_argument("--fault-rate", type=float, default=0.05,
                    help="serve_worker error rate during overload "
                         "(default 0.05)")
    ap.add_argument("--out", default="SERVE_SOAK.json")
    args = ap.parse_args()

    faulthandler.dump_traceback_later(WATCHDOG_S, exit=True)

    import pyruhvro_tpu as p
    from pyruhvro_tpu import serving
    from pyruhvro_tpu.runtime import faults, fsio, knobs, metrics, telemetry
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
    )

    data = kafka_style_datums(ROWS_PER_REQ, seed=11)
    ref = p.deserialize_array(data, KAFKA_SCHEMA_JSON)
    workers = knobs.get_int("PYRUHVRO_TPU_SERVE_WORKERS")

    # -- capacity probe: closed-loop through the PLANE, so the number
    # includes queue/lock/coalesce overhead and GIL contention with the
    # submitting threads — the raw API in a tight loop overstates what
    # the serving path can actually sustain
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        p.deserialize_array(data, KAFKA_SCHEMA_JSON)
    per_call = (time.perf_counter() - t0) / reps

    plane = serving.start()
    done = [0, 0, 0, 0]  # one slot per thread: no shared counter
    cal_stop = time.monotonic() + 1.5

    def _closed_loop(slot):
        while time.monotonic() < cal_stop:
            plane.call("decode", data, KAFKA_SCHEMA_JSON,
                       timeout_s=10.0, tenant="cal")
            done[slot] += 1

    cal_threads = [threading.Thread(target=_closed_loop, args=(i,),
                                    daemon=True)
                   for i in range(len(done))]
    t0 = time.monotonic()
    for t in cal_threads:
        t.start()
    for t in cal_threads:
        t.join(timeout=20)
    capacity_rps = sum(done) / max(1e-6, time.monotonic() - t0)
    plane.drain()
    serving.stop()
    telemetry.reset()
    print(f"capacity probe: {per_call * 1e3:.2f} ms/call raw; "
          f"plane sustains ~{capacity_rps:.0f} req/s "
          f"({workers} worker(s))", flush=True)

    doc = {
        "rows_per_request": ROWS_PER_REQ,
        "workers": workers,
        "capacity_rps": round(capacity_rps, 1),
        "offered_multiple": args.overload,
        "fault_rate": args.fault_rate,
        "phases": {},
        "violations": [],
    }

    def violate(msg):
        print(f"[FAIL] {msg}", flush=True)
        doc["violations"].append(msg)

    # -- phase 1: solo baseline (well-behaved tenant only, 40% cap) ----
    plane = serving.start()
    wb = TenantLoad(plane, "wb", max(2.0, 0.4 * capacity_rps), data,
                    KAFKA_SCHEMA_JSON).start()
    time.sleep(args.duration)
    wb.stop()
    plane.drain()
    serving.stop()
    solo = summary(wb.latencies)
    doc["phases"]["solo"] = {
        "offered_rps": round(wb.rate, 1), "submitted": wb.submitted,
        "shed": wb.shed, "latency": solo,
    }
    print(f"solo: {wb.submitted} submitted, {wb.shed} shed, "
          f"p99 {solo['p99_s'] * 1e3:.1f} ms", flush=True)
    if wb.shed:
        violate("solo phase shed traffic at 40% of measured capacity")
    telemetry.reset()

    # -- phase 2: sustained overload + chaos + flood tenant ------------
    os.environ["PYRUHVRO_TPU_FAULTS"] = (
        f"serve_worker:error:{args.fault_rate:g}")
    faults.reset()  # drop the parsed-plan memo so the spec is re-read
    serving.reset()  # fresh plane, fresh accounting
    plane = serving.start()
    wb2 = TenantLoad(plane, "wb", max(2.0, 0.4 * capacity_rps), data,
                     KAFKA_SCHEMA_JSON).start()
    flood = TenantLoad(
        plane, "flood",
        max(4.0, args.overload * capacity_rps), data,
        KAFKA_SCHEMA_JSON).start()
    rungs_seen = set()
    t_end = time.monotonic() + args.duration
    while time.monotonic() < t_end:
        rungs_seen.update(plane.engaged_rungs())
        time.sleep(0.05)
    wb2.stop()
    flood.stop()

    # -- phase 3: MID-LOAD drain (submissions were just stopped, the
    # backlog is still deep) — the zero-loss verdict ------------------
    snap_before = plane.snapshot()
    rep = plane.drain(timeout_s=60.0)
    serving.stop()
    os.environ["PYRUHVRO_TPU_FAULTS"] = ""
    faults.reset()

    over_wb = summary(wb2.latencies)
    over_fl = summary(flood.latencies)
    c = metrics.snapshot()
    occupancy = snap_before["brownout"]["occupancy_s"]
    admitted = len(wb2.futures) + len(flood.futures)
    shed_total = wb2.shed + flood.shed
    offered = wb2.submitted + flood.submitted
    doc["phases"]["overload"] = {
        "offered_rps": round(wb2.rate + flood.rate, 1),
        "submitted": offered,
        "admitted": admitted,
        "shed": shed_total,
        "shed_ratio": round(shed_total / max(1, offered), 4),
        "submit_errors": wb2.submit_errors + flood.submit_errors,
        "worker_faults_injected": c.get(
            "fault.injected.serve_worker", 0),
        "worker_degraded": c.get("serve.worker_degraded", 0),
        "latency_wb": over_wb,
        "latency_flood": over_fl,
        "brownout_rungs_seen": sorted(rungs_seen),
        "brownout_occupancy_s": {k: round(v, 3)
                                 for k, v in occupancy.items()},
        "brownout_engagements": {
            r: c.get("serve.brownout." + r, 0)
            for r in serving.BROWNOUT_RUNGS},
    }

    wb_res, wb_fail, wb_pend = wb2.account()
    fl_res, fl_fail, fl_pend = flood.account()
    doc["drain"] = {
        "report": rep,
        "admitted": admitted,
        "results": wb_res + fl_res,
        "structured_failures": wb_fail + fl_fail,
        "unresolved": wb_pend + fl_pend,
        "double_resolve": c.get("serve.double_resolve", 0),
        "drain_aborted": c.get("serve.drain_aborted", 0),
    }

    # -- the contract --------------------------------------------------
    if shed_total == 0:
        violate("overload at "
                f"{args.overload:g}x capacity shed nothing — "
                "backpressure never engaged")
    if wb_pend + fl_pend:
        violate(f"{wb_pend + fl_pend} admitted request(s) never "
                "resolved — requests were LOST in the drain")
    if c.get("serve.double_resolve", 0):
        violate("a request was resolved twice")
    if rep["accepted"] != rep["completed"] + rep["failed"]:
        violate("plane accounting does not balance: "
                f"{rep}")
    if over_wb["p99_s"] is not None and over_wb["p99_s"] > SLO_P99_S:
        violate(f"admitted wb p99 {over_wb['p99_s']:.3f}s breaches the "
                f"soak SLO {SLO_P99_S}s under overload")
    if over_wb["p99_s"] is not None and solo["p99_s"] is not None:
        bound = max(ISOLATION_FACTOR * solo["p99_s"], ISOLATION_FLOOR_S)
        if over_wb["p99_s"] > bound:
            violate("flood tenant pushed wb admitted p99 to "
                    f"{over_wb['p99_s']:.3f}s (> {bound:.3f}s = "
                    f"max({ISOLATION_FACTOR:g} x solo, floor))")
    if not rungs_seen:
        violate("brownout ladder never engaged under sustained "
                "overload")

    doc["pass"] = not doc["violations"]
    fsio.atomic_write_json(args.out, doc)
    print(f"serve soak: offered {offered}, admitted {admitted}, shed "
          f"{shed_total} ({doc['phases']['overload']['shed_ratio']:.1%})"
          f", rungs {sorted(rungs_seen)}, "
          f"drain unresolved={wb_pend + fl_pend} -> {args.out}",
          flush=True)
    faulthandler.cancel_dump_traceback_later()
    return 0 if doc["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
