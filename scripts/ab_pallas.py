#!/usr/bin/env python
"""A/B the decode walk: XLA pipeline vs the Pallas kernel, on-device.

Runs the criterion shapes + the kafka headline schema through both device decode paths
(``ops/decode.DeviceDecoder`` and ``ops/pallas_decode.PallasKernelDecoder``)
on whatever backend JAX resolves, checks both against the pure-Python
oracle, and reports wall/launch timing. On a co-located chip this
isolates in-kernel time; through a high-latency tunnel the transport
dominates both (BENCH_NOTES.md) — the oracle equality check is then the
main signal.

Usage: python scripts/ab_pallas.py [--rows 10000] [--interpret]
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--interpret", action="store_true",
                    help="run the pallas kernel in interpreter mode (CPU)")
    args = ap.parse_args()

    import jax

    print(f"devices: {jax.devices()}", file=sys.stderr)

    from pyruhvro_tpu.fallback.decoder import decode_to_record_batch
    from pyruhvro_tpu.ops.decode import DeviceDecoder
    from pyruhvro_tpu.ops.pallas_decode import PallasKernelDecoder
    from pyruhvro_tpu.ops.arrow_build import build_record_batch
    from pyruhvro_tpu.schema.arrow_map import to_arrow_schema
    from pyruhvro_tpu.schema.parser import parse_schema
    from pyruhvro_tpu.utils.datagen import CRITERION_SHAPES, random_datums

    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, kafka_style_datums

    shapes = dict(CRITERION_SHAPES)
    shapes["kafka"] = KAFKA_SCHEMA_JSON  # v2: arrays/maps kernel-eligible
    for shape in ("flat_primitives", "nullable_primitives", "nested_struct",
                  "array_and_map", "kafka"):
        schema = shapes[shape]
        ir = parse_schema(schema)
        arrow = to_arrow_schema(ir)
        datums = (kafka_style_datums(args.rows, seed=11) if shape == "kafka"
                  else random_datums(ir, args.rows, seed=11))
        want = decode_to_record_batch(datums, ir, arrow)

        # decoders are built ONCE per shape: their compiled-kernel caches
        # live on the instance, so rebuilding per rep would time the
        # compiler, not the pipeline. Construction failures report as
        # FAILED and skip only that decoder.
        def make_runner(ctor):
            d = ctor(ir)

            def run():
                host, n, meta = d.decode_to_columns(datums)
                return build_record_batch(ir, arrow, host, n, meta)

            return run

        runners = []
        for name, ctor in (
            ("xla", DeviceDecoder),
            ("pallas",
             lambda ir_: PallasKernelDecoder(ir_, interpret=args.interpret)),
        ):
            try:
                runners.append((name, make_runner(ctor)))
            except Exception as e:  # noqa: BLE001 — report and continue
                print(f"{shape:22s} {name:7s} FAILED (init): {e!r}",
                      flush=True)

        for name, fn in runners:
            try:
                t0 = time.perf_counter()
                got = fn()  # includes compile
                compile_and_first = time.perf_counter() - t0
                ok = got.equals(want)
                best = float("inf")
                for _ in range(args.reps):
                    t0 = time.perf_counter()
                    fn()
                    best = min(best, time.perf_counter() - t0)
                print(
                    f"{shape:22s} {name:7s} rows={args.rows} "
                    f"first={compile_and_first * 1e3:8.1f}ms "
                    f"best={best * 1e3:8.1f}ms "
                    f"({args.rows / best:,.0f} rec/s) oracle={'OK' if ok else 'MISMATCH'}",
                    flush=True,
                )
                if not ok:
                    sys.exit(2)
            except Exception as e:  # noqa: BLE001 — report and continue
                print(f"{shape:22s} {name:7s} FAILED: {e!r}", flush=True)


if __name__ == "__main__":
    main()
