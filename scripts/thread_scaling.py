#!/usr/bin/env python
"""Host-VM thread scaling artifact (VERDICT r04 #5, reworked for the
native shard runner).

The sweep decodes the kafka shape at nthreads ∈ {1, 2, 4} THROUGH the
one-call native shard runner (runtime/native/shard_runner.h) and
records, per point, the runner's own drained busy/wall counters as
``pool.chunk_efficiency`` (= busy / (wall × threads)) plus the router
arm that would serve the call. A 1-vCPU bench box still fans out when
threads are requested explicitly — the efficiency figure then honestly
reads ≈ 1/n (time-sliced, not parallel); the ≥4-core CI runner is the
box where ``efficiency ≥ 0.6`` is enforced (scripts/perf_gate.py).

Run: PYTHONPATH= JAX_PLATFORMS=cpu python scripts/thread_scaling.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pyruhvro_tpu.runtime import fsio  # noqa: E402  (after sys.path)


def main() -> None:
    from pyruhvro_tpu.hostpath.codec import NativeHostCodec
    from pyruhvro_tpu.runtime import costmodel
    from pyruhvro_tpu.runtime.pool import shard_available
    from pyruhvro_tpu.schema.cache import get_or_parse_schema
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, kafka_style_datums

    e = get_or_parse_schema(KAFKA_SCHEMA_JSON)
    codec = NativeHostCodec(e.ir, e.arrow_schema)
    sharded = hasattr(codec._mod, "shard_stats")
    out = {
        "cores": os.cpu_count(),
        "rows": {},
        "engine": None,
        "shard_runner": sharded,
        # the arm the router offers for this shape once the binary is
        # warm (chunked call, native tier, one-call fan-out)
        "shard_arm": (costmodel.arm_key("native", 4, "shard")
                      if sharded and shard_available() else None),
    }
    for rows in (10_000, 1_000_000):
        base = kafka_style_datums(min(rows, 50_000), seed=7)
        datums = (base * (-(-rows // len(base))))[:rows]
        codec.decode(datums[:1000])  # warm (+ maybe specialize)
        cells = {}
        for nt in (1, 2, 4):
            best = float("inf")
            eff = None
            for _ in range(3 if rows <= 10_000 else 2):
                if sharded:
                    codec._drain_shard_stats()
                t0 = time.perf_counter()
                codec.decode(datums, nthreads=nt)
                best = min(best, time.perf_counter() - t0)
                if sharded:
                    d = codec._drain_shard_stats()
                    if d["fanouts"] and d["wall_s"] > 0 and d["threads"]:
                        e_ = min(1.0, d["shard_s"]
                                 / (d["wall_s"] * d["threads"]))
                        eff = e_ if eff is None else max(eff, e_)
            cell = {"rate": round(rows / best, 1)}
            if eff is not None:
                cell["chunk_efficiency"] = round(eff, 4)
            cells[str(nt)] = cell
            print(f"rows={rows} nthreads={nt}: {rows / best:,.0f} rec/s"
                  f" eff={eff if eff is not None else 'serial'}",
                  file=sys.stderr)
        cells["speedup_4t"] = round(
            cells["4"]["rate"] / cells["1"]["rate"], 3)
        out["rows"][str(rows)] = cells
    out["engine"] = "specialized" if codec._spec is not None else "interpreter"
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "THREAD_SCALING.json")
    fsio.atomic_write_json(path, out, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
