#!/usr/bin/env python
"""Host-VM thread scaling artifact (VERDICT r04 #5).

The bench box has one vCPU, so the row-sharded VM threading
(host_vm_core.h run_shard_t fan-out) never shows in BENCH_r*.json.
This script measures decode throughput at nthreads ∈ {1, 2, 4} on
whatever cores the current machine has (the 4-core CI runner is the
intended host) and writes THREAD_SCALING.json.

Run: PYTHONPATH= JAX_PLATFORMS=cpu python scripts/thread_scaling.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pyruhvro_tpu.runtime import fsio  # noqa: E402  (after sys.path)


def main() -> None:
    from pyruhvro_tpu.hostpath.codec import NativeHostCodec
    from pyruhvro_tpu.schema.cache import get_or_parse_schema
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, kafka_style_datums

    e = get_or_parse_schema(KAFKA_SCHEMA_JSON)
    codec = NativeHostCodec(e.ir, e.arrow_schema)
    out = {"cores": os.cpu_count(), "rows": {}, "engine": None}
    for rows in (10_000, 1_000_000):
        base = kafka_style_datums(min(rows, 50_000), seed=7)
        datums = (base * (-(-rows // len(base))))[:rows]
        codec.decode(datums[:1000])  # warm (+ maybe specialize)
        cells = {}
        for nt in (1, 2, 4):
            best = float("inf")
            for _ in range(3 if rows <= 10_000 else 2):
                t0 = time.perf_counter()
                codec.decode(datums, nthreads=nt)
                best = min(best, time.perf_counter() - t0)
            cells[str(nt)] = round(rows / best, 1)
            print(f"rows={rows} nthreads={nt}: {rows / best:,.0f} rec/s",
                  file=sys.stderr)
        cells["speedup_4t"] = round(cells["4"] / cells["1"], 3)
        out["rows"][str(rows)] = cells
    out["engine"] = "specialized" if codec._spec is not None else "interpreter"
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "THREAD_SCALING.json")
    fsio.atomic_write_json(path, out, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
