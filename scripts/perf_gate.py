#!/usr/bin/env python
"""Perf-regression gate: fresh best-of-N bands vs PERF_BASELINE.json.

The machine-checked tripwire behind every perf PR (ISSUE 3): measures
the gate cases (kafka 10k-row host decode + encode, the headline
workload of BENCH_r0*) with bench.py's exact best-of-N protocol,
compares each case's MEDIAN against the committed baseline, and exits
non-zero when any case regressed more than the tolerance (default 15%).
Every run appends a line to the bench trajectory
(``BENCH_TRAJECTORY.jsonl``) and saves the run's full telemetry snapshot
(``telemetry_snapshot.json``) so a red gate arrives with its own
evidence (phase breakdown, routing, per-opcode profile when
``PYRUHVRO_TPU_NATIVE_PROF=1``).

Cross-machine honesty: raw wall-clock baselines only compare on the
machine that produced them, so the baseline stores a ``calib_s``
measured by a fixed numpy workload; the gate measures the same workload
locally and rescales the baseline medians by the ratio (clamped to
[0.25, 4]) before comparing. ``--update-baseline`` reseeds the baseline
from this machine's fresh run.

Usage::

    python scripts/perf_gate.py                       # measure + compare
    python scripts/perf_gate.py --details FILE        # compare a saved run
    python scripts/perf_gate.py --update-baseline     # reseed the baseline
    python scripts/perf_gate.py --tolerance 0.25      # loosen the gate

Exit codes: 0 = within tolerance, 1 = regression, 2 = usage/baseline
problem.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "PERF_BASELINE.json")
DEFAULT_TRAJECTORY = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")
DEFAULT_SNAPSHOT = os.path.join(REPO, "telemetry_snapshot.json")
# the committed telemetry snapshot a gate run's snapshot is diffed
# against (ISSUE 16): the rendered attribution report
# (telemetry_diff.txt) rides along as a CI artifact, so a red gate
# arrives with "which phase moved, which counters appeared" already
# answered. Reseed alongside the baseline with --update-baseline.
DEFAULT_DIFF_REFERENCE = os.path.join(
    REPO, "tests", "data", "perf_gate_reference_snapshot.json")
DEFAULT_DIFF_OUT = os.path.join(REPO, "telemetry_diff.txt")
DEFAULT_TOLERANCE = 0.15


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def case_key(schema: str, op: str, backend: str, rows: int,
             chunks: int) -> str:
    return f"{schema}/{op}/{backend}/{rows}x{chunks}"


# Cases measured fresh but compared against ANOTHER case's committed
# baseline: the error-policy layer (ISSUE 4) must be free when unused,
# so the explicit on_error="raise" run is held to the same allowance as
# the plain call it must be identical to.
ALIAS_BASELINE = {
    "deserialize_raise_policy": "deserialize",
}


def calibrate() -> float:
    """A fixed CPU+memory workload (numpy xor/cumsum over 8M int64):
    the unit the baseline's wall-clock medians are expressed against, so
    a committed baseline transfers across machines of different speed
    without re-measuring the library itself (which would be circular)."""
    import numpy as np

    a = np.arange(1 << 23, dtype=np.int64)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        b = a ^ (a >> 7)
        c = np.cumsum(b, dtype=np.int64)
        _ = int(c[-1])
        best = min(best, time.perf_counter() - t0)
    return best


def measure_cases(rows: int, chunks: int, reps: int) -> Dict[str, dict]:
    """The gate cases with bench.py's protocol (one untimed warmup, all
    reps recorded, band = {n, min_s, median_s}) — host tier only: the
    gate must be deterministic wherever CI happens to run. (The routing
    matrix builds its own ``backend="auto"`` case set in
    :func:`route_matrix`, where the env knobs actually decide
    something.)"""
    from bench import _band, _gen_kafka, _time_reps  # noqa: E402
    from pyruhvro_tpu.api import (
        deserialize_array,
        deserialize_array_threaded,
        serialize_record_batch,
    )
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON as K

    from pyruhvro_tpu.runtime import metrics as _metrics

    datums = _gen_kafka(rows)
    out: Dict[str, dict] = {}

    _metrics.reset()
    times = _time_reps(
        lambda: deserialize_array_threaded(datums, K, chunks,
                                           backend="host"), reps)
    band = _band(times)
    # fused wire→Arrow coverage on the headline case (ISSUE 9): the
    # fallback counter is a budget, not an FYI — compare() ignores the
    # extra key, main() asserts on it, and the baseline records it
    snap = _metrics.snapshot()
    f_hit = int(snap.get("decode.fused", 0))
    f_fb = int(snap.get("decode.fused_fallback", 0))
    if f_hit or f_fb:
        band["fused_coverage"] = round(f_hit / (f_hit + f_fb), 4)
    out[case_key("kafka", "deserialize", "host", rows, chunks)] = band

    # the policy layer must be FREE when unused: the explicit
    # on_error="raise" spelling is measured as its own case and held to
    # the plain deserialize baseline via ALIAS_BASELINE
    times = _time_reps(
        lambda: deserialize_array_threaded(datums, K, chunks,
                                           backend="host",
                                           on_error="raise"), reps)
    out[case_key("kafka", "deserialize_raise_policy", "host", rows,
                 chunks)] = _band(times)

    batch = deserialize_array(datums, K, backend="host")
    times = _time_reps(
        lambda: serialize_record_batch(batch, K, chunks, backend="host"),
        reps)
    out[case_key("kafka", "serialize", "host", rows, chunks)] = _band(times)
    for key, band in out.items():
        _log(f"[perf-gate] {key}: median {band['median_s'] * 1e3:.3f} ms "
             f"(min {band['min_s'] * 1e3:.3f} ms, n={band['n']})")
    return out


def load_details(path: str) -> Dict[str, dict]:
    """Medians from a saved run: either a baseline-style file
    ({"cases": {key: {"median_s"}}}) or a BENCH_DETAILS.json (results
    rows carrying a band)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "cases" in data:
        return {k: dict(v) for k, v in data["cases"].items()}
    out = {}
    for r in data.get("results", []):
        band = r.get("band")
        if not band:
            continue
        key = case_key(r.get("schema", "?"), r.get("op", "?"),
                       r.get("backend", "?"), r.get("rows", 0),
                       r.get("chunks", 0))
        out[key] = dict(band)
    if not out:
        raise ValueError(f"{path}: no banded results to compare")
    return out


def compare(fresh: Dict[str, dict], baseline: dict, tolerance: float,
            scale: float) -> list:
    """-> list of (key, fresh_median, allowed, regressed) for every case
    present in BOTH the fresh run and the baseline (aliased cases —
    ALIAS_BASELINE — borrow their target case's baseline median)."""
    cases = baseline.get("cases", {})
    rows = []
    for key, base in sorted(cases.items()):
        f = fresh.get(key)
        if f is None:
            continue
        allowed = base["median_s"] * scale * (1.0 + tolerance)
        rows.append((key, f["median_s"], allowed, f["median_s"] > allowed))
    for key, f in sorted(fresh.items()):
        if key in cases:
            continue
        parts = key.split("/")
        if len(parts) != 4 or parts[1] not in ALIAS_BASELINE:
            continue
        plain_key = "/".join(
            [parts[0], ALIAS_BASELINE[parts[1]], parts[2], parts[3]])
        base = cases.get(plain_key)
        if base is None:
            continue
        # allowance: the committed baseline OR this run's own plain
        # measurement, whichever is larger — the aliased case asserts
        # "identical to the plain call", and on a noisy runner the
        # same-run plain median is the fairer identical-cost reference
        allowed = base["median_s"] * scale * (1.0 + tolerance)
        plain_fresh = fresh.get(plain_key)
        if plain_fresh is not None:
            allowed = max(
                allowed, plain_fresh["median_s"] * (1.0 + tolerance))
        rows.append((key, f["median_s"], allowed, f["median_s"] > allowed))
    return rows


def append_trajectory(path: str, entry: dict) -> None:
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")


def save_snapshot(path: str) -> None:
    """The run's full telemetry snapshot — including the ``device``
    jit-cache/memory section when the device tier ran — as the gate's
    evidence artifact (CI exports it as a Perfetto trace too)."""
    from pyruhvro_tpu.runtime import fsio, telemetry, timeline

    # close out the current aggregation interval so the artifact's
    # timeline section covers the run's final stretch (ISSUE 20)
    timeline.tick_now()
    fsio.atomic_write_json(path, telemetry.snapshot())
    _log(f"[perf-gate] telemetry snapshot -> {path}")


def save_diff(reference_path: str, out_path: str) -> None:
    """Regression attribution (ISSUE 16): render ``telemetry diff``
    between the committed reference snapshot and THIS run's telemetry —
    per-key counter deltas, per-phase p50/p95/p99 latency shift,
    new/dead keys, routing-arm mix — into a plain-text CI artifact.
    Advisory: the diff explains the wall-clock verdict, it never makes
    one (reference counters are machine/config-dependent)."""
    from pyruhvro_tpu.runtime import fleet, telemetry

    with open(reference_path, encoding="utf-8") as f:
        reference = json.load(f)
    text = fleet.render_diff(reference, telemetry.snapshot())
    tmp = f"{out_path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text + "\n")
    os.replace(tmp, out_path)
    _log(f"[perf-gate] telemetry diff (vs {os.path.basename(reference_path)})"
         f" -> {out_path}")


def _device_counters() -> Dict[str, float]:
    """The flat ``device.*`` counters of the current process (jit cache,
    compile/launch seconds, transfer bytes, retries) — the section the
    baseline/bench snapshots embed."""
    from pyruhvro_tpu.runtime import metrics

    return {k: round(v, 6) for k, v in sorted(metrics.snapshot().items())
            if k.startswith("device.")}


# -- autotuned-vs-static routing matrix (ISSUE 6) ---------------------------
#
# The acceptance harness for the router: measure the gate cases under
# each STATIC env-knob configuration and under the router (trained in
# this run, then measured with exploration off on the warm profile).
# The router must not lose to ANY static config by more than
# --route-tolerance (default 5%) median, per case. Writes
# ROUTE_REPORT.json + a routing snapshot whose ledger the route-report/
# what-if CLI render — CI uploads both.

ROUTE_MATRIX_STATICS = [
    # name -> env overrides; empty = the out-of-the-box static gates
    ("static/thread", {}),
    ("static/process", {"PYRUHVRO_TPU_POOL": "process"}),
    ("static/host_only", {"PYRUHVRO_TPU_DEVICE_MIN_ROWS": "1000000000"}),
]

_ROUTE_ENV_KEYS = (
    "PYRUHVRO_TPU_AUTOTUNE", "PYRUHVRO_TPU_EXPLORE", "PYRUHVRO_TPU_POOL",
    "PYRUHVRO_TPU_DEVICE_MIN_ROWS", "PYRUHVRO_TPU_ROUTING_PROFILE",
)


class _route_env:
    """Set routing env knobs for one matrix leg, restoring on exit (the
    knobs are read per call, so in-process flips take effect)."""

    def __init__(self, overrides: Dict[str, str]):
        self.overrides = overrides

    def __enter__(self):
        self._saved = {k: os.environ.get(k) for k in _ROUTE_ENV_KEYS}
        for k in _ROUTE_ENV_KEYS:
            os.environ.pop(k, None)
        os.environ.update(self.overrides)
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def route_matrix(args) -> int:
    from pyruhvro_tpu.api import (
        deserialize_array,
        deserialize_array_threaded,
        serialize_record_batch,
    )
    from pyruhvro_tpu.runtime import costmodel, fsio, telemetry
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON as K
    from bench import _band, _gen_kafka  # noqa: E402

    profile = os.path.join(REPO, "ROUTING_PROFILE.json")
    report_path = os.path.join(REPO, "ROUTE_REPORT.json")
    snap_path = os.path.join(REPO, "route_snapshot.json")

    # pre-warm OUTSIDE any measured leg: native build + hot-schema
    # specialization land here, not in whichever config runs first
    datums = _gen_kafka(args.rows)
    with _route_env({}):
        for _ in range(3):
            batch = deserialize_array(datums, K, backend="host")
        for _ in range(2):
            deserialize_array_threaded(datums, K, args.chunks,
                                       backend="host")
            serialize_record_batch(batch, K, args.chunks, backend="host")

    def _case_key(op):
        return case_key("kafka", op, "auto", args.rows, args.chunks)

    cases = {
        _case_key("deserialize"): lambda: deserialize_array_threaded(
            datums, K, args.chunks, backend="auto"),
        _case_key("deserialize_raise_policy"):
            lambda: deserialize_array_threaded(
                datums, K, args.chunks, backend="auto",
                on_error="raise"),
        _case_key("serialize"): lambda: serialize_record_batch(
            batch, K, args.chunks, backend="auto"),
    }

    # TRAIN the router first — autotune on, aggressive exploration,
    # fresh profile file (the matrix must prove learning, not luck)
    try:
        os.remove(profile)
    except OSError:
        pass
    with _route_env({"PYRUHVRO_TPU_AUTOTUNE": "1",
                     "PYRUHVRO_TPU_EXPLORE": "0.34",
                     "PYRUHVRO_TPU_ROUTING_PROFILE": profile}):
        telemetry.reset()
        _log("[route-matrix] training the router (explore=0.34)")
        for _ in range(max(3, args.reps)):
            for fn in cases.values():
                fn()
        costmodel.save_profile(profile)
    _log(f"[route-matrix] warm profile -> {profile}")

    # MEASURE all configs round-robin, one rep each per round: every
    # config shares the same machine-noise window, so slow drift on a
    # busy runner cannot hand whichever leg ran first a fake win
    configs = ROUTE_MATRIX_STATICS + [
        ("router", {"PYRUHVRO_TPU_AUTOTUNE": "1",
                    "PYRUHVRO_TPU_EXPLORE": "0",
                    "PYRUHVRO_TPU_ROUTING_PROFILE": profile}),
    ]
    telemetry.reset()
    costmodel.load_profile(profile)
    times: Dict[tuple, list] = {}
    for name, env in configs:  # untimed warmup round
        with _route_env(env):
            for fn in cases.values():
                fn()
    # case-major, config-inner: each rep times every config on the SAME
    # case back to back (the bench overhead-measurement protocol), so a
    # jitter spike hits whichever config it lands on, not a whole leg;
    # the starting config rotates per rep so no config owns a position.
    # Reps floor at 15: a verdict round costs milliseconds per config,
    # and the 5% bar needs more samples than the wall-clock gate does
    matrix_reps = max(args.reps, 15)
    from pyruhvro_tpu.runtime import router as _router

    arms: Dict[tuple, set] = {}  # (config, case) -> every arm executed
    for key, fn in cases.items():
        for rep in range(matrix_reps):
            k = rep % len(configs)
            for name, env in configs[k:] + configs[:k]:
                with _route_env(env):
                    t0 = time.perf_counter()
                    fn()
                    times.setdefault((name, key), []).append(
                        time.perf_counter() - t0)
                    e = _router.last_entry() or {}
                    arms.setdefault((name, key), set()).add(
                        e.get("arm", "?"))
    results: Dict[str, Dict[str, dict]] = {}
    for (name, key), ts in times.items():
        results.setdefault(name, {})[key] = _band(ts)
    for name, _env in configs:
        for key, band in sorted(results.get(name, {}).items()):
            _log(f"[route-matrix] {name} {key}: median "
                 f"{band['median_s'] * 1e3:.3f} ms (n={band['n']})")
    snap = telemetry.snapshot()
    fsio.atomic_write_json(snap_path, snap)
    _log(f"[route-matrix] routing snapshot -> {snap_path}")

    # the ledger-coverage acceptance: every AUTOTUNED call carries an
    # entry with BOTH predicted and observed cost (static-config calls
    # share the ring; they are ledgered too but may lack predictions
    # for arms the model never saw)
    ledger = (snap.get("routing") or {}).get("ledger") or []
    routed = [e for e in ledger if e.get("autotune")]
    covered = [e for e in routed
               if e.get("predicted_s") is not None
               and e.get("observed_s") is not None]
    coverage = len(covered) / len(routed) if routed else 0.0
    _log(f"[route-matrix] ledger coverage: {len(covered)}/{len(routed)} "
         f"autotuned calls with predicted+observed cost")

    tol = args.route_tolerance
    verdicts = {}
    failed = not routed or coverage < 1.0
    if failed:
        _log("[route-matrix] FAIL: ledger coverage below 100%")
    for key in sorted(results["router"]):
        router_med = results["router"][key]["median_s"]
        statics = {n: r[key]["median_s"]
                   for n, r in results.items()
                   if n != "router" and key in r}
        if not statics:
            continue
        best_name = min(statics, key=lambda n: statics[n])
        best = statics[best_name]
        # verdict on the MEDIAN of per-round paired ratios — router vs
        # the best static config's time IN THE SAME round: machine
        # drift hits every config of a round equally, so pairing
        # cancels it. Paired against ONE config (the best by median),
        # not a per-round min over all statics: min-of-k noisy samples
        # is biased low, which would fail a router that exactly ties.
        router_ts = times[("router", key)]
        best_ts = times[(best_name, key)]
        ratios = []
        for rt, bt in zip(router_ts, best_ts):
            if bt > 0:
                ratios.append(rt / bt)
        ratios.sort()
        ratio = (ratios[len(ratios) // 2] if ratios
                 else (router_med / best if best else None))
        # best-of-N corroboration: a real routing mistake (wrong arm)
        # is slower on EVERY rep, so min agrees with median; sub-ms
        # scheduler jitter moves the median but not the floor — it must
        # not fail the gate on a case where the router chose the same
        # arm the static config ran
        min_ratio = (min(router_ts) / min(best_ts)
                     if best_ts and min(best_ts) > 0 else None)
        # when the router and the winning static config EXECUTED the
        # same arm on EVERY rep, identical code ran — there is no
        # routing decision left to lose on, only timer noise between
        # two measurements of one path; the timing verdict applies the
        # moment the router ran ANY different arm mid-run (the model
        # keeps learning during measurement, so it may switch)
        r_arms = arms.get(("router", key)) or set()
        s_arms = arms.get((best_name, key)) or set()
        same_arm = (len(r_arms) == 1 and r_arms == s_arms
                    and "?" not in r_arms)
        lost = (not same_arm
                and ratio is not None and ratio > 1.0 + tol
                and (min_ratio is None or min_ratio > 1.0 + tol))
        verdicts[key] = {
            "router_median_s": round(router_med, 6),
            "router_arms": sorted(r_arms),
            "best_static": best_name,
            "best_static_median_s": round(best, 6),
            "best_static_arms": sorted(s_arms),
            "same_arm": same_arm,
            "ratio": round(ratio, 4) if ratio is not None else None,
            "min_ratio": (round(min_ratio, 4)
                          if min_ratio is not None else None),
            "lost": lost,
        }
        _log(f"[route-matrix] {key}: router {router_med * 1e3:.3f} ms "
             f"[{'/'.join(sorted(r_arms)) or '?'}] vs best static "
             f"{best_name} {best * 1e3:.3f} ms "
             f"[{'/'.join(sorted(s_arms)) or '?'}] "
             f"(paired ratio {ratio:.3f}, min ratio "
             f"{min_ratio if min_ratio is None else round(min_ratio, 3)}"
             f"{', same arm' if same_arm else ''}) -> "
             f"{'LOST' if lost else 'ok'}")
        failed = failed or lost
    report = {
        "metric": "route_matrix",
        "rows": args.rows,
        "chunks": args.chunks,
        "reps": args.reps,
        "tolerance": tol,
        "ledger_coverage": round(coverage, 4),
        "configs": {n: {k: dict(b) for k, b in r.items()}
                    for n, r in results.items()},
        "verdicts": verdicts,
        "pass": not failed,
    }
    fsio.atomic_write_json(report_path, report, sort_keys=True)
    _log(f"[route-matrix] report -> {report_path}")
    print(json.dumps({"metric": "route_matrix", "pass": not failed,
                      "ledger_coverage": round(coverage, 4),
                      "cases": {k: v["ratio"]
                                for k, v in verdicts.items()}}))
    return 1 if failed else 0


def device_warm_check() -> dict:
    """ISSUE 10 acceptance gate: a WARM-schema device call must run
    with zero capacity retries, serve every jitted entry from the cache
    (hits > 0, misses == 0), and overlap pack/h2d with an in-flight
    launch (``device.overlap_s`` > 0). Forces the device pipeline
    (``backend="tpu"`` runs it on whatever XLA backend is attached —
    CPU in CI) with a small overlap-chunk threshold so the 6k-row case
    pipelines through several chunks."""
    from pyruhvro_tpu import telemetry
    from pyruhvro_tpu.api import deserialize_array
    from pyruhvro_tpu.runtime import metrics
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
    )

    data = kafka_style_datums(6_000, seed=11)
    saved = os.environ.get("PYRUHVRO_TPU_OVERLAP_ROWS")
    os.environ["PYRUHVRO_TPU_OVERLAP_ROWS"] = "512"
    try:
        deserialize_array(data, KAFKA_SCHEMA_JSON, backend="tpu")  # cold
        # the overlap figure is is_ready-gated (conservative): a warm
        # rep where every tiny launch happens to finish before the next
        # pack does honestly reads 0 — a scheduler-timing outcome, not
        # a regression. Retrying a few warm reps keeps the gate hard on
        # the CONTRACT (overlap achievable) without being flaky on one
        # unlucky scheduling (container wall swings are 2-3x here).
        for _attempt in range(4):
            telemetry.reset()
            deserialize_array(data, KAFKA_SCHEMA_JSON, backend="tpu")
            snap = metrics.snapshot()
            if snap.get("device.overlap_s", 0.0) > 0:
                break
    finally:
        if saved is None:
            os.environ.pop("PYRUHVRO_TPU_OVERLAP_ROWS", None)
        else:
            os.environ["PYRUHVRO_TPU_OVERLAP_ROWS"] = saved
    pipeline_s = snap.get("device.pipeline_s", 0.0)
    out = {
        "retries": int(snap.get("device.retries", 0)),
        "jit_cache_hits": int(snap.get("device.jit_cache.hits", 0)),
        "jit_cache_misses": int(snap.get("device.jit_cache.misses", 0)),
        "overlap_s": round(snap.get("device.overlap_s", 0.0), 6),
        "overlap_frac": round(
            snap.get("device.overlap_s", 0.0) / pipeline_s, 4)
        if pipeline_s else 0.0,
        "arena_hits": int(snap.get("device.arena.hits", 0)),
    }
    out["pass"] = (
        out["retries"] == 0
        and out["jit_cache_hits"] >= 1
        and out["jit_cache_misses"] == 0
        and out["overlap_s"] > 0
    )
    return out


def shard_efficiency_check() -> dict:
    """Native shard-runner contract: on a ≥4-core box a 4-thread
    one-call decode must overlap its shards at ``chunk_efficiency`` ≥
    0.6 (busy / (wall × threads), from the runner's OWN drained
    counters — the figure Python-side serialization can't fake). On
    fewer cores the check skips with a note: the pool still fans out
    (time-sliced) but parallel efficiency is not a property this box
    can witness."""
    cores = os.cpu_count() or 1
    out = {"cores": cores, "threads": 4}
    if cores < 4:
        out.update({
            "skipped": True, "pass": True,
            "note": f"needs a >=4-core box to witness parallel shard "
                    f"overlap; this host has {cores}",
        })
        return out
    from pyruhvro_tpu.hostpath.codec import NativeHostCodec
    from pyruhvro_tpu.schema.cache import get_or_parse_schema
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
    )

    e = get_or_parse_schema(KAFKA_SCHEMA_JSON)
    codec = NativeHostCodec(e.ir, e.arrow_schema)
    if not hasattr(codec._mod, "shard_stats"):
        out.update({"skipped": True, "pass": True,
                    "note": "host_codec binary predates the shard runner"})
        return out
    base = kafka_style_datums(50_000, seed=7)
    datums = (base * 10)[:500_000]
    codec.decode(datums[:1000])  # warm
    eff = 0.0
    for _ in range(2):
        codec._drain_shard_stats()
        codec.decode(datums, nthreads=4)
        d = codec._drain_shard_stats()
        if d["fanouts"] and d["wall_s"] > 0 and d["threads"]:
            eff = max(eff, min(1.0, d["shard_s"]
                               / (d["wall_s"] * d["threads"])))
    out["chunk_efficiency"] = round(eff, 4)
    out["pass"] = eff >= 0.6
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_gate.py",
        description="fail on >tolerance median regression vs "
                    "PERF_BASELINE.json")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--details",
                    help="compare this saved run (baseline-style 'cases' "
                         "dict or BENCH_DETAILS.json) instead of measuring")
    ap.add_argument("--rows", type=int,
                    default=int(os.environ.get("PERF_GATE_ROWS", 10_000)))
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--reps", type=int,
                    default=int(os.environ.get("PERF_GATE_REPS", 5)))
    ap.add_argument("--tolerance", type=float,
                    default=float(os.environ.get(
                        "PYRUHVRO_TPU_PERF_TOLERANCE", DEFAULT_TOLERANCE)))
    ap.add_argument("--trajectory", default=DEFAULT_TRAJECTORY)
    ap.add_argument("--no-trajectory", dest="trajectory",
                    action="store_const", const=None)
    ap.add_argument("--snapshot-out", default=DEFAULT_SNAPSHOT)
    ap.add_argument("--diff-reference", default=DEFAULT_DIFF_REFERENCE,
                    help="committed snapshot to attribute this run "
                         "against via 'telemetry diff' (missing file = "
                         "diff silently skipped)")
    ap.add_argument("--diff-out", default=DEFAULT_DIFF_OUT)
    ap.add_argument("--update-baseline", action="store_true",
                    help="reseed the baseline from this run and exit 0")
    ap.add_argument("--route-matrix", action="store_true",
                    help="autotuned-vs-static routing matrix: fail when "
                         "the warm router loses any case to any static "
                         "config by more than --route-tolerance")
    ap.add_argument("--route-tolerance", type=float,
                    default=float(os.environ.get(
                        "PYRUHVRO_TPU_ROUTE_TOLERANCE", 0.05)))
    ap.add_argument("--no-device-check", action="store_true",
                    help="skip the warm-device contract check (ISSUE 10:"
                         " zero retries, all-hit jit cache, overlap "
                         "fraction > 0 on a warm forced-device call)")
    ap.add_argument("--no-shard-check", action="store_true",
                    help="skip the native shard-runner efficiency check "
                         "(chunk_efficiency >= 0.6 at 4 threads on a "
                         ">=4-core box; auto-skips with a note on "
                         "smaller hosts)")
    ap.add_argument("--slo-file",
                    default=os.environ.get("PYRUHVRO_TPU_SLO_FILE"),
                    help="evaluate this SLO file over the gate run: the "
                         "saved snapshot gains an 'slo' section (burn "
                         "rates, breach state) the slo-report CLI "
                         "renders — CI uploads it as an artifact")
    args = ap.parse_args(argv)

    if args.slo_file:
        # must be set before the library records any root span so every
        # measured call feeds the burn windows
        os.environ["PYRUHVRO_TPU_SLO_FILE"] = args.slo_file

    if args.route_matrix:
        return route_matrix(args)

    try:
        with open(args.baseline, encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        if not args.update_baseline:
            _log(f"[perf-gate] error: cannot read baseline "
                 f"{args.baseline}: {e}")
            ap.print_usage(sys.stderr)
            return 2
        baseline = {}

    if args.details:
        try:
            fresh = load_details(args.details)
        except (OSError, ValueError) as e:
            _log(f"[perf-gate] error: {e}")
            ap.print_usage(sys.stderr)
            return 2
        calib = None  # a saved run carries no calibration context
        scale = 1.0
    else:
        calib = calibrate()
        base_calib = baseline.get("calib_s")
        scale = 1.0
        if base_calib:
            scale = min(4.0, max(0.25, calib / base_calib))
            _log(f"[perf-gate] calibration {calib * 1e3:.1f} ms "
                 f"(baseline {base_calib * 1e3:.1f} ms, scale {scale:.2f})")
        else:
            _log(f"[perf-gate] calibration {calib * 1e3:.1f} ms "
                 "(no baseline calibration; raw comparison)")
        fresh = measure_cases(args.rows, args.chunks, args.reps)
        if args.slo_file:
            from pyruhvro_tpu.runtime import slo as _slo

            sec = _slo.snapshot_slo()
            hot = sec.get("breached") or []
            fired = sum(int(o.get("breaches") or 0)
                        for o in sec.get("objectives") or [])
            if hot:
                msg = f"CURRENTLY BREACHED: {', '.join(hot)}"
            elif fired:
                # a breach that fired and time-decayed mid-run still
                # happened — the instantaneous state alone would lie
                msg = f"{fired} breach(es) fired during the run (recovered)"
            else:
                msg = "no objective breached over the gate run"
            _log(f"[perf-gate] slo ({args.slo_file}): {msg}")
        if args.snapshot_out:
            try:
                save_snapshot(args.snapshot_out)
            except Exception as e:  # noqa: BLE001 — artifact, not verdict
                _log(f"[perf-gate] snapshot save failed: {e!r}")
        if (args.diff_reference and args.diff_out
                and os.path.exists(args.diff_reference)):
            try:
                save_diff(args.diff_reference, args.diff_out)
            except Exception as e:  # noqa: BLE001 — artifact, not verdict
                _log(f"[perf-gate] telemetry diff failed: {e!r}")

    if args.update_baseline:
        doc = {
            "note": "perf_gate.py baseline: per-case best-of-N medians; "
                    "wall seconds on the machine named below, rescaled "
                    "across machines via calib_s (see scripts/"
                    "perf_gate.py). Reseed with --update-baseline.",
            "tolerance": args.tolerance,
            "calib_s": calib,
            "machine": {"cpus": os.cpu_count()},
            "cases": fresh,
            # device-tier telemetry of the measuring run (ISSUE 5):
            # compile/launch split, jit-cache and transfer counters —
            # empty on host-only gate runs, populated when a device-path
            # case is ever added, so baselines carry their own routing
            # evidence either way
            "device": _device_counters(),
        }
        from pyruhvro_tpu.runtime import fsio

        fsio.atomic_write_json(args.baseline, doc, sort_keys=True)
        _log(f"[perf-gate] baseline reseeded -> {args.baseline}")
        if args.diff_reference:
            from pyruhvro_tpu.runtime import telemetry as _telemetry

            fsio.atomic_write_json(args.diff_reference,
                                   _telemetry.snapshot(), sort_keys=True)
            _log(f"[perf-gate] diff reference reseeded -> "
                 f"{args.diff_reference}")
        return 0

    rows = compare(fresh, baseline, args.tolerance, scale)
    if not rows:
        _log("[perf-gate] error: no overlapping cases between the run "
             "and the baseline")
        return 2
    failed = False
    # warm-device contract (ISSUE 10): zero retries, all-hit jit cache,
    # overlap fraction > 0 on the warm call — enforced, not just logged
    dev_warm = None
    if not args.details and not args.no_device_check:
        try:
            dev_warm = device_warm_check()
        except Exception as e:  # noqa: BLE001 — named failure below
            _log(f"[perf-gate] device warm check errored: {e!r}")
            dev_warm = {"pass": False, "error": repr(e)}
        _log(f"[perf-gate] device warm check: "
             f"retries={dev_warm.get('retries')} "
             f"cache={dev_warm.get('jit_cache_misses')} miss/"
             f"{dev_warm.get('jit_cache_hits')} hit "
             f"overlap_frac={dev_warm.get('overlap_frac')} -> "
             f"{'ok' if dev_warm['pass'] else 'FAILED'}")
        failed = failed or not dev_warm["pass"]
    # native shard-runner efficiency contract: the one-call fan-out
    # must genuinely overlap its shards where the hardware can show it
    shard_eff = None
    if not args.details and not args.no_shard_check:
        try:
            shard_eff = shard_efficiency_check()
        except Exception as e:  # noqa: BLE001 — named failure below
            _log(f"[perf-gate] shard efficiency check errored: {e!r}")
            shard_eff = {"pass": False, "error": repr(e)}
        if shard_eff.get("skipped"):
            _log(f"[perf-gate] shard efficiency check: skipped "
                 f"({shard_eff.get('note')})")
        else:
            _log(f"[perf-gate] shard efficiency check: "
                 f"eff={shard_eff.get('chunk_efficiency')} @ 4 threads "
                 f"-> {'ok' if shard_eff['pass'] else 'FAILED (<0.6)'}")
        failed = failed or not shard_eff["pass"]
    # fused-decode coverage budget (ISSUE 9): when the native tier
    # served the kafka case, at least 95% of its decode calls must have
    # gone through the fused wire→Arrow pass — a creeping fallback rate
    # is a perf regression even when the medians still squeak by
    for key, band in fresh.items():
        cov = band.get("fused_coverage") if isinstance(band, dict) else None
        if cov is None:
            continue
        ok = cov >= 0.95
        _log(f"[perf-gate] {key}: fused decode coverage "
             f"{cov * 100:.1f}% -> {'ok' if ok else 'FAILED (<95%)'}")
        failed = failed or not ok
    for key, med, allowed, regressed in rows:
        verdict = "REGRESSED" if regressed else "ok"
        _log(f"[perf-gate] {key}: {med * 1e3:.3f} ms vs allowed "
             f"{allowed * 1e3:.3f} ms -> {verdict}")
        failed = failed or regressed
    if args.trajectory:
        try:
            append_trajectory(args.trajectory, {
                "kind": "perf_gate",
                "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "tolerance": args.tolerance,
                "scale": round(scale, 4),
                "pass": not failed,
                "cases": {k: {"median_s": m, "allowed_s": round(a, 6)}
                          for k, m, a, _r in rows},
            })
        except OSError as e:
            _log(f"[perf-gate] trajectory append failed: {e!r}")
    print(json.dumps({
        "metric": "perf_gate",
        "pass": not failed,
        "cases": {k: round(m, 6) for k, m, _a, _r in rows},
        **({"device_warm": dev_warm} if dev_warm is not None else {}),
        **({"shard_efficiency": shard_eff} if shard_eff is not None
           else {}),
    }))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
