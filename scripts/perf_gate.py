#!/usr/bin/env python
"""Perf-regression gate: fresh best-of-N bands vs PERF_BASELINE.json.

The machine-checked tripwire behind every perf PR (ISSUE 3): measures
the gate cases (kafka 10k-row host decode + encode, the headline
workload of BENCH_r0*) with bench.py's exact best-of-N protocol,
compares each case's MEDIAN against the committed baseline, and exits
non-zero when any case regressed more than the tolerance (default 15%).
Every run appends a line to the bench trajectory
(``BENCH_TRAJECTORY.jsonl``) and saves the run's full telemetry snapshot
(``telemetry_snapshot.json``) so a red gate arrives with its own
evidence (phase breakdown, routing, per-opcode profile when
``PYRUHVRO_TPU_NATIVE_PROF=1``).

Cross-machine honesty: raw wall-clock baselines only compare on the
machine that produced them, so the baseline stores a ``calib_s``
measured by a fixed numpy workload; the gate measures the same workload
locally and rescales the baseline medians by the ratio (clamped to
[0.25, 4]) before comparing. ``--update-baseline`` reseeds the baseline
from this machine's fresh run.

Usage::

    python scripts/perf_gate.py                       # measure + compare
    python scripts/perf_gate.py --details FILE        # compare a saved run
    python scripts/perf_gate.py --update-baseline     # reseed the baseline
    python scripts/perf_gate.py --tolerance 0.25      # loosen the gate

Exit codes: 0 = within tolerance, 1 = regression, 2 = usage/baseline
problem.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "PERF_BASELINE.json")
DEFAULT_TRAJECTORY = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")
DEFAULT_SNAPSHOT = os.path.join(REPO, "telemetry_snapshot.json")
DEFAULT_TOLERANCE = 0.15


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def case_key(schema: str, op: str, backend: str, rows: int,
             chunks: int) -> str:
    return f"{schema}/{op}/{backend}/{rows}x{chunks}"


# Cases measured fresh but compared against ANOTHER case's committed
# baseline: the error-policy layer (ISSUE 4) must be free when unused,
# so the explicit on_error="raise" run is held to the same allowance as
# the plain call it must be identical to.
ALIAS_BASELINE = {
    "deserialize_raise_policy": "deserialize",
}


def calibrate() -> float:
    """A fixed CPU+memory workload (numpy xor/cumsum over 8M int64):
    the unit the baseline's wall-clock medians are expressed against, so
    a committed baseline transfers across machines of different speed
    without re-measuring the library itself (which would be circular)."""
    import numpy as np

    a = np.arange(1 << 23, dtype=np.int64)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        b = a ^ (a >> 7)
        c = np.cumsum(b, dtype=np.int64)
        _ = int(c[-1])
        best = min(best, time.perf_counter() - t0)
    return best


def measure_cases(rows: int, chunks: int, reps: int) -> Dict[str, dict]:
    """The gate cases with bench.py's protocol (one untimed warmup, all
    reps recorded, band = {n, min_s, median_s}) — host tier only: the
    gate must be deterministic wherever CI happens to run."""
    from bench import _band, _gen_kafka, _time_reps  # noqa: E402
    from pyruhvro_tpu.api import (
        deserialize_array,
        deserialize_array_threaded,
        serialize_record_batch,
    )
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON as K

    datums = _gen_kafka(rows)
    out: Dict[str, dict] = {}

    times = _time_reps(
        lambda: deserialize_array_threaded(datums, K, chunks,
                                           backend="host"), reps)
    out[case_key("kafka", "deserialize", "host", rows, chunks)] = _band(times)

    # the policy layer must be FREE when unused: the explicit
    # on_error="raise" spelling is measured as its own case and held to
    # the plain deserialize baseline via ALIAS_BASELINE
    times = _time_reps(
        lambda: deserialize_array_threaded(datums, K, chunks,
                                           backend="host",
                                           on_error="raise"), reps)
    out[case_key("kafka", "deserialize_raise_policy", "host", rows,
                 chunks)] = _band(times)

    batch = deserialize_array(datums, K, backend="host")
    times = _time_reps(
        lambda: serialize_record_batch(batch, K, chunks, backend="host"),
        reps)
    out[case_key("kafka", "serialize", "host", rows, chunks)] = _band(times)
    for key, band in out.items():
        _log(f"[perf-gate] {key}: median {band['median_s'] * 1e3:.3f} ms "
             f"(min {band['min_s'] * 1e3:.3f} ms, n={band['n']})")
    return out


def load_details(path: str) -> Dict[str, dict]:
    """Medians from a saved run: either a baseline-style file
    ({"cases": {key: {"median_s"}}}) or a BENCH_DETAILS.json (results
    rows carrying a band)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "cases" in data:
        return {k: dict(v) for k, v in data["cases"].items()}
    out = {}
    for r in data.get("results", []):
        band = r.get("band")
        if not band:
            continue
        key = case_key(r.get("schema", "?"), r.get("op", "?"),
                       r.get("backend", "?"), r.get("rows", 0),
                       r.get("chunks", 0))
        out[key] = dict(band)
    if not out:
        raise ValueError(f"{path}: no banded results to compare")
    return out


def compare(fresh: Dict[str, dict], baseline: dict, tolerance: float,
            scale: float) -> list:
    """-> list of (key, fresh_median, allowed, regressed) for every case
    present in BOTH the fresh run and the baseline (aliased cases —
    ALIAS_BASELINE — borrow their target case's baseline median)."""
    cases = baseline.get("cases", {})
    rows = []
    for key, base in sorted(cases.items()):
        f = fresh.get(key)
        if f is None:
            continue
        allowed = base["median_s"] * scale * (1.0 + tolerance)
        rows.append((key, f["median_s"], allowed, f["median_s"] > allowed))
    for key, f in sorted(fresh.items()):
        if key in cases:
            continue
        parts = key.split("/")
        if len(parts) != 4 or parts[1] not in ALIAS_BASELINE:
            continue
        plain_key = "/".join(
            [parts[0], ALIAS_BASELINE[parts[1]], parts[2], parts[3]])
        base = cases.get(plain_key)
        if base is None:
            continue
        # allowance: the committed baseline OR this run's own plain
        # measurement, whichever is larger — the aliased case asserts
        # "identical to the plain call", and on a noisy runner the
        # same-run plain median is the fairer identical-cost reference
        allowed = base["median_s"] * scale * (1.0 + tolerance)
        plain_fresh = fresh.get(plain_key)
        if plain_fresh is not None:
            allowed = max(
                allowed, plain_fresh["median_s"] * (1.0 + tolerance))
        rows.append((key, f["median_s"], allowed, f["median_s"] > allowed))
    return rows


def append_trajectory(path: str, entry: dict) -> None:
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")


def save_snapshot(path: str) -> None:
    """The run's full telemetry snapshot — including the ``device``
    jit-cache/memory section when the device tier ran — as the gate's
    evidence artifact (CI exports it as a Perfetto trace too)."""
    from pyruhvro_tpu.runtime import telemetry

    with open(path, "w", encoding="utf-8") as f:
        json.dump(telemetry.snapshot(), f, indent=1, default=str)
    _log(f"[perf-gate] telemetry snapshot -> {path}")


def _device_counters() -> Dict[str, float]:
    """The flat ``device.*`` counters of the current process (jit cache,
    compile/launch seconds, transfer bytes, retries) — the section the
    baseline/bench snapshots embed."""
    from pyruhvro_tpu.runtime import metrics

    return {k: round(v, 6) for k, v in sorted(metrics.snapshot().items())
            if k.startswith("device.")}


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_gate.py",
        description="fail on >tolerance median regression vs "
                    "PERF_BASELINE.json")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--details",
                    help="compare this saved run (baseline-style 'cases' "
                         "dict or BENCH_DETAILS.json) instead of measuring")
    ap.add_argument("--rows", type=int,
                    default=int(os.environ.get("PERF_GATE_ROWS", 10_000)))
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--reps", type=int,
                    default=int(os.environ.get("PERF_GATE_REPS", 5)))
    ap.add_argument("--tolerance", type=float,
                    default=float(os.environ.get(
                        "PYRUHVRO_TPU_PERF_TOLERANCE", DEFAULT_TOLERANCE)))
    ap.add_argument("--trajectory", default=DEFAULT_TRAJECTORY)
    ap.add_argument("--no-trajectory", dest="trajectory",
                    action="store_const", const=None)
    ap.add_argument("--snapshot-out", default=DEFAULT_SNAPSHOT)
    ap.add_argument("--update-baseline", action="store_true",
                    help="reseed the baseline from this run and exit 0")
    args = ap.parse_args(argv)

    try:
        with open(args.baseline, encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        if not args.update_baseline:
            _log(f"[perf-gate] error: cannot read baseline "
                 f"{args.baseline}: {e}")
            ap.print_usage(sys.stderr)
            return 2
        baseline = {}

    if args.details:
        try:
            fresh = load_details(args.details)
        except (OSError, ValueError) as e:
            _log(f"[perf-gate] error: {e}")
            ap.print_usage(sys.stderr)
            return 2
        calib = None  # a saved run carries no calibration context
        scale = 1.0
    else:
        calib = calibrate()
        base_calib = baseline.get("calib_s")
        scale = 1.0
        if base_calib:
            scale = min(4.0, max(0.25, calib / base_calib))
            _log(f"[perf-gate] calibration {calib * 1e3:.1f} ms "
                 f"(baseline {base_calib * 1e3:.1f} ms, scale {scale:.2f})")
        else:
            _log(f"[perf-gate] calibration {calib * 1e3:.1f} ms "
                 "(no baseline calibration; raw comparison)")
        fresh = measure_cases(args.rows, args.chunks, args.reps)
        if args.snapshot_out:
            try:
                save_snapshot(args.snapshot_out)
            except Exception as e:  # noqa: BLE001 — artifact, not verdict
                _log(f"[perf-gate] snapshot save failed: {e!r}")

    if args.update_baseline:
        doc = {
            "note": "perf_gate.py baseline: per-case best-of-N medians; "
                    "wall seconds on the machine named below, rescaled "
                    "across machines via calib_s (see scripts/"
                    "perf_gate.py). Reseed with --update-baseline.",
            "tolerance": args.tolerance,
            "calib_s": calib,
            "machine": {"cpus": os.cpu_count()},
            "cases": fresh,
            # device-tier telemetry of the measuring run (ISSUE 5):
            # compile/launch split, jit-cache and transfer counters —
            # empty on host-only gate runs, populated when a device-path
            # case is ever added, so baselines carry their own routing
            # evidence either way
            "device": _device_counters(),
        }
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        _log(f"[perf-gate] baseline reseeded -> {args.baseline}")
        return 0

    rows = compare(fresh, baseline, args.tolerance, scale)
    if not rows:
        _log("[perf-gate] error: no overlapping cases between the run "
             "and the baseline")
        return 2
    failed = False
    for key, med, allowed, regressed in rows:
        verdict = "REGRESSED" if regressed else "ok"
        _log(f"[perf-gate] {key}: {med * 1e3:.3f} ms vs allowed "
             f"{allowed * 1e3:.3f} ms -> {verdict}")
        failed = failed or regressed
    if args.trajectory:
        try:
            append_trajectory(args.trajectory, {
                "kind": "perf_gate",
                "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "tolerance": args.tolerance,
                "scale": round(scale, 4),
                "pass": not failed,
                "cases": {k: {"median_s": m, "allowed_s": round(a, 6)}
                          for k, m, a, _r in rows},
            })
        except OSError as e:
            _log(f"[perf-gate] trajectory append failed: {e!r}")
    print(json.dumps({
        "metric": "perf_gate",
        "pass": not failed,
        "cases": {k: round(m, 6) for k, m, _a, _r in rows},
    }))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
