#!/usr/bin/env python
"""OTLP trace round-trip smoke gate (ISSUE 16 acceptance).

Stands up a stub OTLP/HTTP collector (stdlib HTTP server recording
every ``/v1/traces`` + ``/v1/metrics`` POST), injects a W3C
``traceparent`` via the ``PYRUHVRO_TPU_TRACEPARENT`` env knob, and runs
a SPAWN-POOL chunked decode in a fresh subprocess with the exporter
enabled (``PYRUHVRO_TPU_OTLP_ENDPOINT``). Asserts:

* the collector received exactly ONE trace id — the injected one: the
  API root span joined the ingress context, and every process-pool
  chunk span re-parented under it (no synthetic per-pid roots);
* the ``pool.worker`` chunk spans are present with parents, i.e. the
  context crossed the spawn boundary;
* the metrics POSTs carry the counter sums and histogram exemplars
  whose trace id is, again, the injected one;
* a quarantined row (tolerant decode leg) carries the injected trace
  id end-to-end.

Exit 0 = all assertions hold; any failure raises.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TRACE_ID = "4bf92f3577b34da6a3ce929d0e0e4736"  # the W3C spec example
PARENT_SPAN = "00f067aa0ba902b7"
TRACEPARENT = f"00-{TRACE_ID}-{PARENT_SPAN}-01"

_WORKLOAD = r"""
import json, sys
import pyruhvro_tpu as p
from pyruhvro_tpu.runtime import otel
from pyruhvro_tpu.utils.datagen import (
    KAFKA_SCHEMA_JSON as K, kafka_style_datums)

datums = kafka_style_datums(2000, seed=13)
p.deserialize_array_threaded(datums, K, 4, backend="host")
bad = list(datums)
bad[7] = bad[7][:2]
batch, errs = p.deserialize_array_threaded(
    bad, K, 4, backend="host", on_error="skip", return_errors=True)
assert errs, "expected a quarantined row"
print(json.dumps({"quarantine_trace": errs[0].trace_id}))
otel.stop()  # final flush before exit
"""


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    reqs = []

    class Collector(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            reqs.append((self.path, json.loads(self.rfile.read(n))))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):  # noqa: N802 — http.server hook
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    endpoint = f"http://127.0.0.1:{srv.server_address[1]}"
    _log(f"[otlp-smoke] stub collector at {endpoint}")

    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYRUHVRO_TPU_POOL="process",
        PYRUHVRO_TPU_TRACEPARENT=TRACEPARENT,
        PYRUHVRO_TPU_OTLP_ENDPOINT=endpoint,
        PYRUHVRO_TPU_OTLP_INTERVAL_S="0.5",
    )
    out = subprocess.run([sys.executable, "-c", _WORKLOAD],
                         capture_output=True, text=True, env=env,
                         cwd=REPO, timeout=600)
    srv.shutdown()
    if out.returncode != 0:
        _log(out.stdout)
        _log(out.stderr)
        raise SystemExit(f"workload failed rc={out.returncode}")

    # the quarantined row carried the ingress trace id end-to-end
    q = json.loads(out.stdout.strip().splitlines()[-1])
    assert q["quarantine_trace"] == TRACE_ID, q
    _log("[otlp-smoke] quarantined row carries the injected trace id")

    spans = [s
             for path, body in reqs if path.endswith("/v1/traces")
             for rs in body["resourceSpans"]
             for ss in rs["scopeSpans"]
             for s in ss["spans"]]
    assert spans, "collector saw no spans"
    trace_ids = {s["traceId"] for s in spans}
    assert trace_ids == {TRACE_ID}, trace_ids  # ONE trace, the injected
    roots = [s for s in spans
             if s["name"] == "api.deserialize_array_threaded"]
    assert roots and all(s.get("parentSpanId") == PARENT_SPAN
                         for s in roots), roots
    workers = [s for s in spans if s["name"] == "pool.worker"]
    assert len(workers) >= 4, [s["name"] for s in spans]
    assert all(s.get("parentSpanId") for s in workers), workers
    _log(f"[otlp-smoke] {len(spans)} spans, single trace {TRACE_ID}, "
         f"{len(workers)} pool.worker chunk spans re-parented")

    metrics_posts = [body for path, body in reqs
                     if path.endswith("/v1/metrics")]
    assert metrics_posts, "collector saw no metrics"
    mets = [m
            for body in metrics_posts
            for rm in body["resourceMetrics"]
            for sm in rm["scopeMetrics"]
            for m in sm["metrics"]]
    names = {m["name"] for m in mets}
    assert "pool.proc_chunks" in names, sorted(names)
    exemplars = [e
                 for m in mets if "histogram" in m
                 for dp in m["histogram"]["dataPoints"]
                 for e in dp.get("exemplars", [])]
    assert exemplars and all(e["traceId"] == TRACE_ID
                             for e in exemplars), exemplars[:3]
    _log(f"[otlp-smoke] {len(names)} metric families, "
         f"{len(exemplars)} exemplars carry the injected trace id")
    print(json.dumps({"metric": "otlp_smoke", "pass": True,
                      "spans": len(spans), "workers": len(workers),
                      "metric_families": len(names)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
