#!/usr/bin/env python
"""Standalone decode profiling loop (≙ ``ruhvro/examples/prof_decode.rs``).

The reference profiles with samply/flamegraph over a hot loop
(1k records × many iters of the array_and_map schema, 8 chunks); the
JAX-native equivalent is a ``jax.profiler`` trace (open in TensorBoard
or Perfetto) plus the library's own phase counters
(``pyruhvro_tpu.metrics``), which split wall time into pack / h2d /
compile / launch / d2h — the split that matters on a high-latency
interconnect.

Usage::

    python scripts/profile_decode.py --rows 1000 --iters 50
    python scripts/profile_decode.py --op serialize --trace-dir /tmp/tr
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--op", choices=("deserialize", "serialize"),
                    default="deserialize")
    ap.add_argument("--schema", default="array_and_map",
                    help="kafka or a CRITERION_SHAPES name")
    ap.add_argument("--backend", default="tpu",
                    choices=("tpu", "host", "auto"))
    ap.add_argument("--trace-dir", default=None,
                    help="write a jax.profiler trace here (TensorBoard/"
                         "Perfetto); omit to profile counters only")
    args = ap.parse_args()

    from pyruhvro_tpu import (
        deserialize_array,
        deserialize_array_threaded,
        metrics,
        serialize_record_batch,
        telemetry,
    )
    from pyruhvro_tpu.utils.datagen import (
        CRITERION_SHAPES,
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
        random_datums,
    )

    if args.schema == "kafka":
        schema = KAFKA_SCHEMA_JSON
        datums = kafka_style_datums(args.rows, seed=5)
    else:
        schema = CRITERION_SHAPES[args.schema]
        from pyruhvro_tpu.schema.cache import get_or_parse_schema

        datums = random_datums(
            get_or_parse_schema(schema).ir, args.rows, seed=5
        )

    if args.op == "deserialize":
        def step():
            return deserialize_array_threaded(
                datums, schema, args.chunks, backend=args.backend
            )
    else:
        batch = deserialize_array(datums, schema, backend="host")

        def step():
            return serialize_record_batch(
                batch, schema, args.chunks, backend=args.backend
            )

    print(f"warmup (compiles)...", file=sys.stderr, flush=True)
    step()
    telemetry.reset()  # spans + histograms + flat counters

    tracer = None
    if args.trace_dir:
        import jax

        tracer = jax.profiler.trace(args.trace_dir)
        tracer.__enter__()

    t0 = time.perf_counter()
    for _ in range(args.iters):
        step()
    wall = time.perf_counter() - t0

    if tracer is not None:
        tracer.__exit__(None, None, None)
        print(f"trace written to {args.trace_dir}", file=sys.stderr)

    snap = metrics.snapshot()
    tsnap = telemetry.snapshot()
    rec_s = args.rows * args.iters / wall
    phases = {
        k: round(v, 6) for k, v in sorted(snap.items())
    }
    per_iter_ms = {
        k.split(".", 1)[1][:-2]: round(v / args.iters * 1e3, 3)
        for k, v in sorted(snap.items())
        if k.endswith("_s")
    }
    # per-phase latency distributions across the hot loop (p50/p95/p99
    # expose warmup tails and launch jitter the cumulative sums hide)
    percentiles = {
        k: {"count": h["count"],
            "p50_ms": round(h["p50"] * 1e3, 3),
            "p95_ms": round(h["p95"] * 1e3, 3),
            "p99_ms": round(h["p99"] * 1e3, 3)}
        for k, h in tsnap["histograms"].items()
    }
    print(json.dumps({
        "op": args.op, "schema": args.schema, "backend": args.backend,
        "rows": args.rows, "iters": args.iters,
        "wall_s": round(wall, 4),
        "records_per_s": round(rec_s, 1),
        "per_iter_ms": per_iter_ms,
        "counters": phases,
        "percentiles": percentiles,
        "last_span": tsnap["spans"][-1] if tsnap["spans"] else None,
    }, indent=2))


if __name__ == "__main__":
    main()
