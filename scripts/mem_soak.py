#!/usr/bin/env python
"""Bounded-memory soak: the ISSUE 12 acceptance evidence, reproducible.

Two legs, one artifact (``MEM_REPORT.json``):

1. **decompose** — the kafka 10k case decoded repeatedly: after a
   warmup, steady-state RSS growth must be explained (>= 90%) by the
   tracked cache footprints in ``snapshot()["memory"]`` — or be below
   the noise floor entirely (nothing grows invisibly, which is the
   property a serving replica actually needs). Both numbers are
   reported raw.

2. **churn** — ``--schemas`` (default 2000) distinct synthetic schemas
   stream through the API around a hot ``--hot`` (default 64) schema
   working set, with the schema-cache LRU cap and the RSS high-water
   mark armed. Asserted: RSS stays under the high-water mark the whole
   run (sampled per batch of schemas) and the hot set keeps a
   >= 95% warm-hit rate — i.e. eviction holds memory flat WITHOUT
   evicting the schemas that matter.

``--gate`` exits non-zero when either leg misses its criterion (the CI
``mem-soak`` job runs exactly that and uploads the report).

Environment: the soak pins ``PYRUHVRO_TPU_SAMPLE_BUDGET=0`` (no
background profiled-VM build mid-measurement) and
``PYRUHVRO_TPU_NO_SPECIALIZE=1`` for the churn leg (64 hot schemas
crossing the specialize threshold would queue 64 g++ runs — engine
lifecycle is exercised by ``tests/test_memacct.py`` instead).
"""

from __future__ import annotations

import argparse
import gc
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# measurement hygiene BEFORE the library imports (knobs are read at
# call time, but the sampler arms itself from call one)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PYRUHVRO_TPU_SAMPLE_BUDGET"] = "0"

NOISE_FLOOR_BYTES = 8 << 20  # RSS wobble below this is allocator noise


def _mb(v: float) -> float:
    return round(v / (1 << 20), 2)


def leg_decompose(rows: int, calls: int) -> dict:
    """Steady-state RSS growth vs tracked footprint on kafka <rows>."""
    import pyruhvro_tpu as p
    from pyruhvro_tpu.runtime import memacct
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
    )

    data = kafka_style_datums(rows, seed=7)
    # warmup: schema parse, native build/dlopen, specialization (rows
    # accumulate past the threshold), allocator high-water settling
    for _ in range(4):
        p.deserialize_array(data, KAFKA_SCHEMA_JSON, backend="host")
    gc.collect()
    rss0 = memacct.rss_bytes()
    tracked0 = memacct.tracked_bytes()
    for _ in range(calls):
        p.deserialize_array(data, KAFKA_SCHEMA_JSON, backend="host")
    gc.collect()
    rss1 = memacct.rss_bytes()
    tracked1 = memacct.tracked_bytes()
    rss_growth = rss1 - rss0
    tracked_growth = tracked1 - tracked0
    if rss_growth <= NOISE_FLOOR_BYTES:
        ratio = 1.0
        note = ("steady-state RSS growth below the noise floor: every "
                "byte of growth is within allocator wobble, nothing "
                "untracked is accumulating")
    else:
        ratio = max(0.0, tracked_growth) / rss_growth
        note = "tracked cache growth over RSS growth"
    return {
        "rows": rows,
        "calls": calls,
        "rss_warm_mb": _mb(rss0),
        "rss_end_mb": _mb(rss1),
        "rss_growth_bytes": rss_growth,
        "tracked_warm_bytes": tracked0,
        "tracked_end_bytes": tracked1,
        "tracked_growth_bytes": tracked_growth,
        "noise_floor_bytes": NOISE_FLOOR_BYTES,
        "decomposition": round(ratio, 4),
        "decomposed_90pct": ratio >= 0.9,
        "note": note,
    }


def leg_churn(schemas: int, hot: int, hot_rows: int, churn_rows: int,
              high_water_mb: int, max_schemas: int) -> dict:
    """2k-schema churn around a hot working set under the high-water
    mark: RSS bounded, hot set warm."""
    os.environ["PYRUHVRO_TPU_NO_SPECIALIZE"] = "1"
    os.environ["PYRUHVRO_TPU_CACHE_MAX_SCHEMAS"] = str(max_schemas)
    import pyruhvro_tpu as p
    from pyruhvro_tpu.runtime import memacct, metrics
    from pyruhvro_tpu.schema import cache as scache
    from pyruhvro_tpu.utils.datagen import (
        random_datums,
        synthetic_schema_variant,
    )
    from pyruhvro_tpu.schema.parser import parse_schema

    rng = random.Random(42)
    hot_set = [synthetic_schema_variant(i) for i in range(hot)]
    hot_data = {
        s: random_datums(parse_schema(s), hot_rows, seed=i)
        for i, s in enumerate(hot_set)
    }
    for s in hot_set:  # prewarm the working set
        p.deserialize_array(hot_data[s], s, backend="host",
                            tenant="hot-tenant")
    gc.collect()
    base_rss = memacct.rss_bytes()
    high_water = base_rss + (high_water_mb << 20)
    os.environ["PYRUHVRO_TPU_MEM_HIGH_WATER"] = str(high_water)
    c0 = metrics.snapshot()
    hot_calls = hot_hits = 0
    max_rss = base_rss
    t0 = time.perf_counter()
    for i in range(hot, schemas):
        s = synthetic_schema_variant(i)
        data = random_datums(parse_schema(s), churn_rows, seed=i)
        p.deserialize_array(data, s, backend="host",
                            tenant=f"churn-{i % 8}")
        # interleaved hot traffic: the LRU must keep these resident
        hs = rng.choice(hot_set)
        hot_calls += 1
        if hs in scache._cache:
            hot_hits += 1
        p.deserialize_array(hot_data[hs], hs, backend="host",
                            tenant="hot-tenant")
        if i % 50 == 0:
            gc.collect()
            max_rss = max(max_rss, memacct.rss_bytes())
    gc.collect()
    max_rss = max(max_rss, memacct.rss_bytes())
    elapsed = time.perf_counter() - t0
    c1 = metrics.snapshot()

    def delta(key: str) -> float:
        return c1.get(key, 0.0) - c0.get(key, 0.0)

    warm_hit_rate = hot_hits / hot_calls if hot_calls else 0.0
    mem = memacct.snapshot_memory()
    for k in ("PYRUHVRO_TPU_MEM_HIGH_WATER", "PYRUHVRO_TPU_NO_SPECIALIZE",
              "PYRUHVRO_TPU_CACHE_MAX_SCHEMAS"):
        os.environ.pop(k, None)
    return {
        "schemas": schemas,
        "hot_set": hot,
        "hot_rows": hot_rows,
        "churn_rows": churn_rows,
        "max_schemas_cap": max_schemas,
        "elapsed_s": round(elapsed, 2),
        "base_rss_mb": _mb(base_rss),
        "high_water_mb_over_base": high_water_mb,
        "high_water_bytes": high_water,
        "max_rss_mb": _mb(max_rss),
        "rss_under_high_water": max_rss <= high_water,
        "warm_hit_rate": round(warm_hit_rate, 4),
        "warm_hit_95pct": warm_hit_rate >= 0.95,
        "live_schema_entries": len(scache._cache),
        "evictions": {
            "lru": delta("cache.evict.schema.lru"),
            "ttl": delta("cache.evict.schema.ttl"),
            "pressure": delta("cache.evict.schema.pressure"),
        },
        "pressure_events": delta("mem.pressure"),
        "schema_cache": {
            "hits": delta("schema_cache.hits"),
            "misses": delta("schema_cache.misses"),
            "evictions": delta("schema_cache.evictions"),
        },
        "memory_section": {
            "tracked_bytes": mem["tracked_bytes"],
            "caches": mem["caches"],
            "top_tenants": (mem.get("tenants") or [])[:4],
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--schemas", type=int, default=2000)
    ap.add_argument("--hot", type=int, default=64)
    ap.add_argument("--hot-rows", type=int, default=64)
    ap.add_argument("--churn-rows", type=int, default=32)
    ap.add_argument("--high-water-mb", type=int, default=256,
                    help="high-water mark ABOVE the post-prewarm "
                         "baseline RSS")
    ap.add_argument("--max-schemas", type=int, default=512,
                    help="schema-cache LRU cap during the churn leg "
                         "(sized so the hot working set survives the "
                         "churn between its own touches: with cap C "
                         "and hot H, a hot entry must be re-touched "
                         "within C-H churn admissions)")
    ap.add_argument("--decompose-rows", type=int, default=10_000)
    ap.add_argument("--decompose-calls", type=int, default=40)
    ap.add_argument("--skip-decompose", action="store_true")
    ap.add_argument("--skip-churn", action="store_true")
    ap.add_argument("--out", default="MEM_REPORT.json")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when a leg misses its criterion")
    args = ap.parse_args(argv)

    from pyruhvro_tpu.runtime import fsio, memacct

    report = {
        "generated_by": "scripts/mem_soak.py",
        "argv": sys.argv[1:],
        "cpus": os.cpu_count(),
        "baseline_rss_mb": _mb(memacct.rss_bytes()),
    }
    ok = True
    if not args.skip_decompose:
        leg = leg_decompose(args.decompose_rows, args.decompose_calls)
        report["decompose"] = leg
        ok = ok and leg["decomposed_90pct"]
        print(f"[mem_soak] decompose: rss growth "
              f"{leg['rss_growth_bytes']} B, tracked growth "
              f"{leg['tracked_growth_bytes']} B -> "
              f"{leg['decomposition']:.2%} "
              f"({'OK' if leg['decomposed_90pct'] else 'FAIL'})")
    if not args.skip_churn:
        leg = leg_churn(args.schemas, args.hot, args.hot_rows,
                        args.churn_rows, args.high_water_mb,
                        args.max_schemas)
        report["churn"] = leg
        ok = ok and leg["rss_under_high_water"] and leg["warm_hit_95pct"]
        print(f"[mem_soak] churn: {args.schemas} schemas in "
              f"{leg['elapsed_s']}s, max rss {leg['max_rss_mb']} MB "
              f"(high water base+{args.high_water_mb} MB: "
              f"{'under' if leg['rss_under_high_water'] else 'OVER'}), "
              f"warm-hit {leg['warm_hit_rate']:.2%} "
              f"({'OK' if leg['warm_hit_95pct'] else 'FAIL'}), "
              f"lru evictions {leg['evictions']['lru']:.0f}")
    report["pass"] = ok
    fsio.atomic_write_json(args.out, report, indent=1)
    print(f"[mem_soak] report -> {args.out}")
    if args.gate and not ok:
        print("[mem_soak] GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
