#!/usr/bin/env python
"""Fleet-aggregation smoke gate (ISSUE 16 acceptance).

Spawns THREE real replica processes. Each replica runs its own traffic
mix (decode / tolerant decode with corrupt rows / encode), freezes its
``telemetry.snapshot()``, and serves that frozen document from an
in-process obs server on a free port — frozen so every scrape of one
replica returns identical bytes, which is what makes the reconciliation
below exact rather than racy. Replica r0 additionally runs under a
deliberately-unmeetable SLO file, seeding a breach the merged fleet
view must surface.

The gate then:

* merges the three live endpoints via the real CLI
  (``python -m pyruhvro_tpu.telemetry fleet --scrape ...``);
* re-fetches each replica's snapshot directly and asserts every merged
  counter equals the left-fold sum of the per-replica values EXACTLY
  (``==`` on the floats — the merge is sum-in-input-order, so the gate
  reproduces the identical fold), histogram counts/buckets sum, and the
  ``fleet`` section names all three replicas;
* asserts the seeded r0 SLO breach appears (replica-tagged) in the
  merged snapshot and in ``telemetry slo-report`` over it;
* asserts the fleet/diff CLI exit-2 contract on unreachable targets and
  empty input.

Exit 0 = all assertions hold; any failure raises. Artifacts:
``FLEET_SNAPSHOT.json`` (the merged view) + ``fleet_report_smoke.txt``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- replica side -----------------------------------------------------------


def run_replica(index: int) -> None:
    """Traffic -> frozen snapshot -> static obs server; announce the
    port on stdout and hold until the parent closes stdin."""
    from pyruhvro_tpu.api import deserialize_array, serialize_record_batch
    from pyruhvro_tpu.runtime import obs_server, telemetry
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON as K,
        kafka_style_datums,
    )

    rows = 400 * (index + 1)  # distinct per replica: sums are non-trivial
    datums = kafka_style_datums(rows, seed=100 + index)
    batch = deserialize_array(datums, K, backend="host",
                              tenant=f"replica-{index}")
    serialize_record_batch(batch, K, 2, backend="host")
    # tolerant traffic: every replica quarantines a few corrupt rows so
    # the merged quarantine/error counters exercise the sum path
    bad = [d[:2] for d in datums[: 3 + index]]
    deserialize_array(bad, K, backend="host", on_error="skip")

    doc = telemetry.snapshot()
    srv = obs_server.ObsServer(port=0, snapshot=doc).start()
    print(f"PORT={srv.port}", flush=True)
    sys.stdin.readline()  # parent closes stdin -> exit
    srv.stop()


# -- parent side ------------------------------------------------------------


def _spawn_replicas(n: int, slo_file: str):
    procs = []
    for i in range(n):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if i == 0:
            # r0 runs under an unmeetable latency objective: the breach
            # must survive the merge, replica-tagged
            env["PYRUHVRO_TPU_SLO_FILE"] = slo_file
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--replica", str(i)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            text=True)
        procs.append(p)
    endpoints = []
    for i, p in enumerate(procs):
        line = p.stdout.readline().strip()
        assert line.startswith("PORT="), (i, line)
        endpoints.append(f"127.0.0.1:{line.split('=', 1)[1]}")
        _log(f"[fleet-smoke] replica r{i} up at {endpoints[-1]}")
    return procs, endpoints


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "FLEET_SNAPSHOT.json"))
    args = ap.parse_args()
    if args.replica is not None:
        run_replica(args.replica)
        return 0

    from pyruhvro_tpu.runtime import fleet, metrics
    from pyruhvro_tpu.runtime.telemetry import main as telemetry_cli

    from pyruhvro_tpu.runtime import fsio

    slo_file = os.path.join(tempfile.gettempdir(),
                            f"fleet_smoke_slo_{os.getpid()}.json")
    fsio.atomic_write_json(slo_file, {"version": 1, "objectives": [{
        "name": "decode-latency", "op": "decode", "schema": "*",
        "threshold_s": 1e-9,  # unmeetable: every call is "bad"
        "target": 0.99, "windows_s": [3600], "min_calls": 1,
    }]})

    procs, endpoints = _spawn_replicas(3, slo_file)
    try:
        # the real CLI over the three live endpoints
        rc = telemetry_cli(["fleet", "-o", args.out]
                           + [x for ep in endpoints
                              for x in ("--scrape", ep)])
        assert rc in (0, None), rc
        with open(args.out, encoding="utf-8") as f:
            merged = json.load(f)

        # the replicas serve FROZEN documents, so direct re-fetches see
        # the exact bytes the CLI scraped
        snaps = [fleet.fetch_snapshot(ep) for ep in endpoints]

        # 1) counters reconcile exactly: same left-fold float addition
        union = set()
        for s in snaps:
            union.update(s["counters"])
        assert set(merged["counters"]) == union, "counter key drift"
        for k in sorted(union):
            acc = 0.0
            for s in snaps:
                if k in s["counters"]:
                    acc += float(s["counters"][k])
            assert merged["counters"][k] == acc, (
                k, merged["counters"][k], acc)
        _log(f"[fleet-smoke] {len(union)} merged counters reconcile "
             f"exactly against per-replica sums")

        # 2) histograms: counts and per-bucket cumulative counts sum
        for k, h in merged["histograms"].items():
            per = [s["histograms"][k] for s in snaps
                   if k in s.get("histograms", {})]
            assert h["count"] == sum(p["count"] for p in per), k
        _log(f"[fleet-smoke] {len(merged['histograms'])} merged "
             f"histograms reconcile")

        # 3) gauge merge kinds: every merged gauge obeys its declared
        # sum-or-max fold
        for k, v in merged.get("gauges", {}).items():
            vals = [float(s["gauges"][k]) for s in snaps
                    if k in s.get("gauges", {})]
            if metrics.gauge_kind(k) == "max":
                assert v == max(vals), (k, v, vals)
            else:
                acc = 0.0
                for x in vals:
                    acc += x
                assert v == acc, (k, v, vals)

        # 4) fleet section: all three replicas named (scraped replicas
        # are tagged by their endpoint)
        assert merged["fleet"]["count"] == 3, merged["fleet"]
        tags = [r["tag"] for r in merged["fleet"]["replicas"]]
        assert tags == endpoints, (tags, endpoints)

        # 5) the seeded r0 breach survives the merge, replica-tagged
        r0 = f"[{endpoints[0]}] "
        breached = (merged.get("slo") or {}).get("breached") or []
        assert any(b.startswith(r0) for b in breached), breached
        report = os.path.join(REPO, "fleet_report_smoke.txt")
        # slo-report prints to stdout; capture via subprocess for the
        # artifact (the CLI contract under test is the rendering)
        out = subprocess.run(
            [sys.executable, "-m", "pyruhvro_tpu.telemetry",
             "slo-report", args.out],
            capture_output=True, text=True, check=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r0 + "decode-latency" in out.stdout, out.stdout
        with open(report, "w", encoding="utf-8") as f:
            f.write(out.stdout)
        _log("[fleet-smoke] r0 SLO breach visible in merged slo-report")

        # 6) report rendering over the merged view stays green
        out = subprocess.run(
            [sys.executable, "-m", "pyruhvro_tpu.telemetry",
             "report", args.out],
            capture_output=True, text=True, check=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert "phase breakdown" in out.stdout, out.stdout[:400]
    finally:
        for p in procs:
            try:
                p.stdin.close()
                p.wait(timeout=10)
            except Exception:
                p.kill()
        try:
            os.remove(slo_file)
        except OSError:
            pass

    # 7) exit-2 contract: unreachable scrape target, empty input
    assert telemetry_cli(["fleet", "--scrape", "127.0.0.1:1"]) == 2
    assert telemetry_cli(["fleet"]) == 2
    _log("[fleet-smoke] exit-2 contract holds")
    print(json.dumps({"metric": "fleet_smoke", "pass": True,
                      "replicas": 3,
                      "counters": len(merged["counters"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
