#!/usr/bin/env python
"""AOT-lower the Pallas walk kernel for the TPU target — no TPU needed.

``jax.export`` with ``platforms=["tpu"]`` runs the full
pallas→Mosaic-IR lowering pipeline on any host, which is exactly where
unsupported ops/layouts surface (VERDICT r04 #9: a lowering regression
must break CI, not a user's first run on real hardware). It does NOT
execute the kernel — the Mosaic→machine-code stage still happens on a
chip at XLA compile time — so this is a compilability guard, not a
perf check (``scripts/ab_pallas.py`` covers the live chip).

Beyond pass/fail, every covered (schema, BW, cap) shape's lowering
stats — wall seconds to lower, serialized MLIR byte size, and the
kernel-eligibility verdict — persist to ``PALLAS_LOWER_STATS.json``
(ISSUE 5), so lowering-time and module-size regressions are diffable
across rounds instead of vanishing into CI logs.

Run on CPU: ``PYTHONPATH= JAX_PLATFORMS=cpu python scripts/pallas_lower_check.py``
Exit 0 = every covered shape lowers; 1 = a lowering failure (printed).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_STATS = os.path.join(REPO, "PALLAS_LOWER_STATS.json")


def main(out_path: str = DEFAULT_STATS) -> int:
    import jax
    import numpy as np
    # jax.export is a lazily-importable submodule on some JAX versions
    # (plain `jax.export` raises AttributeError there)
    from jax import export as jax_export

    from pyruhvro_tpu.ops import UnsupportedOnDevice
    from pyruhvro_tpu.ops.pallas_decode import PallasKernelDecoder
    from pyruhvro_tpu.schema.parser import parse_schema
    from pyruhvro_tpu.utils.datagen import CRITERION_SHAPES, KAFKA_SCHEMA_JSON

    shapes = dict(CRITERION_SHAPES)
    shapes["kafka"] = KAFKA_SCHEMA_JSON
    failures = 0
    stats = []
    for name, schema in sorted(shapes.items()):
        try:
            dec = PallasKernelDecoder(parse_schema(schema), interpret=False)
        except UnsupportedOnDevice as e:
            print(f"{name:22s} SKIP (outside kernel subset): {e}")
            stats.append({"schema": name, "kernel_eligible": False,
                          "reason": str(e)})
            continue
        has_items = dec.n_regions > 1
        for BW, cap in [(16, 8), (64, 8)] + ([(16, 128)] if has_items
                                             else []):
            caps = tuple(0 if r == 0 else cap
                         for r in range(dec.n_regions))
            tile_r = dec._tile_rows(BW, caps)
            row = {"schema": name, "BW": BW, "cap": cap,
                   "tile_r": tile_r, "kernel_eligible": True}
            if tile_r < 128:
                print(f"{name:22s} BW={BW:3d} cap={cap} SKIP "
                      f"(tile cannot fit VMEM — runtime falls back)")
                row.update(kernel_eligible=False, reason="vmem_budget")
                stats.append(row)
                continue
            grid_r = 1
            fn = dec._build(grid_r, tile_r, BW, caps)
            R = grid_r * tile_r
            args = (
                np.zeros((R, BW), np.uint32),
                np.zeros(R, np.int32),
                np.zeros(R, np.int32),
            )
            try:
                t0 = time.perf_counter()
                exp = jax_export.export(fn, platforms=["tpu"])(*args)
                row["lower_s"] = round(time.perf_counter() - t0, 4)
                row["mlir_bytes"] = len(exp.mlir_module_serialized)
                print(f"{name:22s} BW={BW:3d} cap={cap:3d} "
                      f"tile_r={tile_r:4d} "
                      f"lowered ({row['mlir_bytes']} B mlir, "
                      f"{row['lower_s'] * 1e3:.0f} ms)")
            except Exception as e:  # noqa: BLE001 — the guard's output
                print(f"{name:22s} BW={BW:3d} cap={cap:3d} "
                      f"LOWERING FAILED: "
                      f"{type(e).__name__}: {str(e)[:300]}")
                row.update(kernel_eligible=False, lowering_failed=True,
                           error=f"{type(e).__name__}: {str(e)[:300]}")
                failures += 1
            stats.append(row)
    doc = {
        "note": "per-shape Pallas→Mosaic lowering stats "
                "(scripts/pallas_lower_check.py); lower_s is the "
                "jax.export wall time on the producing host, "
                "mlir_bytes the serialized module size.",
        "jax": jax.__version__,
        "failures": failures,
        "stats": stats,
    }
    try:
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"stats -> {out_path}")
    except OSError as e:
        print(f"could not write {out_path}: {e!r}")
    print(f"pallas lowering check: {failures} failures")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else DEFAULT_STATS))
