#!/usr/bin/env python
"""AOT-lower the Pallas walk kernel for the TPU target — no TPU needed.

``jax.export`` with ``platforms=["tpu"]`` runs the full
pallas→Mosaic-IR lowering pipeline on any host, which is exactly where
unsupported ops/layouts surface (VERDICT r04 #9: a lowering regression
must break CI, not a user's first run on real hardware). It does NOT
execute the kernel — the Mosaic→machine-code stage still happens on a
chip at XLA compile time — so this is a compilability guard, not a
perf check (``scripts/ab_pallas.py`` covers the live chip).

Run on CPU: ``PYTHONPATH= JAX_PLATFORMS=cpu python scripts/pallas_lower_check.py``
Exit 0 = every covered shape lowers; 1 = a lowering failure (printed).
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")


def main() -> int:
    import jax
    import numpy as np

    from pyruhvro_tpu.ops import UnsupportedOnDevice
    from pyruhvro_tpu.ops.pallas_decode import PallasKernelDecoder
    from pyruhvro_tpu.schema.parser import parse_schema
    from pyruhvro_tpu.utils.datagen import CRITERION_SHAPES, KAFKA_SCHEMA_JSON

    shapes = dict(CRITERION_SHAPES)
    shapes["kafka"] = KAFKA_SCHEMA_JSON
    failures = 0
    for name, schema in sorted(shapes.items()):
        try:
            dec = PallasKernelDecoder(parse_schema(schema), interpret=False)
        except UnsupportedOnDevice as e:
            print(f"{name:22s} SKIP (outside kernel subset): {e}")
            continue
        has_items = dec.n_regions > 1
        for BW, cap in [(16, 8), (64, 8)] + ([(16, 128)] if has_items
                                             else []):
            caps = tuple(0 if r == 0 else cap
                         for r in range(dec.n_regions))
            tile_r = dec._tile_rows(BW, caps)
            if tile_r < 128:
                print(f"{name:22s} BW={BW:3d} cap={cap} SKIP "
                      f"(tile cannot fit VMEM — runtime falls back)")
                continue
            grid_r = 1
            fn = dec._build(grid_r, tile_r, BW, caps)
            R = grid_r * tile_r
            args = (
                np.zeros((R, BW), np.uint32),
                np.zeros(R, np.int32),
                np.zeros(R, np.int32),
            )
            try:
                exp = jax.export.export(fn, platforms=["tpu"])(*args)
                print(f"{name:22s} BW={BW:3d} cap={cap:3d} "
                      f"tile_r={tile_r:4d} "
                      f"lowered ({len(exp.mlir_module_serialized)} B mlir)")
            except Exception as e:  # noqa: BLE001 — the guard's output
                print(f"{name:22s} BW={BW:3d} cap={cap:3d} "
                      f"LOWERING FAILED: "
                      f"{type(e).__name__}: {str(e)[:300]}")
                failures += 1
    print(f"pallas lowering check: {failures} failures")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
