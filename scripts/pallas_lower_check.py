#!/usr/bin/env python
"""AOT-lower the Pallas walk kernel for the TPU target — no TPU needed.

``jax.export`` with ``platforms=["tpu"]`` runs the full
pallas→Mosaic-IR lowering pipeline on any host, which is exactly where
unsupported ops/layouts surface (VERDICT r04 #9: a lowering regression
must break CI, not a user's first run on real hardware). It does NOT
execute the kernel — the Mosaic→machine-code stage still happens on a
chip at XLA compile time — so this is a compilability guard, not a
perf check (``scripts/ab_pallas.py`` covers the live chip).

Beyond pass/fail, every covered (schema, BW, cap) shape's lowering
stats — wall seconds to lower, serialized MLIR byte size, and the
kernel-eligibility verdict — persist to ``PALLAS_LOWER_STATS.json``
(ISSUE 5), so lowering-time and module-size regressions are diffable
across rounds instead of vanishing into CI logs.

Run on CPU: ``PYTHONPATH= JAX_PLATFORMS=cpu python scripts/pallas_lower_check.py``
Exit 0 = every covered shape lowers; 1 = a lowering failure (printed).

``--gate`` (ISSUE 10): additionally diff the fresh stats against the
COMMITTED ``PALLAS_LOWER_STATS.json`` and fail on any *regression* —
a shape that lowered at the baseline and fails now, or a shape that
was kernel-eligible and no longer is. New shapes and new failures of
shapes the baseline already recorded as failing do not re-fail the
gate (the absolute failure count still does, via the base exit code);
fixing failures only improves the diff. The fresh stats are written
next to the baseline ONLY when the gate passes, so a red run never
overwrites the evidence it was judged against.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

from pyruhvro_tpu.runtime import fsio  # noqa: E402  (after sys.path)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_STATS = os.path.join(REPO, "PALLAS_LOWER_STATS.json")


def _shape_key(row: dict) -> tuple:
    return (row.get("schema"), row.get("BW"), row.get("cap"))


def gate(fresh: dict, baseline_path: str = DEFAULT_STATS) -> int:
    """Compare ``fresh`` stats against the committed baseline; return
    the number of regressions (0 = gate passes). A missing/corrupt
    baseline is a pass-with-warning — the first run seeds it."""
    try:
        with open(baseline_path, encoding="utf-8") as f:
            base = json.load(f)
    except (OSError, ValueError) as e:
        print(f"[gate] no usable baseline at {baseline_path} ({e!r}); "
              f"fresh stats become the baseline")
        return 0
    base_rows = {_shape_key(r): r for r in base.get("stats", [])}
    regressions = 0
    for row in fresh.get("stats", []):
        b = base_rows.get(_shape_key(row))
        if b is None:
            continue  # newly covered shape: judged by the absolute check
        if row.get("lowering_failed") and not b.get("lowering_failed"):
            print(f"[gate] REGRESSION: {row['schema']} BW={row.get('BW')} "
                  f"cap={row.get('cap')} lowered at the baseline, now "
                  f"fails: {str(row.get('error', ''))[:200]}")
            regressions += 1
        elif (b.get("kernel_eligible") and not row.get("kernel_eligible")
              and not row.get("lowering_failed")):
            print(f"[gate] REGRESSION: {row['schema']} BW={row.get('BW')} "
                  f"cap={row.get('cap')} lost kernel eligibility "
                  f"({row.get('reason', 'unspecified')})")
            regressions += 1
    if regressions:
        print(f"[gate] {regressions} lowering regression(s) vs "
              f"{baseline_path}")
    else:
        print(f"[gate] no lowering regressions vs {baseline_path} "
              f"({len(base_rows)} baseline shapes)")
    return regressions


def main(out_path: str = DEFAULT_STATS, gate_mode: bool = False) -> int:
    import jax
    import numpy as np
    # jax.export is a lazily-importable submodule on some JAX versions
    # (plain `jax.export` raises AttributeError there)
    from jax import export as jax_export

    from pyruhvro_tpu.ops import UnsupportedOnDevice
    from pyruhvro_tpu.ops.pallas_decode import PallasKernelDecoder
    from pyruhvro_tpu.schema.parser import parse_schema
    from pyruhvro_tpu.utils.datagen import CRITERION_SHAPES, KAFKA_SCHEMA_JSON

    shapes = dict(CRITERION_SHAPES)
    shapes["kafka"] = KAFKA_SCHEMA_JSON
    failures = 0
    stats = []
    for name, schema in sorted(shapes.items()):
        try:
            dec = PallasKernelDecoder(parse_schema(schema), interpret=False)
        except UnsupportedOnDevice as e:
            print(f"{name:22s} SKIP (outside kernel subset): {e}")
            stats.append({"schema": name, "kernel_eligible": False,
                          "reason": str(e)})
            continue
        has_items = dec.n_regions > 1
        for BW, cap in [(16, 8), (64, 8)] + ([(16, 128)] if has_items
                                             else []):
            caps = tuple(0 if r == 0 else cap
                         for r in range(dec.n_regions))
            tile_r = dec._tile_rows(BW, caps)
            row = {"schema": name, "BW": BW, "cap": cap,
                   "tile_r": tile_r, "kernel_eligible": True}
            if tile_r < 128:
                print(f"{name:22s} BW={BW:3d} cap={cap} SKIP "
                      f"(tile cannot fit VMEM — runtime falls back)")
                row.update(kernel_eligible=False, reason="vmem_budget")
                stats.append(row)
                continue
            grid_r = 1
            fn = dec._build(grid_r, tile_r, BW, caps)
            R = grid_r * tile_r
            args = (
                np.zeros((R, BW), np.uint32),
                np.zeros(R, np.int32),
                np.zeros(R, np.int32),
            )
            try:
                t0 = time.perf_counter()
                exp = jax_export.export(fn, platforms=["tpu"])(*args)
                row["lower_s"] = round(time.perf_counter() - t0, 4)
                row["mlir_bytes"] = len(exp.mlir_module_serialized)
                print(f"{name:22s} BW={BW:3d} cap={cap:3d} "
                      f"tile_r={tile_r:4d} "
                      f"lowered ({row['mlir_bytes']} B mlir, "
                      f"{row['lower_s'] * 1e3:.0f} ms)")
            except Exception as e:  # noqa: BLE001 — the guard's output
                print(f"{name:22s} BW={BW:3d} cap={cap:3d} "
                      f"LOWERING FAILED: "
                      f"{type(e).__name__}: {str(e)[:300]}")
                row.update(kernel_eligible=False, lowering_failed=True,
                           error=f"{type(e).__name__}: {str(e)[:300]}")
                failures += 1
            stats.append(row)
    doc = {
        "note": "per-shape Pallas→Mosaic lowering stats "
                "(scripts/pallas_lower_check.py); lower_s is the "
                "jax.export wall time on the producing host, "
                "mlir_bytes the serialized module size.",
        "jax": jax.__version__,
        "failures": failures,
        "stats": stats,
    }
    regressions = gate(doc, out_path) if gate_mode else 0
    # gate mode never overwrites the judged-against baseline on ANY red
    # run — regressions OR absolute failures (a failing newly-covered
    # shape must not become tomorrow's expected baseline)
    if not (gate_mode and (regressions or failures)):
        try:
            fsio.atomic_write_json(out_path, doc, indent=1)
            print(f"stats -> {out_path}")
        except OSError as e:
            print(f"could not write {out_path}: {e!r}")
    print(f"pallas lowering check: {failures} failures"
          + (f", {regressions} regression(s)" if gate_mode else ""))
    return 1 if (failures or regressions) else 0


if __name__ == "__main__":
    argv = [a for a in sys.argv[1:] if a != "--gate"]
    sys.exit(main(argv[0] if argv else DEFAULT_STATS,
                  gate_mode="--gate" in sys.argv[1:]))
