#!/usr/bin/env python
"""Measured fastavro head-to-head (VERDICT r04 #6).

≙ the reference's sweep (/root/reference/scripts/benchmark_sweep.py:
{500, 5k, 50k} rows × {1, 2, 4, 8, 16} chunks, pyruhvro vs fastavro).
fastavro is not in the bench image, so this runs where it IS installed
(the CI job pip-installs it) and writes FASTAVRO_SWEEP.json with
MEASURED ratios — replacing the arithmetic stand-in of earlier rounds.

Run: PYTHONPATH= JAX_PLATFORMS=cpu python scripts/fastavro_sweep.py
"""

from __future__ import annotations

import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pyruhvro_tpu.runtime import fsio  # noqa: E402  (after sys.path)


def _best(fn, reps=3):
    fn()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    import fastavro

    from pyruhvro_tpu import deserialize_array_threaded
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, kafka_style_datums

    parsed = fastavro.parse_schema(json.loads(KAFKA_SCHEMA_JSON))
    out = {"cells": []}
    for rows in (500, 5_000, 50_000):
        datums = kafka_style_datums(rows, seed=7)

        t_fa = _best(lambda: [
            fastavro.schemaless_reader(io.BytesIO(d), parsed) for d in datums
        ])
        for chunks in (1, 2, 4, 8, 16):
            t_us = _best(lambda: deserialize_array_threaded(
                datums, KAFKA_SCHEMA_JSON, chunks
            ))
            cell = {
                "rows": rows, "chunks": chunks,
                "ours_rec_s": round(rows / t_us, 1),
                "fastavro_rec_s": round(rows / t_fa, 1),
                "speedup": round(t_fa / t_us, 2),
            }
            out["cells"].append(cell)
            print(f"rows={rows} chunks={chunks}: ours {rows/t_us:,.0f} "
                  f"vs fastavro {rows/t_fa:,.0f} rec/s "
                  f"({t_fa/t_us:.1f}x)", file=sys.stderr)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "FASTAVRO_SWEEP.json")
    fsio.atomic_write_json(path, out, indent=2)
    print(json.dumps({"cells": len(out["cells"]),
                      "min_speedup": min(c["speedup"] for c in out["cells"]),
                      "max_speedup": max(c["speedup"] for c in out["cells"])}))


if __name__ == "__main__":
    main()
