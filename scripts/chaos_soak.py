#!/usr/bin/env python
"""Chaos soak: the fault matrix of tests/test_chaos.py scaled up and
run as a standalone gate for the slow CI perf-artifacts job.

Drives every (fault site x kind) cell through the public API under
every on_error policy, with and without a per-call deadline, and
asserts the ISSUE 8 invariants per cell:

  * never a hang — the whole run sits under a faulthandler watchdog
    and every bounded cell must return inside its budget + slack;
  * never an interpreter crash — a fault either degrades or raises;
  * correct output via a degraded path (byte-equal to the healthy
    reference) or a structured error (FaultInjected / DeadlineExceeded
    / MalformedAvro) — never silent corruption;
  * recovery — after the spec clears, every breaker-owned seam
    (native_extract, device_backend, process_pool) re-admits its arm
    via the half-open probe.

Each cell appends a record to the chaos ledger
(``CHAOS_LEDGER.json``, atomic write) so CI uploads a replayable
artifact: the spec string alone reproduces any cell (injection is
counter-based, not random).

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_soak.py [--rounds N]
        [--out CHAOS_LEDGER.json] [--skip-pool]

Exit 1 on any invariant violation.
"""

from __future__ import annotations

import argparse
import faulthandler
import json
import os
import sys
import time
import traceback

sys.path.append(".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# a soak must see its own chaos clearly: short hangs, fast breakers
os.environ.setdefault("PYRUHVRO_TPU_FAULT_HANG_S", "0.4")
os.environ.setdefault("PYRUHVRO_TPU_BREAKER_BACKOFF", "0.1")

WATCHDOG_S = 300  # any wedged cell dumps all stacks and kills the run

DEV_SCHEMA = json.dumps({
    "type": "record", "name": "ChaosSoak",
    "fields": [
        {"name": "a", "type": "long"},
        {"name": "b", "type": "string"},
    ],
})


def _spec(site: str, kind: str, rate: float = 1.0) -> str:
    return f"{site}:{kind}:{rate:g}"


class Cell:
    """One matrix cell: run `fn` under `spec`, classify the outcome."""

    def __init__(self, ledger, site, kind, op, policy, deadline_s=None):
        self.ledger = ledger
        self.rec = {
            "site": site, "kind": kind, "op": op, "policy": policy,
            "deadline_s": deadline_s,
            "spec": _spec(site, kind),
        }

    def run(self, fn, check=None) -> bool:
        from pyruhvro_tpu.fallback.io import MalformedAvro
        from pyruhvro_tpu.runtime import faults, metrics
        from pyruhvro_tpu.runtime.deadline import DeadlineExceeded
        from pyruhvro_tpu.runtime.faults import FaultInjected

        faults.reset()
        os.environ["PYRUHVRO_TPU_FAULTS"] = self.rec["spec"]
        budget = self.rec["deadline_s"]
        t0 = time.monotonic()
        ok, outcome, err = True, None, None
        try:
            out = fn()
            outcome = "degraded_ok"
            if check is not None and not check(out):
                ok, outcome = False, "WRONG_OUTPUT"
        except (FaultInjected, DeadlineExceeded, MalformedAvro) as e:
            outcome = "structured_error"
            err = type(e).__name__
            if isinstance(e, DeadlineExceeded) and budget is None:
                ok, outcome = False, "UNEXPECTED_DEADLINE"
        except Exception as e:  # noqa: BLE001 — the invariant breaker
            ok, outcome, err = False, "UNSTRUCTURED_ERROR", repr(e)
            traceback.print_exc()
        finally:
            os.environ["PYRUHVRO_TPU_FAULTS"] = ""
        dt = time.monotonic() - t0
        # the no-hang invariant, per cell: a bounded call must return
        # within budget + hang + generous slack
        if budget is not None and dt > budget + 1.0 + 10.0:
            ok, outcome = False, "OVERRAN_BUDGET"
        self.rec.update({
            "outcome": outcome, "error": err, "wall_s": round(dt, 4),
            "injected": metrics.snapshot().get(
                "fault.injected." + self.rec["site"], 0.0),
            "pass": ok,
        })
        self.ledger.append(self.rec)
        tag = "ok" if ok else "FAIL"
        print(f"[{tag}] {self.rec['site']}:{self.rec['kind']} "
              f"op={self.rec['op']} policy={self.rec['policy']} "
              f"dl={budget} -> {outcome} ({dt:.2f}s)", flush=True)
        return ok


def _recover(name: str) -> bool:
    """After the spec cleared: the named breaker must re-admit its seam
    (closed already, or half-open and closable by the next probe)."""
    from pyruhvro_tpu.runtime import breaker

    br = breaker.get(name)
    deadline_at = time.monotonic() + 10.0
    while time.monotonic() < deadline_at:
        if br.state() in ("closed", "half_open"):
            return True
        time.sleep(0.05)
    print(f"[FAIL] breaker {name} stuck {br.state()} after fault cleared",
          flush=True)
    return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3,
                    help="matrix passes (default 3)")
    ap.add_argument("--out", default="CHAOS_LEDGER.json")
    ap.add_argument("--skip-pool", action="store_true",
                    help="skip the spawn-pool worker-death leg")
    args = ap.parse_args()

    faulthandler.dump_traceback_later(WATCHDOG_S, exit=True)

    import pyruhvro_tpu as p
    from pyruhvro_tpu.hostpath import native_available
    from pyruhvro_tpu.runtime import breaker, fsio, telemetry
    from pyruhvro_tpu.schema.cache import get_or_parse_schema
    from pyruhvro_tpu.utils.datagen import (
        KAFKA_SCHEMA_JSON,
        kafka_style_datums,
        random_datums,
    )

    data = kafka_style_datums(400, seed=11)
    bad = list(data)
    for i in (7, 123, 300):
        bad[i] = b"\xff\xff\xff"
    dev_data = random_datums(get_or_parse_schema(DEV_SCHEMA).ir, 64,
                             seed=11)
    ref = p.deserialize_array(data, KAFKA_SCHEMA_JSON, backend="host")
    ref_skip = p.deserialize_array(bad, KAFKA_SCHEMA_JSON, backend="host",
                                   on_error="skip")
    dev_ref = p.deserialize_array(dev_data, DEV_SCHEMA, backend="host")
    [enc_ref] = p.serialize_record_batch(ref, KAFKA_SCHEMA_JSON, 1,
                                         backend="host")

    # shard_worker cells need the 400-row corpus to reach the one-call
    # native fan-out: drop the large-batch gate for the whole soak
    shard_seam = False
    if native_available():
        from pyruhvro_tpu.hostpath.codec import NativeHostCodec
        from pyruhvro_tpu.runtime.native.build import load_host_codec

        mod = load_host_codec()
        if mod is not None and hasattr(mod, "shard_stats"):
            NativeHostCodec._PER_CHUNK_ROWS = 64
            shard_seam = True

    ledger: list = []
    ok = True
    for rnd in range(args.rounds):
        print(f"--- round {rnd} ---", flush=True)
        telemetry.reset()
        for kind in ("error", "hang"):
            dl = 2.0 if kind == "hang" else None
            # native VM seam, every policy, decode + threaded decode
            for policy in ("raise", "skip", "null"):
                corpus, expect = (data, ref) if policy == "raise" \
                    else (bad, None)
                ok &= Cell(ledger, "vm_decode", kind, "decode", policy,
                           dl).run(
                    lambda c=corpus, po=policy, d=dl: p.deserialize_array(
                        c, KAFKA_SCHEMA_JSON, backend="host", on_error=po,
                        timeout_s=d),
                    check=(lambda out, e=expect: out.equals(e))
                    if expect is not None else
                    (lambda out: out.num_rows in (ref_skip.num_rows,
                                                  len(bad))))
            ok &= Cell(ledger, "vm_decode", kind, "decode_threaded",
                       "raise", dl).run(
                lambda d=dl: p.deserialize_array_threaded(
                    data, KAFKA_SCHEMA_JSON, 4, backend="host",
                    timeout_s=d),
                check=lambda out: sum(b.num_rows for b in out) == len(
                    data))
            # one-call native shard-runner seam (ISSUE 17): a struck
            # worker degrades the fan-out to the retained serial
            # per-chunk loop (rows identical); a hang stops at the
            # per-chunk deadline checkpoint; the native_shards breaker
            # must re-admit once the spec clears
            if shard_seam:
                ok &= Cell(ledger, "shard_worker", kind,
                           "decode_threaded", "raise", dl).run(
                    lambda d=dl: p.deserialize_array_threaded(
                        data, KAFKA_SCHEMA_JSON, 4, backend="host",
                        timeout_s=d),
                    check=lambda out: sum(
                        b.num_rows for b in out) == len(data))
                ok &= Cell(ledger, "shard_worker", kind,
                           "encode_threaded", "raise", dl).run(
                    lambda d=dl: p.serialize_record_batch(
                        ref, KAFKA_SCHEMA_JSON, 4, backend="host",
                        timeout_s=d),
                    check=lambda out: sum(len(a) for a in out) == len(
                        data))
                ok &= _recover("native_shards")
            # fused-extract encode seam
            ok &= Cell(ledger, "native_extract", kind, "encode", "raise",
                       dl).run(
                lambda d=dl: p.serialize_record_batch(
                    ref, KAFKA_SCHEMA_JSON, 1, backend="host",
                    timeout_s=d)[0],
                check=lambda out: out.equals(enc_ref))
            ok &= _recover("native_extract")
            # device seams degrade to host
            for site in ("device_compile", "device_launch", "h2d"):
                ok &= Cell(ledger, site, kind, "decode", "raise", dl).run(
                    lambda d=dl: p.deserialize_array(
                        dev_data, DEV_SCHEMA, backend="tpu", timeout_s=d),
                    check=lambda out: out.equals(dev_ref))
            ok &= _recover("device_backend")
        # persistence / observability seams: counted, never call-fatal
        from pyruhvro_tpu.runtime import costmodel

        prof = os.path.join(os.getcwd(), f"_chaos_prof_{os.getpid()}.json")
        try:
            ok &= Cell(ledger, "profile_save", "error", "save_profile",
                       "-").run(
                lambda: costmodel.save_profile(prof),
                check=lambda out: out is None)
            ok &= Cell(ledger, "profile_load", "error", "load_profile",
                       "-").run(
                lambda: costmodel.load_profile(prof),
                check=lambda out: out is False)
        finally:
            # save_profile leaves a flock sidecar next to the profile
            for leftover in (prof, prof + ".lock"):
                if os.path.exists(leftover):
                    os.remove(leftover)
        if native_available():
            ok &= Cell(ledger, "native_build", "error", "decode",
                       "raise").run(
                lambda: p.deserialize_array(data, KAFKA_SCHEMA_JSON,
                                            backend="host"),
                check=lambda out: out.equals(ref))
        ok &= _serve_leg(ledger)
        ok &= _incident_leg(ledger)

    if not args.skip_pool:
        ok &= _pool_leg(ledger)

    snap = {"breakers": breaker.snapshot_breakers()}
    doc = {
        "rounds": args.rounds,
        "cells": len(ledger),
        "failed": sum(1 for r in ledger if not r["pass"]),
        "breakers_final": snap["breakers"],
        "ledger": ledger,
    }
    fsio.atomic_write_json(args.out, doc)
    print(f"chaos soak: {len(ledger)} cells, {doc['failed']} failed "
          f"-> {args.out}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    return 0 if ok and not doc["failed"] else 1


def _serve_leg(ledger) -> bool:
    """Serving-plane cells (ISSUE 19): a crashing coalesced batch under
    shed policy and a WEDGED one under block policy. Both must drain to
    the per-request serial path with byte-identical output; the hang
    must be bounded by the batch stall watchdog, not the members'
    request budgets."""
    import pyruhvro_tpu as p
    from pyruhvro_tpu.runtime import breaker, faults
    from pyruhvro_tpu.serving import ServePlane
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, \
        kafka_style_datums

    corpora = [kafka_style_datums(8, seed=40 + i) for i in range(3)]
    refs = [p.deserialize_array(c, KAFKA_SCHEMA_JSON) for c in corpora]
    ok = True
    for kind, policy in (("error", "shed"), ("hang", "block")):
        faults.reset()
        breaker.reset()  # a tripped serve_worker from the error cell
        os.environ["PYRUHVRO_TPU_SERVE_POLICY"] = policy
        if kind == "hang":
            os.environ["PYRUHVRO_TPU_SERVE_BATCH_TIMEOUT_S"] = "0.05"

        def run_cell():
            plane = ServePlane(autostart=False)
            futs = [plane.submit("decode", c, KAFKA_SCHEMA_JSON,
                                 timeout_s=30.0) for c in corpora]
            plane.drain()
            return [f.result(timeout=0) for f in futs]

        try:
            ok &= Cell(ledger, "serve_worker", kind, "serve_decode",
                       policy, 30.0).run(
                run_cell,
                check=lambda out: all(b.equals(r) for b, r in
                                      zip(out, refs)))
        finally:
            os.environ.pop("PYRUHVRO_TPU_SERVE_POLICY", None)
            os.environ.pop("PYRUHVRO_TPU_SERVE_BATCH_TIMEOUT_S", None)
    ok &= _recover("serve_worker")
    return ok


def _incident_leg(ledger) -> bool:
    """Incident-bundle write seam (ISSUE 20): an injected error during
    the bundle write degrades to a counted ``incident.capture_failed``
    and the live decode alongside is untouched; a hang is bounded by
    the soak's FAULT_HANG_S and the (delayed) capture still lands."""
    import tempfile

    import pyruhvro_tpu as p
    from pyruhvro_tpu.runtime import metrics
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, \
        kafka_style_datums

    data = kafka_style_datums(64, seed=41)
    ref = p.deserialize_array(data, KAFKA_SCHEMA_JSON, backend="host")
    ok = True
    with tempfile.TemporaryDirectory() as d:
        os.environ["PYRUHVRO_TPU_INCIDENT_DIR"] = d
        try:
            for kind in ("error", "hang"):

                def run_cell():
                    from pyruhvro_tpu.runtime import incident

                    path = incident.capture_now("chaos_soak")
                    out = p.deserialize_array(data, KAFKA_SCHEMA_JSON,
                                              backend="host")
                    return path, out

                def check(pair, k=kind):
                    path, out = pair
                    if not out.equals(ref):  # the live call, unaffected
                        return False
                    if k == "error":
                        return (path is None and metrics.snapshot().get(
                            "incident.capture_failed", 0) >= 1)
                    return path is not None and os.path.exists(path)

                ok &= Cell(ledger, "incident_capture", kind,
                           "incident_bundle", "-",
                           2.0 if kind == "hang" else None).run(
                    run_cell, check=check)
        finally:
            os.environ.pop("PYRUHVRO_TPU_INCIDENT_DIR", None)
    return ok


def _pool_leg(ledger) -> bool:
    """Worker-death leg: a spawn worker dies mid-fan-out (kind=exit),
    the call degrades to threads, the process_pool breaker opens, and
    after backoff the half-open probe re-admits real fan-outs."""
    import pyruhvro_tpu as p
    from pyruhvro_tpu.runtime import breaker, metrics, telemetry
    from pyruhvro_tpu.utils.datagen import KAFKA_SCHEMA_JSON, \
        kafka_style_datums

    os.environ["PYRUHVRO_TPU_POOL"] = "process"
    data = kafka_style_datums(200, seed=13)
    telemetry.reset()
    breaker.reset()
    rec = {"site": "pool_worker", "kind": "exit", "op": "decode_threaded",
           "policy": "raise", "spec": "pool_worker:exit:1"}
    ok = True
    try:
        os.environ["PYRUHVRO_TPU_FAULTS"] = "pool_worker:exit:1"
        out = p.deserialize_array_threaded(data, KAFKA_SCHEMA_JSON, 2,
                                           backend="host")
        assert sum(b.num_rows for b in out) == len(data)
        assert breaker.get("process_pool").state() == "open", \
            breaker.get("process_pool").state()
        os.environ["PYRUHVRO_TPU_FAULTS"] = ""
        time.sleep(0.3)  # backoff expires -> half-open
        out = p.deserialize_array_threaded(data, KAFKA_SCHEMA_JSON, 2,
                                           backend="host")
        assert sum(b.num_rows for b in out) == len(data)
        assert breaker.get("process_pool").state() == "closed", \
            breaker.get("process_pool").state()
        assert metrics.snapshot().get("pool.proc_chunks", 0) >= 2
        rec.update({"outcome": "recovered", "pass": True})
        print("[ok] pool_worker:exit -> degrade -> breaker reopen cycle",
              flush=True)
    except Exception as e:  # noqa: BLE001 — the invariant breaker
        traceback.print_exc()
        rec.update({"outcome": "FAILED", "error": repr(e), "pass": False})
        ok = False
    finally:
        os.environ["PYRUHVRO_TPU_FAULTS"] = ""
        os.environ.pop("PYRUHVRO_TPU_POOL", None)
    ledger.append(rec)
    return ok


if __name__ == "__main__":
    sys.exit(main())
