"""Cross-language opcode contract checker.

The host fast path keeps ONE logical contract — opcode kinds, column
types, error bits, per-op aux shapes, profiler slot names — in four
hand-synchronized places:

* ``hostpath/program.py`` — the Python constants the lowering emits;
* ``runtime/native/host_vm_core.h`` — the C++ ``OpKind`` / ``ColType``
  / ``Err`` enums the VM dispatches on, plus the profiler's pseudo-op
  slots (``P_COLLECT`` / ``P_MERGE`` / ``N_SLOT``) and the
  ``kSlotName`` / ``kDomPrefix`` telemetry string tables;
* ``runtime/native/extract_core.h`` — the ``AuxLane`` enum and the
  aux-tuple tag parser both native extraction walks consume;
* ``hostpath/specialize.py`` — the generated translation units' embedded
  ``kOps`` / ``kAux`` static tables.

Nothing but the differential suite stood between a silent drift and a
miscompiled engine. This pass parses each surface INDEPENDENTLY — the
Python side via ``ast`` (no import), the C++ side via comment-stripped
regex over the enum bodies — and fails on any divergence in value,
arity, aux kind, or op-name string. A final generative check lowers a
representative all-op-kinds schema and diffs the specializer's emitted
tables against the program they embed.

Every checker takes the repo root as a parameter so the test suite can
run them against fixture copies with seeded drift.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional

from . import Finding

__all__ = ["check_contracts", "EXPECTED_AUX_TAGS"]

# program.py aux tags <-> extract_core.h AuxLane members. The tag
# strings are the wire format of the contract (the C++ parser strcmp's
# them; the specializer's codegen switches on them).
EXPECTED_AUX_TAGS = {
    "uuid": "AUX_UUID",
    "binary": "AUX_BINARY",
    "duration": "AUX_DURATION",
    "decimal": "AUX_DECIMAL",
    "enum": "AUX_ENUM",
}

# C++ snprintf buffer for a drain key in host_vm_core.h prof::drain_py
_DRAIN_KEY_BUF = 48


# ---------------------------------------------------------------------------
# Python-side parsing (AST, no import)
# ---------------------------------------------------------------------------


def _const_eval(node: ast.AST) -> Optional[int]:
    """Evaluate the tiny constant-expression subset the contract files
    use: int literals, ``1 << n``, ``a + b``, ``-a``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_eval(node.operand)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        a, b = _const_eval(node.left), _const_eval(node.right)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.LShift):
            return a << b
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
    return None


def parse_py_constants(path: str, prefix: str) -> Dict[str, int]:
    """``NAME = <const>`` and ``A, B, ... = v0, v1, ...`` /
    ``= range(n)`` assignments whose names start with ``prefix``."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        if isinstance(tgt, ast.Name) and tgt.id.startswith(prefix):
            v = _const_eval(val)
            if v is not None:
                out[tgt.id] = v
        elif isinstance(tgt, ast.Tuple) and all(
            isinstance(e, ast.Name) for e in tgt.elts
        ):
            names = [e.id for e in tgt.elts]
            if not any(n.startswith(prefix) for n in names):
                continue
            values: Optional[List[int]] = None
            if isinstance(val, ast.Tuple):
                vs = [_const_eval(e) for e in val.elts]
                if None not in vs and len(vs) == len(names):
                    values = vs  # type: ignore[assignment]
            elif (isinstance(val, ast.Call)
                  and isinstance(val.func, ast.Name)
                  and val.func.id == "range"
                  and len(val.args) == 1):
                n = _const_eval(val.args[0])
                if n is not None and n == len(names):
                    values = list(range(n))
            if values is not None:
                for n2, v2 in zip(names, values):
                    if n2.startswith(prefix):
                        out[n2] = v2
    return out


def parse_err_mappings(path: str) -> Dict[str, Dict[str, str]]:
    """The ``ERR_NAMES`` / ``ERR_SLUGS`` dict literals of
    ``ops/varint.py`` as ``{dict_name: {ERR_CONST: string}}`` — the
    Python exception wording (MalformedAvro messages) and the machine
    slugs (quarantine attribution) per error bit."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out: Dict[str, Dict[str, str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in ("ERR_NAMES", "ERR_SLUGS")
                and isinstance(node.value, ast.Dict)):
            continue
        m: Dict[str, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if (isinstance(k, ast.Name)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                m[k.id] = v.value
        out[node.targets[0].id] = m
    return out


def check_error_taxonomy(root: str) -> List[Finding]:
    """ISSUE 15 satellite: every C++ ``Err`` enum value must map to a
    Python exception path — an ``ERR_NAMES`` message (the MalformedAvro
    wording ``hostpath/codec.py`` raises) and an ``ERR_SLUGS`` slug
    (the quarantine channel's attribution) — and each slug must be
    exercised by at least one test (the quoted slug literal appears in
    ``tests/``). An error bit no test can produce is an error path
    nobody has ever seen work."""
    findings: List[Finding] = []
    vm_core_h = os.path.join(
        root, "pyruhvro_tpu/runtime/native/host_vm_core.h")
    varint_py = os.path.join(root, "pyruhvro_tpu/ops/varint.py")
    varint_rel = "pyruhvro_tpu/ops/varint.py"
    cpp_errs = parse_cpp_enum(vm_core_h, "Err")
    if not cpp_errs:
        return [Finding("contract.err-taxonomy",
                        "pyruhvro_tpu/runtime/native/host_vm_core.h",
                        "Err enum not parsed")]
    maps = parse_err_mappings(varint_py)
    names = maps.get("ERR_NAMES", {})
    slugs = maps.get("ERR_SLUGS", {})
    if not names or not slugs:
        return [Finding("contract.err-taxonomy", varint_rel,
                        "ERR_NAMES/ERR_SLUGS dicts not parsed")]
    test_dir = os.path.join(root, "tests")
    blob = ""
    try:
        for fn in sorted(os.listdir(test_dir)):
            if fn.endswith(".py"):
                with open(os.path.join(test_dir, fn),
                          encoding="utf-8") as f:
                    blob += f.read()
    except OSError:
        pass
    for cname in sorted(cpp_errs):
        if cname not in names:
            findings.append(Finding(
                "contract.err-taxonomy", varint_rel,
                f"C++ Err member {cname} has no ERR_NAMES message — "
                "the native VM can set a bit the Python raise path "
                "cannot word"))
        if cname not in slugs:
            findings.append(Finding(
                "contract.err-taxonomy", varint_rel,
                f"C++ Err member {cname} has no ERR_SLUGS slug — the "
                "quarantine channel cannot attribute it"))
            continue
        slug = slugs[cname]
        if (f'"{slug}"' not in blob) and (f"'{slug}'" not in blob):
            findings.append(Finding(
                "contract.err-taxonomy", "tests/",
                f"error code {cname} (slug {slug!r}) is exercised by "
                "no test — craft a wire input that trips it and assert "
                "MalformedAvro.err_name"))
    return findings


def parse_py_aux_tags(path: str) -> set:
    """The aux TAG strings ``hostpath/program.py`` emits: first elements
    of tuples assigned into ``self.aux[...]``."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    tags = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.targets[0], ast.Subscript)):
            continue
        tgt = node.targets[0].value
        if not (isinstance(tgt, ast.Attribute) and tgt.attr == "aux"):
            continue
        val = node.value
        # ("tag", ...) or ("tag",) + tuple(...)
        if isinstance(val, ast.BinOp):
            val = val.left
        if (isinstance(val, ast.Tuple) and val.elts
                and isinstance(val.elts[0], ast.Constant)
                and isinstance(val.elts[0].value, str)):
            tags.add(val.elts[0].value)
    return tags


# ---------------------------------------------------------------------------
# C++-side parsing (comment-stripped regex)
# ---------------------------------------------------------------------------


def _strip_cpp_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def _cpp_const_eval(expr: str) -> Optional[int]:
    expr = expr.strip()
    m = re.fullmatch(r"(-?\d+)\s*<<\s*(\d+)", expr)
    if m:
        return int(m.group(1)) << int(m.group(2))
    try:
        return int(expr, 0)
    except ValueError:
        return None


def parse_cpp_enum(path: str, enum_name: str) -> Dict[str, int]:
    """Members of ``enum <name> [: type] { ... };`` as name -> value.
    Implicit (unassigned) members continue from the previous value, like
    the compiler does."""
    with open(path, encoding="utf-8") as f:
        text = _strip_cpp_comments(f.read())
    m = re.search(
        r"enum\s+" + re.escape(enum_name) + r"\s*(?::\s*[\w:]+\s*)?\{(.*?)\}",
        text, flags=re.S,
    )
    if m is None:
        return {}
    return _parse_enum_body(m.group(1))


def parse_cpp_anon_enum_with(path: str, member: str) -> Dict[str, int]:
    """The anonymous ``enum : int { ... };`` that contains ``member``
    (the profiler's pseudo-slot block)."""
    with open(path, encoding="utf-8") as f:
        text = _strip_cpp_comments(f.read())
    for m in re.finditer(r"enum\s*(?::\s*[\w:]+\s*)?\{(.*?)\}", text,
                         flags=re.S):
        body = _parse_enum_body(m.group(1))
        if member in body:
            return body
    return {}


def _parse_enum_body(body: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    nxt = 0
    for ent in body.split(","):
        ent = ent.strip()
        if not ent:
            continue
        if "=" in ent:
            name, expr = ent.split("=", 1)
            v = _cpp_const_eval(expr)
            if v is None:
                continue
            out[name.strip()] = v
            nxt = v + 1
        elif re.fullmatch(r"\w+", ent):
            out[ent] = nxt
            nxt += 1
    return out


def parse_cpp_string_array(path: str, array_name: str) -> List[str]:
    """The quoted strings of ``<array_name>[...] = { "a", "b", ... };``."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    m = re.search(re.escape(array_name) + r"\s*\[[^\]]*\]\s*=\s*\{(.*?)\};",
                  text, flags=re.S)
    if m is None:
        return []
    return re.findall(r'"([^"]*)"', m.group(1))


def parse_cpp_strcmp_tags(path: str) -> set:
    """Aux tag strings the C++ parser compares against
    (``std::strcmp(t, "<tag>")`` in extract_core.h AuxTables::parse)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    return set(re.findall(r'strcmp\(t,\s*"(\w+)"\)', text))


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------


def _diff_enum(findings: List[Finding], rule: str, py: Dict[str, int],
               cpp: Dict[str, int], py_path: str, cpp_path: str,
               require_same_names: bool = True) -> None:
    """Shared value-diff: names present on both sides must agree; with
    ``require_same_names`` the name SETS must match too (else C++ may be
    a subset — e.g. ``Err`` lacks the device-only bit)."""
    if not py:
        findings.append(Finding(rule, py_path, "no constants parsed"))
        return
    if not cpp:
        findings.append(Finding(rule, cpp_path, "enum not found/parsed"))
        return
    for name in sorted(set(py) & set(cpp)):
        if py[name] != cpp[name]:
            findings.append(Finding(
                rule, cpp_path,
                f"{name}: C++ value {cpp[name]} != Python value "
                f"{py[name]} ({py_path})",
            ))
    missing_cpp = sorted(set(py) - set(cpp))
    extra_cpp = sorted(set(cpp) - set(py))
    if require_same_names and missing_cpp:
        findings.append(Finding(
            rule, cpp_path,
            f"missing members vs {py_path}: {', '.join(missing_cpp)}",
        ))
    if extra_cpp:
        findings.append(Finding(
            rule, cpp_path,
            f"members with no Python counterpart in {py_path}: "
            f"{', '.join(extra_cpp)}",
        ))


def check_contracts(root: str, generative: bool = True) -> List[Finding]:
    """Run every contract check against the tree at ``root``; returns
    findings (empty = contracts hold). ``generative=False`` skips the
    import-the-package specializer-table diff (fixture trees in tests
    are not importable packages)."""
    findings: List[Finding] = []
    program_py = os.path.join(root, "pyruhvro_tpu/hostpath/program.py")
    varint_py = os.path.join(root, "pyruhvro_tpu/ops/varint.py")
    codec_py = os.path.join(root, "pyruhvro_tpu/hostpath/codec.py")
    specialize_py = os.path.join(root, "pyruhvro_tpu/hostpath/specialize.py")
    vm_core_h = os.path.join(
        root, "pyruhvro_tpu/runtime/native/host_vm_core.h")
    extract_h = os.path.join(
        root, "pyruhvro_tpu/runtime/native/extract_core.h")
    arrow_h = os.path.join(
        root, "pyruhvro_tpu/runtime/native/arrow_decode_core.h")

    # -- 1. opcode kinds --------------------------------------------------
    py_ops = parse_py_constants(program_py, "OP_")
    cpp_ops = parse_cpp_enum(vm_core_h, "OpKind")
    _diff_enum(findings, "contract.opkind", py_ops, cpp_ops,
               "pyruhvro_tpu/hostpath/program.py",
               "pyruhvro_tpu/runtime/native/host_vm_core.h")

    # -- 2. column types --------------------------------------------------
    py_cols = parse_py_constants(program_py, "COL_")
    py_cols.pop("COL_NBUF", None)  # a dict of buffer counts, not a code
    cpp_cols = parse_cpp_enum(vm_core_h, "ColType")
    _diff_enum(findings, "contract.coltype", py_cols, cpp_cols,
               "pyruhvro_tpu/hostpath/program.py",
               "pyruhvro_tpu/runtime/native/host_vm_core.h")

    # -- 3. error bits (C++ may be a strict subset: ERR_ITEM_OVERFLOW is
    #       device-tier-only by design) -----------------------------------
    py_errs = {k: v for k, v in
               parse_py_constants(varint_py, "ERR_").items()
               if isinstance(v, int)}
    cpp_errs = parse_cpp_enum(vm_core_h, "Err")
    _diff_enum(findings, "contract.err", py_errs, cpp_errs,
               "pyruhvro_tpu/ops/varint.py",
               "pyruhvro_tpu/runtime/native/host_vm_core.h",
               require_same_names=False)

    # -- 4. profiler slots + op-name string table -------------------------
    slots = parse_cpp_anon_enum_with(vm_core_h, "P_COLLECT")
    slot_names = parse_cpp_string_array(vm_core_h, "kSlotName")
    vm_core_rel = "pyruhvro_tpu/runtime/native/host_vm_core.h"
    if not slots or not slot_names:
        findings.append(Finding(
            "contract.prof-slots", vm_core_rel,
            "profiler pseudo-slot enum or kSlotName table not parsed"))
    elif py_ops:
        n_ops = len(py_ops)
        if slots.get("P_COLLECT") != n_ops:
            findings.append(Finding(
                "contract.prof-slots", vm_core_rel,
                f"P_COLLECT = {slots.get('P_COLLECT')} but program.py "
                f"defines {n_ops} opcodes (pseudo-slots must start right "
                "after the real ones)"))
        if slots.get("P_MERGE") != slots.get("P_COLLECT", -2) + 1 \
                or slots.get("P_SHARD") != slots.get("P_MERGE", -2) + 1 \
                or slots.get("N_SLOT") != slots.get("P_SHARD", -2) + 1:
            findings.append(Finding(
                "contract.prof-slots", vm_core_rel,
                f"pseudo-slot layout drifted: {slots}"))
        if len(slot_names) != slots.get("N_SLOT"):
            findings.append(Finding(
                "contract.prof-slots", vm_core_rel,
                f"kSlotName has {len(slot_names)} entries, N_SLOT is "
                f"{slots.get('N_SLOT')}"))
        # slot i names opcode value i: OP_DEC_BYTES=14 -> "dec_bytes"
        by_value = {v: k for k, v in py_ops.items()}
        for i, nm in enumerate(slot_names[:n_ops]):
            expect = by_value.get(i, "?")[len("OP_"):].lower()
            if nm != expect:
                findings.append(Finding(
                    "contract.prof-slots", vm_core_rel,
                    f"kSlotName[{i}] is {nm!r}, expected {expect!r} "
                    f"(from {by_value.get(i)})"))
        if slot_names[len(py_ops):] != ["collect", "merge", "shard"]:
            findings.append(Finding(
                "contract.prof-slots", vm_core_rel,
                f"pseudo-slot names drifted: {slot_names[len(py_ops):]}"
                " != ['collect', 'merge', 'shard']"))

    # -- 5. drain-key prefixes: C++ kDomPrefix <-> the telemetry names
    #       hostpath/codec.py documents/consumes, and every full key must
    #       fit the C++ snprintf buffer ------------------------------------
    prefixes = parse_cpp_string_array(vm_core_h, "kDomPrefix")
    codec_rel = "pyruhvro_tpu/hostpath/codec.py"
    if not prefixes:
        findings.append(Finding("contract.drain-keys", vm_core_rel,
                                "kDomPrefix table not parsed"))
    else:
        with open(codec_py, encoding="utf-8") as f:
            codec_src = f.read()
        for p in prefixes:
            if p not in codec_src:
                findings.append(Finding(
                    "contract.drain-keys", codec_rel,
                    f"drain prefix {p!r} (kDomPrefix) is not mentioned "
                    "in hostpath/codec.py — the Python drain consumer "
                    "no longer documents every native domain"))
        for p in prefixes:
            for nm in slot_names:
                # + "_s" suffix the Python side appends for self-time
                if len(p) + len(nm) + len("_s") + 1 > _DRAIN_KEY_BUF:
                    findings.append(Finding(
                        "contract.drain-keys", vm_core_rel,
                        f"drain key {p + nm!r} + '_s' overflows the "
                        f"{_DRAIN_KEY_BUF}-byte snprintf buffer"))

    # -- 6. aux tags: program.py emits <-> extract_core.h parses <->
    #       specialize.py embeds <-> AuxLane enum --------------------------
    py_tags = parse_py_aux_tags(program_py)
    cpp_tags = parse_cpp_strcmp_tags(extract_h)
    aux_enum = parse_cpp_enum(extract_h, "AuxLane")
    extract_rel = "pyruhvro_tpu/runtime/native/extract_core.h"
    if py_tags != set(EXPECTED_AUX_TAGS):
        findings.append(Finding(
            "contract.aux-tags", "pyruhvro_tpu/hostpath/program.py",
            f"aux tags emitted by the lowering drifted: {sorted(py_tags)}"
            f" != {sorted(EXPECTED_AUX_TAGS)} (update EXPECTED_AUX_TAGS "
            "and every consumer together)"))
    missing_parse = py_tags - cpp_tags
    if missing_parse:
        findings.append(Finding(
            "contract.aux-tags", extract_rel,
            f"AuxTables::parse does not handle tag(s) "
            f"{sorted(missing_parse)} that program.py emits"))
    if not aux_enum:
        findings.append(Finding("contract.aux-tags", extract_rel,
                                "AuxLane enum not parsed"))
    else:
        for tag, lane in EXPECTED_AUX_TAGS.items():
            if lane not in aux_enum:
                findings.append(Finding(
                    "contract.aux-tags", extract_rel,
                    f"AuxLane lacks {lane} (tag {tag!r})"))
        # lanes named in specialize.py's codegen and in the fused decode
        # walk must exist in the enum
        for src_path, rel in ((specialize_py,
                               "pyruhvro_tpu/hostpath/specialize.py"),
                              (arrow_h,
                               "pyruhvro_tpu/runtime/native/"
                               "arrow_decode_core.h")):
            with open(src_path, encoding="utf-8") as f:
                used = set(re.findall(r"\b(AUX_\w+)\b", f.read()))
            unknown = used - set(aux_enum)
            if unknown:
                findings.append(Finding(
                    "contract.aux-tags", rel,
                    f"references unknown AuxLane member(s) "
                    f"{sorted(unknown)}"))

    # -- 7. error-taxonomy coverage (ISSUE 15) ----------------------------
    findings.extend(check_error_taxonomy(root))

    if generative:
        findings.extend(_check_specializer_tables())
    return findings


# ---------------------------------------------------------------------------
# generative check: the specializer's embedded kOps/kAux tables
# ---------------------------------------------------------------------------

# a schema that lowers to every opcode kind and every aux lane; if an op
# kind is ever added to program.py this check fails loudly until the
# schema below exercises it too
_ALL_OPS_SCHEMA = """
{"type": "record", "name": "AllOps", "fields": [
  {"name": "i",    "type": "int"},
  {"name": "l",    "type": "long"},
  {"name": "f",    "type": "float"},
  {"name": "d",    "type": "double"},
  {"name": "b",    "type": "boolean"},
  {"name": "s",    "type": "string"},
  {"name": "u",    "type": {"type": "string", "logicalType": "uuid"}},
  {"name": "by",   "type": "bytes"},
  {"name": "dec",  "type": {"type": "bytes", "logicalType": "decimal",
                            "precision": 10, "scale": 2}},
  {"name": "fx",   "type": {"type": "fixed", "name": "F8", "size": 8}},
  {"name": "dur",  "type": {"type": "fixed", "name": "Dur", "size": 12,
                            "logicalType": "duration"}},
  {"name": "decf", "type": {"type": "fixed", "name": "DF", "size": 16,
                            "logicalType": "decimal", "precision": 20,
                            "scale": 4}},
  {"name": "e",    "type": {"type": "enum", "name": "E",
                            "symbols": ["A", "B", "C"]}},
  {"name": "n",    "type": "null"},
  {"name": "opt",  "type": ["null", "int"]},
  {"name": "un",   "type": ["int", "string", "null"]},
  {"name": "arr",  "type": {"type": "array", "items": "int"}},
  {"name": "m",    "type": {"type": "map", "values": "string"}},
  {"name": "sub",  "type": {"type": "record", "name": "Sub", "fields":
                            [{"name": "x", "type": "int"}]}}
]}
"""

_LANE_FOR_TAG = {None: "AUX_NONE", "uuid": "AUX_UUID",
                 "binary": "AUX_BINARY", "duration": "AUX_DURATION",
                 "decimal": "AUX_DECIMAL", "enum": "AUX_ENUM"}


def _check_specializer_tables() -> List[Finding]:
    """Lower the all-ops schema, generate the specialized C++, and diff
    the embedded ``kOps`` / ``kAux`` static tables against the program
    they were generated from. Catches codegen drift the enum diffs
    cannot (a transposed field, a dropped aux lane, a stale arity)."""
    findings: List[Finding] = []
    rel = "pyruhvro_tpu/hostpath/specialize.py"
    from ..hostpath.program import lower_host
    from ..hostpath.specialize import generate_source
    from ..schema.parser import parse_schema

    prog = lower_host(parse_schema(_ALL_OPS_SCHEMA))
    kinds = {int(k) for k in prog.ops[:, 0]}
    # every LOWERING-emitted kind (OP_FIXED_RUN=16 is optimizer-only:
    # the specializer consumes raw programs and never sees it)
    expected_kinds = set(range(16))
    if kinds != expected_kinds:
        return [Finding(
            "contract.spec-tables", "pyruhvro_tpu/analysis/contracts.py",
            f"the representative schema no longer covers every opcode "
            f"kind (missing {sorted(expected_kinds - kinds)}) — extend "
            "_ALL_OPS_SCHEMA")]
    src = generate_source(prog, "M")

    m = re.search(r"static const Op kOps\[\] = \{(.*?)\};", src, flags=re.S)
    rows = re.findall(
        r"\{(-?\d+), (-?\d+), (-?\d+), (-?\d+), (-?\d+), 0\},",
        m.group(1) if m else "")
    if len(rows) != len(prog.ops):
        findings.append(Finding(
            "contract.spec-tables", rel,
            f"kOps has {len(rows)} rows, program has {len(prog.ops)}"))
    else:
        for i, row in enumerate(rows):
            want = tuple(int(x) for x in prog.ops[i][:5])
            got = tuple(int(x) for x in row)
            if got != want:
                findings.append(Finding(
                    "contract.spec-tables", rel,
                    f"kOps[{i}] = {got} but HostProgram.ops[{i}] = "
                    f"{want}"))

    m = re.search(r"static const OpAux kAux\[\] = \{(.*?)\};", src,
                  flags=re.S)
    entries = re.findall(r"\{(AUX_\w+), [^,]+, [^,]+, (\w+)\},",
                         m.group(1) if m else "")
    if len(entries) != len(prog.ops):
        findings.append(Finding(
            "contract.spec-tables", rel,
            f"kAux has {len(entries)} entries, program has "
            f"{len(prog.ops)} ops"))
    else:
        for i, (lane, last) in enumerate(entries):
            aux = prog.op_aux[i]
            tag = aux[0] if aux else None
            want_lane = _LANE_FOR_TAG.get(tag)
            if lane != want_lane:
                findings.append(Finding(
                    "contract.spec-tables", rel,
                    f"kAux[{i}] lane {lane} != {want_lane} (op_aux "
                    f"entry {aux!r})"))
                continue
            # arity payload: decimal carries precision, enum its symbol
            # count, in the shared nsyms field
            if tag == "decimal" and int(last) != int(aux[1]):
                findings.append(Finding(
                    "contract.spec-tables", rel,
                    f"kAux[{i}] decimal precision {last} != {aux[1]}"))
            if tag == "enum" and int(last) != len(aux) - 1:
                findings.append(Finding(
                    "contract.spec-tables", rel,
                    f"kAux[{i}] enum symbol count {last} != "
                    f"{len(aux) - 1}"))
    return findings
