"""AST invariant lints over the package source.

Four rules, each enforcing an invariant PR 7/8/11 previously left to
reviewer memory:

* ``lint.env-read`` — no direct ``os.environ`` / ``os.getenv`` read of
  a ``PYRUHVRO_*`` name outside ``runtime/knobs.py``: every knob goes
  through the typed registry (parse-with-fallback, documented,
  inventoried).
* ``lint.signal-safety`` — no ``metrics.inc``/``merge``/``mark``,
  ``faults.fire``, ``schedtest.yield_point``/``yp`` (ISSUE 14: a
  yield-point under an active harness parks the thread on a condition
  variable), blocking ``.acquire()`` or ``with <lock>:`` in code
  reachable (same-module call graph) from a function registered via
  ``signal.signal``: the handler may have interrupted the very frame
  that holds the non-reentrant lock. Counters bumped from signal
  context must use ``metrics.DeferredCount``. An audited construct can
  be waived with a ``# signal-ok: <reason>`` comment on the flagged
  line.
* ``lint.json-write`` — no whole-file ``json.dump`` outside
  ``runtime/fsio.py`` (a kill mid-dump leaves a torn artifact; writers
  go through ``fsio.atomic_write_json``). Dumping to
  ``sys.stdout``/``sys.stderr`` is a stream, not a file, and passes.
* ``lint.fault-seam`` — no bare ``except:`` anywhere, and every
  handler that swallows ``FaultInjected`` (the 12 chaos seams of
  ``runtime/faults.py``) must count a metric: a degradation that does
  not count is a degradation nobody will ever see.
* ``lint.metric-keys`` (ISSUE 15) — the telemetry key contract: every
  statically-extracted counter/gauge/mark/span key (plus the C++
  profiler drain keys) must appear in the generated README registry
  table, and every key-shaped token in README prose must name a key
  the code still emits (no dead documentation).

The analysis is deliberately path-INsensitive (a ``metrics.inc`` behind
``if counters:`` still flags) — that keeps it trivially sound, and the
``# signal-ok`` waiver documents the audited exceptions in place.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set

from . import Finding

__all__ = [
    "lint_env_reads",
    "lint_signal_safety",
    "lint_json_writes",
    "lint_fault_seams",
    "metric_key_registry",
    "render_metric_key_table",
    "lint_metric_keys",
    "run_lints",
    "iter_py_files",
]

_KNOB_PREFIX = "PYRUHVRO_"
_ENV_ALLOWED = ("runtime/knobs.py",)
_JSON_ALLOWED = ("runtime/fsio.py",)
_SIGNAL_WAIVER = "# signal-ok"

# calls that may take the non-reentrant metrics/telemetry locks —
# forbidden in signal-reachable code (DeferredCount.bump is the
# sanctioned counter there). schedtest yield-points (ISSUE 14) park
# the calling thread on a condition variable under an active harness,
# and faults.fire can sleep at a seam — a handler that reaches either
# can wedge the very frame it interrupted.
_UNSAFE_MODULE_CALLS = {
    ("metrics", "inc"), ("metrics", "merge"), ("metrics", "mark"),
    ("faults", "fire"),
    ("schedtest", "yield_point"), ("schedtest", "yp"),
}


def iter_py_files(root: str,
                  subdirs: Sequence[str] = ("pyruhvro_tpu",)) -> List[str]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            out.append(base)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("_spec", "__pycache__")]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root)
    except ValueError:
        return path


def _parse(path: str):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return ast.parse(src, filename=path), src.splitlines()


# ---------------------------------------------------------------------------
# lint.env-read
# ---------------------------------------------------------------------------


def _env_read_name(node: ast.AST) -> Optional[str]:
    """The literal env-var name when ``node`` reads the environment:
    ``os.environ.get(LIT, ...)``, ``os.getenv(LIT, ...)`` or
    ``os.environ[LIT]`` (Load context)."""
    if isinstance(node, ast.Call):
        f = node.func
        is_get = (isinstance(f, ast.Attribute) and f.attr == "get"
                  and isinstance(f.value, ast.Attribute)
                  and f.value.attr == "environ"
                  and isinstance(f.value.value, ast.Name)
                  and f.value.value.id == "os")
        is_getenv = (isinstance(f, ast.Attribute) and f.attr == "getenv"
                     and isinstance(f.value, ast.Name)
                     and f.value.id == "os")
        if (is_get or is_getenv) and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
    elif isinstance(node, ast.Subscript):
        v = node.value
        if (isinstance(node.ctx, ast.Load)
                and isinstance(v, ast.Attribute) and v.attr == "environ"
                and isinstance(v.value, ast.Name) and v.value.id == "os"):
            s = node.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                return s.value
    elif isinstance(node, ast.Compare):
        # '"NAME" in os.environ' membership tests read the environment
        # too (knobs.is_set is the sanctioned form)
        if (len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.left, ast.Constant)
                and isinstance(node.left.value, str)
                and len(node.comparators) == 1):
            c = node.comparators[0]
            if (isinstance(c, ast.Attribute) and c.attr == "environ"
                    and isinstance(c.value, ast.Name)
                    and c.value.id == "os"):
                return node.left.value
    return None


def lint_env_reads(files: Iterable[str], root: str = ".") -> List[Finding]:
    findings = []
    for path in files:
        rel = _rel(path, root)
        if rel.replace(os.sep, "/").endswith(_ENV_ALLOWED):
            continue
        tree, _ = _parse(path)
        for node in ast.walk(tree):
            name = _env_read_name(node)
            if name and name.startswith(_KNOB_PREFIX):
                findings.append(Finding(
                    "lint.env-read", rel,
                    f"direct environment read of {name!r} — go through "
                    "runtime/knobs.py (typed registry, counted parse "
                    "fallback)", node.lineno))
    return findings


# ---------------------------------------------------------------------------
# lint.signal-safety
# ---------------------------------------------------------------------------


def _collect_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """All function defs in the module, flattened by name (nested
    handlers included; later defs win, like runtime rebinding would)."""
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _called_names(fn: ast.FunctionDef) -> Set[str]:
    """Plain ``name(...)`` calls inside ``fn`` (same-module call graph
    edges; attribute calls are cross-module and judged directly)."""
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            names.add(node.func.id)
    return names


def _handler_names(tree: ast.AST) -> Set[str]:
    """Functions registered via ``signal.signal(<sig>, <fn>)``."""
    out = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "signal"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "signal"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Name)):
            out.add(node.args[1].id)
    return out


def _waived(lines: List[str], lineno: int) -> bool:
    """A ``# signal-ok: <reason>`` waiver on the flagged line or in the
    comment block immediately above it."""
    for ln in range(max(1, lineno - 2), min(lineno, len(lines)) + 1):
        if _SIGNAL_WAIVER in lines[ln - 1]:
            return True
    return False


def _unsafe_in_function(fn: ast.FunctionDef, rel: str,
                        lines: List[str]) -> List[Finding]:
    findings = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and (f.value.id, f.attr) in _UNSAFE_MODULE_CALLS):
                if not _waived(lines, node.lineno):
                    findings.append(Finding(
                        "lint.signal-safety", rel,
                        f"{f.value.id}.{f.attr}() reachable from a "
                        "signal handler may deadlock on the "
                        "non-reentrant lock — defer via "
                        "metrics.DeferredCount (or waive with "
                        "'# signal-ok: <reason>' after an audit)",
                        node.lineno))
            elif isinstance(f, ast.Attribute) and f.attr == "acquire":
                blocking = None
                for kw in node.keywords:
                    if kw.arg == "blocking":
                        blocking = kw.value
                ok = blocking is not None and not (
                    isinstance(blocking, ast.Constant)
                    and blocking.value is True)
                if not ok and not _waived(lines, node.lineno):
                    findings.append(Finding(
                        "lint.signal-safety", rel,
                        "blocking .acquire() reachable from a signal "
                        "handler (pass blocking=False / a caller-"
                        "controlled flag, or waive with '# signal-ok')",
                        node.lineno))
        elif isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                nm = (ctx.id if isinstance(ctx, ast.Name)
                      else ctx.attr if isinstance(ctx, ast.Attribute)
                      else "")
                if "lock" in nm.lower() and not _waived(lines,
                                                        node.lineno):
                    findings.append(Finding(
                        "lint.signal-safety", rel,
                        f"'with {nm}:' reachable from a signal handler "
                        "may deadlock on the non-reentrant lock",
                        node.lineno))
    return findings


def lint_signal_safety(files: Iterable[str],
                       root: str = ".") -> List[Finding]:
    findings = []
    for path in files:
        rel = _rel(path, root)
        tree, lines = _parse(path)
        handlers = _handler_names(tree)
        if not handlers:
            continue
        fns = _collect_functions(tree)
        # BFS over the same-module call graph from each handler
        reachable: Set[str] = set()
        frontier = [h for h in handlers if h in fns]
        while frontier:
            name = frontier.pop()
            if name in reachable:
                continue
            reachable.add(name)
            frontier.extend(n for n in _called_names(fns[name])
                            if n in fns and n not in reachable)
        for name in sorted(reachable):
            findings.extend(_unsafe_in_function(fns[name], rel, lines))
    return findings


# ---------------------------------------------------------------------------
# lint.json-write
# ---------------------------------------------------------------------------


def _is_std_stream(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and node.attr in ("stdout", "stderr")
            and isinstance(node.value, ast.Name)
            and node.value.id == "sys")


def lint_json_writes(files: Iterable[str], root: str = ".") -> List[Finding]:
    findings = []
    for path in files:
        rel = _rel(path, root)
        if rel.replace(os.sep, "/").endswith(_JSON_ALLOWED):
            continue
        tree, _ = _parse(path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "dump"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "json"):
                continue
            if len(node.args) >= 2 and _is_std_stream(node.args[1]):
                continue
            findings.append(Finding(
                "lint.json-write", rel,
                "whole-file json.dump outside runtime/fsio.py — a kill "
                "mid-dump leaves a torn artifact; use "
                "fsio.atomic_write_json", node.lineno))
    return findings


# ---------------------------------------------------------------------------
# lint.fault-seam
# ---------------------------------------------------------------------------


def _catches_fault_injected(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    types = t.elts if isinstance(t, ast.Tuple) else [t] if t else []
    for e in types:
        if isinstance(e, ast.Name) and e.id == "FaultInjected":
            return True
        if isinstance(e, ast.Attribute) and e.attr == "FaultInjected":
            return True
    return False


def _body_counts_metric(handler: ast.ExceptHandler) -> bool:
    """Does the handler body count its degradation? ``metrics.inc`` /
    ``metrics.merge``, a ``DeferredCount.bump``, or a breaker
    ``record_failure`` (the breaker exports its state to telemetry)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            f = node.func
            if (isinstance(f.value, ast.Name) and f.value.id == "metrics"
                    and f.attr in ("inc", "merge")):
                return True
            if f.attr in ("bump", "record_failure"):
                return True
    return False


def _body_reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def lint_fault_seams(files: Iterable[str], root: str = ".") -> List[Finding]:
    findings = []
    for path in files:
        rel = _rel(path, root)
        tree, _ = _parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(Finding(
                    "lint.fault-seam", rel,
                    "bare 'except:' swallows everything including "
                    "KeyboardInterrupt — name the exceptions",
                    node.lineno))
                continue
            if (_catches_fault_injected(node)
                    and not _body_reraises(node)
                    and not _body_counts_metric(node)):
                findings.append(Finding(
                    "lint.fault-seam", rel,
                    "handler swallows FaultInjected without counting a "
                    "metric — a degradation that does not count is one "
                    "nobody will ever see", node.lineno))
    return findings


# ---------------------------------------------------------------------------
# lint.metric-keys (ISSUE 15): the telemetry key contract
# ---------------------------------------------------------------------------
#
# Every counter/gauge/mark/span key the package emits is statically
# extracted — ``metrics.inc/observe/set_gauge/mark/timer`` and
# ``telemetry.phase/root_span`` first-argument literals (f-string and
# ``"lit" + expr`` call sites register as dotted PREFIXES), plus the
# C++ native-profiler drain keys (``kDomPrefix`` x ``kSlotName`` and
# their ``_s`` self-time twins, parsed with the ISSUE 11 contract
# parsers). The registry renders as a generated README table between
# the ``<!-- metric-keys:start/end -->`` markers (the knob-table
# pattern: docs generated from code cannot drift) and the gate fails
# both directions: a key emitted but missing from the committed table
# (undocumented), and a key-shaped token in README prose that matches
# no emitted key (dead documentation).

_METRIC_PRODUCERS = {
    ("metrics", "inc"): "counter",
    ("metrics", "observe"): "histogram",
    ("metrics", "set_gauge"): "gauge",
    ("metrics", "mark"): "event",
    ("metrics", "timer"): "seconds",
    ("telemetry", "phase"): "span",
    ("telemetry", "root_span"): "span",
    ("telemetry", "observe"): "span",
    ("telemetry", "observe_value"): "histogram",
    # memory-plane probe names become the mem.<plane>.* gauge namespace
    ("memacct", "register_probe"): "plane",
}

# Dynamically-built keys (f-strings with no literal head, name+suffix
# concatenations, relay loops) declare themselves in place with an
# audited ``# metric-key: <key-pattern>`` comment on or just above the
# producing line — the same in-place-waiver idiom as ``# signal-ok`` /
# ``# blocking-ok``. ``<seg>`` / ``*`` are wildcards.
_KEY_DECL = re.compile(r"#\s*metric-key:\s*(\S+)")

_KEY_TABLE_START = "<!-- metric-keys:start -->"
_KEY_TABLE_END = "<!-- metric-keys:end -->"


def _key_literal(node: ast.Call):
    """(key, is_prefix) of a producer call's first argument: a constant
    string, the leading constant of an f-string, or the left constant
    of ``"lit" + expr`` — else (None, False) for fully dynamic relays
    (the keys they forward come from literal sites elsewhere)."""
    if not node.args:
        return None, False
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, False
    if isinstance(a, ast.JoinedStr) and a.values:
        head = a.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value, True
        return None, False
    if isinstance(a, ast.BinOp) and isinstance(a.op, ast.Add):
        left = a.left
        if isinstance(left, ast.Constant) and isinstance(left.value, str):
            return left.value, True
    return None, False


def metric_key_registry(root: str) -> Dict[str, dict]:
    """key -> {kind, prefix, sources} over the package tree plus the
    native profiler's drain-key tables."""
    registry: Dict[str, dict] = {}

    def add(key, kind, src, prefix=False):
        rec = registry.setdefault(key, {"kind": kind, "prefix": prefix,
                                        "sources": []})
        if src not in rec["sources"]:
            rec["sources"].append(src)
        rec["prefix"] = rec["prefix"] or prefix

    for path in iter_py_files(root, ("pyruhvro_tpu",)):
        rel = _rel(path, root).replace(os.sep, "/")
        if rel.startswith("pyruhvro_tpu/analysis/"):
            continue  # the analyzers' own sources hold example patterns
        tree, lines = _parse(path)
        for ln in lines:
            dm = _KEY_DECL.search(ln)
            if dm:
                add(dm.group(1), "declared", rel,
                    prefix=dm.group(1).endswith("."))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)):
                continue
            kind = _METRIC_PRODUCERS.get(
                (node.func.value.id, node.func.attr))
            if kind is None:
                continue
            key, prefix = _key_literal(node)
            if key is None:
                continue
            if kind in ("counter", "seconds") and key.endswith("_s"):
                kind = "seconds"
            add(key, kind, rel, prefix)

    # the C++ drain keys (hostpath/codec.py feeds them to metrics.inc
    # verbatim, plus the "_s" self-time twin per key)
    from .contracts import parse_cpp_string_array

    vm_core = os.path.join(
        root, "pyruhvro_tpu/runtime/native/host_vm_core.h")
    rel = "pyruhvro_tpu/runtime/native/host_vm_core.h"
    try:
        prefixes = parse_cpp_string_array(vm_core, "kDomPrefix")
        slots = parse_cpp_string_array(vm_core, "kSlotName")
    except OSError:  # fixture trees without the native core
        prefixes, slots = [], []
    for p in prefixes:
        for s in slots:
            add(p + s, "counter", rel)
            add(p + s + "_s", "seconds", rel)
    return registry


def render_metric_key_table(registry: Dict[str, dict]) -> str:
    """The generated README block: the native drain families collapse
    to their ``<prefix>.<opcode>`` wildcard rows (16+ opcode keys per
    domain would drown the table), everything else is one row per key;
    trailing-dot prefixes render with a ``<...>`` placeholder."""
    from .contracts import parse_cpp_string_array  # noqa: F401 (doc)

    rows = []
    seen_fam = set()
    for key in sorted(registry):
        rec = registry[key]
        fam = None
        for dom in ("vm.op.", "vm.encop.", "extract.op."):
            if key.startswith(dom):
                fam = dom
        if fam is not None:
            if fam in seen_fam:
                continue
            seen_fam.add(fam)
            rows.append((f"`{fam}<opcode>[_s]`", "counter/seconds",
                         "native profiler drain (host_vm_core.h "
                         "kDomPrefix x kSlotName)"))
            continue
        shown = f"`{key}<...>`" if rec["prefix"] or key.endswith(".") \
            else f"`{key}`"
        rows.append((shown, rec["kind"],
                     ", ".join(s.rsplit("/", 1)[-1]
                               for s in rec["sources"][:3])))
    out = ["| key | kind | emitted by |", "| --- | --- | --- |"]
    out += [f"| {k} | {kind} | {src} |" for k, kind, src in rows]
    return "\n".join(out) + "\n"


def _doc_key_tokens(text: str, root: str = "."):
    """Key-shaped backtick tokens in README prose: dotted lowercase
    identifiers that are not file paths, module paths, or attribute
    references; ``<...>``/``[...]``/``*`` segments are documentation
    wildcards."""
    out = []
    for m in re.finditer(r"`([^`\n]+)`", text):
        tok = m.group(1)
        if "/" in tok or "(" in tok or " " in tok or "=" in tok:
            continue
        # segments start alphanumeric (or a wildcard): `pool._broken`
        # is an attribute reference, not a key
        if re.fullmatch(
                r"[a-z][a-z0-9_]*(\.[a-z0-9<*\[][a-z0-9_<>.*\[\]]*)+",
                tok):
            if tok.rsplit(".", 1)[-1] in ("py", "json", "md", "cpp",
                                          "h", "jsonl", "yml", "avsc"):
                continue
            # `fallback.decoder.decode_records`-style module/function
            # references: the leading segments name a package module
            segs = tok.split(".")
            if os.path.exists(os.path.join(
                    root, "pyruhvro_tpu", segs[0], segs[1] + ".py")):
                continue
            out.append((tok, text[: m.start()].count("\n") + 1))
    return out


def _wild_rx(s: str):
    """Regex for a key with ``<seg>`` / ``[seg]`` / ``*`` wildcards
    (used by both documented tokens and ``# metric-key`` patterns)."""
    parts = re.split(r"(<[^>]*>|\[[^\]]*\]|\*)", s)
    return re.compile("^" + "".join(
        re.escape(p) if i % 2 == 0 else "[A-Za-z0-9_.-]+"
        for i, p in enumerate(parts)) + "$")


def _doc_token_matches(tok: str, registry: Dict[str, dict]) -> bool:
    """Does a documented token name at least one emitted key? Wildcard
    segments match anything on either side; a token that is a dotted
    family prefix of an emitted key (or extends an emitted trailing-dot
    prefix) also matches."""
    tok_rx = _wild_rx(tok)
    sample = re.sub(r"<[^>]*>|\[[^\]]*\]|\*", "x", tok)
    for key, rec in registry.items():
        if "<" in key or "*" in key or "[" in key:
            # a declared pattern: match pattern-vs-sample
            if _wild_rx(key).match(sample):
                return True
            continue
        if tok_rx.match(key):
            return True
        if rec["prefix"] and (sample.startswith(key)
                              or key.startswith(sample)):
            return True
        if key.startswith(tok + ".") or key.startswith(tok + "_"):
            # a documented family name ("slo.breach" covers
            # "slo.breach.<name>")
            return True
    return False


def lint_metric_keys(root: str, fix: bool = False) -> List[Finding]:
    """Both directions of the key contract: the committed README table
    must equal the fresh registry rendering (``--fix-metric-keys``
    rewrites it), and every key-shaped token in README prose must name
    an emitted key."""
    findings: List[Finding] = []
    registry = metric_key_registry(root)
    lint_metric_keys.last_registry = registry  # report material
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return [Finding("lint.metric-keys", "README.md",
                        "README.md unreadable")]
    want = render_metric_key_table(registry)
    m = re.search(re.escape(_KEY_TABLE_START) + r"\n(.*?)"
                  + re.escape(_KEY_TABLE_END), text, flags=re.S)
    if m is None:
        findings.append(Finding(
            "lint.metric-keys", "README.md",
            f"metric-key registry markers missing ({_KEY_TABLE_START} "
            f"... {_KEY_TABLE_END}) — the table is generated from the "
            "statically-extracted key registry"))
    elif m.group(1) != want:
        if fix:
            text = text[: m.start(1)] + want + text[m.end(1):]
            with open(readme, "w", encoding="utf-8") as f:
                f.write(text)
            # re-anchor the match on the REWRITTEN text: the dead-key
            # scan below slices around it, and stale offsets would
            # misalign the prose
            m = re.search(re.escape(_KEY_TABLE_START) + r"\n(.*?)"
                          + re.escape(_KEY_TABLE_END), text, flags=re.S)
            print("analysis_gate: rewrote the README metric-key table "
                  "from the extracted registry")
        else:
            findings.append(Finding(
                "lint.metric-keys", "README.md",
                "metric-key table drifted from the emitted keys — a "
                "key was added/removed without documentation; run "
                "scripts/analysis_gate.py --fix-metric-keys",
                text[: m.start(1)].count("\n") + 1))
    # dead documentation: prose keys outside the generated block that
    # match no emitted key
    prose = text
    if m is not None:
        prose = text[: m.start(1)] + text[m.end(1):]
    emitted_roots = {k.split(".", 1)[0] for k in registry}
    for tok, line in _doc_key_tokens(prose, root):
        if tok.split(".", 1)[0] not in emitted_roots:
            continue  # not a telemetry family (api params, attrs, ...)
        if not _doc_token_matches(tok, registry):
            findings.append(Finding(
                "lint.metric-keys", "README.md",
                f"documented key {tok!r} is emitted nowhere (dead "
                "key) — the docs promise telemetry the code no longer "
                "produces", line))
    return findings


# ---------------------------------------------------------------------------
# the combined pass
# ---------------------------------------------------------------------------


def run_lints(root: str = ".", fix_metric_keys: bool = False) -> List[Finding]:
    """All five lints over the package tree (plus scripts/ and bench.py
    for the json-write rule — CI artifacts torn mid-write poison later
    runs exactly like profile files do)."""
    pkg = iter_py_files(root, ("pyruhvro_tpu",))
    findings = []
    findings.extend(lint_env_reads(pkg, root))
    findings.extend(lint_signal_safety(pkg, root))
    json_scope = list(pkg)
    json_scope += iter_py_files(root, ("scripts",))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        json_scope.append(bench)
    findings.extend(lint_json_writes(json_scope, root))
    findings.extend(lint_fault_seams(pkg, root))
    findings.extend(lint_metric_keys(root, fix=fix_metric_keys))
    return findings
