"""Repo-native static-analysis plane (ISSUE 11 + 14 + 15).

Five coupled passes, run as one CI gate (``scripts/analysis_gate.py``):

1. :mod:`.contracts` — the cross-language opcode contract checker. The
   fused decode path mirrors one contract in four hand-synchronized
   places (``hostpath/program.py`` constants, the C++ enums in
   ``runtime/native/host_vm_core.h`` / ``extract_core.h``, the
   profiler's pseudo-op slots, and the specializer's embedded
   ``kOps``/``kAux`` codegen); this pass makes any divergence in value,
   arity, aux kind or op-name string a machine-checked failure instead
   of a reviewer-memory item.
2. :mod:`.lints` — AST invariant lints: no direct ``PYRUHVRO_TPU_*``
   env reads outside ``runtime/knobs.py``, no metrics/lock acquisition
   reachable from a registered signal handler, no whole-file
   ``json.dump`` outside ``runtime/fsio.py``, no swallowed
   ``FaultInjected`` without a counted metric, and (ISSUE 15) the
   metric-key contract: every statically-extracted telemetry key in
   the generated README registry, no dead documented keys.
3. :mod:`.concurrency` — the concurrency-correctness pass (ISSUE 14):
   lock-order inversion cycles over the acquired-while-held graph,
   locks held across blocking seams, and the ``# guarded-by:`` /
   ``# lock-free-ok(...)`` discipline for ``runtime/`` module globals,
   with an audited waiver list exported to the report.
4. :mod:`.irverify` — the IR verification plane (ISSUE 15): abstract
   interpretation over the compiled hostpath opcode programs (generic
   lowering AND the specializer's generated units, decode + encode) —
   type/effect discipline, wire-progress/termination proofs,
   int32/int64 overflow lanes against anchored native guards, and
   generic<->specialized effect-trace equivalence — driven across the
   full schema-construct lattice with a seeded mutation self-test
   (``analysis_gate.py --ir``, ``IR_VERIFY_REPORT.json``).
5. sanitizer builds — ``runtime/native/build.py``'s ASan/UBSan flavor
   (gate ``--sanitize``) and ThreadSanitizer flavor (``--tsan``, the
   dynamic complement of the lock-graph pass), each with its own CI
   job.

Every pass reports plain :class:`Finding` rows; the gate exits non-zero
on any finding and writes ``ANALYSIS_REPORT.json``.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Finding:
    """One analysis finding: where, which rule, and what diverged."""

    rule: str      # e.g. "contract.opkind", "lint.env-read"
    path: str      # repo-relative file
    message: str
    line: int = 0

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self) -> str:  # gate output: one grep-able line each
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


__all__ = ["Finding"]
