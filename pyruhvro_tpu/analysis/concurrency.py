"""Concurrency-correctness analyzer: lock graph + guarded-by discipline.

ISSUE 14's tentpole. The repo holds ~40 locks across ~26 files, and
every recent PR's review pass found real races by hand (PR 7's
signal-handler deadlock, PR 12's eviction-vs-call and memo-vs-eviction
races). Before the chunk fan-out moves inside one native call (ROADMAP
item 3 — a strictly more concurrent design), the concurrency invariants
must be machine-checked the way PR 11 made the opcode contracts
machine-checked. Three coupled passes over the package AST:

* ``conc.lock-order`` — every lock acquisition site (``with <lock>:``,
  blocking ``.acquire()``) feeds an **acquired-while-held graph**,
  propagated through the call graph (same-module calls, ``self``
  methods, and cross-module calls through import aliases). Any cycle —
  two locks ever taken in both orders on any path — is a deadlock
  waiting for the right interleaving and fails the gate. Lexically
  nesting the *same* non-reentrant lock is reported as a self-deadlock.
* ``conc.blocking-seam`` — no lock may be held across a **blocking
  seam**: fault-injection sites (``faults.fire`` can sleep for the
  chaos ``hang`` kind — and one sits on every native VM call path),
  subprocess launches (the g++ JIT), future/pool waits (``.result``,
  ``pool.map_chunks*``), ``time.sleep``, ``fsio`` artifact writes,
  extension-module execs and device blocking waits. A lock held across
  seconds of blocking work turns every sibling caller into a convoy —
  or a deadline breach. Audited exceptions carry an inline
  ``# blocking-ok: <reason>`` waiver, and every waiver is exported to
  ``ANALYSIS_REPORT.json`` as the audit trail.
* ``conc.unguarded-global`` / ``conc.guard-discipline`` — every
  module-level **mutable** container (and every name rebound through
  ``global``) in ``runtime/`` must declare its synchronization story:
  ``# guarded-by: <lock>`` ties it to a module lock and every mutation
  site is then checked to sit inside a ``with <lock>:`` block;
  ``# lock-free-ok(<reason>)`` records the audited lock-free designs
  (GIL-atomic single stores, append-only registries). State without a
  declaration fails the gate — the declaration is cheap, and its
  absence is exactly how PR 12's races got in.

Soundness posture: the analysis is lexical and deliberately
path-INsensitive (an acquisition behind ``if`` still counts as held),
the same trade the PR 11 lints made. It cannot see through callables
passed as values (``factory()``, registered hooks) — the deterministic
interleaving harness (``runtime/schedtest.py``) and the TSan build
flavor cover the dynamic remainder; the three planes ship as one gate.

Entry points: :func:`analyze` returns ``(findings, info)`` where
``info`` carries the lock inventory, the full edge list and the audited
waiver list for ``ANALYSIS_REPORT.json``; ``scripts/analysis_gate.py``
wires it in as the ``concurrency`` pass.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding
from .lints import iter_py_files

__all__ = ["analyze", "run_concurrency"]

# the package subtrees whose module-level mutable state must declare a
# guard: the runtime plane is the one imported by every tier and hit
# from API threads, pool workers, the obs server thread and atexit; the
# serving plane adds its own worker threads and signal-drain thread
_GUARD_SCOPES = ("pyruhvro_tpu/runtime", "pyruhvro_tpu/serving")

_GUARDED_BY = "guarded-by:"
_LOCK_FREE_OK = "lock-free-ok"
_BLOCKING_OK = "# blocking-ok"
_LOCK_ORDER_OK = "# lock-order-ok"

# lock constructors we track. threading.Condition is deliberately NOT a
# lock here: it is a rendezvous (wait() releases it), and treating it
# as a data guard would make every wait look like a held-across-block
_LOCK_CTORS = {"Lock", "RLock"}

# mutable module-global constructors that demand a guard declaration
_MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                  "deque", "WeakSet", "WeakValueDictionary", "Counter"}

# container mutators: a call of one of these methods on a guarded name
# is a write site
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear"}

# directly-blocking calls, keyed by (base name-or-resolved-module, attr).
# base "*" matches any receiver expression.
_BLOCKING_MODULE_CALLS = {
    ("subprocess", "run"), ("subprocess", "check_call"),
    ("subprocess", "check_output"), ("subprocess", "call"),
    ("time", "sleep"),
    ("faults", "fire"),          # chaos 'hang' kind sleeps at the seam
    ("fsio", "atomic_write_json"),
    ("pool", "map_chunks"), ("pool", "map_chunks_proc"),
}
_BLOCKING_ANY_ATTRS = {
    "result",                    # concurrent.futures waits
    "exec_module",               # extension-module import/exec
    "block_until_ready",         # device sync barriers
    "wait",                      # Event/Condition/process waits
}


# ---------------------------------------------------------------------------
# per-module model
# ---------------------------------------------------------------------------


@dataclass
class _Fn:
    """One function/method: its lexical lock events + call-graph edges,
    then the fixed-point summaries."""

    rel: str
    qualname: str
    node: ast.AST
    # direct lexical acquisitions (lock ids) anywhere in the body
    acquires: Set[str] = field(default_factory=set)
    # (held_tuple, lock_id, lineno): a with/acquire entered while held
    edges: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held_tuple, what, lineno): a DIRECT blocking call while held
    blocking_sites: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held_tuple, callee_key, lineno): resolved call-graph edges
    calls: List[Tuple[Tuple[str, ...], Tuple[str, str], int]] = field(
        default_factory=list)
    blocks_directly: bool = False
    # fixed-point results
    acq_star: Set[str] = field(default_factory=set)
    blocks_star: bool = False
    block_why: str = ""


@dataclass
class _Module:
    rel: str
    tree: ast.AST
    lines: List[str]
    # import alias -> analyzed module rel path
    aliases: Dict[str, str] = field(default_factory=dict)
    # module-level lock name -> (lock_id, is_rlock)
    mod_locks: Dict[str, Tuple[str, bool]] = field(default_factory=dict)
    # (class, attr) -> (lock_id, is_rlock) for self.<attr> locks
    cls_locks: Dict[Tuple[str, str], Tuple[str, bool]] = field(
        default_factory=dict)
    fns: Dict[str, _Fn] = field(default_factory=dict)
    classes: Set[str] = field(default_factory=set)


def _rel(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _lock_ctor(node: ast.AST) -> Optional[bool]:
    """Is ``node`` a tracked lock constructor call? Returns is_rlock,
    or None. Matches ``threading.Lock()`` / ``Lock()`` styles."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in _LOCK_CTORS:
        return name == "RLock"
    return None


def _mutable_ctor(node: ast.AST) -> bool:
    """Module-global RHS that demands a guard declaration: a mutable
    literal or a known mutable-container constructor."""
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        name = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else "")
        return name in _MUTABLE_CTORS
    return False


def _is_threading_local(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return ((isinstance(f, ast.Attribute) and f.attr == "local")
            or (isinstance(f, ast.Name) and f.id == "local"))


def _own_lines(lines: List[str], lineno: int, span: int = 8):
    """The annotation surface OF this statement: its own line, then up
    to ``span`` lines above as long as they are pure comments — so an
    annotation trailing the PREVIOUS assignment can never bleed onto
    this one."""
    if 1 <= lineno <= len(lines):
        yield lines[lineno - 1]
    for ln in range(lineno - 1, max(0, lineno - 1 - span), -1):
        if ln < 1:
            return
        text = lines[ln - 1].strip()
        if not text.startswith("#"):
            return
        yield text


def _comment_near(lines: List[str], lineno: int, token: str,
                  span: int = 8) -> bool:
    """``token`` on the statement's own annotation surface (the shared
    waiver convention of the PR 11 lints)."""
    return any(token in text for text in _own_lines(lines, lineno, span))


def _declared_guard(lines: List[str], lineno: int) -> Optional[str]:
    """The ``# guarded-by: <lock>`` declaration for an assignment at
    ``lineno`` (same line or contiguous comment lines above)."""
    for text in _own_lines(lines, lineno):
        idx = text.find(_GUARDED_BY)
        if idx >= 0:
            return text[idx + len(_GUARDED_BY):].strip().split()[0]
    return None


def _has_lock_free_waiver(lines: List[str], lineno: int) -> bool:
    return _comment_near(lines, lineno, _LOCK_FREE_OK)


# ---------------------------------------------------------------------------
# import alias resolution
# ---------------------------------------------------------------------------


def _module_parts(rel: str) -> List[str]:
    parts = rel[:-3].split("/")  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return parts


def _resolve_alias(rel: str, node: ast.ImportFrom,
                   known: Set[str]) -> Dict[str, str]:
    """Map ``from ..x import y [as z]`` aliases to analyzed module rel
    paths (only aliases that name an analyzed MODULE matter here)."""
    out: Dict[str, str] = {}
    parts = _module_parts(rel)
    if node.level:
        base = parts[: len(parts) - node.level]
    else:
        base = (node.module or "").split(".") if node.module else []
    if node.level and node.module:
        base = base + node.module.split(".")
    for alias in node.names:
        target = base + [alias.name]
        cand = "/".join(target) + ".py"
        if cand in known:
            out[alias.asname or alias.name] = cand
    return out


# ---------------------------------------------------------------------------
# pass 1: per-module collection
# ---------------------------------------------------------------------------


def _collect_module(rel: str, path: str, known: Set[str]) -> _Module:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    m = _Module(rel=rel, tree=tree, lines=src.splitlines())

    for node in tree.body:
        if isinstance(node, ast.ImportFrom):
            m.aliases.update(_resolve_alias(rel, node, known))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            rl = _lock_ctor(node.value)
            if rl is not None:
                name = node.targets[0].id
                m.mod_locks[name] = (f"{rel}:{name}", rl)
        if isinstance(node, ast.ClassDef):
            m.classes.add(node.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Attribute) \
                        and isinstance(sub.targets[0].value, ast.Name) \
                        and sub.targets[0].value.id == "self":
                    rl = _lock_ctor(sub.value)
                    if rl is not None:
                        attr = sub.targets[0].attr
                        m.cls_locks[(node.name, attr)] = (
                            f"{rel}:{node.name}.{attr}", rl)
    return m


def _resolve_lock(m: _Module, cls: Optional[str], expr: ast.AST,
                  mods: Optional[Dict[str, "_Module"]] = None
                  ) -> Optional[Tuple[str, bool]]:
    """Resolve an acquisition context expression to a tracked lock."""
    if isinstance(expr, ast.Name):
        return m.mod_locks.get(expr.id)
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                     ast.Name):
        base, attr = expr.value.id, expr.attr
        if base == "self":
            if cls and (cls, attr) in m.cls_locks:
                return m.cls_locks[(cls, attr)]
            owners = [v for (c, a), v in m.cls_locks.items() if a == attr]
            if len(owners) == 1:
                return owners[0]
            return None
        target = m.aliases.get(base)
        if target is not None:
            # cross-module module-level lock (e.g. ``with nb._lock:``)
            # — only when the TARGET module actually defines a tracked
            # lock of that name (so its RLock-ness is known and an
            # arbitrary module-attribute context manager never injects
            # phantom graph edges)
            if mods is not None and target in mods:
                return mods[target].mod_locks.get(attr)
            return None
    return None


def _blocking_what(m: _Module, node: ast.Call,
                   held: Tuple[str, ...]) -> Optional[str]:
    """A human tag when ``node`` is a directly-blocking call."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    if isinstance(f.value, ast.Name):
        base = f.value.id
        # normalize through import aliases: `nb.fire` on an alias of
        # runtime/faults.py still matches ("faults", "fire")
        target = m.aliases.get(base)
        if target is not None:
            base = _module_parts(target)[-1]
        if (base, attr) in _BLOCKING_MODULE_CALLS:
            return f"{base}.{attr}()"
    if attr in _BLOCKING_ANY_ATTRS:
        # Condition.wait on a lock you hold RELEASES it — that is the
        # rendezvous working as designed, not a held-across-block
        if attr == "wait":
            rl = _resolve_lock(m, None, f.value)
            if rl is not None and rl[0] in held:
                return None
        return f".{attr}()"
    return None


class _FnWalker:
    """Lexical walk of one function body tracking the held-lock stack."""

    def __init__(self, m: _Module, fn: _Fn, cls: Optional[str],
                 mods: Optional[Dict[str, _Module]] = None):
        self.m = m
        self.fn = fn
        self.cls = cls
        self.mods = mods

    def walk(self, body: Sequence[ast.stmt],
             held: Tuple[str, ...]) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, node: ast.stmt, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                rl = _resolve_lock(self.m, self.cls,
                                   item.context_expr, self.mods)
                if rl is not None:
                    lock_id, _is_rlock = rl
                    self.fn.acquires.add(lock_id)
                    self.fn.edges.append((inner, lock_id, node.lineno))
                    inner = inner + (lock_id,)
                else:
                    self._expr(item.context_expr, inner)
            self.walk(node.body, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are separate _Fn entries
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, held)
            elif isinstance(child, ast.expr):
                self._expr(child, held)

    def _expr(self, node: ast.expr, held: Tuple[str, ...]) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            self._call(sub, held)

    def _call(self, node: ast.Call, held: Tuple[str, ...]) -> None:
        f = node.func
        # explicit .acquire(): an ordering edge when it can block
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            rl = _resolve_lock(self.m, self.cls, f.value, self.mods)
            if rl is not None:
                nonblocking = any(
                    kw.arg == "blocking"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords)
                if not nonblocking:
                    self.fn.acquires.add(rl[0])
                    self.fn.edges.append((held, rl[0], node.lineno))
                return
        what = _blocking_what(self.m, node, held)
        if what is not None:
            self.fn.blocks_directly = True
            if held:
                self.fn.blocking_sites.append((held, what, node.lineno))
            return
        # call-graph edges: local functions, self methods, constructor
        # calls, and alias.function cross-module calls
        callee: Optional[Tuple[str, str]] = None
        if isinstance(f, ast.Name):
            if f.id in self.m.classes:
                callee = (self.m.rel, f"{f.id}.__init__")
            else:
                callee = (self.m.rel, f.id)
        elif isinstance(f, ast.Attribute) and isinstance(f.value,
                                                         ast.Name):
            if f.value.id == "self" and self.cls:
                callee = (self.m.rel, f"{self.cls}.{f.attr}")
            else:
                target = self.m.aliases.get(f.value.id)
                if target is not None:
                    callee = (target, f.attr)
        if callee is not None:
            self.fn.calls.append((held, callee, node.lineno))


def _collect_functions(m: _Module,
                       mods: Optional[Dict[str, _Module]] = None) -> None:
    def visit(body, prefix: str, cls: Optional[str]):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{node.name}"
                fn = _Fn(rel=m.rel, qualname=qn, node=node)
                m.fns[qn] = fn
                _FnWalker(m, fn, cls, mods).walk(node.body, ())
                visit(node.body, qn + ".", cls)
            elif isinstance(node, ast.ClassDef):
                visit(node.body, node.name + ".", node.name)

    visit(m.tree.body, "", None)


# ---------------------------------------------------------------------------
# pass 2: whole-program fixed point
# ---------------------------------------------------------------------------


def _fixed_point(mods: Dict[str, _Module]) -> Dict[Tuple[str, str], _Fn]:
    table: Dict[Tuple[str, str], _Fn] = {}
    for m in mods.values():
        for fn in m.fns.values():
            fn.acq_star = set(fn.acquires)
            fn.blocks_star = fn.blocks_directly
            if fn.blocks_directly:
                fn.block_why = "direct blocking call"
            table[(m.rel, fn.qualname)] = fn
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for key, fn in table.items():
            for _held, callee, _ln in fn.calls:
                target = table.get(callee)
                if target is None:
                    # unqualified name may be a plain function OR a
                    # method sharing the prefix; try a method lookup in
                    # the same module for self-less helper styles
                    continue
                if not target.acq_star <= fn.acq_star:
                    fn.acq_star |= target.acq_star
                    changed = True
                if target.blocks_star and not fn.blocks_star:
                    fn.blocks_star = True
                    fn.block_why = (f"calls {callee[1]} "
                                    f"({target.block_why})")
                    changed = True
    return table


# ---------------------------------------------------------------------------
# pass 3: findings
# ---------------------------------------------------------------------------


def _lock_graph(mods: Dict[str, _Module],
                table: Dict[Tuple[str, str], _Fn],
                rlocks: Set[str]):
    """-> (edges {(a, b): site}, self_deadlocks, blocking findings
    pre-waiver). Edges fold direct nesting AND call-graph transitive
    acquisitions."""
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    self_dead: List[Tuple[str, str, int]] = []
    blocking: List[Tuple[str, Tuple[str, ...], str, int, str]] = []

    for m in mods.values():
        for fn in m.fns.values():
            for held, lock_id, ln in fn.edges:
                if lock_id in held and lock_id not in rlocks:
                    self_dead.append((lock_id, m.rel, ln))
                    continue
                for h in held:
                    if h != lock_id:
                        edges.setdefault((h, lock_id),
                                         (m.rel, ln, fn.qualname))
            for held, what, ln in fn.blocking_sites:
                blocking.append((m.rel, held, what, ln, fn.qualname))
            for held, callee, ln in fn.calls:
                if not held:
                    continue
                target = table.get(callee)
                if target is None:
                    continue
                for lock_id in target.acq_star:
                    if lock_id in held:
                        if lock_id not in rlocks:
                            self_dead.append((lock_id, m.rel, ln))
                        continue
                    for h in held:
                        edges.setdefault((h, lock_id),
                                         (m.rel, ln, fn.qualname))
                if target.blocks_star:
                    blocking.append(
                        (m.rel, held,
                         f"{callee[1]}() [{target.block_why}]", ln,
                         fn.qualname))
    return edges, self_dead, blocking


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int, str]]
                 ) -> List[List[str]]:
    """Elementary cycles in the lock digraph via iterative DFS over
    SCCs — small graph, simple approach: for each node, DFS for a path
    back to itself; deduplicate by the cycle's canonical rotation."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []

    def dfs(start: str, node: str, path: List[str],
            visited: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 0:
                cyc = path[:]
                pivot = cyc.index(min(cyc))
                canon = tuple(cyc[pivot:] + cyc[:pivot])
                if canon not in seen:
                    seen.add(canon)
                    cycles.append(list(canon))
            elif nxt not in visited and nxt > start:
                # only explore nodes > start: each cycle is found from
                # its smallest member exactly once
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return cycles


def _check_guarded_globals(mods: Dict[str, _Module]) -> Tuple[
        List[Finding], List[dict], List[dict]]:
    """The guarded-by discipline over ``runtime/`` module globals."""
    findings: List[Finding] = []
    guarded_inv: List[dict] = []
    waived_inv: List[dict] = []
    for m in mods.values():
        in_scope = any(s in m.rel for s in _GUARD_SCOPES)
        # every name assigned under a `global` declaration anywhere
        rebound: Dict[str, int] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                declared: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Global):
                        declared.update(sub.names)
                for sub in ast.walk(node):
                    targets = []
                    if isinstance(sub, ast.Assign):
                        targets = sub.targets
                    elif isinstance(sub, ast.AugAssign):
                        targets = [sub.target]
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id in declared:
                            rebound.setdefault(t.id, sub.lineno)
        # module-level mutable containers
        flagged: Dict[str, int] = {}
        for node in m.tree.body:
            tgt = None
            val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                tgt, val = node.target.id, node.value
            if tgt is None or val is None:
                continue
            if _is_threading_local(val) or _lock_ctor(val) is not None:
                continue
            # dunders (__all__) and ALL_CAPS names are constants by
            # convention: populated at import, frozen after — the
            # convention IS their declaration
            if tgt.startswith("__") or tgt.isupper():
                continue
            if _mutable_ctor(val) or tgt in rebound:
                flagged[tgt] = node.lineno
        for name, extra_ln in rebound.items():
            if not (name.startswith("__") or name.isupper()):
                flagged.setdefault(name, extra_ln)

        guards: Dict[str, str] = {}
        for name, ln in sorted(flagged.items(), key=lambda kv: kv[1]):
            guard = _declared_guard(m.lines, ln)
            if guard is not None:
                guards[name] = guard
                guarded_inv.append({"module": m.rel, "name": name,
                                    "guard": guard})
                if guard not in m.mod_locks:
                    findings.append(Finding(
                        "conc.unknown-guard", m.rel,
                        f"global {name!r} declares guard {guard!r} but "
                        f"no module-level threading lock of that name "
                        f"exists", ln))
                continue
            if _has_lock_free_waiver(m.lines, ln):
                waived_inv.append({"module": m.rel, "name": name,
                                   "line": ln, "kind": "lock-free-ok"})
                continue
            if in_scope:
                findings.append(Finding(
                    "conc.unguarded-global", m.rel,
                    f"module-level mutable state {name!r} has no "
                    f"declared guard — annotate '# guarded-by: <lock>' "
                    f"(and hold it at every mutation) or "
                    f"'# lock-free-ok(<reason>)' after an audit", ln))

        if guards:
            findings.extend(_check_mutations(m, guards))
    return findings, guarded_inv, waived_inv


def _stmt_mutations(node: ast.stmt, guards: Dict[str, str]):
    """``(name, lineno)`` per mutation of a guarded name in ONE simple
    statement (and in the immediate test/iter expressions of compound
    ones) — nested statement bodies are the recursive visitor's job."""
    out = []
    if isinstance(node, ast.Assign):
        targets = node.targets
        exprs = [node.value]
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
        exprs = [node.value]
    elif isinstance(node, ast.Delete):
        targets = node.targets
        exprs = []
    else:
        targets = []
        exprs = [v for f in ("value", "test", "iter", "exc")
                 for v in [getattr(node, f, None)] if v is not None]
    for t in targets:
        if isinstance(t, ast.Name) and t.id in guards:
            out.append((t.id, node.lineno))
        elif isinstance(t, ast.Subscript) \
                and isinstance(t.value, ast.Name) \
                and t.value.id in guards:
            out.append((t.value.id, node.lineno))
    for e in exprs:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id in guards:
                out.append((sub.func.value.id, sub.lineno))
    return out


def _check_mutations(m: _Module, guards: Dict[str, str]) -> List[Finding]:
    """Every mutation of a guarded global must sit inside a
    ``with <declared lock>:`` block (module top level is import-time
    single-threaded and exempt; ``# lock-free-ok`` waives one site)."""
    findings: List[Finding] = []

    def report(node: ast.stmt, held: Set[str]) -> None:
        for site_name, ln in _stmt_mutations(node, guards):
            if guards[site_name] in held:
                continue
            if _comment_near(m.lines, ln, _LOCK_FREE_OK):
                continue
            findings.append(Finding(
                "conc.guard-discipline", m.rel,
                f"{site_name!r} is declared guarded-by "
                f"{guards[site_name]!r} but this mutation is outside "
                f"any 'with {guards[site_name]}:' block", ln))

    def visit(body, held: Set[str], in_function: bool):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the repo's `_locked` suffix convention: the function's
                # CONTRACT is that every caller already holds the guard
                # (the lock-order pass still sees callers' with-blocks)
                inner = (set(guards.values())
                         if node.name.endswith("_locked") else set())
                visit(node.body, inner, True)
                continue
            if isinstance(node, ast.ClassDef):
                visit(node.body, held, in_function)
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    ctx = item.context_expr
                    # only a bare module-level name satisfies a
                    # module-level guard: 'with self._lock:' or
                    # 'with othermod._lock:' holding a DIFFERENT lock
                    # that merely shares the name must not pass
                    if isinstance(ctx, ast.Name):
                        inner.add(ctx.id)
                visit(node.body, inner, in_function)
                continue
            if in_function:
                report(node, held)
            for f in ("body", "orelse", "finalbody"):
                sub = getattr(node, f, None)
                if sub:
                    visit(sub, held, in_function)
            for h in getattr(node, "handlers", ()) or ():
                visit(h.body, held, in_function)

    visit(m.tree.body, set(), False)
    return findings


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def analyze(root: str, subdirs: Sequence[str] = ("pyruhvro_tpu",)
            ) -> Tuple[List[Finding], Dict]:
    """Run all concurrency passes. Returns ``(findings, info)``;
    ``info`` carries the lock inventory, edge list, and the audited
    waiver list for ``ANALYSIS_REPORT.json``."""
    files = iter_py_files(root, subdirs)
    known = {_rel(p, root) for p in files}
    mods: Dict[str, _Module] = {}
    for p in files:
        rel = _rel(p, root)
        mods[rel] = _collect_module(rel, p, known)
    # second phase: function walks resolve cross-module locks against
    # the full module map (a `with alias.attr:` is only a lock when the
    # target module defines one — RLock-ness included)
    for m in mods.values():
        _collect_functions(m, mods)

    rlocks: Set[str] = set()
    lock_inventory: List[dict] = []
    for m in mods.values():
        for name, (lock_id, is_rlock) in m.mod_locks.items():
            lock_inventory.append({"id": lock_id, "module": m.rel,
                                   "name": name,
                                   "kind": "RLock" if is_rlock
                                   else "Lock"})
            if is_rlock:
                rlocks.add(lock_id)
        for (cls, attr), (lock_id, is_rlock) in m.cls_locks.items():
            lock_inventory.append({"id": lock_id, "module": m.rel,
                                   "name": f"{cls}.{attr}",
                                   "kind": "RLock" if is_rlock
                                   else "Lock"})
            if is_rlock:
                rlocks.add(lock_id)

    table = _fixed_point(mods)
    edges, self_dead, blocking = _lock_graph(mods, table, rlocks)

    findings: List[Finding] = []
    waivers: List[dict] = []

    # lock-order: waive edges whose acquisition site carries the
    # comment, then fail on any remaining cycle
    live_edges = {}
    for (a, b), (rel, ln, qn) in edges.items():
        if _comment_near(mods[rel].lines, ln, _LOCK_ORDER_OK):
            waivers.append({"kind": "lock-order-ok", "module": rel,
                            "line": ln, "edge": [a, b]})
            continue
        live_edges[(a, b)] = (rel, ln, qn)
    for cyc in _find_cycles(live_edges):
        chain = " -> ".join(cyc + [cyc[0]])
        sites = "; ".join(
            f"{live_edges[e][0]}:{live_edges[e][1]}"
            for e in zip(cyc, cyc[1:] + [cyc[0]]) if e in live_edges)
        rel0, ln0, _ = live_edges.get(
            (cyc[0], cyc[1 % len(cyc)]), ("", 0, ""))
        findings.append(Finding(
            "conc.lock-order", rel0 or "pyruhvro_tpu",
            f"lock-order inversion cycle: {chain} (edges at {sites}) — "
            f"two threads taking these locks in opposite order "
            f"deadlock", ln0))
    for lock_id, rel, ln in sorted(set(self_dead)):
        if _comment_near(mods[rel].lines, ln, _LOCK_ORDER_OK):
            waivers.append({"kind": "lock-order-ok", "module": rel,
                            "line": ln, "edge": [lock_id, lock_id]})
            continue
        findings.append(Finding(
            "conc.lock-order", rel,
            f"non-reentrant lock {lock_id} re-acquired while already "
            f"held (self-deadlock)", ln))

    # blocking seams
    for rel, held, what, ln, qn in blocking:
        if _comment_near(mods[rel].lines, ln, _BLOCKING_OK):
            waivers.append({"kind": "blocking-ok", "module": rel,
                            "line": ln, "held": list(held),
                            "call": what})
            continue
        findings.append(Finding(
            "conc.blocking-seam", rel,
            f"{qn} holds {', '.join(held)} across blocking call "
            f"{what} — a stall there convoys every sibling caller "
            f"(waive with '# blocking-ok: <reason>' after an audit)",
            ln))

    g_findings, guarded_inv, lf_waivers = _check_guarded_globals(mods)
    findings.extend(g_findings)
    waivers.extend(lf_waivers)

    info = {
        "locks": sorted(lock_inventory, key=lambda d: d["id"]),
        "edges": sorted(
            [{"from": a, "to": b, "site": f"{s[0]}:{s[1]}"}
             for (a, b), s in edges.items()],
            key=lambda d: (d["from"], d["to"])),
        "guarded": sorted(guarded_inv,
                          key=lambda d: (d["module"], d["name"])),
        "waivers": sorted(waivers,
                          key=lambda d: (d["module"], d["line"])),
    }
    return findings, info


def run_concurrency(root: str = ".") -> List[Finding]:
    """Gate-facing convenience: findings only."""
    return analyze(root)[0]
