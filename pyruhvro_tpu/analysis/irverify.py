"""IR verification plane: abstract interpretation over opcode programs.

ISSUE 15 tentpole. PR 11's contract checker diffs *tables* (enum
values, the specializer's embedded ``kOps``/``kAux`` bytes); this pass
machine-checks program *meaning*: every compiled hostpath program —
the generic ``hostpath/program.py`` lowering AND the specializer's
generated translation units, decode and encode directions — is
abstract-executed against the effect contract
(:data:`..hostpath.program.OP_EFFECTS`) and four invariant classes are
proved per program:

1. **Type/effect discipline** (``irverify.effect``) — subtree ``nops``
   tiling (the walk advances strictly and terminates), column-index
   bounds and one-writer-per-column ownership, column-type stack
   effects (each op's primary/key column carries the ColType the
   engines expect), per-axis push-count exactness (every column
   appends exactly once per element of its region axis — item columns
   on the item axis, everything else per record; a column appearing
   off-axis desyncs every later column), aux-table arity/placement
   (required tags,
   enum symbol count == ``op.a``, decimal precision >= 1) and the aux
   consumption matrix (an aux entry no consumer reads is dead weight
   in every embedded table), and validity-chain nesting depth vs the
   ``PYRUHVRO_TPU_MAX_DEPTH`` walker cap.
2. **Wire progress / termination** (``irverify.progress``) — every
   array/map item subtree either consumes >= 1 wire byte per item
   (bounded by the record span) or is reachable only under the
   zero-width budget (``kMaxZeroWidthItems``), whose native guard must
   be anchored in the sources; block loops terminate on the zero count
   by the same anchor discipline. No schema can therefore compile to a
   non-terminating record decode.
3. **Overflow safety** (``irverify.overflow``) — symbolic int32/int64
   analysis of the offset/length/capacity lanes: every int32-narrowing
   sink an op writes (string lens, offsets running totals, merge
   rebase, fused prefix sums, enum expansion, encode positions) must
   carry a declared guard whose *anchor* — a source pattern naming the
   actual range check — is present in the native cores. Deleting a C++
   bound check (or its declaration) fails the gate; this is how the
   >2GiB string-length lane (``string_len_i32``, fixed in this PR) is
   kept fixed.
4. **Generic <-> specialized equivalence** (``irverify.equiv``) — the
   generated source's embedded tables are re-parsed and abstract-
   executed, its ``EFFECTS-v1`` journal (recorded by the code
   generators as they emit) is diffed against this module's own
   abstract walk, and the emitted bodies are censused for column
   references — a strictly stronger check than the PR 11 byte diff
   (a body that pushes the wrong column still embeds the right table).

A generative driver walks the schema-construct lattice (every op kind
x nullable x union-position x nesting depth) and a seeded mutation
self-test proves each invariant class still turns red; both land in
``IR_VERIFY_REPORT.json`` (see ``scripts/analysis_gate.py --ir``).
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding

__all__ = [
    "ProgramModel",
    "scan_native_guards",
    "verify_program",
    "verify_structure",
    "verify_progress",
    "verify_overflow",
    "verify_equivalence",
    "verify_optimized",
    "abstract_trace",
    "lattice_points",
    "run_lattice",
    "run_mutation_selftest",
    "run_ir_verification",
    "GUARD_ANCHORS",
    "AUX_CONSUMERS",
]

# ---------------------------------------------------------------------------
# the program model (engine-independent view of one opcode program)
# ---------------------------------------------------------------------------


class ProgramModel:
    """One opcode program as plain Python data: the generic lowering's
    arrays, or the specializer's embedded tables re-parsed out of a
    generated translation unit — both feed the same passes."""

    def __init__(self, ops: List[Tuple[int, int, int, int, int, int]],
                 coltypes: List[int], aux: Sequence, label: str,
                 col_regions: Optional[List[int]] = None):
        self.ops = [tuple(int(x) for x in row) for row in ops]
        self.coltypes = [int(c) for c in coltypes]
        self.aux = tuple(aux)
        self.label = label
        # per-column region id the LOWERING declared (0 = rows, then
        # one id per array/map in pre-order) — None when the model was
        # re-parsed from a generated unit, which embeds no region table
        self.col_regions = col_regions

    @classmethod
    def from_host_program(cls, prog, label: str = "generic"):
        aux = prog.op_aux or tuple(None for _ in range(len(prog.ops)))
        return cls([tuple(int(x) for x in row) for row in prog.ops],
                   [int(c) for c in prog.coltypes], aux, label,
                   col_regions=[int(c.region) for c in prog.cols])

    @classmethod
    def from_generated_source(cls, src: str, coltypes: List[int],
                              label: str = "specialized"):
        """Re-parse the embedded ``kOps``/``kAux`` static tables out of
        a generated translation unit (coltypes are not embedded — the
        caller supplies the program's)."""
        m = re.search(r"static const Op kOps\[\] = \{(.*?)\};", src,
                      flags=re.S)
        rows = re.findall(
            r"\{(-?\d+), (-?\d+), (-?\d+), (-?\d+), (-?\d+), (-?\d+)\},",
            m.group(1) if m else "")
        ops = [tuple(int(x) for x in r) for r in rows]
        # symbol byte arrays: kSym_<op>_<k>[] = {98, 97, 0}
        syms: Dict[Tuple[int, int], bytes] = {}
        for om, km, body in re.findall(
                r"static const char kSym_(\d+)_(\d+)\[\] = \{([^}]*)\};",
                src):
            vals = [int(v) for v in body.split(",") if v.strip()]
            if vals and vals[-1] == 0:
                vals = vals[:-1]  # the NUL terminator
            syms[(int(om), int(km))] = bytes(vals)
        m = re.search(r"static const OpAux kAux\[\] = \{(.*?)\};", src,
                      flags=re.S)
        entries = re.findall(r"\{(AUX_\w+), ([^,]+), [^,]+, (\w+)\},",
                             m.group(1) if m else "")
        aux: List[Optional[tuple]] = []
        for i, (lane, symref, last) in enumerate(entries):
            if lane == "AUX_NONE":
                aux.append(None)
            elif lane == "AUX_UUID":
                aux.append(("uuid",))
            elif lane == "AUX_BINARY":
                aux.append(("binary",))
            elif lane == "AUX_DURATION":
                aux.append(("duration",))
            elif lane == "AUX_DECIMAL":
                aux.append(("decimal", int(last)))
            elif lane == "AUX_ENUM":
                sm = re.match(r"kSyms_(\d+)", symref.strip())
                oi = int(sm.group(1)) if sm else i
                n = int(last)
                aux.append(("enum",) + tuple(syms.get((oi, k), b"")
                                             for k in range(n)))
            else:
                aux.append(("?" + lane,))
        return cls(ops, coltypes, tuple(aux), label)

    @property
    def ncols(self) -> int:
        return len(self.coltypes)


# ---------------------------------------------------------------------------
# native guard anchors: the symbolic pass's link to the real sources
# ---------------------------------------------------------------------------

# guard name -> [(repo-relative file, raw-text regex)]: EVERY pattern
# must match for the guard to count as present. The patterns name the
# actual range checks (or audited design notes) in the native cores and
# the specializer's codegen strings, so deleting a bound check in C++
# breaks the declaration in hostpath/program.py OP_EFFECTS and the gate
# goes red — the declaration cannot rot into a rubber stamp.
GUARD_ANCHORS: Dict[str, List[Tuple[str, str]]] = {
    # OP_INT truncates the 64-bit zigzag to its low 32 bits BY CONTRACT
    # (matches the device walk); the audited note is the anchor
    "int_low32_by_design": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp",
         r"low-32 like the device walk"),
    ],
    # rd_string: length bounded by the remaining span...
    "string_len_span": [
        ("pyruhvro_tpu/runtime/native/host_vm_core.h",
         r"len > r\.end - r\.cur"),
    ],
    # ...AND by int32 before landing in the lens lane (the 2GiB lane
    # this PR fixed); the fallback reader mirrors it for tier agreement
    "string_len_i32": [
        ("pyruhvro_tpu/runtime/native/host_vm_core.h",
         r"len > \(int64_t\)INT32_MAX"),
        ("pyruhvro_tpu/fallback/io.py", r"ln > 0x7FFFFFFF"),
    ],
    "enum_range": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp",
         r"v < 0 \|\| v >= op\.a"),
        ("pyruhvro_tpu/hostpath/specialize.py",
         r"v\{u\} < 0 \|\| v\{u\} >= \{a\}"),
    ],
    "union_branch_range": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp",
         r"br < 0 \|\| br >= op\.a"),
        ("pyruhvro_tpu/hostpath/specialize.py",
         r"br\{u\} < 0 \|\| br\{u\} >= \{a\}"),
    ],
    # offsets running totals are int32 and checked after each increment
    # in BOTH engines
    "offs_running_i32": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp",
         r"offs\.running < 0"),
        ("pyruhvro_tpu/hostpath/specialize.py", r"\.running < 0"),
    ],
    # shard-merge rebase of offsets columns
    "merge_offsets_i32": [
        ("pyruhvro_tpu/runtime/native/host_vm_core.h", r"v > INT32_MAX"),
    ],
    # fused finalize: string offsets prefix sums fall back past int32
    "fused_str_offsets_i32": [
        ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
         r"acc > INT32_MAX"),
    ],
    # fused finalize: enum symbol expansion capped at 2 GiB
    "enum_expand_2gib": [
        ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
         r"total >= \(\(int64_t\)1 << 31\)"),
    ],
    # fused finalize: repeated-node offsets rebase
    "repeated_offsets_i32": [
        ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
         r"val > INT32_MAX"),
    ],
    # duration ms total bounded before the int64 store
    "duration_ms_i64": [
        ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
         r"total > \(uint64_t\)INT64_MAX"),
    ],
    # optimizer-fused member run (OP_FIXED_RUN, a=1): ONE upfront span
    # check justifies every unchecked member read on the bulk lane
    "fixed_run_span": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp",
         r"op\.b <= \(int64_t\)\(r\.end - r\.cur\)"),
    ],
    # encode wire position checked against int32 offsets per record
    "encode_pos_i32": [
        ("pyruhvro_tpu/runtime/native/host_vm_core.h",
         r"pos > \(size_t\)INT32_MAX"),
    ],
    # zero-width items charge the per-record budget in every engine
    # (and the fallback walker agrees on the constant)
    "zero_width_budget": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp",
         r"zw > kMaxZeroWidthItems"),
        ("pyruhvro_tpu/hostpath/specialize.py", r"kMaxZeroWidthItems"),
        ("pyruhvro_tpu/fallback/io.py", r"MAX_ZERO_WIDTH_ITEMS"),
    ],
    # block loops terminate on the zero count in both engines
    "block_zero_terminates": [
        ("pyruhvro_tpu/runtime/native/host_codec.cpp", r"count == 0"),
        ("pyruhvro_tpu/hostpath/specialize.py", r"cnt\{u\} == 0"),
    ],
}

# aux tag -> {direction: consumer anchor (file, pattern)}. An aux entry
# whose tag has NO anchored consumer in ANY direction is dead weight in
# every embedded table (irverify.effect.dead-aux). Direction-scoped
# entries carry an audit note exported to the report: the encode
# extractor copies binary bytes verbatim (the UTF-8 contract only
# matters on decode) and trusts pyarrow's decimal128 precision
# enforcement (the declared precision is re-checked on decode only).
AUX_CONSUMERS: Dict[str, Dict[str, Tuple[str, str]]] = {
    "uuid": {
        "decode": ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
                   r"AUX_UUID"),
        "encode": ("pyruhvro_tpu/runtime/native/extract_core.h",
                   r"aux_\[pc\]\.lane == AUX_UUID"),
    },
    "binary": {
        "decode": ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
                   r"AUX_BINARY"),
        # encode: NOT consumed — audited: bytes copy verbatim either way
    },
    "duration": {
        "decode": ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
                   r"AUX_DURATION"),
        "encode": ("pyruhvro_tpu/runtime/native/extract_core.h",
                   r"aux_\[pc\]\.lane == AUX_DURATION"),
    },
    "decimal": {
        "decode": ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
                   r"AUX_DECIMAL"),
        # encode: NOT consumed — audited: pyarrow enforces precision on
        # the decimal128 column; wr_decimal checks the wire-size fit
    },
    "enum": {
        "decode": ("pyruhvro_tpu/runtime/native/arrow_decode_core.h",
                   r"AUX_ENUM"),
        "encode": ("pyruhvro_tpu/runtime/native/extract_core.h",
                   r"aux_\[pc\]\.lane != AUX_ENUM"),
    },
}

AUX_AUDIT_NOTES = {
    ("binary", "encode"):
        "bytes copy verbatim on encode; the UTF-8 contract is a "
        "decode-direction concern (arrow_decode_core.h string_entry)",
    ("decimal", "encode"):
        "pyarrow enforces declared precision on the decimal128 input "
        "column; the wire-size fit check lives in wr_decimal",
}


def scan_native_guards(root: str) -> Dict[str, bool]:
    """Which guard anchors are actually present in the tree at
    ``root``. Raw-text scan (some anchors are audited comments)."""
    out: Dict[str, bool] = {}
    cache: Dict[str, str] = {}
    for guard, pats in GUARD_ANCHORS.items():
        ok = True
        for rel, pat in pats:
            path = os.path.join(root, rel)
            if path not in cache:
                try:
                    with open(path, encoding="utf-8") as f:
                        cache[path] = f.read()
                except OSError:
                    cache[path] = ""
            if not re.search(pat, cache[path]):
                ok = False
        out[guard] = ok
    return out


def scan_aux_consumers(root: str) -> Dict[str, List[str]]:
    """tag -> directions whose consumer anchor is present at ``root``."""
    out: Dict[str, List[str]] = {}
    cache: Dict[str, str] = {}
    for tag, dirs in AUX_CONSUMERS.items():
        found = []
        for direction, (rel, pat) in dirs.items():
            path = os.path.join(root, rel)
            if path not in cache:
                try:
                    with open(path, encoding="utf-8") as f:
                        cache[path] = f.read()
                except OSError:
                    cache[path] = ""
            if re.search(pat, cache[path]):
                found.append(direction)
        out[tag] = sorted(found)
    return out


# ---------------------------------------------------------------------------
# pass 1: type/effect discipline (+ structural termination)
# ---------------------------------------------------------------------------


def _effects():
    from ..hostpath import program as hp

    return hp


def _default_max_depth() -> int:
    """The PYRUHVRO_TPU_MAX_DEPTH *registered default* — the verifier
    proves programs against the shipped walker cap, not whatever the
    current environment happens to tune it to (a tuned-down knob must
    not turn a pristine tree red)."""
    from ..runtime import knobs

    return int(knobs.registry()["PYRUHVRO_TPU_MAX_DEPTH"].default)


def verify_structure(m: ProgramModel,
                     max_depth: Optional[int] = None) -> List[Finding]:
    """Subtree tiling, column ownership/typing, push balance, aux
    arity/placement, nesting depth. Structural termination failures
    (``nops < 1`` — the walk would never advance) report under
    ``irverify.progress`` since they are non-termination bugs."""
    hp = _effects()
    findings: List[Finding] = []
    n = len(m.ops)
    path = m.label

    def f(rule, msg, pc=0):
        findings.append(Finding(rule, path, msg, pc))

    if n == 0:
        f("irverify.effect", "empty program")
        return findings
    if max_depth is None:
        max_depth = _default_max_depth()

    if len(m.aux) != n:
        f("irverify.effect",
          f"aux table has {len(m.aux)} entries for {n} ops")

    owners: Dict[int, int] = {}  # col -> pc

    def own(col: int, pc: int, what: str, want_ctype: int):
        if col < 0 or col >= m.ncols:
            f("irverify.effect",
              f"op {pc} ({what}): column index {col} out of range "
              f"[0, {m.ncols})", pc)
            return
        if col in owners:
            f("irverify.effect",
              f"op {pc} ({what}): column {col} already written by op "
              f"{owners[col]} — one writer per column", pc)
        owners[col] = pc
        got = m.coltypes[col]
        if got != want_ctype:
            f("irverify.effect",
              f"op {pc} ({what}): column {col} has ColType {got}, the "
              f"effect contract requires {want_ctype}", pc)

    max_seen_depth = 0

    def check_axis(counts: Dict[int, int], pc: int, what: str):
        """Per-axis push exactness: every column on this region axis
        appends exactly once per axis element — in BOTH execution
        modes (the engines append defaults/advance cursors for absent
        subtrees by construction; the equivalence pass checks the
        generated code actually does)."""
        bad = {c: k for c, k in counts.items() if k != 1}
        if bad:
            f("irverify.effect",
              f"{what}: column(s) {bad} appended != 1 time per "
              "element of their region axis — every later column "
              "would desync", pc)

    next_rid = [1]  # region ids in pre-order, like the lowering

    def region_check(c: int, pc: int, what: str, axis: int):
        """A column must live on the region axis the walk reaches it
        under — the lowering's declared region (prog.cols). An op
        absorbed into the wrong loop (corrupted ``nops``) appends per
        ITEM what the assembler consumes per RECORD."""
        if m.col_regions is None or not (0 <= c < len(m.col_regions)):
            return
        declared = m.col_regions[c]
        if declared != axis:
            f("irverify.effect",
              f"op {pc} ({what}): column {c} is declared in region "
              f"{declared} but the walk reaches it on axis {axis} — "
              "its per-element append cadence would not match the "
              "assembler's", pc)

    # returns (end_pc, counts) where counts maps col -> appends per
    # element of THIS region axis (identical for the present and
    # absent modes by the engines' default-append construction).
    # ``uncond`` tracks whether every ancestor is a plain record (or a
    # fused header inside one) — the reachability fact the optimizer's
    # FLAG_ALWAYS_PRESENT claim must be re-derived against.
    def walk(pc: int, depth: int, axis: int = 0, uncond: bool = True):
        nonlocal max_seen_depth
        max_seen_depth = max(max_seen_depth, depth)
        if pc >= n:
            f("irverify.progress",
              f"walk ran past the program end at pc {pc}", pc)
            return n, {}
        kind, a, b, col, nops, pad = m.ops[pc]
        if kind not in hp.OP_EFFECTS:
            f("irverify.effect", f"op {pc}: unknown kind {kind}", pc)
            return pc + 1, {}
        eff = hp.OP_EFFECTS[kind]
        name = hp.OP_NAMES[kind]
        if nops < 1:
            f("irverify.progress",
              f"op {pc} ({name}): nops={nops} < 1 — the walk cannot "
              "advance (non-terminating decode)", pc)
            return pc + 1, {}
        stop = pc + nops
        if stop > n:
            f("irverify.progress",
              f"op {pc} ({name}): subtree [pc, pc+{nops}) overruns the "
              f"program ({n} ops)", pc)
            stop = n

        # primary column discipline
        if eff["ctype"] is None:
            if col != -1:
                f("irverify.effect",
                  f"op {pc} ({name}): carries column {col} but the "
                  "effect contract declares none", pc)
        else:
            own(col, pc, name, eff["ctype"])
            region_check(col, pc, name, axis)
        if kind == hp.OP_MAP:
            own(b, pc, "map-key", hp.COL_STR)

        # aux placement / arity
        aux = m.aux[pc] if pc < len(m.aux) else None
        allowed = eff["aux"]
        tag = aux[0] if aux else None
        plain = tuple(t.lstrip("!") if isinstance(t, str) else t
                      for t in allowed)
        required = [t[1:] for t in allowed
                    if isinstance(t, str) and t.startswith("!")]
        if tag not in plain:
            f("irverify.effect",
              f"op {pc} ({name}): aux tag {tag!r} not permitted "
              f"(allowed: {plain})", pc)
        elif required and tag not in required:
            f("irverify.effect",
              f"op {pc} ({name}): required aux {required} missing", pc)
        if tag == "enum":
            nsyms = len(aux) - 1
            if nsyms != a or a < 1:
                f("irverify.effect",
                  f"op {pc} (enum): aux carries {nsyms} symbols, op.a "
                  f"= {a} — the fused decode indexes symbols by the "
                  "range check on op.a", pc)
        if tag == "decimal":
            if len(aux) < 2 or int(aux[1]) < 1:
                f("irverify.effect",
                  f"op {pc} ({name}): decimal aux needs precision >= 1 "
                  f"(got {aux[1:]!r})", pc)
        if kind == hp.OP_ENUM and a < 1:
            f("irverify.effect", f"op {pc} (enum): no symbols (a={a})",
              pc)
        if kind == hp.OP_NULLABLE and a not in (0, 1):
            f("irverify.effect",
              f"op {pc} (nullable): null index {a} not 0/1", pc)
        if kind == hp.OP_UNION and a < 1:
            f("irverify.effect", f"op {pc} (union): a={a} arms", pc)
        if kind in (hp.OP_FIXED, hp.OP_DEC_FIXED) and a < 0:
            f("irverify.effect", f"op {pc} ({name}): size a={a} < 0", pc)

        # pad-flag discipline: the optimizer's proof-carrying bits are
        # only meaningful on the ops whose engines read them; a stray
        # bit elsewhere is a corrupted (or misapplied) rewrite
        allowed_pad = 0
        if kind == hp.OP_FIXED_RUN:
            allowed_pad = hp.FLAG_ALWAYS_PRESENT
        elif kind in (hp.OP_ARRAY, hp.OP_MAP):
            allowed_pad = hp.FLAG_STR_ITEMS
        if pad & ~allowed_pad:
            f("irverify.optimize",
              f"op {pc} ({name}): pad flag bits {pad:#x} are not "
              "permitted on this op kind", pc)

        counts: Dict[int, int] = {}

        def push(counts_, c, k=1):
            if c >= 0:
                counts_[c] = counts_.get(c, 0) + k

        if eff["ctype"] is not None:
            push(counts, col)

        if kind == hp.OP_RECORD:
            p = pc + 1
            while p < stop:
                p, cp = walk(p, depth + 1, axis, uncond)
                for c, k in cp.items():
                    push(counts, c, k)
            if p != stop:
                f("irverify.effect",
                  f"op {pc} (record): children end at {p}, nops claims "
                  f"{stop}", pc)
        elif kind == hp.OP_FIXED_RUN:
            # optimizer-emitted header (hostpath/optimize.py): >= 2
            # plain fixed-layout leaves of one record, walked on the
            # SAME axis. Every operand claim is re-derived, never
            # trusted: b must equal the members' summed wire floors
            # (the bulk lane's span pre-check admits exactly b bytes)
            # and a=1 only when every member is exact-width — one span
            # check cannot bound a varint member's reads.
            fusable = {hp.OP_INT: 1, hp.OP_LONG: 1, hp.OP_FLOAT: 4,
                       hp.OP_DOUBLE: 8, hp.OP_BOOL: 1}
            exact_kinds = (hp.OP_FLOAT, hp.OP_DOUBLE, hp.OP_BOOL)
            member_pcs = []
            p = pc + 1
            while p < stop:
                member_pcs.append(p)
                mk = m.ops[p][0]
                maux = m.aux[p] if p < len(m.aux) else None
                if mk not in fusable or maux is not None:
                    f("irverify.optimize",
                      f"op {pc} (fixed_run): member at pc {p} "
                      f"(kind {hp.OP_NAMES.get(mk, mk)}, aux={maux!r}) "
                      "is not a plain fixed-layout leaf — the bulk "
                      "lane would misread the wire", pc)
                p, cp = walk(p, depth + 1, axis, uncond)
                for c, k in cp.items():
                    push(counts, c, k)
            if p != stop:
                f("irverify.effect",
                  f"op {pc} (fixed_run): members end at {p}, nops "
                  f"claims {stop}", pc)
            if len(member_pcs) < 2:
                f("irverify.optimize",
                  f"op {pc} (fixed_run): {len(member_pcs)} member(s) "
                  "— a fused header must absorb >= 2 leaves", pc)
            width = sum(fusable.get(m.ops[q][0], 0)
                        for q in member_pcs)
            if b != width:
                f("irverify.optimize",
                  f"op {pc} (fixed_run): b={b} but the members' wire "
                  f"floors sum to {width} — the span pre-check would "
                  "mis-bound the bulk reads", pc)
            want_exact = int(bool(member_pcs) and all(
                m.ops[q][0] in exact_kinds for q in member_pcs))
            if a != want_exact:
                f("irverify.optimize",
                  f"op {pc} (fixed_run): a={a} but exact-width is "
                  f"{want_exact} — a=1 over varint members licenses "
                  "unchecked reads one span check cannot bound", pc)
            if (pad & hp.FLAG_ALWAYS_PRESENT) and not uncond:
                f("irverify.optimize",
                  f"op {pc} (fixed_run): FLAG_ALWAYS_PRESENT under a "
                  "conditional ancestor chain — the bulk lane would "
                  "consume wire bytes for an absent subtree", pc)
        elif kind == hp.OP_NULLABLE:
            # both the live and the null side execute the inner subtree
            # (live decodes, null appends defaults) — same counts
            p, cp = walk(pc + 1, depth + 1, axis, False)
            for c, k in cp.items():
                push(counts, c, k)
            if p != stop:
                f("irverify.effect",
                  f"op {pc} (nullable): inner ends at {p}, nops claims "
                  f"{stop}", pc)
        elif kind == hp.OP_UNION:
            p = pc + 1
            for _k in range(a):
                if p >= stop:
                    f("irverify.effect",
                      f"op {pc} (union): arm {_k} of {a} missing "
                      f"(subtree exhausted at {p})", pc)
                    break
                p, cp = walk(p, depth + 1, axis, False)
                for c, k in cp.items():
                    push(counts, c, k)
            if p != stop:
                f("irverify.effect",
                  f"op {pc} (union): arms end at {p}, nops claims "
                  f"{stop}", pc)
        elif kind in (hp.OP_ARRAY, hp.OP_MAP):
            # the item subtree appends on the ITEM axis: its own
            # exactness boundary; nothing lands on this axis's counts.
            # Region ids run in pre-order, exactly like the lowering's
            rid = next_rid[0]
            next_rid[0] += 1
            if kind == hp.OP_MAP:
                region_check(b, pc, "map-key", rid)
            if pad & hp.FLAG_STR_ITEMS:
                # the optimizer's pre-decided string block lane: the
                # claim must match the engines' own runtime test
                # (item subtree == exactly one OP_STRING leaf)
                item_kind = m.ops[pc + 1][0] if pc + 1 < n else None
                if nops != 2 or item_kind != hp.OP_STRING:
                    f("irverify.optimize",
                      f"op {pc} ({name}): FLAG_STR_ITEMS but the item "
                      f"subtree is not a single string leaf "
                      f"(nops={nops}, item kind={item_kind}) — the "
                      "string block lane would misread the items", pc)
            p, cp = walk(pc + 1, depth + 1, rid, False)
            if kind == hp.OP_MAP:
                push(cp, b)  # the key column, once per item
            check_axis(cp, pc, f"op {pc} ({name}) item axis")
            if p != stop:
                f("irverify.effect",
                  f"op {pc} ({name}): item subtree ends at {p}, nops "
                  f"claims {stop}", pc)
        else:
            if nops != 1:
                f("irverify.effect",
                  f"op {pc} ({name}): leaf with nops={nops}", pc)
        return stop, counts

    end, counts = walk(0, 1)
    if end != n:
        f("irverify.effect",
          f"program has {n} ops but the root subtree ends at {end}")
    check_axis(counts, 0, "row axis")
    orphans = [c for c in range(m.ncols) if c not in owners]
    if orphans:
        f("irverify.effect",
          f"column(s) {orphans} allocated but written by no op — dead "
          "buffers in every decode")
    if max_seen_depth > max_depth:
        f("irverify.effect",
          f"validity/structure chain nests {max_seen_depth} deep, past "
          f"the PYRUHVRO_TPU_MAX_DEPTH walker cap ({max_depth}) — the "
          "fallback oracle would refuse what the VM accepts")
    return findings


def verify_aux_consumption(m: ProgramModel,
                           consumers: Dict[str, List[str]]) -> List[Finding]:
    """Every aux entry's tag must have at least one anchored consumer
    direction (``irverify.effect.dead-aux``)."""
    findings = []
    for pc, aux in enumerate(m.aux):
        if not aux:
            continue
        tag = aux[0]
        dirs = consumers.get(tag)
        if not dirs:
            findings.append(Finding(
                "irverify.effect", m.label,
                f"op {pc}: aux entry {tag!r} is emitted into the "
                "tables but consumed by no direction (dead aux) — "
                "either a consumer lost its read or the emission is "
                "vestigial", pc))
    return findings


# ---------------------------------------------------------------------------
# pass 2: wire progress / termination
# ---------------------------------------------------------------------------


def _min_wire(m: ProgramModel, pc: int) -> Tuple[int, int]:
    """(end_pc, minimum wire bytes one present execution consumes)."""
    hp = _effects()
    kind, a, b, col, nops, _pad = m.ops[pc]
    stop = pc + max(nops, 1)
    if kind in (hp.OP_RECORD, hp.OP_FIXED_RUN):
        # a fused header consumes nothing itself; its members still
        # account their own floors (op.b only SUMMARIZES them)
        total = 0
        p = pc + 1
        while p < stop:
            p, mb = _min_wire(m, p)
            total += mb
        return stop, total
    if kind == hp.OP_NULLABLE:
        # branch varint (1) + min over {null side: 0, live side}
        return stop, 1
    if kind == hp.OP_UNION:
        # tid varint (1) + the cheapest arm
        p = pc + 1
        arm_min = None
        for _ in range(max(a, 1)):
            if p >= stop:
                break
            p, mb = _min_wire(m, p)
            arm_min = mb if arm_min is None else min(arm_min, mb)
        return stop, 1 + (arm_min or 0)
    if kind in (hp.OP_ARRAY, hp.OP_MAP):
        # zero items: one block-count varint (the 0 terminator)
        return stop, 1
    eff = hp.OP_EFFECTS.get(kind)
    if eff is None:
        return stop, 0
    mw = eff["min_wire"]
    return stop, (a if mw == "a" else mw)


def verify_progress(m: ProgramModel,
                    guards: Dict[str, bool]) -> List[Finding]:
    """Every array/map item loop either consumes >= 1 wire byte per
    item (count bounded by the record span) or is reachable only under
    the anchored zero-width budget; block loops terminate on the zero
    count. Returns loop inventory findings."""
    hp = _effects()
    findings: List[Finding] = []
    loops: List[dict] = []

    def walk(pc: int):
        if pc >= len(m.ops):
            return pc
        kind, a, b, col, nops, _pad = m.ops[pc]
        stop = pc + max(nops, 1)
        if kind in (hp.OP_ARRAY, hp.OP_MAP):
            _, item_min = _min_wire(m, pc + 1)
            if kind == hp.OP_MAP:
                item_min += 1  # the key length varint
            zw = item_min == 0
            loops.append({"pc": pc, "kind": hp.OP_NAMES[kind],
                          "item_min_bytes": item_min,
                          "zw_capped": zw})
            if zw and not guards.get("zero_width_budget"):
                findings.append(Finding(
                    "irverify.progress", m.label,
                    f"op {pc} ({hp.OP_NAMES[kind]}): item subtree "
                    "consumes 0 wire bytes and the zero-width budget "
                    "guard (kMaxZeroWidthItems) is not anchored in the "
                    "engines — a 3-byte block header could demand 2^60 "
                    "items (non-terminating/unbounded decode)", pc))
            if not guards.get("block_zero_terminates"):
                findings.append(Finding(
                    "irverify.progress", m.label,
                    f"op {pc} ({hp.OP_NAMES[kind]}): block loop "
                    "zero-count termination is not anchored in the "
                    "engines", pc))
            walk(pc + 1)
            return stop
        if kind in (hp.OP_RECORD, hp.OP_NULLABLE, hp.OP_UNION,
                    hp.OP_FIXED_RUN):
            p = pc + 1
            while p < stop:
                p = walk(p)
            return stop
        return stop

    walk(0)
    verify_progress.last_loops = loops  # inventory for the report
    return findings


# ---------------------------------------------------------------------------
# pass 3: overflow safety (symbolic int32/int64 lanes vs guard anchors)
# ---------------------------------------------------------------------------

# aux-conditional sinks folded in on top of OP_EFFECTS' static ones
_AUX_SINKS = {
    "duration": (("duration_total", ("duration_ms_i64",)),),
    "enum": (("enum_expand", ("enum_expand_2gib",)),),
}


def verify_overflow(m: ProgramModel,
                    guards: Dict[str, bool]) -> List[Finding]:
    hp = _effects()
    findings: List[Finding] = []
    lanes: List[dict] = []

    def check(pc, op_name, lane, needed):
        missing = [g for g in needed if not guards.get(g)]
        lanes.append({"pc": pc, "op": op_name, "lane": lane,
                      "guards": list(needed),
                      "missing": missing})
        if missing:
            findings.append(Finding(
                "irverify.overflow", m.label,
                f"op {pc} ({op_name}): int32 lane {lane!r} is "
                f"unguarded — native guard anchor(s) {missing} not "
                "found in the sources (a value past the bound would "
                "silently wrap at serving-plane scale)", pc))

    has_ops = False
    for pc, row in enumerate(m.ops):
        kind = row[0]
        eff = hp.OP_EFFECTS.get(kind)
        if eff is None:
            continue
        has_ops = True
        name = hp.OP_NAMES[kind]
        for lane, needed in eff["sinks"]:
            check(pc, name, lane, needed)
        if kind == hp.OP_STRING:
            check(pc, name, "fused_offsets", ("fused_str_offsets_i32",))
        if kind in (hp.OP_ARRAY, hp.OP_MAP):
            check(pc, name, "repeated_offsets",
                  ("repeated_offsets_i32",))
        aux = m.aux[pc] if pc < len(m.aux) else None
        if aux:
            for lane, needed in _AUX_SINKS.get(aux[0], ()):
                check(pc, name, lane, needed)
    if has_ops:
        # the encode wire position is a program-level int32 lane
        check(0, "program", "encode_pos", ("encode_pos_i32",))
    verify_overflow.last_lanes = lanes
    return findings


# ---------------------------------------------------------------------------
# pass 4: generic <-> specialized equivalence
# ---------------------------------------------------------------------------


def abstract_trace(m: ProgramModel) -> List[Tuple[int, int, int, tuple]]:
    """The canonical effect trace: (pc, kind, col, aux-signature) in
    walk order — what any correct engine must do, in the order it must
    do it."""
    out = []
    for pc, row in enumerate(m.ops):
        kind, a, b, col, nops, _pad = row
        aux = m.aux[pc] if pc < len(m.aux) else None
        sig: tuple = ()
        if aux:
            if aux[0] == "enum":
                sig = ("enum", len(aux) - 1, tuple(aux[1:]))
            else:
                sig = tuple(aux)
        out.append((pc, kind, col, sig))
    return out


def _effects_trailer(src: str) -> Optional[dict]:
    m = re.search(r"// EFFECTS-v1 (\{.*\})", src)
    if m is None:
        return None
    try:
        return json.loads(m.group(1))
    except ValueError:
        return None


def verify_equivalence(prog, src: Optional[str] = None,
                       label: str = "specialized") -> List[Finding]:
    """Diff the specializer's generated translation unit against the
    generic program it was generated from: re-parsed embedded tables
    (abstract-executed, not byte-diffed), the generators' EFFECTS-v1
    journals vs this module's abstract walk, and a column-reference
    census of the emitted decode/encode bodies — both directions."""
    from ..hostpath.specialize import generate_source

    findings: List[Finding] = []
    gm = ProgramModel.from_host_program(prog, "generic")
    if src is None:
        src = generate_source(prog, "M", with_effects=True)

    sm = ProgramModel.from_generated_source(src, gm.coltypes, label)
    want = abstract_trace(gm)
    got = abstract_trace(sm)
    if len(got) != len(want):
        findings.append(Finding(
            "irverify.equiv", label,
            f"specialized tables carry {len(got)} ops, the generic "
            f"program {len(want)}"))
    else:
        for (wpc, wk, wc, ws), (gpc, gk, gc, gs) in zip(want, got):
            if (wk, wc, ws) != (gk, gc, gs):
                findings.append(Finding(
                    "irverify.equiv", label,
                    f"effect trace diverges at pc {wpc}: generic "
                    f"(kind={wk}, col={wc}, aux={ws!r}) vs specialized "
                    f"(kind={gk}, col={gc}, aux={gs!r})", wpc))
        for i, (wrow, grow) in enumerate(zip(gm.ops, sm.ops)):
            if tuple(wrow[:5]) != tuple(grow[:5]):
                findings.append(Finding(
                    "irverify.equiv", label,
                    f"kOps[{i}] = {tuple(grow[:5])} but the program "
                    f"row is {tuple(wrow[:5])}", i))

    # the generators' own journals: every op handled live exactly once,
    # in program order, with the table's (kind, col)
    trailer = _effects_trailer(src)
    if trailer is None:
        findings.append(Finding(
            "irverify.equiv", label,
            "generated source carries no EFFECTS-v1 trailer (generate "
            "with with_effects=True)"))
    else:
        n = len(gm.ops)
        for direction in ("decode", "encode"):
            events = trailer.get(direction, [])
            live = [(pc, k, c) for mode, pc, k, c in events
                    if mode in ("live", "cond")]
            live_pcs = [pc for pc, _k, _c in live]
            if sorted(live_pcs) != list(range(n)):
                findings.append(Finding(
                    "irverify.equiv", label,
                    f"{direction} generator handled pcs "
                    f"{sorted(set(live_pcs))[:8]}... live "
                    f"{len(live_pcs)} times for {n} ops — every op "
                    "must be emitted live exactly once"))
                continue
            if live_pcs != sorted(live_pcs):
                findings.append(Finding(
                    "irverify.equiv", label,
                    f"{direction} generator emitted live ops out of "
                    "program order"))
            for pc, k, c in live:
                wk, _a, _b, wc = gm.ops[pc][:4]
                if (k, c) != (wk, wc):
                    findings.append(Finding(
                        "irverify.equiv", label,
                        f"{direction} generator journal at pc {pc}: "
                        f"(kind={k}, col={c}) vs program (kind={wk}, "
                        f"col={wc})", pc))

    # column-reference census: every owned column must be referenced in
    # both emitted bodies (a dropped column compiles fine and silently
    # desyncs the cursors)
    hp = _effects()
    owned = set()
    for row in gm.ops:
        kind, _a, b, col = row[0], row[1], row[2], row[3]
        if col >= 0:
            owned.add(col)
        if kind == hp.OP_MAP and b >= 0:
            owned.add(b)
    dec_m = re.search(
        r"inline void decode_record\(.*?\n\}", src, flags=re.S)
    enc_m = re.search(r"struct EncRec \{.*?\n\};", src, flags=re.S)
    for direction, bm in (("decode", dec_m), ("encode", enc_m)):
        if bm is None:
            findings.append(Finding(
                "irverify.equiv", label,
                f"could not locate the {direction} body in the "
                "generated source"))
            continue
        refs = {int(c) for c in re.findall(r"\bC(\d+)\b", bm.group(0))}
        missing = sorted(owned - refs)
        if missing:
            findings.append(Finding(
                "irverify.equiv", label,
                f"{direction} body never references column(s) "
                f"{missing} the program writes — cursor desync", 0))
    return findings


# ---------------------------------------------------------------------------
# the combined per-program verdict
# ---------------------------------------------------------------------------


def verify_program(prog, guards: Dict[str, bool],
                   consumers: Dict[str, List[str]],
                   label: str = "program",
                   equivalence: bool = True,
                   max_depth: Optional[int] = None) -> List[Finding]:
    m = ProgramModel.from_host_program(prog, label)
    findings = verify_structure(m, max_depth=max_depth)
    findings += verify_aux_consumption(m, consumers)
    findings += verify_progress(m, guards)
    findings += verify_overflow(m, guards)
    if equivalence:
        findings += verify_equivalence(prog, label=label)
    return findings


def verify_optimized(orig, opt, guards: Dict[str, bool],
                     consumers: Dict[str, List[str]],
                     label: str = "optimized") -> List[Finding]:
    """The superoptimizer's equivalence oracle
    (``hostpath/optimize.py``). The optimized program must (1) pass
    every abstract-interpretation pass on its own — including the
    ``irverify.optimize`` re-derivation of each fused header's operand
    claims and flag bits — and (2) strip back to the ORIGINAL program
    byte-for-byte (headers spliced out, flags cleared, ancestor
    ``nops`` restored): a rewrite that cannot round-trip is by
    definition not effect-preserving. Zero findings proves the
    rewrite; ANY finding makes the caller reject the program (it is
    counted, never run)."""
    findings = verify_program(opt, guards, consumers, label=label,
                              equivalence=False)
    try:
        from ..hostpath.optimize import strip_optimizations

        stripped = strip_optimizations(opt)
    except Exception as e:
        findings.append(Finding(
            "irverify.optimize", label,
            f"optimized program does not strip back to a raw program: "
            f"{type(e).__name__}: {e}"))
        return findings
    got = [tuple(int(x) for x in row) for row in stripped.ops]
    want = [tuple(int(x) for x in row) for row in orig.ops]
    if got != want:
        i = next((k for k, (x, y) in enumerate(zip(got, want))
                  if x != y), min(len(got), len(want)))
        findings.append(Finding(
            "irverify.optimize", label,
            f"strip(optimized) != original program: {len(got)} vs "
            f"{len(want)} ops, first divergence at stripped pc {i} — "
            "the rewrite reordered or altered a member op", i))

    def norm_aux(p, count):
        ax = tuple(p.op_aux or ())
        return ax if ax else (None,) * count

    if norm_aux(stripped, len(got)) != norm_aux(orig, len(want)):
        findings.append(Finding(
            "irverify.optimize", label,
            "strip(optimized) aux table != original aux table — the "
            "rewrite moved or dropped a logical-type fact"))
    if [int(c) for c in stripped.coltypes] != \
            [int(c) for c in orig.coltypes]:
        findings.append(Finding(
            "irverify.optimize", label,
            "strip(optimized) coltypes != original coltypes"))
    return findings


# ---------------------------------------------------------------------------
# the schema-construct lattice driver
# ---------------------------------------------------------------------------

# every construct the lowering can emit, each tagged with the op kinds
# it covers; names are uniquified per lattice point (Avro named types)
_CONSTRUCTS = [
    ("int", lambda u: '"int"'),
    ("long", lambda u: '"long"'),
    ("float", lambda u: '"float"'),
    ("double", lambda u: '"double"'),
    ("boolean", lambda u: '"boolean"'),
    ("string", lambda u: '"string"'),
    ("uuid", lambda u: '{"type": "string", "logicalType": "uuid"}'),
    ("bytes", lambda u: '"bytes"'),
    ("dec_bytes", lambda u: '{"type": "bytes", "logicalType": '
                            '"decimal", "precision": 10, "scale": 2}'),
    ("enum", lambda u: '{"type": "enum", "name": "E%s", "symbols": '
                       '["A", "B", "C"]}' % u),
    ("null", lambda u: '"null"'),
    ("nullable", lambda u: '["null", "int"]'),
    ("union", lambda u: '["int", "string", "null"]'),
    ("array", lambda u: '{"type": "array", "items": "int"}'),
    ("map", lambda u: '{"type": "map", "values": "string"}'),
    ("fixed", lambda u: '{"type": "fixed", "name": "F%s", "size": 8}'
                        % u),
    ("duration", lambda u: '{"type": "fixed", "name": "Du%s", "size": '
                           '12, "logicalType": "duration"}' % u),
    ("dec_fixed", lambda u: '{"type": "fixed", "name": "Df%s", "size": '
                            '16, "logicalType": "decimal", '
                            '"precision": 20, "scale": 4}' % u),
    ("record", lambda u: '{"type": "record", "name": "Sub%s", '
                         '"fields": [{"name": "x", "type": "int"}]}'
                         % u),
    # optimizer coverage: records whose adjacent fixed-layout leaves
    # fuse into OP_FIXED_RUN — exact-width (bulk-lane a=1) and
    # varint-mixed (dispatch-only a=0) — so the lattice verifies the
    # fused-op programs the engines actually execute, not just the raw
    # lowerings
    ("exact_run_rec", lambda u: '{"type": "record", "name": "Xr%s", '
                                '"fields": [{"name": "a", "type": '
                                '"double"}, {"name": "b", "type": '
                                '"float"}, {"name": "c", "type": '
                                '"boolean"}]}' % u),
    ("varint_run_rec", lambda u: '{"type": "record", "name": "Vr%s", '
                                 '"fields": [{"name": "a", "type": '
                                 '"long"}, {"name": "b", "type": '
                                 '"int"}, {"name": "c", "type": '
                                 '"double"}]}' % u),
]

_UNION_LIKE = ("nullable", "union")


def lattice_depths() -> Tuple[int, int, int]:
    """Lattice depth samples derived from the shipped walker cap: the
    deepest sample nests to cap - 4 (the wrapping record/union
    constructs add up to 3 more levels), so the deepest verified
    points track the cap instead of silently colliding with it."""
    cap = _default_max_depth()
    return (1, 8, max(3, cap - 4))


def lattice_points(depths: Optional[Sequence[int]] = None) -> List[dict]:
    """The full schema-construct lattice: construct x nullable-wrap x
    union-position x nesting depth. Avro-invalid combinations (a union
    may not immediately contain a union; the null wrap duplicates a
    null arm) are enumerated with their skip reason so coverage is
    measured over the CONSTRUCTIBLE set, with nothing silently
    dropped."""
    if depths is None:
        depths = lattice_depths()
    points = []
    uid = 0
    for cname, mk in _CONSTRUCTS:
        for nullable in (False, True):
            for in_union in (False, True):
                for depth in depths:
                    uid += 1
                    point = {
                        "id": f"{cname}/null={int(nullable)}/"
                              f"union={int(in_union)}/d={depth}",
                        "construct": cname, "nullable": nullable,
                        "in_union": in_union, "depth": depth,
                    }
                    skip = None
                    if cname in _UNION_LIKE and (nullable or in_union):
                        skip = ("Avro forbids a union immediately "
                                "inside a union")
                    elif cname == "null" and nullable:
                        skip = ('["null", "null"] duplicates the null '
                                "arm")
                    if skip:
                        point["status"] = "skipped-invalid"
                        point["reason"] = skip
                        points.append(point)
                        continue
                    inner = mk(uid)
                    if nullable and in_union:
                        # null + construct + partner: nullable inside a
                        # true multi-arm union
                        typ = f'["null", {inner}, "long"]' \
                            if cname != "long" else \
                            f'["null", {inner}, "double"]'
                    elif nullable:
                        typ = f'["null", {inner}]'
                    elif in_union:
                        partners = [p for p in ('"long"', '"double"',
                                                '"boolean"')
                                    if p.strip('"') != cname][:2]
                        typ = f'[{inner}, {", ".join(partners)}]'
                    else:
                        typ = inner
                    for d in range(depth - 1):
                        typ = ('{"type": "record", "name": '
                               f'"D{uid}_{d}", "fields": [{{"name": '
                               f'"f", "type": {typ}}}]}}')
                    point["schema"] = (
                        '{"type": "record", "name": "Top%d", "fields":'
                        ' [{"name": "v", "type": %s}]}' % (uid, typ))
                    points.append(point)
    return points


def run_lattice(guards: Dict[str, bool],
                consumers: Dict[str, List[str]],
                depths: Optional[Sequence[int]] = None,
                equivalence: bool = True,
                optimizer: bool = True):
    """Verify every constructible lattice point; returns
    (findings, report-dict with per-point verdicts + coverage). With
    ``optimizer`` (the default) every point's program is ALSO run
    through the superoptimizer — whose internal oracle re-verifies the
    rewritten program against this module's passes — so the lattice
    covers the fused-op programs the engines actually execute, and a
    rewrite the oracle rejects on any constructible schema is a gate
    finding."""
    from ..hostpath.program import lower_host
    from ..schema.parser import parse_schema

    findings: List[Finding] = []
    points = lattice_points(depths)
    constructible = verified = optimized = fused_runs = 0
    for point in points:
        if point.get("status") == "skipped-invalid":
            continue
        constructible += 1
        label = f"lattice:{point['id']}"
        try:
            prog = lower_host(parse_schema(point["schema"]))
        except Exception as e:  # lowering refused a constructible point
            point["status"] = "error"
            point["reason"] = f"{type(e).__name__}: {e}"
            findings.append(Finding(
                "irverify.lattice", label,
                f"constructible lattice point failed to lower: {e}"))
            continue
        fs = verify_program(prog, guards, consumers, label=label,
                            equivalence=equivalence)
        if optimizer:
            from ..hostpath.optimize import optimize_program

            try:
                _opt, ost = optimize_program(prog)
            except Exception as e:
                fs.append(Finding(
                    "irverify.optimize", label,
                    f"optimizer crashed on a lattice point: "
                    f"{type(e).__name__}: {e}"))
            else:
                if ost.applied or ost.rejected:
                    point["optimizer"] = {
                        "applied": ost.applied,
                        "fused_runs": ost.fused_runs,
                        "always_present": ost.always_present,
                        "str_items": ost.str_items,
                        "rejected": ost.rejected,
                    }
                if ost.applied:
                    optimized += 1
                    fused_runs += ost.fused_runs
                if ost.rejected:
                    fs.append(Finding(
                        "irverify.optimize", label,
                        "optimizer rewrite rejected by the "
                        "equivalence oracle on a constructible "
                        "lattice point — the rewrite pass is unsound "
                        f"here: {ost.findings[:2]!r}"))
        if fs:
            point["status"] = "failed"
            point["findings"] = [f.to_dict() for f in fs]
            findings.extend(fs)
        else:
            point["status"] = "verified"
            verified += 1
    coverage = {
        "points": len(points),
        "constructible": constructible,
        "verified": verified,
        "skipped_invalid": sum(1 for p in points
                               if p.get("status") == "skipped-invalid"),
        "coverage_pct": round(100.0 * verified / constructible, 2)
        if constructible else 0.0,
    }
    if optimizer:
        coverage["optimized"] = optimized
        coverage["fused_runs"] = fused_runs
    return findings, {"points": points, "coverage": coverage}


# ---------------------------------------------------------------------------
# mutation self-test: every invariant class must turn red on a seeded
# perturbation — the verifier is only trustworthy while this passes
# ---------------------------------------------------------------------------

_REF_SCHEMA = """
{"type": "record", "name": "MutRef", "fields": [
  {"name": "i",   "type": "int"},
  {"name": "s",   "type": "string"},
  {"name": "e",   "type": {"type": "enum", "name": "ME",
                           "symbols": ["A", "B"]}},
  {"name": "opt", "type": ["null", "long"]},
  {"name": "un",  "type": ["int", "string", "null"]},
  {"name": "arr", "type": {"type": "array", "items": "int"}},
  {"name": "m",   "type": {"type": "map", "values": "string"}}
]}
"""

_ZW_SCHEMA = """
{"type": "record", "name": "ZwRef", "fields": [
  {"name": "a", "type": {"type": "array", "items": "null"}}
]}
"""

# optimizer-mutation reference: an unconditional exact-width run (x, y,
# k — fused with a=1 + FLAG_ALWAYS_PRESENT) plus a second run under a
# nullable chain (p, q — fused but NOT always-present)
_OPT_SCHEMA = """
{"type": "record", "name": "OptRef", "fields": [
  {"name": "x", "type": "double"},
  {"name": "y", "type": "float"},
  {"name": "k", "type": "boolean"},
  {"name": "opt", "type": ["null", {"type": "record", "name": "OInner",
    "fields": [{"name": "p", "type": "double"},
               {"name": "q", "type": "double"}]}]}
]}
"""


def _leaf_pcs(m: ProgramModel, kinds) -> List[int]:
    return [pc for pc, row in enumerate(m.ops) if row[0] in kinds]


def run_mutation_selftest(guards: Dict[str, bool],
                          consumers: Dict[str, List[str]]):
    """Seeded perturbations, one per invariant class (plus spares):
    each must be caught by the pass that owns its class. Returns
    (findings — nonempty iff a mutation ESCAPED —, report rows)."""
    import copy

    from ..hostpath.program import lower_host
    from ..hostpath.specialize import generate_source
    from ..schema.parser import parse_schema

    hp = _effects()
    prog = lower_host(parse_schema(_REF_SCHEMA))
    zw_prog = lower_host(parse_schema(_ZW_SCHEMA))
    base = ProgramModel.from_host_program(prog, "mutation")

    def model(**over):
        m = ProgramModel(copy.deepcopy(base.ops), list(base.coltypes),
                         copy.deepcopy(base.aux), "mutation",
                         col_regions=list(base.col_regions or []))
        for k, v in over.items():
            setattr(m, k, v)
        return m

    cases = []

    # -- effect class -----------------------------------------------------
    def col_transpose():
        m = model()
        i_pc = _leaf_pcs(m, (hp.OP_INT,))[0]
        s_pc = _leaf_pcs(m, (hp.OP_STRING,))[0]
        oi, os_ = list(m.ops[i_pc]), list(m.ops[s_pc])
        oi[3], os_[3] = os_[3], oi[3]
        m.ops[i_pc], m.ops[s_pc] = tuple(oi), tuple(os_)
        return verify_structure(m)

    def coltype_drift():
        m = model()
        i_pc = _leaf_pcs(m, (hp.OP_INT,))[0]
        m.coltypes[m.ops[i_pc][3]] = hp.COL_F64
        return verify_structure(m)

    def aux_arity():
        m = model()
        e_pc = _leaf_pcs(m, (hp.OP_ENUM,))[0]
        aux = list(m.aux)
        aux[e_pc] = ("enum", b"A")  # one symbol dropped vs op.a == 2
        m.aux = tuple(aux)
        return verify_structure(m)

    def aux_misplaced():
        m = model()
        i_pc = _leaf_pcs(m, (hp.OP_INT,))[0]
        aux = list(m.aux)
        aux[i_pc] = ("duration",)
        m.aux = tuple(aux)
        return verify_structure(m)

    def depth_cap():
        m = model()
        return verify_structure(m, max_depth=2)

    def dead_aux():
        m = model()
        stripped = {t: [] for t in consumers}  # no consumer anchored
        return verify_aux_consumption(m, stripped)

    def region_drift():
        # a lowering bug allocating an item column on the row region:
        # the per-element append cadence would desync the assembler
        m = model()
        a_pc = _leaf_pcs(m, (hp.OP_ARRAY,))[0]
        item_col = m.ops[a_pc + 1][3]
        m.col_regions[item_col] = 0
        return verify_structure(m)

    cases += [("effect", "col-transpose", col_transpose,
               "irverify.effect"),
              ("effect", "region-drift", region_drift,
               "irverify.effect"),
              ("effect", "coltype-drift", coltype_drift,
               "irverify.effect"),
              ("effect", "aux-arity", aux_arity, "irverify.effect"),
              ("effect", "aux-misplaced", aux_misplaced,
               "irverify.effect"),
              ("effect", "depth-cap", depth_cap, "irverify.effect"),
              ("effect", "dead-aux", dead_aux, "irverify.effect")]

    # -- progress class ---------------------------------------------------
    def nops_corrupt():
        m = model()
        a_pc = _leaf_pcs(m, (hp.OP_ARRAY,))[0]
        row = list(m.ops[a_pc + 1])
        row[4] = 0  # the item subtree never advances the walk
        m.ops[a_pc + 1] = tuple(row)
        return verify_structure(m)

    def zw_anchor_strip():
        zm = ProgramModel.from_host_program(zw_prog, "mutation")
        g = dict(guards)
        g["zero_width_budget"] = False  # = the C++ cap check deleted
        return verify_progress(zm, g)

    cases += [("progress", "nops-corrupt", nops_corrupt,
               "irverify.progress"),
              ("progress", "zw-anchor-strip", zw_anchor_strip,
               "irverify.progress")]

    # -- overflow class ---------------------------------------------------
    def strlen_anchor_strip():
        g = dict(guards)
        g["string_len_i32"] = False  # = the 2GiB lens check deleted
        return verify_overflow(model(), g)

    def running_anchor_strip():
        g = dict(guards)
        g["offs_running_i32"] = False
        return verify_overflow(model(), g)

    cases += [("overflow", "strlen-anchor-strip", strlen_anchor_strip,
               "irverify.overflow"),
              ("overflow", "running-anchor-strip",
               running_anchor_strip, "irverify.overflow")]

    # -- equivalence class ------------------------------------------------
    def codegen_col_swap():
        import numpy as np

        mut = copy.deepcopy(prog)
        ops = np.array(mut.ops, copy=True)
        pcs = [pc for pc in range(len(ops))
               if int(ops[pc][0]) in (hp.OP_INT, hp.OP_LONG)]
        i_pc = pcs[0]
        l_pc = _leaf_pcs(base, (hp.OP_LONG,))[0]
        ops[i_pc][3], ops[l_pc][3] = int(ops[l_pc][3]), int(ops[i_pc][3])
        mut.ops = ops
        src = generate_source(mut, "M", with_effects=True)
        return verify_equivalence(prog, src=src)

    def kops_row_tamper():
        src = generate_source(prog, "M", with_effects=True)
        m = re.search(r"static const Op kOps\[\] = \{\n(    \{[^\n]*\n)",
                      src)
        row = m.group(1)
        tampered = re.sub(r"\{(-?\d+),", lambda g: "{%d," %
                          ((int(g.group(1)) + 1) % 16), row, count=1)
        src = src.replace(row, tampered, 1)
        return verify_equivalence(prog, src=src)

    cases += [("equiv", "codegen-col-swap", codegen_col_swap,
               "irverify.equiv"),
              ("equiv", "kops-row-tamper", kops_row_tamper,
               "irverify.equiv")]

    # -- optimize class (superoptimizer rewrites vs the oracle) -----------
    from ..hostpath import optimize as hopt

    opt_raw = lower_host(parse_schema(_OPT_SCHEMA))
    opt_prog, _ost = hopt.optimize_program(opt_raw, verify=False)

    def _mutated_opt(mutfn):
        import numpy as np

        mut = copy.deepcopy(opt_prog)
        ops = np.array(mut.ops, copy=True)
        mutfn(ops)
        mut.ops = ops
        return verify_optimized(opt_raw, mut, guards, consumers)

    def _run_pcs(ops):
        return [i for i in range(len(ops))
                if int(ops[i][0]) == hp.OP_FIXED_RUN]

    def fused_span_tamper():
        # a rewrite that mis-sums the members' wire floors: the bulk
        # lane's span pre-check would admit reads past the record
        def mt(ops):
            ops[_run_pcs(ops)[0]][2] += 1
        return _mutated_opt(mt)

    def reordered_rewrite():
        # members swapped inside the fused run: structure still tiles
        # and the span sum is unchanged — only strip-equality sees it
        def mt(ops):
            pc = _run_pcs(ops)[0]
            ops[[pc + 1, pc + 2]] = ops[[pc + 2, pc + 1]]
        return _mutated_opt(mt)

    def always_present_overclaim():
        # the nullable-chain run flagged always-present: the bulk lane
        # would consume wire bytes when the record is absent
        def mt(ops):
            ops[_run_pcs(ops)[-1]][5] |= hp.FLAG_ALWAYS_PRESENT
        return _mutated_opt(mt)

    cases += [("optimize", "fused-span-tamper", fused_span_tamper,
               "irverify.optimize"),
              ("optimize", "reordered-rewrite", reordered_rewrite,
               "irverify.optimize"),
              ("optimize", "always-present-overclaim",
               always_present_overclaim, "irverify.optimize")]

    findings: List[Finding] = []
    rows = []
    for cls, name, fn, want_rule in cases:
        try:
            fs = fn()
        except Exception as e:  # a crashing pass is NOT a catch
            fs = []
            crash = f"{type(e).__name__}: {e}"
        else:
            crash = None
        caught = any(f.rule.startswith(want_rule) for f in fs)
        rows.append({"class": cls, "name": name, "caught": caught,
                     "rule": want_rule,
                     "findings": len(fs), "crash": crash})
        if not caught:
            findings.append(Finding(
                "irverify.selftest", "pyruhvro_tpu/analysis/irverify.py",
                f"seeded {cls} mutation {name!r} escaped the verifier"
                + (f" (pass crashed: {crash})" if crash else "")))
    return findings, {"cases": rows,
                      "all_caught": all(r["caught"] for r in rows)}


# ---------------------------------------------------------------------------
# the gate entry
# ---------------------------------------------------------------------------


def run_ir_verification(root: str,
                        depths: Optional[Sequence[int]] = None,
                        selftest: bool = True,
                        equivalence: bool = True):
    """The full IR verification run for ``analysis_gate.py --ir``:
    guard-anchor scan, the schema-construct lattice, the aux
    consumption matrix, and the mutation self-test. Returns
    (findings, IR_VERIFY_REPORT-shaped dict). The report is a
    COMMITTED artifact: it carries no timestamp or other run-varying
    field, so a re-run on an unchanged tree is byte-identical and
    leaves the checkout clean."""
    guards = scan_native_guards(root)
    consumers = scan_aux_consumers(root)
    findings: List[Finding] = []

    # a guard named by the contract but anchored nowhere is itself a
    # finding even before any program references it
    for g, ok in guards.items():
        if not ok:
            findings.append(Finding(
                "irverify.overflow", "pyruhvro_tpu/analysis/irverify.py",
                f"guard anchor {g!r} not found in the native sources — "
                "either the range check was deleted or the anchor "
                "pattern rotted (update GUARD_ANCHORS with the code)"))

    lat_findings, lattice = run_lattice(guards, consumers,
                                        depths=depths,
                                        equivalence=equivalence)
    findings += lat_findings

    mut = {"cases": [], "all_caught": None}
    if selftest:
        mut_findings, mut = run_mutation_selftest(guards, consumers)
        findings += mut_findings

    report = {
        "schema_version": 1,
        "generated_by": "pyruhvro_tpu.analysis.irverify",
        "guards": guards,
        "aux_consumers": consumers,
        "aux_audit_notes": {f"{t}/{d}": note for (t, d), note
                            in AUX_AUDIT_NOTES.items()},
        "lattice": lattice,
        "mutation": mut,
        "finding_count": len(findings),
        "findings": [f.to_dict() for f in findings],
    }
    return findings, report
