"""Optional Arrow Flight front end for the serving plane.

The wire contract is deliberately tiny:

* **DoPut** — the descriptor ``command`` is a JSON document
  ``{"schema": <avro schema json>, "tenant": ..., "traceparent": ...,
  "timeout_s": ...}`` and the uploaded stream is record batches with a
  single binary column (any name) of Avro wire bytes. The handler
  submits the rows to the process serving plane (starting it on
  demand) and writes back one metadata message: the ticket (a UTF-8
  token) under which the decode result is retrievable.
* **DoGet** — exchanging that ticket returns the decoded Arrow
  ``RecordBatch`` stream, or raises ``FlightServerError`` carrying the
  structured failure (``Overloaded`` rejections include the
  ``retry_after_s`` hint in the message).

``tenant`` feeds per-tenant accounting/admission and ``traceparent``
joins the fleet trace exactly as the one-shot API's ``trace_ctx``
would. Everything here degrades: without ``pyarrow.flight`` in the
environment, :func:`start_flight_server` is a counted
(``serve.flight_unavailable``) no-op returning ``None`` — the rest of
the serving plane is unaffected. The ``serve_flight`` chaos seam fires
in both handlers; degradable faults fail ONLY the affected RPC with a
structured Flight error, never the server.
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Any, Dict, Optional

from ..runtime import faults, metrics

__all__ = ["flight_available", "start_flight_server", "FlightFrontDoor"]


def flight_available() -> bool:
    try:
        import pyarrow.flight  # noqa: F401
    except Exception:  # noqa: BLE001 — absence is the signal
        return False
    return True


def _make_server_cls():
    import pyarrow as pa
    import pyarrow.flight as fl

    from . import Overloaded, start

    class FlightFrontDoor(fl.FlightServerBase):
        """DoPut wire bytes in → DoGet decoded RecordBatch out."""

        def __init__(self, location: str = "grpc://127.0.0.1:0",
                     **server_kw):
            super().__init__(location, **server_kw)
            self._lock = threading.Lock()
            self._pending: Dict[str, Any] = {}  # ticket -> (future, req)

        # -- ingest -------------------------------------------------------

        def do_put(self, context, descriptor, reader, writer):
            metrics.inc("serve.flight_put")
            try:
                faults.fire("serve_flight")
                spec = json.loads(descriptor.command.decode("utf-8"))
                schema = spec["schema"]
                data = []
                for chunk in reader:
                    batch = chunk.data
                    if batch.num_columns != 1:
                        raise ValueError(
                            "DoPut expects one binary column of Avro "
                            "wire bytes")
                    data.extend(batch.column(0).to_pylist())
                fut = start().submit(
                    "decode", data, schema,
                    backend=spec.get("backend", "auto"),
                    on_error=spec.get("on_error", "raise"),
                    timeout_s=spec.get("timeout_s"),
                    tenant=spec.get("tenant"),
                    trace_ctx=spec.get("traceparent"))
            except Exception as e:  # noqa: BLE001 — RPC-scoped failure
                self._rpc_fail(e)
            else:
                ticket = uuid.uuid4().hex
                with self._lock:
                    self._pending[ticket] = fut
                writer.write(ticket.encode("utf-8"))

        # -- retrieve -----------------------------------------------------

        def do_get(self, context, ticket):
            metrics.inc("serve.flight_get")
            try:
                faults.fire("serve_flight")
                token = ticket.ticket.decode("utf-8")
                with self._lock:
                    fut = self._pending.pop(token, None)
                if fut is None:
                    raise KeyError(f"unknown ticket {token!r}")
                batch = fut.result()
            except Exception as e:  # noqa: BLE001 — RPC-scoped failure
                self._rpc_fail(e)
            return fl.RecordBatchStream(
                pa.Table.from_batches([batch]))

        # -- failure shaping ---------------------------------------------

        @staticmethod
        def _rpc_fail(e: BaseException) -> None:
            if faults.degradable(e):
                metrics.inc("serve.flight_degraded")
            if isinstance(e, Overloaded):
                hint = (f" retry_after_s={e.retry_after_s:.3f}"
                        if e.retry_after_s is not None else "")
                raise fl.FlightUnavailableError(
                    f"overloaded ({e.reason}){hint}")
            raise fl.FlightServerError(
                f"{type(e).__name__}: {e}")

    return FlightFrontDoor


# resolved lazily so importing pyruhvro_tpu.serving.flight never pulls
# grpc; None until first successful _make_server_cls()
# lock-free-ok(idempotent memo of a pure class object — racing writers
# store the same value; readers see None or the class, never torn state)
FlightFrontDoor = None


def start_flight_server(location: str = "grpc://127.0.0.1:0",
                        **server_kw) -> Optional[Any]:
    """Start the Flight front door, or count+skip when the optional
    ``pyarrow.flight`` extra is missing (the documented degrade: the
    plane still serves the in-process and HTTP surfaces)."""
    global FlightFrontDoor
    if not flight_available():
        metrics.inc("serve.flight_unavailable")
        return None
    if FlightFrontDoor is None:
        FlightFrontDoor = _make_server_cls()
    server = FlightFrontDoor(location, **server_kw)
    metrics.inc("serve.flight_started")
    return server
