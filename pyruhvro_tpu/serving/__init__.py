"""Overload-hardened micro-batching serving plane (ISSUE 19).

Every organ of a production decoder existed before this package —
SLOs/healthz, breakers/deadlines/fault seams, the adaptive router,
per-tenant accounting, the differential-audit plane — but the library
was still driven by one-shot API calls, so nothing defended the system
when offered load exceeded capacity. This is the front door that
*stays up under overload*:

* **Bounded queues**, one per (op, schema fingerprint, tenant,
  on_error, backend) — the coalescing key — each capped at
  ``PYRUHVRO_TPU_SERVE_QUEUE`` requests. Worker threads drain the
  queue whose head deadline is tightest and coalesce whole requests
  into ONE ``api.deserialize_array`` call (micro-batching keeps the
  jit/specializer/arena caches warm and amortizes per-call overhead);
  results are split back per request and quarantine indices are
  re-based to each caller's own record indices
  (:func:`..runtime.quarantine.rebase`).
* **Deadlines measured from enqueue**: a request's ``timeout_s``
  starts burning when :meth:`ServePlane.submit` accepts it, so queue
  wait counts against the budget. Requests that expire while still
  queued are shed with a structured ``DeadlineExceeded`` WITHOUT
  running the decode.
* **Backpressure policies** (``PYRUHVRO_TPU_SERVE_POLICY``):
  ``block`` waits up to the enqueue deadline for queue space; ``shed``
  rejects immediately with :class:`Overloaded` carrying a retry-after
  hint derived from the cost model's predicted drain time of the
  backlog (:func:`..runtime.costmodel.predict_drain`).
* **Per-tenant admission control** fed by the PR 12 heavy-hitter
  sketch (:func:`..runtime.memacct.tenant_hotlist`) plus live queue
  occupancy: once the plane is over half full, no tenant may hold more
  than ``PYRUHVRO_TPU_SERVE_TENANT_SHARE`` of the queued requests —
  one tenant's flood cannot starve others.
* **Brownout degradation ladder** under sustained pressure: rungs shed
  audit shadowing → deep sampling → explore arms → flood tenants, in
  that order, each engagement counted (``serve.brownout.<rung>``) and
  reflected in ``/healthz`` degraded bits; rungs auto-release (with
  hysteresis) when pressure clears.
* **Zero-loss graceful drain**: :meth:`ServePlane.drain` stops intake,
  flushes every queued request to a terminal state (result or
  structured error — none silently dropped), restores the brownout
  overrides and flushes telemetry/profile persistence.
  :func:`install_drain_signal` arms the same drain on SIGTERM/SIGINT,
  obeying the signal-safety rules (the handler only bumps a
  :class:`..runtime.metrics.DeferredCount` and sets an Event; the
  drain itself runs on a normal thread).
* **Chaos seams** (:mod:`..runtime.faults`): ``serve_enqueue``
  degrades admission to a direct synchronous call (byte-identical,
  queue bypassed); ``serve_worker`` fires inside the coalesced batch
  attempt — failures and stalls trip the ``serve_worker`` breaker and
  drain to the per-request serial path, byte-identical by
  construction. The optional Arrow Flight endpoint lives in
  :mod:`.flight` and degrades to a counted no-op without
  ``pyarrow.flight``.

Synchronization: one :class:`threading.Condition` per plane guards all
queue/accounting state (a rendezvous, not a data lock held across
blocking calls); the module-level singleton is guarded by ``_lock``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..runtime import (
    breaker,
    costmodel,
    deadline,
    faults,
    knobs,
    memacct,
    metrics,
    slo,
    telemetry,
)
from ..runtime import audit as _audit
from ..runtime import quarantine as _quarantine
from ..runtime import sampling as _sampling
from ..runtime import timeline

__all__ = [
    "Overloaded",
    "ServePlane",
    "start",
    "plane",
    "stop",
    "install_drain_signal",
    "snapshot_serving",
    "engaged_rungs",
    "render_serve_report",
    "reset",
]


class Overloaded(Exception):
    """Structured admission rejection: the serving plane refused this
    request (full queue, enqueue-deadline expiry, tenant fairness cap,
    brownout tenant shedding, or drain in progress). A capacity
    CONTRACT like ``BatchTooLarge`` — deliberately not a
    ``RuntimeError``, so no degrade seam ever swallows it.

    ``retry_after_s`` (when known) is the cost model's predicted drain
    time of the backlog that caused the rejection — the client's
    Retry-After header."""

    def __init__(self, message: str, *, reason: str,
                 tenant: Optional[str] = None,
                 retry_after_s: Optional[float] = None,
                 queued: Optional[int] = None):
        super().__init__(message)
        self.reason = reason
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        self.queued = queued


class _Request:
    """One accepted (or about-to-be-accepted) serving request."""

    __slots__ = ("op", "data", "schema", "fp", "tenant", "backend",
                 "on_error", "return_errors", "num_chunks", "n_rows",
                 "timeout_s", "enqueue_t", "deadline_t", "trace_ctx",
                 "future", "done", "coalescable")

    def __init__(self, op, data, schema, fp, tenant, backend, on_error,
                 return_errors, num_chunks, n_rows, timeout_s,
                 enqueue_t, trace_ctx):
        import concurrent.futures

        self.op = op
        self.data = data
        self.schema = schema
        self.fp = fp
        self.tenant = tenant          # None = untagged
        self.backend = backend
        self.on_error = on_error
        self.return_errors = return_errors
        self.num_chunks = num_chunks
        self.n_rows = n_rows
        self.timeout_s = timeout_s
        self.enqueue_t = enqueue_t
        self.deadline_t = (enqueue_t + timeout_s
                           if timeout_s is not None else None)
        self.trace_ctx = trace_ctx
        self.future = concurrent.futures.Future()
        self.done = False
        # only plain datum sequences coalesce; arrow-array inputs keep
        # their zero-copy ingestion lane by running uncoalesced
        self.coalescable = (op == "decode"
                            and isinstance(data, (list, tuple)))

    @property
    def tenant_key(self) -> str:
        return self.tenant or "-"

    def remaining(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline_t is None:
            return None
        return max(0.0, self.deadline_t - (now or time.monotonic()))


# the ladder, least- to most-intrusive: each rung trades a little
# observability/fairness for capacity, and the order is the promise —
def _schema_has_union(schema) -> bool:
    """True when any column type (recursively) is a union — those
    cannot be zero-copy sliced at a non-zero offset without value
    corruption in downstream conversions (see _split_decode)."""
    import pyarrow.types as pt

    def walk(t):
        if pt.is_union(t):
            return True
        return any(walk(t.field(i).type)
                   for i in range(getattr(t, "num_fields", 0) or 0))

    return any(walk(f.type) for f in schema)


# correctness shadowing goes first, paying tenants go last
BROWNOUT_RUNGS = ("audit", "sampling", "explore", "tenant")
_RUNG_STEP = 0.08       # pressure headroom between consecutive rungs
_RUNG_HYSTERESIS = 0.15  # release this far below the engage threshold
_TICK_INTERVAL_S = 0.02


class _Brownout:
    """The degradation ladder. All state is instance-held and guarded
    by the owning plane's condition; the engage/release side effects
    flip process-wide overrides (audit/sampling/explore) that
    :meth:`release_all` and :func:`reset` restore."""

    def __init__(self, plane: "ServePlane"):
        self._plane = plane
        self._engaged_at: Dict[str, float] = {}
        self._over: Dict[str, int] = {r: 0 for r in BROWNOUT_RUNGS}
        self._occupancy: Dict[str, float] = {r: 0.0
                                             for r in BROWNOUT_RUNGS}
        self._last_tick = 0.0

    # -- queries (call under the plane cond or tolerate staleness) ----------

    def engaged(self) -> Tuple[str, ...]:
        return tuple(r for r in BROWNOUT_RUNGS if r in self._engaged_at)

    def occupancy(self) -> Dict[str, float]:
        now = time.monotonic()
        out = dict(self._occupancy)
        for r, t0 in self._engaged_at.items():
            out[r] += now - t0
        return out

    # -- evaluation ---------------------------------------------------------

    def tick_locked(self, pressure: float, now: float) -> None:
        if now - self._last_tick < _TICK_INTERVAL_S:
            return
        self._last_tick = now
        base = knobs.get_float("PYRUHVRO_TPU_SERVE_BROWNOUT")
        if base is None or base > 1.0:
            return
        sustain = max(1, knobs.get_int(
            "PYRUHVRO_TPU_SERVE_BROWNOUT_SUSTAIN"))
        for i, rung in enumerate(BROWNOUT_RUNGS):
            thr = min(0.97, base + _RUNG_STEP * i)
            rel = max(0.0, thr - _RUNG_HYSTERESIS)
            if rung in self._engaged_at:
                if pressure <= rel:
                    self._release_locked(rung, now)
            elif pressure >= thr:
                self._over[rung] += 1
                if self._over[rung] >= sustain:
                    self._engage_locked(rung, now)
            else:
                self._over[rung] = 0

    def _engage_locked(self, rung: str, now: float) -> None:
        self._engaged_at[rung] = now
        self._over[rung] = 0
        # metric-key: serve.brownout.<rung>
        metrics.inc("serve.brownout." + rung)
        metrics.mark("serve_brownout")  # the /healthz degraded bit
        timeline.event("serve.brownout", severity="warn",
                       attrs={"rung": rung})
        if rung == "audit":
            _audit.set_enabled(False)
        elif rung == "sampling":
            _sampling.set_enabled(False)
        elif rung == "explore":
            costmodel.set_explore_override(0.0)
        # "tenant" is a flag the admission path reads via engaged()

    def _release_locked(self, rung: str, now: float) -> None:
        t0 = self._engaged_at.pop(rung, None)
        if t0 is not None:
            self._occupancy[rung] += now - t0
        metrics.inc("serve.brownout_release." + rung)  # metric-key: serve.brownout_release.<rung>
        timeline.event("serve.brownout_release", attrs={"rung": rung})
        if rung == "audit":
            _audit.set_enabled(None)
        elif rung == "sampling":
            _sampling.set_enabled(None)
        elif rung == "explore":
            costmodel.set_explore_override(None)

    def release_all(self) -> None:
        now = time.monotonic()
        for rung in list(self._engaged_at):
            self._release_locked(rung, now)


class ServePlane:
    """The micro-batching front door over the one-shot API.

    One instance per service process (module-level :func:`start` keeps
    the singleton); tests may build private instances with
    ``autostart=False`` to control worker scheduling explicitly."""

    def __init__(self, *, workers: Optional[int] = None,
                 autostart: bool = True):
        self._cond = threading.Condition()
        # everything below is guarded by _cond (instance state; the
        # condition is the plane's single rendezvous + data guard)
        self._queues: Dict[tuple, Deque[_Request]] = {}
        self._schemas: Dict[tuple, str] = {}   # key -> schema string
        self._queued_total = 0
        self._tenant_queued: Dict[str, int] = {}
        self._inflight = 0
        self._accepted = 0
        self._shed = 0
        self._completed = 0
        self._failed = 0
        self._drained = 0
        self._draining = False
        self._closed = False
        self._running = False
        self._threads: List[threading.Thread] = []
        self._workers = (workers if workers is not None
                         else max(1, knobs.get_int(
                             "PYRUHVRO_TPU_SERVE_WORKERS")))
        self._brownout = _Brownout(self)
        # (op, fp) -> EWMA seconds/row from completed work: the drain
        # estimator's fallback when the cost model has no observation
        self._spr: Dict[tuple, float] = {}
        # per-name re-arm stamps for onset timeline events: shedding /
        # saturation fire per REQUEST, but the timeline wants the
        # episode boundary, not a per-call flood of the event ring
        self._evt_mono: Dict[str, float] = {}
        self._started_at = time.time()
        if autostart:
            self.start_workers()

    # ------------------------------------------------------------------
    # knobs (read per call so tests can flip them in-process)
    # ------------------------------------------------------------------

    _EVENT_REARM_S = 5.0

    def _onset_event(self, name: str, severity: str,
                     attrs: Dict[str, Any]) -> None:
        """Publish a timeline event for a per-request condition at most
        once per :data:`_EVENT_REARM_S` — the timeline wants the
        episode onset, not one event per shed request."""
        now = time.monotonic()
        if now - self._evt_mono.get(name, -1e9) < self._EVENT_REARM_S:
            return
        self._evt_mono[name] = now
        timeline.event(name, severity=severity, attrs=attrs)

    @staticmethod
    def _depth() -> int:
        return max(1, knobs.get_int("PYRUHVRO_TPU_SERVE_QUEUE"))

    @staticmethod
    def _policy() -> str:
        return knobs.get_enum("PYRUHVRO_TPU_SERVE_POLICY")

    @staticmethod
    def _max_batch_rows() -> int:
        return max(1, knobs.get_int("PYRUHVRO_TPU_SERVE_MAX_BATCH_ROWS"))

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, op: str, data, schema: str, *,
               backend: str = "auto", on_error: str = "raise",
               return_errors: bool = False,
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None, trace_ctx=None,
               num_chunks: int = 1):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to exactly what the corresponding one-shot API call
        would return (or raising its structured error). ``op`` is
        ``"decode"`` (→ :func:`..api.deserialize_array`) or
        ``"encode"`` (→ :func:`..api.serialize_record_batch`).
        ``timeout_s`` starts burning NOW — queue wait counts."""
        if op not in ("decode", "encode"):
            raise ValueError(f"op must be 'decode' or 'encode', "
                             f"got {op!r}")
        t0 = time.monotonic()
        metrics.inc("serve.submitted")
        # chaos seam: a degradable admission fault bypasses the queue
        # and serves the call directly (the pre-serving path — byte-
        # identical results; a hang here burns the caller's budget,
        # exactly as a slow admission would)
        try:
            faults.fire("serve_enqueue")
        except Exception as e:
            if not faults.degradable(e):
                raise
            metrics.inc("serve.enqueue_degraded")
            return self._direct_future(op, data, schema, backend,
                                       on_error, return_errors,
                                       timeout_s, tenant, trace_ctx,
                                       num_chunks, t0)
        from .. import api  # lazy: serving must not import jax eagerly

        entry = api.get_or_parse_schema(schema)
        if timeout_s is None:
            timeout_s = deadline.default_timeout_s()
        n_rows = (len(data) if op == "decode" else data.num_rows)
        r = _Request(op, data, schema, entry.fingerprint, tenant,
                     backend, on_error, return_errors, num_chunks,
                     n_rows, timeout_s, t0, trace_ctx)
        key = (op, r.fp, r.tenant_key, on_error, backend)
        with self._cond:
            self._brownout.tick_locked(self._pressure_locked(), t0)
            reason = self._admit_locked(r, key)
            if reason == "queue_full" and self._policy() == "block":
                reason = self._block_for_space_locked(r, key)
            if reason is not None:
                self._shed += 1
                # metric-key: serve.shed.<reason>
                metrics.inc("serve.shed." + reason)
                metrics.inc("serve.shed")
                metrics.mark("serve_shed")  # /healthz degraded bit
                self._onset_event("serve.shed", "warn",
                                  {"reason": reason, "tenant": tenant,
                                   "queued": self._queued_total})
                raise Overloaded(
                    f"request shed at admission ({reason})",
                    reason=reason, tenant=tenant,
                    retry_after_s=self._retry_after_locked(r, key),
                    queued=self._queued_total)
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
                self._schemas[key] = schema
            q.append(r)
            self._queued_total += 1
            self._tenant_queued[r.tenant_key] = (
                self._tenant_queued.get(r.tenant_key, 0) + 1)
            self._accepted += 1
            metrics.inc("serve.accepted")
            self._cond.notify_all()
        return r.future

    def call(self, op: str, data, schema: str, **kw):
        """Synchronous convenience: submit + wait."""
        return self.submit(op, data, schema, **kw).result()

    def _admit_locked(self, r: _Request, key: tuple) -> Optional[str]:
        """None = admit; else the shed reason."""
        if self._closed or self._draining:
            return "draining"
        # brownout rung 4: flood tenants (heavy hitters by attributed
        # bytes) are shed entirely while the rung is engaged
        if ("tenant" in self._brownout.engaged()
                and r.tenant_key in _flood_tenants()):
            return "tenant_flood"
        # fairness cap: past half-full, one TAGGED tenant may not hold
        # more than its share of all queued requests (untagged traffic
        # is exempt — there is no tenant to be fair between)
        share = knobs.get_float("PYRUHVRO_TPU_SERVE_TENANT_SHARE")
        if (share and share > 0 and r.tenant is not None
                and self._queued_total > 0):
            capacity = self._depth() * max(1, len(self._queues))
            mine = self._tenant_queued.get(r.tenant_key, 0)
            if (self._queued_total >= 0.5 * capacity
                    and (mine + 1) > share * (self._queued_total + 1)):
                return "tenant_share"
        q = self._queues.get(key)
        if q is not None and len(q) >= self._depth():
            metrics.mark("queue_saturated")  # /healthz unhealthy bit
            self._onset_event("serve.queue_saturated", "incident",
                              {"depth": len(q),
                               "queued": self._queued_total})
            return "queue_full"
        return None

    def _block_for_space_locked(self, r: _Request,
                                key: tuple) -> Optional[str]:
        """'block' policy: wait for space up to the enqueue deadline
        (bounded by the request's own remaining budget). Returns None
        once admitted, or the terminal shed reason."""
        limit = max(0.0, knobs.get_float(
            "PYRUHVRO_TPU_SERVE_ENQUEUE_WAIT_S"))
        rem = r.remaining()
        if rem is not None:
            limit = min(limit, rem)
        until = time.monotonic() + limit
        while True:
            left = until - time.monotonic()
            if left <= 0:
                return "enqueue_timeout"
            self._cond.wait(min(left, 0.05))
            if self._closed or self._draining:
                return "draining"
            reason = self._admit_locked(r, key)
            if reason is None:
                return None
            if reason != "queue_full":
                return reason

    def _retry_after_locked(self, r: _Request,
                            key: tuple) -> Optional[float]:
        """Predicted drain time of the backlog the request would have
        joined — cost model first, the plane's own service-rate EWMA
        as fallback."""
        q = self._queues.get(key)
        backlog_rows = sum(x.n_rows for x in q) if q else 0
        backlog_rows += r.n_rows
        est = costmodel.predict_drain(r.fp, r.op, backlog_rows)
        if est is None:
            spr = self._spr.get((r.op, r.fp))
            est = spr * backlog_rows if spr else None
        if est is None:
            return None
        workers = max(1, self._workers)
        return round(est / workers, 6)

    def _pressure_locked(self) -> float:
        if not self._queues:
            return 0.0
        depth = self._depth()
        return max(len(q) for q in self._queues.values()) / depth

    def _direct_future(self, op, data, schema, backend, on_error,
                       return_errors, timeout_s, tenant, trace_ctx,
                       num_chunks, t0):
        """The serve_enqueue degrade path: run synchronously on the
        caller thread (byte-identical to the one-shot API) and hand
        back an already-resolved future."""
        import concurrent.futures

        from .. import api

        fut: Any = concurrent.futures.Future()
        rem = timeout_s
        if rem is not None:
            rem = max(0.0, rem - (time.monotonic() - t0))
        try:
            if op == "decode":
                res = api.deserialize_array(
                    data, schema, backend=backend, on_error=on_error,
                    return_errors=return_errors, timeout_s=rem,
                    tenant=tenant, trace_ctx=trace_ctx)
            else:
                res = api.serialize_record_batch(
                    data, schema, num_chunks, backend=backend,
                    on_error=on_error, return_errors=return_errors,
                    timeout_s=rem, tenant=tenant, trace_ctx=trace_ctx)
            fut.set_result(res)
        except BaseException as e:  # noqa: BLE001 — future carries it
            fut.set_exception(e)
        return fut

    # ------------------------------------------------------------------
    # workers
    # ------------------------------------------------------------------

    def start_workers(self) -> None:
        with self._cond:
            if self._running or self._closed:
                return
            self._running = True
            for i in range(self._workers):
                t = threading.Thread(target=self._worker_loop,
                                     name=f"pyruhvro-serve-{i}",
                                     daemon=True)
                self._threads.append(t)
                t.start()

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                while (self._running and self._queued_total == 0
                       and not (self._draining and self._inflight == 0)):
                    self._cond.wait(0.1)
                    self._brownout.tick_locked(self._pressure_locked(),
                                               time.monotonic())
                if not self._running or (self._draining
                                         and self._queued_total == 0):
                    return
                picked = self._pop_batch_locked()
                if picked is None:
                    continue
                key, reqs = picked
                self._inflight += 1
            try:
                self._run_requests(key, reqs)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._brownout.tick_locked(self._pressure_locked(),
                                               time.monotonic())
                    self._cond.notify_all()

    def _pop_batch_locked(self) -> Optional[tuple]:
        """Deadline-aware pick: drain the queue whose HEAD is most
        urgent (earliest absolute deadline, FIFO within a queue), then
        coalesce whole requests up to the batch row cap."""
        best_key = None
        best_rank: Tuple[float, float] = (float("inf"), float("inf"))
        for key, q in self._queues.items():
            if not q:
                continue
            head = q[0]
            rank = (head.deadline_t if head.deadline_t is not None
                    else float("inf"), head.enqueue_t)
            if rank < best_rank:
                best_rank, best_key = rank, key
        if best_key is None:
            return None
        q = self._queues[best_key]
        cap = self._max_batch_rows()
        # optional coalescing window: let a micro-batch form behind a
        # lone head before dispatching (skipped when draining — flush
        # beats batching on the way down)
        wait = knobs.get_float("PYRUHVRO_TPU_SERVE_COALESCE_S")
        if (wait and wait > 0 and not self._draining and len(q) == 1
                and q[0].coalescable and q[0].n_rows < cap):
            self._cond.wait(wait)
            q = self._queues.get(best_key)
            if q is None or not q:
                return None
        reqs: List[_Request] = [q.popleft()]
        rows = reqs[0].n_rows
        while (q and reqs[0].coalescable and q[0].coalescable
               and rows + q[0].n_rows <= cap):
            nxt = q.popleft()
            reqs.append(nxt)
            rows += nxt.n_rows
        self._queued_total -= len(reqs)
        for r in reqs:
            n = self._tenant_queued.get(r.tenant_key, 0) - 1
            if n <= 0:
                self._tenant_queued.pop(r.tenant_key, None)
            else:
                self._tenant_queued[r.tenant_key] = n
        self._cond.notify_all()  # wake block-policy space waiters
        return best_key, reqs

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _run_requests(self, key: tuple, reqs: List[_Request]) -> None:
        now = time.monotonic()
        live: List[_Request] = []
        for r in reqs:
            if r.deadline_t is not None and now >= r.deadline_t:
                # expired while queued: shed WITHOUT running the decode
                metrics.inc("serve.expired")
                self._resolve(r, exc=deadline.DeadlineExceeded(
                    "expired in serving queue",
                    op="serve." + r.op, budget_s=r.timeout_s,
                    elapsed_s=now - r.enqueue_t, site="serve_queue"))
            else:
                live.append(r)
        if not live:
            return
        metrics.inc("serve.batches")
        if len(live) > 1:
            br = breaker.get("serve_worker")
            if br.acquire():
                try:
                    self._exec_coalesced(key, live)
                    br.record_success()
                    metrics.inc("serve.coalesced", float(len(live)))
                    return
                except deadline.DeadlineExceeded as e:
                    now = time.monotonic()
                    survivors = [r for r in live
                                 if r.deadline_t is None
                                 or now < r.deadline_t]
                    for r in live:
                        if r not in survivors:
                            self._resolve(r, exc=e)
                    if survivors:
                        # the batch died while members still had
                        # budget: the wedged-batch signature (an
                        # injected hang, a stalled tier) — trip the
                        # breaker, drain survivors to the serial path
                        br.record_failure()
                        metrics.inc("serve.worker_degraded")
                    live = survivors
                except Exception as e:  # noqa: BLE001 — classified below
                    if faults.degradable(e):
                        br.record_failure()
                        metrics.inc("serve.worker_degraded")
                    else:
                        # a data error poisons a coalesced batch; the
                        # serial path isolates it to the guilty
                        # request(s)
                        metrics.inc("serve.batch_isolate")
            else:
                metrics.inc("serve.breaker_serial")
        for r in live:
            self._exec_serial(r)

    def _exec_coalesced(self, key: tuple, reqs: List[_Request]) -> None:
        """One API call for the whole micro-batch, bounded by the
        tightest member deadline AND the batch stall watchdog; the
        chaos seam fires inside the bound so an injected hang is
        indistinguishable from a stalled tier."""
        op, fp, tenant_key, on_error, backend = key
        from .. import api

        r0 = reqs[0]
        now = time.monotonic()
        budget = knobs.get_float("PYRUHVRO_TPU_SERVE_BATCH_TIMEOUT_S")
        budget = budget if budget and budget > 0 else None
        tight = min((r.deadline_t for r in reqs
                     if r.deadline_t is not None), default=None)
        if tight is not None:
            rem = max(0.0, tight - now)
            budget = rem if budget is None else min(budget, rem)
        combined: List[bytes] = []
        for r in reqs:
            combined.extend(r.data)
        with deadline.scope(budget, op="serve.batch"):
            faults.fire("serve_worker")
            batch, quar = api.deserialize_array(
                combined, self._schemas[key], backend=backend,
                on_error=on_error, return_errors=True, timeout_s=None,
                tenant=None if tenant_key == "-" else tenant_key,
                trace_ctx=r0.trace_ctx)
        self._note_spr(op, fp, len(combined), time.monotonic() - now)
        self._split_decode(reqs, batch, quar)

    def _split_decode(self, reqs: List[_Request], batch, quar) -> None:
        """Slice the coalesced result back per request and re-base
        quarantine indices to each caller's OWN record indices."""
        import pyarrow as pa

        total = sum(r.n_rows for r in reqs)
        preserved = batch.num_rows == total  # raise/null keep rows
        # pyarrow's zero-copy slice is value-corrupting on union
        # columns at non-zero offsets (the type_ids offset is dropped
        # in conversions) — for union-bearing schemas, materialize the
        # split with take() instead
        gather = _schema_has_union(batch.schema)
        qs = sorted(quar, key=lambda q: q.index)
        base = 0
        out_off = 0
        qpos = 0
        for r in reqs:
            mine = []
            while qpos < len(qs) and qs[qpos].index < base + r.n_rows:
                mine.append(qs[qpos])
                qpos += 1
            local = _quarantine.rebase(mine, -base)
            keep = r.n_rows if preserved else r.n_rows - len(mine)
            if gather and out_off:
                sl = batch.take(pa.array(
                    range(out_off, out_off + keep), type=pa.int64()))
            else:
                sl = batch.slice(out_off, keep)
            out_off += keep
            self._resolve(r, result=(sl, local) if r.return_errors
                          else sl)
            base += r.n_rows

    def _exec_serial(self, r: _Request) -> None:
        """The surviving path: one direct API call per request —
        byte-identical to what the caller would have gotten from the
        one-shot API, still under the from-enqueue deadline."""
        from .. import api

        metrics.inc("serve.serial_calls")
        t0 = time.monotonic()
        try:
            kw = dict(backend=r.backend, on_error=r.on_error,
                      return_errors=r.return_errors,
                      timeout_s=r.remaining(), tenant=r.tenant,
                      trace_ctx=r.trace_ctx)
            if r.op == "decode":
                res = api.deserialize_array(r.data, r.schema, **kw)
            else:
                res = api.serialize_record_batch(
                    r.data, r.schema, r.num_chunks, **kw)
        except BaseException as e:  # noqa: BLE001 — future carries it
            self._resolve(r, exc=e)
            return
        self._note_spr(r.op, r.fp, r.n_rows, time.monotonic() - t0)
        self._resolve(r, result=res)

    def _note_spr(self, op: str, fp: str, rows: int,
                  seconds: float) -> None:
        if rows <= 0 or seconds <= 0:
            return
        spr = seconds / rows
        with self._cond:
            prev = self._spr.get((op, fp))
            self._spr[(op, fp)] = (spr if prev is None
                                   else 0.8 * prev + 0.2 * spr)

    def _resolve(self, r: _Request, result=None, exc=None) -> None:
        """The single terminal gate: every accepted request passes here
        EXACTLY once (double resolution would double-answer a caller;
        the guard makes the zero-loss invariant checkable)."""
        with self._cond:
            if r.done:
                metrics.inc("serve.double_resolve")  # should stay 0
                return
            r.done = True
            if exc is None:
                self._completed += 1
            else:
                self._failed += 1
            if self._draining:
                self._drained += 1
                metrics.inc("serve.drained")
            self._cond.notify_all()
        e2e = time.monotonic() - r.enqueue_t
        if exc is None:
            metrics.inc("serve.completed")
            r.future.set_result(result)
        else:
            metrics.inc("serve.failed")
            r.future.set_exception(exc)
        telemetry.observe("serve.e2e_s", e2e)
        slo.record_root("serve.request", r.fp, e2e,
                        error=exc is not None)

    # ------------------------------------------------------------------
    # drain / shutdown
    # ------------------------------------------------------------------

    def drain(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Zero-loss graceful shutdown: stop intake, flush every queued
        request to a terminal state, stop the workers, restore the
        brownout overrides and flush telemetry/profile saves. Every
        accepted request completes or fails STRUCTURED — none silently
        dropped. Idempotent; returns the accounting report."""
        t0 = time.monotonic()
        with self._cond:
            already = self._closed
            self._draining = True
            self._cond.notify_all()
            had_workers = bool(self._threads)
        if not already:
            metrics.inc("serve.drain")
        until = t0 + timeout_s if timeout_s is not None else None
        if not had_workers:
            # no workers were ever started (tests; a plane built with
            # autostart=False): flush inline, serially
            while True:
                with self._cond:
                    picked = self._pop_batch_locked()
                if picked is None:
                    break
                self._run_requests(*picked)
        with self._cond:
            while self._queued_total > 0 or self._inflight > 0:
                if until is not None and time.monotonic() >= until:
                    break
                self._cond.wait(0.1)
            self._running = False
            self._cond.notify_all()
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=5.0)
        # a timed-out drain still resolves the leftovers — structured,
        # never silent
        leftovers: List[_Request] = []
        with self._cond:
            for key, q in self._queues.items():
                while q:
                    r = q.popleft()
                    leftovers.append(r)
            self._queued_total = 0
            self._tenant_queued.clear()
        for r in leftovers:
            metrics.inc("serve.drain_aborted")
            self._resolve(r, exc=Overloaded(
                "drain timed out before this request ran",
                reason="drain_aborted", tenant=r.tenant))
        with self._cond:
            self._brownout.release_all()
            self._closed = True
            self._draining = False
        _flush_saves()
        telemetry.observe("serve.drain_s", time.monotonic() - t0)
        return self.report()

    def report(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "accepted": self._accepted,
                "shed": self._shed,
                "completed": self._completed,
                "failed": self._failed,
                "drained": self._drained,
                "queued": self._queued_total,
                "inflight": self._inflight,
                "closed": self._closed,
            }

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._cond:
            depth = self._depth()
            queues = [{
                "op": key[0], "schema": key[1], "tenant": key[2],
                "on_error": key[3], "backend": key[4],
                "queued": len(q), "depth": depth,
            } for key, q in sorted(self._queues.items()) if q]
            doc = {
                "active": not self._closed,
                "policy": self._policy(),
                "workers": self._workers,
                "queue_depth": depth,
                "queued": self._queued_total,
                "inflight": self._inflight,
                "pressure": round(self._pressure_locked(), 4),
                "accepted": self._accepted,
                "shed": self._shed,
                "completed": self._completed,
                "failed": self._failed,
                "drained": self._drained,
                "draining": self._draining,
                "queues": queues,
                "tenants_queued": dict(self._tenant_queued),
                "brownout": {
                    "engaged": list(self._brownout.engaged()),
                    "occupancy_s": {
                        k: round(v, 4) for k, v in
                        self._brownout.occupancy().items()},
                },
            }
        return doc

    def engaged_rungs(self) -> Tuple[str, ...]:
        with self._cond:
            return self._brownout.engaged()


# ---------------------------------------------------------------------------
# flood-tenant detection (heavy-hitter sketch, cached briefly)
# ---------------------------------------------------------------------------

_flood_lock = threading.Lock()
_flood_memo: Tuple[float, frozenset] = (0.0, frozenset())  # guarded-by: _flood_lock
_FLOOD_TTL_S = 0.25


def _flood_tenants() -> frozenset:
    """Tenants holding more than the fairness share of all attributed
    bytes in the PR 12 heavy-hitter sketch — the brownout ladder's
    shed set. Cached briefly: this runs on the admission path."""
    global _flood_memo
    now = time.monotonic()
    with _flood_lock:
        ts, memo = _flood_memo
        if now - ts <= _FLOOD_TTL_S:
            return memo
    share = knobs.get_float("PYRUHVRO_TPU_SERVE_TENANT_SHARE")
    share = share if share and share > 0 else 0.5
    rows = memacct.tenant_hotlist()
    # weight by attributed bytes; rows when no payload was ever sized
    # (the sketch can't size opaque inputs)
    field = ("bytes" if any(row["bytes"] for row in rows) else "rows")
    per_tenant: Dict[str, float] = {}
    for row in rows:
        per_tenant[row["tenant"]] = (per_tenant.get(row["tenant"], 0.0)
                                     + row[field])
    total = sum(per_tenant.values())
    floods = frozenset(t for t, b in per_tenant.items()
                       if t != "-" and total > 0 and b / total > share)
    with _flood_lock:
        _flood_memo = (now, floods)
    return floods


# ---------------------------------------------------------------------------
# drain-time persistence flush
# ---------------------------------------------------------------------------


def _flush_saves() -> None:
    """Drain-time flush of everything that persists: the learned
    routing profile (only when persistence was armed — never creating
    files nobody asked for) and a flight-recorder dump (only when
    ``PYRUHVRO_TPU_FLIGHT_DIR`` is configured). Best-effort and
    counted: a failed flush must never fail the drain."""
    try:
        if costmodel.persistence_armed():
            costmodel.save_profile()
    except Exception:  # noqa: BLE001 — drain must complete
        metrics.inc("serve.flush_error")
    try:
        telemetry._flight_autodump("serve_drain")
    except Exception:  # noqa: BLE001
        metrics.inc("serve.flush_error")


# ---------------------------------------------------------------------------
# module-level singleton + helpers
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_plane: Optional[ServePlane] = None  # guarded-by: _lock


def start(**kw) -> ServePlane:
    """Start (or return) the process-wide serving plane."""
    global _plane
    with _lock:
        if _plane is None or _plane.report()["closed"]:
            _plane = ServePlane(**kw)
            metrics.inc("serve.plane_started")
    return _plane


def plane() -> Optional[ServePlane]:
    return _plane


def stop(timeout_s: Optional[float] = None) -> Optional[Dict[str, Any]]:
    """Drain and discard the process-wide plane."""
    global _plane
    with _lock:
        p, _plane = _plane, None
    return p.drain(timeout_s=timeout_s) if p is not None else None


def engaged_rungs() -> Tuple[str, ...]:
    """Currently-engaged brownout rungs (the /healthz degraded bit);
    empty when no plane is running."""
    p = _plane
    return p.engaged_rungs() if p is not None else ()


def snapshot_serving() -> Dict[str, Any]:
    """The ``serving`` section of ``telemetry.snapshot()`` — empty dict
    when no plane ever started (snapshots stay shape-compatible)."""
    p = _plane
    return p.snapshot() if p is not None else {}


def reset() -> None:
    """Test isolation: hard-stop any plane, resolving still-pending
    requests structured, and restore every brownout override."""
    global _plane
    with _lock:
        p, _plane = _plane, None
    if p is not None:
        p.drain(timeout_s=0.0)
    # restore overrides even if a test used a private plane and leaked
    # an engaged rung
    _audit.set_enabled(None)
    _sampling.set_enabled(None)
    costmodel.set_explore_override(None)
    with _flood_lock:
        global _flood_memo
        _flood_memo = (0.0, frozenset())


# ---------------------------------------------------------------------------
# SIGTERM/SIGINT graceful drain
# ---------------------------------------------------------------------------

# bumped from signal context (increment-only; flushed on the drainer
# thread) — the one counter allowed inside a handler
_signal_drains = metrics.DeferredCount("serve.signal_drain")
# lock-free-ok(main-thread-only install flag — signal.signal itself
# enforces main-thread, so there is no racing writer)
_drain_signal_installed = False


def install_drain_signal(exit_after: bool = True) -> bool:
    """Arm zero-loss drain on SIGTERM/SIGINT. The handler itself only
    bumps a :class:`DeferredCount` and sets an Event (signal-safe by
    the PR 11 rules); a pre-spawned waiter thread performs the actual
    drain + flush. With ``exit_after`` (the service default) the
    original disposition is restored and the signal re-raised once the
    drain completes, so the process still terminates; tests pass
    ``exit_after=False`` and assert on the drained plane. Returns False
    off the main thread."""
    global _drain_signal_installed
    if _drain_signal_installed:
        return True
    import signal

    fired = threading.Event()
    received: List[int] = []
    prev = {s: signal.getsignal(s)
            for s in (signal.SIGTERM, signal.SIGINT)}

    def handler(signum, frame):
        _signal_drains.bump()
        received.append(signum)
        fired.set()

    def drainer():
        fired.wait()
        _signal_drains.flush()  # normal thread: safe to take the lock
        try:
            stop(timeout_s=30.0)
        finally:
            if exit_after and received:
                import os as _os

                signum = received[-1]
                try:
                    signal.signal(signum, prev.get(signum,
                                                   signal.SIG_DFL))
                except (ValueError, TypeError):
                    pass
                _os.kill(_os.getpid(), signum)

    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            signal.signal(s, handler)
    except ValueError:  # not the main thread
        return False
    threading.Thread(target=drainer, name="pyruhvro-serve-drain",
                     daemon=True).start()
    _drain_signal_installed = True
    return True


# ---------------------------------------------------------------------------
# serve-report renderer (the telemetry CLI subcommand)
# ---------------------------------------------------------------------------


def render_serve_report(snap: Dict[str, Any]) -> str:
    """Text report of the ``serving`` section of a saved snapshot.
    Legacy snapshots (pre-serving-plane) degrade to a note, matching
    every other report subcommand."""
    out: List[str] = ["== serving plane =="]
    sv = snap.get("serving")
    counters = snap.get("counters") or {}
    if not sv:
        out.append("no serving section in this snapshot (predates the "
                   "serving plane, or no plane ran)")
        shed = counters.get("serve.shed")
        if shed:
            out.append(f"(counters still show {shed:.0f} shed "
                       "request(s))")
        return "\n".join(out) + "\n"
    out.append(
        f"policy {sv.get('policy')}, {sv.get('workers')} worker(s), "
        f"queue depth {sv.get('queue_depth')}, "
        f"{'active' if sv.get('active') else 'closed'}")
    out.append(
        f"accepted {sv.get('accepted', 0)}  shed {sv.get('shed', 0)}  "
        f"completed {sv.get('completed', 0)}  "
        f"failed {sv.get('failed', 0)}  drained {sv.get('drained', 0)}")
    out.append(f"queued {sv.get('queued', 0)} "
               f"(pressure {sv.get('pressure', 0):.2f}), "
               f"inflight {sv.get('inflight', 0)}")
    sheds = {k: v for k, v in counters.items()
             if k.startswith("serve.shed.")}
    if sheds:
        out.append("shed by reason:")
        out.extend(f"  {k[len('serve.shed.'):]:<18} {v:>10.0f}"
                   for k, v in sorted(sheds.items()))
    bo = sv.get("brownout") or {}
    engaged = bo.get("engaged") or []
    occ = bo.get("occupancy_s") or {}
    out.append(f"brownout rungs engaged: {', '.join(engaged) or 'none'}")
    hot = {k: v for k, v in occ.items() if v}
    if hot:
        out.extend(f"  {k:<10} {v:>9.3f}s occupied"
                   for k, v in sorted(hot.items()))
    queues = sv.get("queues") or []
    if queues:
        out.append(f"{len(queues)} non-empty queue(s):")
        for q in queues[:16]:
            out.append(
                f"  {q['op']:<6} {q['schema'][:16]:<16} "
                f"tenant={q['tenant']:<10} {q['queued']}/{q['depth']}")
    hists = snap.get("histograms") or {}
    e2e = hists.get("serve.e2e_s")
    if e2e:
        out.append(
            f"e2e latency: p50 {e2e.get('p50', 0) * 1e3:.2f} ms  "
            f"p99 {e2e.get('p99', 0) * 1e3:.2f} ms  "
            f"({e2e.get('count', 0):.0f} request(s))")
    return "\n".join(out) + "\n"
