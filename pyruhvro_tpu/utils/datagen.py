"""Random test-data generation for any Avro schema.

Plays the role of the reference's use of ``apache_avro::types::Record`` +
``to_avro_datum`` to generate test input (``fast_decode.rs:935-943``) and
of ``scripts/generate_avro.py``'s Faker-based Kafka workload (no Faker in
this environment; we synthesize comparable strings from word lists).

``random_value`` produces value trees in the fallback codec's convention
(record→dict, map→list[(k,v)], union→(branch, value)), which
``encode_value`` turns into wire bytes via the fallback encoder.
"""

from __future__ import annotations

import random
import string as _string
from typing import List

from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)
from ..fallback.encoder import compile_writer

__all__ = [
    "random_value",
    "random_datums",
    "kafka_style_datums",
    "synthetic_schema_variant",
    "KAFKA_SCHEMA_JSON",
    "CRITERION_SHAPES",
]

_WORDS = (
    "alpha bravo charlie delta echo foxtrot golf hotel india juliett kilo lima "
    "mike november oscar papa quebec romeo sierra tango uniform victor whiskey "
    "xray yankee zulu amber birch cedar dune ember flint grove harbor inlet"
).split()


def _word(rng) -> str:
    return rng.choice(_WORDS)


def _text(rng, lo=0, hi=24) -> str:
    n = rng.randint(lo, hi)
    return "".join(rng.choice(_string.ascii_letters + _string.digits + " _@.")
                   for _ in range(n))


def random_value(t: AvroType, rng: random.Random, depth: int = 0):
    if isinstance(t, Primitive):
        name = t.name
        if name == "null":
            return None
        if name == "boolean":
            return rng.random() < 0.5
        if name == "int":
            if t.logical is not None:
                return rng.randint(0, 20_000)
            return rng.randint(-(2**31), 2**31 - 1)
        if name == "long":
            if t.logical is not None:
                return rng.randint(0, 2**41)
            return rng.randint(-(2**63), 2**63 - 1)
        if name == "float":
            # keep float32-representable to make round trips exact
            import struct
            v = rng.uniform(-1e6, 1e6)
            return struct.unpack("<f", struct.pack("<f", v))[0]
        if name == "double":
            return rng.uniform(-1e12, 1e12)
        if name == "bytes":
            if t.logical == "decimal":
                return rng.randint(-(10**t.precision) + 1, 10**t.precision - 1)
            return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 16)))
        if name == "string":
            if t.logical == "uuid":
                import uuid
                return str(uuid.UUID(int=rng.getrandbits(128)))
            return _text(rng)
        raise NotImplementedError(name)
    if isinstance(t, Fixed):
        if t.logical == "decimal":
            return rng.randint(-(10**t.precision) + 1, 10**t.precision - 1)
        return bytes(rng.getrandbits(8) for _ in range(t.size))
    if isinstance(t, Enum):
        return rng.choice(t.symbols)
    if isinstance(t, Array):
        n = rng.randint(0, 4 if depth < 2 else 1)
        return [random_value(t.items, rng, depth + 1) for _ in range(n)]
    if isinstance(t, Map):
        n = rng.randint(0, 4 if depth < 2 else 1)
        # distinct keys: Avro maps are logically string→value
        keys = rng.sample(_WORDS, n)
        return [(k, random_value(t.values, rng, depth + 1)) for k in keys]
    if isinstance(t, Union):
        idx = rng.randrange(len(t.variants))
        return (idx, random_value(t.variants[idx], rng, depth + 1))
    if isinstance(t, Record):
        return {f.name: random_value(f.type, rng, depth + 1) for f in t.fields}
    raise NotImplementedError(repr(t))


def random_datums(t: AvroType, n: int, seed: int = 0) -> List[bytes]:
    """n random wire-encoded datums of schema ``t``."""
    rng = random.Random(seed)
    writer = compile_writer(t)
    out = []
    for _ in range(n):
        buf = bytearray()
        writer(buf, random_value(t, rng))
        out.append(bytes(buf))
    return out


# The four schema shapes of the reference's criterion benchmark suite
# (``ruhvro/benches/common/mod.rs:37-165``), reproduced by shape (not
# copied): flat primitives, nullable primitives, a nested struct, and an
# array+map pair. bench.py runs each × {1k, 10k} rows × backends.
CRITERION_SHAPES = {
    "flat_primitives": """{"type":"record","name":"FlatPrimitives","fields":[
        {"name":"id","type":"long"},{"name":"count","type":"int"},
        {"name":"score","type":"double"},{"name":"weight","type":"float"},
        {"name":"flag","type":"boolean"},{"name":"label","type":"string"}]}""",
    "nullable_primitives": """{"type":"record","name":"NullablePrimitives","fields":[
        {"name":"id","type":["null","long"]},
        {"name":"label","type":["null","string"]},
        {"name":"score","type":["null","double"]},
        {"name":"flag","type":["null","boolean"]}]}""",
    "nested_struct": """{"type":"record","name":"Outer","fields":[
        {"name":"id","type":"long"},
        {"name":"inner","type":{"type":"record","name":"Inner","fields":[
            {"name":"name","type":"string"},
            {"name":"value","type":["null","int"]}]}},
        {"name":"maybe","type":["null",{"type":"record","name":"Inner2",
            "fields":[{"name":"x","type":"double"}]}]}]}""",
    "array_and_map": """{"type":"record","name":"ArrayAndMap","fields":[
        {"name":"tags","type":{"type":"array","items":"string"}},
        {"name":"metrics","type":{"type":"map","values":"double"}}]}""",
}


KAFKA_SCHEMA_JSON = """\
{
  "type": "record",
  "name": "User",
  "fields": [
    {"name": "name", "type": ["null", "string"], "default": null},
    {"name": "age", "type": ["null", "int"], "default": null},
    {"name": "emails", "type": {"type": "array", "items": "string"}},
    {"name": "address", "type": ["null", {
      "type": "record", "name": "Address",
      "fields": [
        {"name": "street", "type": "string"},
        {"name": "city", "type": "string"},
        {"name": "zipcode", "type": "string"}
      ]}], "default": null},
    {"name": "phone_numbers", "type": {"type": "map", "values": "string"}},
    {"name": "preferences", "type": ["null", {
      "type": "record", "name": "Preferences",
      "fields": [
        {"name": "contact_method", "type": ["null", "string"], "default": null},
        {"name": "newsletter", "type": "boolean"}
      ]}], "default": null},
    {"name": "status", "type": ["null", "string", "int", "boolean"], "default": null},
    {"name": "created_at", "type": "long"},
    {"name": "class", "type": {"type": "enum", "name": "enum_col",
                               "symbols": ["A", "B", "C"]}}
  ]
}
"""


def kafka_style_datums(n: int, seed: int = 0) -> List[bytes]:
    """Workload equivalent to ``scripts/generate_avro.py`` (Faker-free):
    same 9-field Kafka-style schema, realistic-ish field distributions
    (``generate_avro.py:44-63``)."""
    from ..schema.parser import parse_schema

    t = parse_schema(KAFKA_SCHEMA_JSON)
    rng = random.Random(seed)
    writer = compile_writer(t)
    out = []
    for _ in range(n):
        rec = {
            "name": (1, f"{_word(rng).title()} {_word(rng).title()}")
                    if rng.random() < 0.5 else None,
            "age": (1, rng.randint(18, 80)) if rng.random() < 0.5 else None,
            "emails": [f"{_word(rng)}{rng.randint(0,99)}@example.com"
                       for _ in range(rng.randint(0, 3))],
            "address": (1, {
                "street": f"{rng.randint(1,9999)} {_word(rng).title()} St",
                "city": _word(rng).title(),
                "zipcode": f"{rng.randint(10000,99999)}",
            }) if rng.random() < 0.5 else None,
            "phone_numbers": [
                (k, f"+1-{rng.randint(200,999)}-{rng.randint(1000,9999)}")
                for k in rng.sample(_WORDS, rng.randint(0, 3))
            ],
            "preferences": (1, {
                "contact_method": (1, rng.choice(["email", "phone"]))
                                  if rng.random() < 0.67 else None,
                "newsletter": rng.random() < 0.5,
            }) if rng.random() < 0.5 else None,
            "status": rng.choice([
                (0, None),
                (1, _word(rng)),
                (2, rng.randint(0, 100)),
                (3, rng.random() < 0.5),
            ]),
            "created_at": rng.randint(1_600_000_000, 1_800_000_000),
            "class": rng.choice(["A", "B", "C"]),
        }
        buf = bytearray()
        writer(buf, rec)
        out.append(bytes(buf))
    return out

def synthetic_schema_variant(i: int) -> str:
    """Schema #i of the schema-churn population (ISSUE 12): thousands
    of DISTINCT schema strings (distinct fingerprints, distinct cache
    entries) that are individually cheap to parse, lower and decode —
    the "millions of users means thousands of schemas" traffic shape
    the cache-lifecycle soak (``scripts/mem_soak.py``) drives. Field
    names vary with ``i`` so no two variants share a schema string."""
    import json

    return json.dumps({
        "type": "record", "name": f"Churn{i}",
        "fields": [
            {"name": f"id_{i % 7}", "type": "long"},
            {"name": f"label_{i % 5}", "type": "string"},
            {"name": f"score_{i % 3}", "type": "double"},
            {"name": "flag", "type": "boolean"},
        ],
    })


# ---------------------------------------------------------------------------
# Random schema generation (differential-fuzz harness)
# ---------------------------------------------------------------------------

def random_schema(seed: int, max_depth: int = 3) -> str:
    """A random record schema drawn from the native host subset
    (SURVEY.md §4's differential strategy, extended from fixed shapes to
    generated ones). Respects Avro's union rules: no nested unions, at
    most one variant per unnamed kind. ``duration`` is excluded — its
    random 12-byte fixeds overflow the oracle's Duration(ms) int64 by
    construction (covered by targeted tests instead); decimals stay
    within precision so both paths are exact."""
    import json as _json

    rng = random.Random(seed)
    counter = [0]

    def fresh(prefix):
        counter[0] += 1
        return f"{prefix}{counter[0]}"

    LEAVES = [
        "string", "bytes", "int", "long", "float", "double", "boolean",
        {"type": "int", "logicalType": "date"},
        {"type": "long", "logicalType": "timestamp-millis"},
        {"type": "long", "logicalType": "timestamp-micros"},
        {"type": "int", "logicalType": "time-millis"},
        {"type": "long", "logicalType": "time-micros"},
        {"type": "long", "logicalType": "local-timestamp-millis"},
        {"type": "long", "logicalType": "local-timestamp-micros"},
        {"type": "string", "logicalType": "uuid"},
    ]

    def gen_type(depth, allow_union=True):
        roll = rng.random()
        if depth >= max_depth or roll < 0.45:
            leaf = rng.choice(LEAVES + [None, None])  # None → named leaf
            if leaf is not None:
                return leaf
            named = rng.random()
            if named < 0.34:
                return {"type": "enum", "name": fresh("E"),
                        "symbols": ["A", "B", "C", "D"][: rng.randint(2, 4)]}
            if named < 0.67:
                return {"type": "fixed", "name": fresh("F"),
                        "size": rng.randint(1, 16)}
            prec = rng.randint(1, 18)
            if rng.random() < 0.5:
                return {"type": "bytes", "logicalType": "decimal",
                        "precision": prec, "scale": rng.randint(0, prec)}
            return {"type": "fixed", "name": fresh("FD"), "size": 16,
                    "logicalType": "decimal", "precision": prec,
                    "scale": rng.randint(0, prec)}
        if roll < 0.60:
            return {"type": "array", "items": gen_type(depth + 1)}
        if roll < 0.72:
            return {"type": "map", "values": gen_type(depth + 1)}
        if roll < 0.84:
            return {"type": "record", "name": fresh("R"), "fields": [
                {"name": f"f{i}", "type": gen_type(depth + 1)}
                for i in range(rng.randint(1, 3))
            ]}
        if allow_union:
            if rng.random() < 0.6:  # nullable pair
                inner = gen_type(depth + 1, allow_union=False)
                pair = ["null", inner]
                rng.shuffle(pair)
                return pair
            # sparse union: distinct kinds only
            kinds = rng.sample(
                ["null", "string", "long", "boolean", "double"],
                rng.randint(2, 4),
            )
            return kinds
        return rng.choice(["string", "long", "double"])

    fields = [
        {"name": f"c{i}", "type": gen_type(0)}
        for i in range(rng.randint(1, 6))
    ]
    return _json.dumps(
        {"type": "record", "name": f"Fuzz{seed}", "fields": fields}
    )


# ---------------------------------------------------------------------------
# widened-surface workload (beyond the reference's fast subset)
# ---------------------------------------------------------------------------

WIDENED_SCHEMA_JSON = """\
{
  "type": "record",
  "name": "Wide",
  "fields": [
    {"name": "b", "type": "bytes"},
    {"name": "nb", "type": ["null", "bytes"]},
    {"name": "f8", "type": {"type": "fixed", "name": "F8", "size": 8}},
    {"name": "nf", "type": ["null", {"type": "fixed", "name": "F3", "size": 3}]},
    {"name": "uid", "type": {"type": "string", "logicalType": "uuid"}},
    {"name": "dur", "type": {"type": "fixed", "name": "Dur", "size": 12,
                             "logicalType": "duration"}},
    {"name": "dec", "type": {"type": "bytes", "logicalType": "decimal",
                             "precision": 20, "scale": 4}},
    {"name": "ndec", "type": ["null", {"type": "bytes", "logicalType": "decimal",
                              "precision": 10, "scale": 2}]},
    {"name": "decf", "type": {"type": "fixed", "name": "DF", "size": 9,
                              "logicalType": "decimal", "precision": 16,
                              "scale": 2}},
    {"name": "tm", "type": {"type": "int", "logicalType": "time-millis"}},
    {"name": "tu", "type": {"type": "long", "logicalType": "time-micros"}},
    {"name": "lts", "type": {"type": "long",
                             "logicalType": "local-timestamp-micros"}},
    {"name": "ab", "type": {"type": "array", "items": "bytes"}}
  ]
}
"""


def widened_datums(n: int, seed: int = 0) -> List[bytes]:
    """Wire datums over the WIDENED type surface — the types the
    reference serves only via its Value-tree fallback (bytes, fixed,
    uuid, duration, decimal, time-*), here first-class on every backend.
    Values stay in-range (duration under int64 ms, decimals within
    precision) so all paths and the oracle agree exactly."""
    import uuid as _uuid

    rng = random.Random(seed)
    out = []

    def vint(buf, v):
        z = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        while z >= 0x80:
            buf.append((z & 0x7F) | 0x80)
            z >>= 7
        buf.append(z)

    def wbytes(buf, b):
        vint(buf, len(b))
        buf += b

    for _ in range(n):
        buf = bytearray()
        wbytes(buf, rng.randbytes(rng.randrange(0, 24)))          # b
        if rng.random() < 0.3:
            vint(buf, 0)                                          # nb null
        else:
            vint(buf, 1)
            wbytes(buf, rng.randbytes(5))
        buf += rng.randbytes(8)                                   # f8
        if rng.random() < 0.5:
            vint(buf, 0)                                          # nf null
        else:
            vint(buf, 1)
            buf += rng.randbytes(3)
        wbytes(buf, str(_uuid.UUID(int=rng.getrandbits(128)))
               .encode())                                         # uid
        for comp in (rng.randrange(0, 12), rng.randrange(0, 28),
                     rng.randrange(0, 86_400_000)):               # dur
            buf += comp.to_bytes(4, "little")
        v = rng.randrange(-(10 ** 19), 10 ** 19)                  # dec
        nb_ = max((abs(v).bit_length() + 8) // 8, 1)
        wbytes(buf, v.to_bytes(nb_, "big", signed=True))
        if rng.random() < 0.4:
            vint(buf, 0)                                          # ndec null
        else:
            vint(buf, 1)
            v = rng.randrange(-(10 ** 9), 10 ** 9)
            nb_ = max((abs(v).bit_length() + 8) // 8, 1)
            wbytes(buf, v.to_bytes(nb_, "big", signed=True))
        v = rng.randrange(-(10 ** 15), 10 ** 15)                  # decf
        buf += v.to_bytes(9, "big", signed=True)
        vint(buf, rng.randrange(0, 86_400_000))                   # tm
        vint(buf, rng.randrange(0, 86_400_000_000))               # tu
        vint(buf, rng.randrange(0, 2 ** 50))                      # lts
        cnt = rng.randrange(0, 4)                                 # ab
        if cnt:
            vint(buf, cnt)
            for _i in range(cnt):
                wbytes(buf, rng.randbytes(rng.randrange(0, 6)))
        vint(buf, 0)
        out.append(bytes(buf))
    return out
