"""Sharded encode: the device encode program ``shard_map``-ped over
chunks — the serialize counterpart of :mod:`.sharded`
(≙ the chunk fan-out of ``serialize_record_batch``,
``ruhvro/src/serialize.rs:38-99``, with devices in place of threads).

One multi-device launch encodes all chunks: each chunk's extracted
input dict is padded to the common shape bucket, stacked ``[D, ...]``
and sharded over the mesh's ``"chunks"`` axis; each device runs the
single-chunk size→prefix-sum→scatter program on its shard; one
transfer fetches the ``[D, cap + 4R]`` blobs, and the host builds one
BinaryArray per chunk (the reference's chunked return shape).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from ..ops.encode import (
    _BIG,
    DeviceEncoder,
    extract_batch,
    input_entries,
    unpack_input_entries,
)
from ..runtime import device_obs, metrics, telemetry
from ..runtime.chunking import chunk_bounds
from ..runtime.pack import bucket_len
from .sharded import _shard_map, chunk_mesh

__all__ = ["ShardedEncoder"]


class ShardedEncoder:
    """Encode a RecordBatch in ``D`` mesh-sharded chunks, one launch."""

    def __init__(self, ir=None, arrow_schema=None, *,
                 base: Optional[DeviceEncoder] = None, mesh=None,
                 devices=None, n_devices: Optional[int] = None):
        if base is None:
            if ir is None:
                raise ValueError("need a schema IR or a DeviceEncoder")
            if arrow_schema is None:
                from ..schema.arrow_map import to_arrow_schema

                arrow_schema = to_arrow_schema(ir)
            base = DeviceEncoder(ir, arrow_schema)
        self.base = base
        self._jax = base._jax
        self.mesh = mesh if mesh is not None else chunk_mesh(
            devices, n_devices
        )
        self.D = int(self.mesh.devices.size)
        self._cache: Dict[tuple, object] = {}
        self._lock = threading.Lock()
        device_obs.track_holder(self)  # executable lifecycle (ISSUE 12)

    def _jit_caches(self):
        return [self._cache]

    def _sharded_fn(self, entries: tuple, cap: int):
        """Jit of ``shard_map(per-chunk encode)`` over ONE packed
        ``[D, bytes]`` input buffer (a dict input would be one transfer
        per leaf per shard; layout shared with the single-device path
        via ``ops.encode.input_entries``/``unpack_input_entries``),
        cached per (entries, cap) bucket like the single-device
        encoder's jit cache."""
        key = (entries, cap)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        jax = self._jax
        jnp = jax.numpy
        lax = jax.lax
        run = self.base._program()
        P = jax.sharding.PartitionSpec

        def per_shard(buf):
            dv = unpack_input_entries(jnp, lax, buf[0], entries)
            return run(dv, cap)[None]

        smap = _shard_map(jax)
        kwargs = dict(
            mesh=self.mesh,
            in_specs=(P("chunks"),),
            out_specs=P("chunks"),
        )
        try:
            fn = smap(per_shard, check_vma=False, **kwargs)
        except TypeError:
            fn = smap(per_shard, check_rep=False, **kwargs)
        import hashlib

        eh = hashlib.sha1(repr(entries).encode()).hexdigest()[:6]
        total = sum(np.dtype(dt).itemsize * ln for _k, dt, ln in entries)
        fn = device_obs.InstrumentedJit(
            jax, jax.jit(fn), kind="encode.sharded",
            bucket=f"D{self.D},in{total},cap{cap},e{eh}",
            fingerprint=self.base.fingerprint, family="encode",
        )
        with self._lock:
            self._cache[key] = fn
        return fn

    def encode(self, batch: pa.RecordBatch) -> List[pa.Array]:
        """Full sharded encode → one BinaryArray per mesh chunk
        (``device.pipeline_s``-spanned like every other device entry)."""
        with telemetry.phase("device.pipeline_s", rows=batch.num_rows,
                             op="encode", shards=self.D):
            return self._encode(batch)

    def _encode(self, batch: pa.RecordBatch) -> List[pa.Array]:
        jax = self._jax
        n_all = batch.num_rows
        bounds = chunk_bounds(n_all, self.D)
        while len(bounds) < self.D:  # fewer rows than devices: empty pads
            bounds.append((n_all, n_all))

        prog, ir = self.base.prog, self.base.ir
        with telemetry.phase("encode.extract_s", rows=n_all):
            dvs, bound = [], 16
            for a, b in bounds:
                dv, bd = extract_batch(prog, batch.slice(a, b - a), ir)
                dvs.append(dv)
                bound = max(bound, bd)
        cap = bucket_len(bound, minimum=64)

        # unify per-chunk shapes to the max bucket, then stack [D, ...];
        # "#src" columns pad with the out-of-range sentinel (dropped by
        # the scatter), everything else with zeros (inactive lanes)
        stacked: Dict[str, np.ndarray] = {}
        for key in dvs[0]:
            target = max(dv[key].shape[0] for dv in dvs)
            parts = []
            for dv in dvs:
                arr = dv[key]
                if arr.shape[0] < target:
                    fill = _BIG if key.endswith("#src") else 0
                    pad = np.full(target - arr.shape[0], fill, arr.dtype)
                    arr = np.concatenate([arr, pad])
                parts.append(arr)
            stacked[key] = np.stack(parts)

        entries = input_entries(stacked, axis=1)
        packed = np.concatenate(
            [stacked[k].view(np.uint8).reshape(self.D, -1)
             for k, _dt, _ln in entries],
            axis=1,
        )
        spec = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec("chunks")
        )
        fn = self._sharded_fn(entries, cap)
        with telemetry.phase("encode.h2d_s", bytes=packed.nbytes):
            packed_d = jax.device_put(packed, spec)
        metrics.inc("encode.h2d_bytes", packed.nbytes)
        metrics.inc("device.h2d_bytes", packed.nbytes)
        res = fn(packed_d)  # compile/launch split by the wrapper
        with telemetry.phase("encode.d2h_s"):
            blob = np.asarray(jax.device_get(res))
        metrics.inc("encode.d2h_bytes", blob.nbytes)
        metrics.inc("device.d2h_bytes", blob.nbytes)
        device_obs.note_memory(jax)

        out: List[pa.Array] = []
        R = stacked["#active:0"].shape[1]
        for d, (a, b) in enumerate(bounds[: self.D]):
            n = b - a
            sizes = blob[d, cap : cap + 4 * R].view(np.int32)[:n]
            offsets = np.zeros(n + 1, np.int32)
            np.cumsum(sizes, out=offsets[1:])
            total = int(offsets[-1])
            out.append(pa.Array.from_buffers(
                pa.binary(), n,
                [None, pa.py_buffer(offsets),
                 pa.py_buffer(np.ascontiguousarray(blob[d, :total]))],
            ))
        return out[: len(chunk_bounds(n_all, self.D))]
