"""Sharded decode: the fused pipeline ``shard_map``-ped over chunks.

One multi-device launch decodes all chunks: inputs are stacked
``[D, ...]`` arrays sharded over the mesh's ``"chunks"`` axis, each
device runs the per-chunk pipeline (``DeviceDecoder.build_pipeline``) on
its shard, and one transfer fetches the ``[D, blob]`` result. The host
then splits each device's blob and assembles one RecordBatch per chunk —
exactly the reference's chunked return shape (one batch per chunk, never
concatenated, ``deserialize.rs:90-121``).

Capacity handling is shared with the single-device path
(``DeviceDecoder.caps_snapshot`` / ``grow_caps``): caps are global across
shards — every shard runs the same compiled program — and the retry
reductions are max-reduced across shards on the host.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from ..fallback.io import MalformedAvro, malformed_record
from ..ops.decode import (
    BatchTooLarge,
    DeviceDecoder,
    _bucket_label,
    pack_launch_input,
    pad_views,
    split_blob,
    unpack_launch_input,
)
from ..ops.fieldprog import ROWS
from ..ops.varint import ERR_ITEM_OVERFLOW, ERR_NAMES, ERR_SLUGS
from ..runtime import device_obs, metrics, telemetry
from ..runtime.chunking import chunk_bounds
from ..runtime.pack import bucket_len, concat_records

__all__ = ["ShardedDecoder", "chunk_mesh"]


def _shard_map(jax):
    """``jax.shard_map`` across JAX versions."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map  # jax < 0.4.35

    return shard_map


def chunk_mesh(devices=None, n_devices: Optional[int] = None):
    """A 1-D mesh over the ``"chunks"`` axis (the only parallel axis this
    workload has — chunks are independent, SURVEY.md §2)."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices for the chunk mesh, "
                f"have {len(devs)} ({devs[0].platform})"
            )
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), ("chunks",))


class ShardedDecoder:
    """Decode Avro datums in ``D`` mesh-sharded chunks, one launch.

    ≙ the chunk fan-out of ``per_datum_deserialize_threaded``
    (``deserialize.rs:90-121``) with devices in place of threads.
    """

    def __init__(self, ir=None, *, base: Optional[DeviceDecoder] = None,
                 mesh=None, devices=None, n_devices: Optional[int] = None):
        if base is None:
            if ir is None:
                raise ValueError("need a schema IR or a DeviceDecoder")
            base = DeviceDecoder(ir)
        self.base = base
        self._jax = base._jax
        self.mesh = mesh if mesh is not None else chunk_mesh(
            devices, n_devices
        )
        self.D = int(self.mesh.devices.size)
        self._cache: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()

    # -- compiled sharded launch ------------------------------------------

    def _sharded_fn(self, R: int, B: int, item_caps: Tuple[int, ...],
                    tot_caps: Tuple[int, ...], compact: bool = True):
        """Jit of ``shard_map(per-chunk pipeline)`` over the mesh, cached
        per (R, B, caps) bucket like the single-device pipeline."""
        key = (R, B, item_caps, tot_caps, compact)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        jax = self._jax
        jnp = jax.numpy
        lax = jax.lax
        pipe, layout = self.base.build_pipeline(R, B, item_caps, tot_caps,
                                                compact)
        P = jax.sharding.PartitionSpec
        W = B // 4

        def per_shard(buf):
            # local block: leading chunk axis of size 1; the shard buffer
            # is the same packed [words|starts|lengths|n] launch input
            # the single-device path ships (ops/decode.py pack_launch_input
            # — one transfer per call, no scalar args)
            return pipe(*unpack_launch_input(jnp, lax, buf[0], W, R))[None]

        smap = _shard_map(jax)
        kwargs = dict(
            mesh=self.mesh,
            in_specs=(P("chunks"),),
            out_specs=P("chunks"),
        )
        # the body is collective-free (chunks are independent), so the
        # varying-manual-axes/replication check only costs false
        # positives on while_loop carries initialized inside the body;
        # the flag name moved across JAX versions
        try:
            fn = smap(per_shard, check_vma=False, **kwargs)
        except TypeError:
            fn = smap(per_shard, check_rep=False, **kwargs)
        inst = device_obs.InstrumentedJit(
            jax, jax.jit(fn), kind="decode.sharded",
            bucket=f"D{self.D}," + _bucket_label(R, B, item_caps,
                                                 tot_caps, compact),
            fingerprint=self.base.fingerprint, family="decode",
        )
        pair = (inst, layout)
        with self._lock:
            self._cache[key] = pair
        return pair

    # -- orchestration -----------------------------------------------------

    def decode_to_chunk_columns(self, data: Sequence[bytes]):
        """Decode into exactly ``D`` chunks (reference slicing: even, with
        the remainder in the LAST chunk). Returns a list of
        ``(host_columns, n_rows, meta)`` per chunk — the same triple the
        single-device path produces, ready for ``arrow_build``.

        Observability mirrors the single-device pipeline (ISSUE 5): one
        ``device.pipeline_s`` span whose children are the pack, the
        sharded h2d, each ladder rung's compile/launch, and the [D, blob]
        d2h."""
        with telemetry.phase("device.pipeline_s", rows=len(data),
                             op="decode", shards=self.D):
            return self._decode_to_chunk_columns(data)

    def _decode_to_chunk_columns(self, data: Sequence[bytes]):
        n_all = len(data)
        bounds = chunk_bounds(n_all, self.D)
        # fewer records than devices: pad with empty shards so the launch
        # shape stays [D, ...] (inactive lanes decode nothing)
        while len(bounds) < self.D:
            bounds.append((n_all, n_all))

        with telemetry.phase("decode.pack_s", rows=n_all):
            packs = []
            for a, b in bounds:
                flat, offsets = concat_records(data[a:b])
                packs.append((flat, offsets, b - a))
        max_total = max(int(p[1][-1]) for p in packs)
        max_rows = max(p[2] for p in packs)
        if max_total > (1 << 30):
            raise BatchTooLarge(n_all, max_total)
        B = bucket_len(max(max_total, 4), minimum=16)
        R = bucket_len(max(max_rows, 1), minimum=8)
        self.base.seed_caps_from_sample(data, R)

        D = self.D
        W = B // 4
        # ONE host-side materialization: the packed buffer is the only
        # copy of the launch inputs; the rare shard-error path and the
        # output meta reconstruct views from it
        buf = np.empty((D, W + 2 * R + 1), np.uint32)
        ns = np.empty(D, np.int32)
        flats = []
        for d, (flat, offsets, n) in enumerate(packs):
            w, s, ln, fpad = pad_views(flat, offsets, n, R, B)
            buf[d] = pack_launch_input(w, s, ln, n)
            ns[d] = n
            flats.append(fpad)

        jax = self._jax
        prog = self.base.prog
        # place the shards once (ONE packed transfer); cap retries
        # relaunch without re-sending the inputs over the interconnect
        spec = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec("chunks")
        )
        with telemetry.phase("decode.h2d_s", bytes=buf.nbytes):
            buf_d = jax.device_put(buf, spec)
        metrics.inc("decode.h2d_bytes", buf.nbytes)
        metrics.inc("device.h2d_bytes", buf.nbytes)
        hosts = None
        for _attempt in range(24):
            item_caps, tot_caps = self.base.caps_snapshot(R)
            compact = (R, B) not in self.base._str_full
            fn, layout = self._sharded_fn(R, B, item_caps, tot_caps,
                                          compact)
            res = fn(buf_d)  # compile/launch split by the wrapper
            with telemetry.phase("decode.d2h_s"):
                blob = np.asarray(jax.device_get(res))
            metrics.inc("decode.d2h_bytes", blob.nbytes)
            metrics.inc("device.d2h_bytes", blob.nbytes)
            hosts = [split_blob(blob[d], layout) for d in range(D)]
            if compact and "#red:strfit" in hosts[0] and not all(
                h["#red:strfit"][0] for h in hosts
            ):
                self.base._str_full.add((R, B))
                metrics.inc("device.retries")
                telemetry.observe(
                    "device.retry_s", 0.0,
                    reason="str_descriptor_overflow", attempt=_attempt,
                    capacity=_bucket_label(R, B, item_caps, tot_caps,
                                           compact),
                )
                continue
            red_max = {}
            red_sum = {}
            for rid, path in enumerate(prog.regions):
                if rid == ROWS:
                    continue
                red_max[rid] = max(
                    int(h["#red:max:" + path][0]) for h in hosts
                )
                # tot caps bound the PER-SHARD item total, so the shard
                # max (not the sum) is the right growth signal
                red_sum[rid] = max(
                    int(h["#red:sum:" + path][0]) for h in hosts
                )
            t0 = time.perf_counter()
            if not self.base.grow_caps(R, item_caps, tot_caps,
                                       red_max, red_sum):
                break
            metrics.inc("device.retries")
            telemetry.observe(
                "device.retry_s", time.perf_counter() - t0,
                reason="cap_growth", attempt=_attempt,
                capacity=_bucket_label(R, B, item_caps, tot_caps, compact),
                need_items=max(red_max.values(), default=0),
                need_total=max(red_sum.values(), default=0),
            )
        else:
            raise MalformedAvro("array/map item capacity did not converge")
        device_obs.note_memory(jax)

        for d, h in enumerate(hosts):
            if h["#red:err"][0]:
                self._raise_shard_error(
                    buf[d][:W],
                    buf[d][W : W + R].view(np.int32),
                    buf[d][W + R : W + 2 * R].view(np.int32),
                    ns[d],
                    R, B, item_caps, bounds[d][0],
                )

        out = []
        for d, h in enumerate(hosts):
            h = self.base.expand_host(h)
            meta = {"item_totals": {}, "flat": flats[d]}
            for rid, path in enumerate(prog.regions):
                if rid != ROWS:
                    meta["item_totals"][path] = int(
                        h["#red:sum:" + path][0]
                    )
            out.append((h, int(ns[d]), meta))
        return out

    def _raise_shard_error(self, words, starts, lengths, n, R, B,
                           item_caps, base_row: int):
        """Re-run the (lazily compiled) walk-only error pass on the one
        failing shard — single device, rare path — and report the GLOBAL
        record index."""
        jax = self._jax
        err = np.asarray(
            jax.device_get(
                self.base._err_fn(R, B, item_caps)(
                    words, starts, lengths, np.int32(n)
                )
            )
        )[: int(n)]
        bad = err & ~np.uint32(ERR_ITEM_OVERFLOW)
        idx = np.flatnonzero(bad)
        if idx.size == 0:  # pragma: no cover — err flag implies a bad lane
            raise MalformedAvro("device reported a malformed record")
        indices = []
        for r in idx:
            v = int(bad[int(r)])
            b = v & -v
            indices.append(
                (base_row + int(r), ERR_SLUGS.get(b, f"bit_{b:#x}"))
            )
        i = int(idx[0])
        v = int(bad[i])
        bit = v & -v
        raise malformed_record(
            base_row + i, ERR_NAMES.get(bit, f"error bit {bit:#x}"),
            err_name=ERR_SLUGS.get(bit, f"bit_{bit:#x}"),
            tier="device", indices=indices,
        )

    def decode(self, data: Sequence[bytes], ir=None,
               arrow_schema: Optional[pa.Schema] = None
               ) -> List[pa.RecordBatch]:
        """Full sharded decode → one RecordBatch per mesh chunk."""
        from ..ops.arrow_build import build_record_batch

        ir = ir if ir is not None else self.base.prog.ir
        if arrow_schema is None:
            from ..schema.arrow_map import to_arrow_schema

            arrow_schema = to_arrow_schema(ir)
        return [
            build_record_batch(ir, arrow_schema, host, n, meta)
            for host, n, meta in self.decode_to_chunk_columns(data)
        ]
