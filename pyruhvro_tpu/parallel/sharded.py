"""Sharded decode: the fused pipeline ``shard_map``-ped over chunks.

One multi-device launch decodes all chunks: inputs are stacked
``[D, ...]`` arrays sharded over the mesh's ``"chunks"`` axis, each
device runs the per-chunk pipeline (``DeviceDecoder.build_pipeline``) on
its shard, and one transfer fetches the ``[D, blob]`` result. The host
then splits each device's blob and assembles one RecordBatch per chunk —
exactly the reference's chunked return shape (one batch per chunk, never
concatenated, ``deserialize.rs:90-121``).

Capacity handling is shared with the single-device path
(``DeviceDecoder.caps_snapshot`` / ``grow_caps``): caps are global across
shards — every shard runs the same compiled program — and the retry
reductions are max-reduced across shards on the host.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from ..fallback.io import MalformedAvro
from ..ops.decode import (
    BatchTooLarge,
    DeviceDecoder,
    _bucket_label,
    _ready,
    pack_launch_into,
    raise_aggregated_malformed,
    split_blob,
    unpack_launch_input,
)
from ..ops.fieldprog import ROWS
from ..ops.varint import ERR_ITEM_OVERFLOW, ERR_SLUGS
from ..runtime import device_obs, metrics, telemetry
from ..runtime.chunking import chunk_bounds
from ..runtime.pack import bucket_len, concat_records

__all__ = ["ShardedDecoder", "chunk_mesh"]


def _shard_map(jax):
    """``jax.shard_map`` across JAX versions."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map  # jax < 0.4.35

    return shard_map


def chunk_mesh(devices=None, n_devices: Optional[int] = None):
    """A 1-D mesh over the ``"chunks"`` axis (the only parallel axis this
    workload has — chunks are independent, SURVEY.md §2)."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices for the chunk mesh, "
                f"have {len(devs)} ({devs[0].platform})"
            )
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), ("chunks",))


class ShardedDecoder:
    """Decode Avro datums in ``D`` mesh-sharded chunks, one launch.

    ≙ the chunk fan-out of ``per_datum_deserialize_threaded``
    (``deserialize.rs:90-121``) with devices in place of threads.
    """

    def __init__(self, ir=None, *, base: Optional[DeviceDecoder] = None,
                 mesh=None, devices=None, n_devices: Optional[int] = None):
        if base is None:
            if ir is None:
                raise ValueError("need a schema IR or a DeviceDecoder")
            base = DeviceDecoder(ir)
        self.base = base
        self._jax = base._jax
        self.mesh = mesh if mesh is not None else chunk_mesh(
            devices, n_devices
        )
        self.D = int(self.mesh.devices.size)
        self._cache: Dict[tuple, tuple] = {}
        # persistent [D, W + 2R + 1] packed-input host arenas, one per
        # (R, B) bucket (the sharded mirror of DeviceDecoder._arena)
        self._arenas: Dict[tuple, np.ndarray] = {}
        self._arena_used: Dict[tuple, float] = {}
        self._lock = threading.Lock()
        device_obs.track_holder(self)  # lifecycle planes (ISSUE 12)

    def _jit_caches(self):
        return [self._cache]

    def _arena(self, R: int, B: int) -> np.ndarray:
        # thread-keyed like DeviceDecoder._arena: concurrent callers of
        # one memoized codec must not overwrite each other's packed
        # bytes between pack and device_put
        key = (R, B, threading.get_ident())
        with self._lock:
            buf = self._arenas.get(key)
            if buf is None:
                # keep only the largest B per (R, thread) — bounds
                # process-lifetime arena memory (see DeviceDecoder._arena)
                for old in [k for k in self._arenas
                            if k[0] == R and k[2] == key[2]
                            and k[1] < B]:
                    del self._arenas[old]
                    self._arena_used.pop(old, None)
                buf = self._arenas[key] = np.empty(
                    (self.D, B // 4 + 2 * R + 1), np.uint32
                )
                metrics.inc("device.arena.misses")
            else:
                metrics.inc("device.arena.hits")
            self._arena_used[key] = time.monotonic()
        return buf

    # -- compiled sharded launch ------------------------------------------

    def _sharded_fn(self, R: int, B: int, item_caps: Tuple[int, ...],
                    tot_caps: Tuple[int, ...], compact: bool = True):
        """Jit of ``shard_map(per-chunk pipeline)`` over the mesh, cached
        per (R, B, caps) bucket like the single-device pipeline."""
        key = (R, B, item_caps, tot_caps, compact)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        jax = self._jax
        jnp = jax.numpy
        lax = jax.lax
        pipe, layout = self.base.build_pipeline(R, B, item_caps, tot_caps,
                                                compact)
        P = jax.sharding.PartitionSpec
        W = B // 4

        def per_shard(buf):
            # local block: leading chunk axis of size 1; the shard buffer
            # is the same packed [words|starts|lengths|n] launch input
            # the single-device path ships (ops/decode.py pack_launch_input
            # — one transfer per call, no scalar args)
            return pipe(*unpack_launch_input(jnp, lax, buf[0], W, R))[None]

        smap = _shard_map(jax)
        kwargs = dict(
            mesh=self.mesh,
            in_specs=(P("chunks"),),
            out_specs=P("chunks"),
        )
        # the body is collective-free (chunks are independent), so the
        # varying-manual-axes/replication check only costs false
        # positives on while_loop carries initialized inside the body;
        # the flag name moved across JAX versions
        try:
            fn = smap(per_shard, check_vma=False, **kwargs)
        except TypeError:
            fn = smap(per_shard, check_rep=False, **kwargs)
        # the packed shard buffer is donated like the single-device
        # input (ISSUE 10): XLA recycles its memory for the [D, blob]
        # outputs; capacity-ladder retries re-put from the host arena
        # (the "donation not usable" warning is scoped away inside the
        # InstrumentedJit compile paths)
        inst = device_obs.InstrumentedJit(
            jax, jax.jit(fn, donate_argnums=0), kind="decode.sharded",
            bucket=f"D{self.D}," + _bucket_label(R, B, item_caps,
                                                 tot_caps, compact),
            fingerprint=self.base.fingerprint, family="decode",
        )
        pair = (inst, layout)
        with self._lock:
            self._cache[key] = pair
        return pair

    # -- orchestration -----------------------------------------------------

    def decode_to_chunk_columns(self, data: Sequence[bytes]):
        """Decode into exactly ``D`` chunks (reference slicing: even, with
        the remainder in the LAST chunk). Returns a list of
        ``(host_columns, n_rows, meta)`` per chunk — the same triple the
        single-device path produces, ready for ``arrow_build``.

        Observability mirrors the single-device pipeline (ISSUE 5): one
        ``device.pipeline_s`` span whose children are the pack, the
        sharded h2d, each ladder rung's compile/launch, and the [D, blob]
        d2h."""
        with telemetry.phase("device.pipeline_s", rows=len(data),
                             op="decode", shards=self.D):
            return self._decode_to_chunk_columns(data)

    def _decode_to_chunk_columns(self, data: Sequence[bytes]):
        n_all = len(data)
        bounds = chunk_bounds(n_all, self.D)
        # fewer records than devices: pad with empty shards so the launch
        # shape stays [D, ...] (inactive lanes decode nothing)
        while len(bounds) < self.D:
            bounds.append((n_all, n_all))

        jax = self._jax
        time0 = time.perf_counter()
        # ONE flat concat of the whole batch (C++ shim, GIL released);
        # shards are slices of it — per-shard concat_records would walk
        # the datum list D times
        with telemetry.phase("decode.pack_s", rows=n_all):
            flat_all, offsets_all = concat_records(data)
        max_total = max(
            int(offsets_all[b] - offsets_all[a]) for a, b in bounds
        )
        max_rows = max(b - a for a, b in bounds)
        if max_total > (1 << 30):
            raise BatchTooLarge(n_all, max_total)
        B = bucket_len(max(max_total, 4), minimum=16)
        R = bucket_len(max(max_rows, 1), minimum=8)
        # capacity planner first (ISSUE 10): a schema ANY decoder in
        # this process (or a previous one, via ROUTING_PROFILE.json)
        # converged starts at the learned rung — zero retry compiles,
        # no host sample probe
        if not self.base.seed_from_plan(R):
            self.base.seed_caps_from_sample(data, R)

        D = self.D
        W = B // 4
        prog = self.base.prog
        # persistent host arena (identity-stable across warm calls) —
        # the packed buffer is the only host copy of the launch inputs;
        # the rare shard-error path reconstructs views from it
        buf = self._arena(R, B)
        ns = np.empty(D, np.int32)
        flats = []
        devs = list(self.mesh.devices.reshape(-1))
        spec = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec("chunks")
        )
        # per-shard pack pipelined with per-device async h2d (ISSUE 10):
        # shard d's transfer is dispatched BEFORE shard d+1 is packed, so
        # the copies overlap the packing loop instead of waiting for one
        # big [D, ...] buffer to finish; the single-device arrays then
        # assemble into the mesh-sharded input without another copy
        shards_d = []
        overlap_s = 0.0
        with telemetry.phase("decode.h2d_s", bytes=buf.nbytes):
            for d, (a, b) in enumerate(bounds):
                t0 = time.perf_counter()
                n = b - a
                base_off = int(offsets_all[a])
                pack_launch_into(
                    buf[d], flat_all[base_off : int(offsets_all[b])],
                    offsets_all[a : b + 1], n, R, B,
                )
                ns[d] = n
                flats.append(
                    flat_all[base_off : int(offsets_all[b])]
                )
                dt_pack = time.perf_counter() - t0
                if any(not _ready(s) for s in shards_d):
                    # an earlier shard's async transfer was STILL in
                    # flight when this shard's pack finished — those
                    # host seconds genuinely ran concurrently with the
                    # copy (checked AFTER the pack: conservative, a
                    # transfer completing mid-pack goes uncounted)
                    overlap_s += dt_pack
                telemetry.observe("decode.shard_pack_s", dt_pack,
                                  shard=d, rows=n)
                shards_d.append(
                    jax.device_put(buf[d : d + 1], devs[d])
                )
            buf_d = jax.make_array_from_single_device_arrays(
                (D, W + 2 * R + 1), spec, shards_d
            )
        metrics.inc("decode.h2d_bytes", buf.nbytes)
        metrics.inc("device.h2d_bytes", buf.nbytes)
        if overlap_s:
            metrics.inc("device.overlap_s", overlap_s)
            metrics.inc("device.overlap_calls")
        hosts = None
        grew = False
        for _attempt in range(24):
            item_caps, tot_caps = self.base.caps_snapshot(R)
            compact = (R, B) not in self.base._str_full
            fn, layout = self._sharded_fn(R, B, item_caps, tot_caps,
                                          compact)
            if buf_d is None or getattr(buf_d, "is_deleted",
                                        lambda: True)():
                # the previous rung's donated input was consumed:
                # re-place the shards from the host arena
                with telemetry.phase("decode.h2d_s", bytes=buf.nbytes):
                    buf_d = jax.device_put(buf, spec)
                metrics.inc("decode.h2d_bytes", buf.nbytes)
                metrics.inc("device.h2d_bytes", buf.nbytes)
            res = fn(buf_d)  # compile/launch split by the wrapper
            buf_d = None  # donated: dead after the launch
            with telemetry.phase("decode.d2h_s"):
                blob = np.asarray(jax.device_get(res))
            metrics.inc("decode.d2h_bytes", blob.nbytes)
            metrics.inc("device.d2h_bytes", blob.nbytes)
            hosts = [split_blob(blob[d], layout) for d in range(D)]
            if compact and "#red:strfit" in hosts[0] and not all(
                h["#red:strfit"][0] for h in hosts
            ):
                self.base._str_full.add((R, B))
                grew = True
                metrics.inc("device.retries")
                telemetry.observe(
                    "device.retry_s", 0.0,
                    reason="str_descriptor_overflow", attempt=_attempt,
                    capacity=_bucket_label(R, B, item_caps, tot_caps,
                                           compact),
                )
                continue
            red_max = {}
            red_sum = {}
            for rid, path in enumerate(prog.regions):
                if rid == ROWS:
                    continue
                red_max[rid] = max(
                    int(h["#red:max:" + path][0]) for h in hosts
                )
                # tot caps bound the PER-SHARD item total, so the shard
                # max (not the sum) is the right growth signal
                red_sum[rid] = max(
                    int(h["#red:sum:" + path][0]) for h in hosts
                )
            t0 = time.perf_counter()
            if not self.base.grow_caps(R, item_caps, tot_caps,
                                       red_max, red_sum):
                break
            grew = True
            metrics.inc("device.retries")
            telemetry.observe(
                "device.retry_s", time.perf_counter() - t0,
                reason="cap_growth", attempt=_attempt,
                capacity=_bucket_label(R, B, item_caps, tot_caps, compact),
                need_items=max(red_max.values(), default=0),
                need_total=max(red_sum.values(), default=0),
            )
        else:
            raise MalformedAvro("array/map item capacity did not converge")
        # teach the planner the converged rung (shared with the
        # single-device path: its next cold call also starts warm);
        # grew=True re-harvests a bucket whose caps climbed THIS call
        self.base._harvest_plan(R, grew)
        device_obs.note_memory(jax)
        wall = time.perf_counter() - time0
        if overlap_s and wall > 0:
            telemetry.annotate(
                overlap_s=round(overlap_s, 6),
                overlap_frac=round(min(overlap_s / wall, 1.0), 4),
            )

        # per-shard quarantine (ISSUE 10): EVERY failing shard runs the
        # walk-only error pass and the indices aggregate — globally
        # re-based — into ONE MalformedAvro, so a tolerant caller
        # (api.py on_error=skip/null) isolates all offenders across the
        # whole mesh in a single relaunch instead of one per shard
        bad_indices: list = []
        for d, h in enumerate(hosts):
            if h["#red:err"][0]:
                t0 = time.perf_counter()
                self._collect_shard_errors(
                    buf[d][:W],
                    buf[d][W : W + R].view(np.int32),
                    buf[d][W + R : W + 2 * R].view(np.int32),
                    ns[d],
                    R, B, item_caps, bounds[d][0], bad_indices,
                )
                telemetry.observe(
                    "decode.shard_err_s", time.perf_counter() - t0,
                    shard=d,
                )
        if bad_indices:
            raise_aggregated_malformed(bad_indices)

        out = []
        for d, h in enumerate(hosts):
            h = self.base.expand_host(h)
            meta = {"item_totals": {}, "flat": flats[d]}
            for rid, path in enumerate(prog.regions):
                if rid != ROWS:
                    meta["item_totals"][path] = int(
                        h["#red:sum:" + path][0]
                    )
            out.append((h, int(ns[d]), meta))
        return out

    def _collect_shard_errors(self, words, starts, lengths, n, R, B,
                              item_caps, base_row: int,
                              collect: list) -> None:
        """Run the (lazily compiled) walk-only error pass on one failing
        shard — single device, rare path — and append its
        ``(GLOBAL record index, slug)`` pairs into ``collect``."""
        jax = self._jax
        err = np.asarray(
            jax.device_get(
                self.base._err_fn(R, B, item_caps)(
                    words, starts, lengths, np.int32(n)
                )
            )
        )[: int(n)]
        bad = err & ~np.uint32(ERR_ITEM_OVERFLOW)
        idx = np.flatnonzero(bad)
        if idx.size == 0:  # pragma: no cover — err flag implies a bad lane
            raise MalformedAvro("device reported a malformed record")
        for r in idx:
            v = int(bad[int(r)])
            b = v & -v
            collect.append(
                (base_row + int(r), ERR_SLUGS.get(b, f"bit_{b:#x}"))
            )

    def decode(self, data: Sequence[bytes], ir=None,
               arrow_schema: Optional[pa.Schema] = None
               ) -> List[pa.RecordBatch]:
        """Full sharded decode → one RecordBatch per mesh chunk."""
        from ..ops.arrow_build import build_record_batch

        ir = ir if ir is not None else self.base.prog.ir
        if arrow_schema is None:
            from ..schema.arrow_map import to_arrow_schema

            arrow_schema = to_arrow_schema(ir)
        return [
            build_record_batch(ir, arrow_schema, host, n, meta)
            for host, n, meta in self.decode_to_chunk_columns(data)
        ]
