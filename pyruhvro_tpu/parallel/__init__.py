"""Multi-chip chunk parallelism: ``shard_map`` over a device mesh.

The reference's one real parallelism strategy is data-parallel chunking —
the input is sliced into ``num_chunks`` independent pieces, one decode
task per chunk, one RecordBatch per chunk returned
(``ruhvro/src/deserialize.rs:57-68,90-121``; SURVEY.md §2 parallelism
table). Its mechanism is host threads on a tokio pool; the TPU-native
mechanism here is a 1-D ``jax.sharding.Mesh`` over a ``"chunks"`` axis:
each device in the mesh runs the SAME fused decode pipeline
(``ops/decode.py``) on its shard of the packed records via ``shard_map``,
in one jitted multi-device launch.

Because chunks are independent, the program body contains **no
collectives** — the sharding costs zero ICI/DCN traffic (the scaling-book
recipe degenerates to pure DP). That is a property of the workload, not a
shortcut: the reference has no cross-chunk communication either
(SURVEY.md §2 "Distributed communication backend: absent").

This module is exercised three ways (SURVEY.md §4.7):

* unit tests on a spoofed 8-device CPU mesh
  (``XLA_FLAGS=--xla_force_host_platform_device_count=8``),
* the driver's ``dryrun_multichip`` entry (``__graft_entry__.py``),
* ``backend='tpu'`` calls on real multi-chip meshes, via
  ``DeviceCodec.decode_threaded``.
"""

from .sharded import ShardedDecoder, chunk_mesh
from .sharded_encode import ShardedEncoder

__all__ = ["ShardedDecoder", "ShardedEncoder", "chunk_mesh"]
