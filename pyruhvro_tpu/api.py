"""Public API — drop-in parity with the reference's 5 functions.

≙ ``src/lib.rs:150-158``:

* ``deserialize_array(data, schema)`` → one ``pyarrow.RecordBatch``
* ``deserialize_array_threaded(data, schema, num_chunks)`` → ``list[RecordBatch]``
  (one per chunk, never concatenated — ``deserialize.rs:76-121``)
* ``deserialize_array_threaded_spawn`` — same result; the reference's
  spawn variant differs only in host thread-pool strategy
  (``src/lib.rs:108-128``), which has no analogue on the device path;
  kept for signature parity.
* ``serialize_record_batch(batch, schema, num_chunks)`` → ``list[BinaryArray]``
* ``serialize_record_batch_spawn`` — ditto.

Additions over the reference (the BASELINE.json north star):
``backend=`` on every function — ``"auto"`` (default), ``"tpu"`` (force
device; errors if unsupported), ``"host"`` (force the host path) — and
the error-policy layer: ``on_error="raise" | "skip" | "null"`` plus
``return_errors=True`` on every function, with quarantined rows
reported through :func:`pyruhvro_tpu.last_quarantine` (see the
"error-policy layer" section below).

The host path itself is two-tiered, mirroring the reference's
fast/fallback split (``deserialize.rs:26-29``): schemas in the fast
subset decode through the **native C++ VM** (:mod:`.hostpath`, built on
demand); everything else through the pure-Python fallback decoder (the
differential oracle). ``backend="auto"`` picks device vs host by a
one-time interconnect probe: on a co-located accelerator the device
path wins from small batch sizes, while behind a high-latency tunnel
(~tens of ms RTT) the native host path wins at every size — forcing
``backend="tpu"`` always bypasses the probe. Override with
``PYRUHVRO_TPU_DEVICE_MIN_ROWS=<n>`` (device for batches ≥ n) and
disable the native VM entirely with ``PYRUHVRO_TPU_NO_NATIVE=1``.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence

import pyarrow as pa

from .gate import device_supported
from .ops import UnsupportedOnDevice
from .fallback.decoder import (
    compile_reader,
    decode_pairs_tolerant,
    decode_to_record_batch,
    rows_to_record_batch,
)
from .fallback.encoder import compile_encoder_plan, encode_record_batch
from .fallback.io import MalformedAvro, max_datum_bytes, shift_malformed
from .runtime import (
    audit,
    breaker,
    coldigest,
    deadline,
    faults,
    memacct,
    metrics,
    quarantine,
    router,
    sampling,
    telemetry,
    traceprop,
)
from .runtime.deadline import DeadlineExceeded
from .runtime.chunking import bounds_rows, chunk_bounds
from .runtime.ingest import as_datum_input
from .runtime.pool import map_chunks, map_chunks_proc
from .schema.cache import SchemaEntry, get_or_parse_schema

__all__ = [
    "deserialize_array",
    "deserialize_array_threaded",
    "deserialize_array_threaded_spawn",
    "serialize_record_batch",
    "serialize_record_batch_spawn",
]


def _device_codec_ex(entry: SchemaEntry, backend: str):
    """Resolve the TPU codec for this schema → ``(codec_or_None, reason)``.

    ``reason`` names why the device path was NOT taken (the routing
    explainer recorded on the call's span). backend="auto": device if
    the schema passes the fast gate AND a JAX device backend
    initializes; silently falls back otherwise (reference semantics).
    backend="tpu": device or raise. backend="host": None.
    """
    if backend == "host":
        return None, "backend_host"
    br = breaker.get("device_backend")
    probing = False
    if backend == "auto" and entry._extras.get("device_failure") is not None:
        # device codec for THIS schema already blew up. The failure is
        # no longer a permanent latch, but it is SCHEMA-SCOPED: one
        # schema whose init deterministically fails must not starve
        # every other schema of the device arm (and must not flap the
        # shared breaker). The latch carries its own exponential retry
        # schedule (breaker backoff knob/cap): while within backoff the
        # cached verdict serves — no re-paying a seconds-long failed
        # init per call — then ONE call clears the latch and retries
        # the construction (failure re-latches with doubled backoff).
        # An open device_backend breaker (call-time failures elsewhere)
        # also withholds the retry. Counted per call so a fallback
        # storm is visible in snapshots.
        import time as _time

        if (_time.monotonic() < entry._extras.get(
                "device_failure_retry_at", 0.0) or not br.allow()):
            metrics.inc("route.device_failure")
            return None, "device_failure_cached"
        probing = True
        with entry._lock:
            entry._extras.pop("device_failure", None)
        _reset_failed_device_probe()
    elif backend == "auto" and not br.allow():
        # call-time device failures elsewhere opened the breaker: stop
        # offering the device arm at all until the backoff expires
        metrics.inc("route.device_breaker_open")
        return None, "device_breaker_open"
    supported = device_supported(entry.ir)
    if backend == "auto" and not supported:
        return None, "gate_fail"
    if not supported:  # backend == "tpu"
        raise ValueError(
            "schema is outside the device subset (e.g. decimals beyond "
            "decimal128's 16 bytes / precision 38, or unknown logical "
            "types on fixed); use backend='auto' or backend='host'"
        )
    try:
        from .ops.codec import get_device_codec
    except ImportError as e:
        if backend == "tpu":
            raise RuntimeError(
                f"TPU backend is not available in this build: {e}"
            ) from e
        # missing module = deliberately host-only build, not a broken
        # backend: stay silent (reference fallback semantics)
        return None, "no_device_build"
    try:
        codec = get_device_codec(entry)
        if probing:
            # successful retry: forget the schema's backoff history
            with entry._lock:
                entry._extras.pop("device_failure_opens", None)
                entry._extras.pop("device_failure_retry_at", None)
        return codec, None
    except UnsupportedOnDevice:
        # schema outside the *device* subset (e.g. nested repetition): the
        # silent fallback here mirrors the reference's unsupported-schema
        # gate (deserialize.rs:26-29)
        if backend == "tpu":
            raise
        metrics.inc("route.gate_reject")
        return None, "gate_reject"
    except Exception as e:
        # a *broken backend* is not the reference's silent-fallback case:
        # surface it once per schema, remember the failure, degrade in
        # 'auto' / raise in 'tpu'. Store only the repr — keeping the live
        # exception would pin its whole traceback (and every local in the
        # failed device init) in the process-lifetime schema cache.
        if backend == "tpu":
            raise
        import time as _time

        with entry._lock:
            entry._extras["device_failure"] = repr(e)
            opens = int(entry._extras.get("device_failure_opens", 0)) + 1
            entry._extras["device_failure_opens"] = opens
            entry._extras["device_failure_retry_at"] = (
                _time.monotonic() + breaker.backoff_schedule(opens))
        # deliberately NOT br.record_failure(): a schema-scoped init
        # failure must not open the process-wide breaker and withhold
        # the device arm from healthy schemas. Backend-wide faults
        # reach the breaker through their own feeds — the backend
        # probe (ops/codec) and call-time launch failures.
        metrics.inc("route.device_failure")
        warnings.warn(
            f"pyruhvro_tpu device backend failed to initialize for this "
            f"schema; falling back to the (much slower) host path: {e!r}",
            RuntimeWarning,
            stacklevel=4,  # user -> api fn -> _route -> _device_codec_ex
        )
        return None, "device_failure"


def _reset_failed_device_probe() -> None:
    """Clear a FAILED backend-probe memo so a backoff-granted re-probe
    actually re-runs the probe (a successful memo is never touched —
    its devices/RTT verdicts stay valid for the process lifetime)."""
    try:
        from .ops import codec as _dev

        _dev.reset_failed_probe()
    except ImportError:
        pass


def _device_codec(entry: SchemaEntry, backend: str):
    """Back-compat probe (bench/tests): the codec without the reason."""
    return _device_codec_ex(entry, backend)[0]


def _route_candidates(entry: SchemaEntry, backend: str, n_rows: int,
                      *, need_encode: bool = False):
    """Static-gate verdict PLUS the available-tier candidate map the
    router chooses among → ``(tier, impl, reason, candidates)``.

    The static verdict is the pre-router behavior bit for bit (and the
    router's cold-start policy). ``candidates`` maps every tier that
    COULD serve this call to its impl: a device codec that the static
    gate passes over (``device_min_rows`` / ``devices_cpu_only`` /
    ``interconnect_remote``) stays a candidate arm — under
    ``PYRUHVRO_TPU_AUTOTUNE=1`` the learned cost model, not the env
    knob, decides whether it ever runs. A forced backend collapses the
    candidate set to the forced tier's options."""
    codec = None
    reason = None
    host_pref = None
    if backend == "host":
        reason = "backend_host"
    elif need_encode and not _device_encode_available():
        # decided before constructing the (decode-lowering +
        # backend-probing) device codec, so serialize-only workloads in
        # a host-only build never pay for it
        if backend == "tpu":
            raise RuntimeError(
                "the device encode kernel is not available in this build"
            )
        reason = "no_device_encode"
    else:
        codec, reason = _device_codec_ex(entry, backend)
        if codec is not None and backend == "auto":
            host_pref = _auto_prefers_host(entry, n_rows)
    # a forced-device call never runs (or offers) a host tier: don't
    # build and pin a native codec it can't use
    native = None if backend == "tpu" else _native_host_codec(entry)
    candidates = {}
    if codec is not None:
        candidates["device"] = codec
    if backend != "tpu":
        if native is not None:
            candidates["native"] = native
        else:
            candidates["fallback"] = None
    if codec is not None and host_pref is None:
        return "device", codec, (
            "backend_tpu" if backend == "tpu" else "device_selected"
        ), candidates
    if host_pref is not None:
        reason = host_pref
    if native is not None:
        return "native", native, reason, candidates
    return "fallback", None, reason, candidates


def _route(entry: SchemaEntry, backend: str, n_rows: int,
           *, need_encode: bool = False):
    """Resolve which tier serves this call → ``(tier, impl, reason)``.

    tier: ``"device"`` (impl = DeviceCodec), ``"native"`` (impl =
    NativeHostCodec) or ``"fallback"`` (impl = None, pure-Python path).
    ``reason`` is the routing explainer recorded on the call span — for
    host-side tiers it names why the device path was NOT taken. This is
    the STATIC verdict; API calls route through :func:`_decide`, which
    may override it from the learned cost model when
    ``PYRUHVRO_TPU_AUTOTUNE=1``."""
    tier, impl, reason, _cands = _route_candidates(
        entry, backend, n_rows, need_encode=need_encode)
    return tier, impl, reason


def _decide(entry: SchemaEntry, backend: str, n_rows: int, *, op: str,
            chunks: int = 1, need_encode: bool = False):
    """One routed decision: static gates feed the router as the
    cold-start policy, the router predicts/acts (ledger +
    autotune), and the verdict lands on the call span."""
    tier, impl, reason, cands = _route_candidates(
        entry, backend, n_rows, need_encode=need_encode)
    dec = router.decide(entry, backend, n_rows, op=op, chunks=chunks,
                        candidates=cands, static=(tier, impl, reason))
    telemetry.set_route(dec.tier, dec.reason)
    return dec


def _native_host_codec(entry: SchemaEntry):
    """The C++ host VM codec for this schema, or None (outside the fast
    subset, no toolchain, or disabled via PYRUHVRO_TPU_NO_NATIVE)."""
    from .runtime import knobs

    if knobs.get_bool("PYRUHVRO_TPU_NO_NATIVE"):
        return None

    def make():
        try:
            from .hostpath import NativeHostCodec

            return NativeHostCodec(entry.ir, entry.arrow_schema)
        except Exception:
            # unsupported schema / missing toolchain: the Python
            # fallback serves the call (reference silent-gate semantics)
            return None

    return entry.get_extra("native_host_codec", make)


def _auto_prefers_host(entry: SchemaEntry, n_rows: int):
    """In ``backend="auto"`` with BOTH a device codec and the native host
    VM available: route to host when the device cannot win.

    Returns the routing reason (truthy string) when host should serve,
    else None: ``"device_min_rows"`` (env override), ``"devices_cpu_only"``
    or ``"interconnect_remote"`` — the verdict lands on the call span and
    in the ``route.reason.*`` counters.

    Two signals, cheapest first:

    1. platform: when every JAX device is a host CPU, the XLA pipeline
       is just a slower CPU program than the native VM (measured 60×
       slower at the 10M-row scale) — host wins at every size. The
       device pipeline exists for accelerators.
    2. a one-time interconnect RTT probe
       (:func:`.ops.codec.interconnect_rtt_s`): a co-located
       accelerator (sub-ms RTT) beats the single-core host VM from
       small sizes, so the device keeps the batch; a remote tunnel
       (tens of ms per round trip, ~30 MB/s) loses to the multi-M rec/s
       host VM at every batch size, so host serves ``auto`` and
       ``backend="tpu"`` remains the explicit override.

    ``PYRUHVRO_TPU_DEVICE_MIN_ROWS=<n>`` replaces both signals."""
    from .runtime import knobs

    if _native_host_codec(entry) is None:
        return None
    min_rows = knobs.get_int("PYRUHVRO_TPU_DEVICE_MIN_ROWS")
    if min_rows is not None:
        return "device_min_rows" if n_rows < min_rows else None
    from .ops.codec import devices_cpu_only, interconnect_remote

    # safe: callers reach here only with a constructed device codec, so
    # the memoized backend probe has already resolved (never wedges)
    if devices_cpu_only():
        return "devices_cpu_only"
    if interconnect_remote():
        return "interconnect_remote"
    return None


# tri-state module global: None = not yet probed, else the cached bool
_device_encode_available_memo: Optional[bool] = None


def _device_encode_available() -> bool:
    """True when ``ops.encode`` exists (checked once, without importing
    JAX or building any codec)."""
    global _device_encode_available_memo
    if _device_encode_available_memo is None:
        import importlib.util

        _device_encode_available_memo = (
            importlib.util.find_spec("pyruhvro_tpu.ops.encode") is not None
        )
    return _device_encode_available_memo


def _native_degradable(e: BaseException) -> bool:
    """Native-VM failures that justify degrading to the pure-Python
    fallback decoder — the shared fault-domain taxonomy
    (``runtime.faults.degradable``)."""
    from .runtime import faults

    return faults.degradable(e)


def _count_native_degrade(e: BaseException) -> None:
    metrics.inc("route.native_failure")
    telemetry.annotate(native_degraded=type(e).__name__)


def _host_reader(entry: SchemaEntry):
    """Per-schema memoized fallback wire reader (compile once, use on every
    call/chunk — the host analogue of the schema→kernel cache)."""
    return entry.get_extra("host_reader", lambda: compile_reader(entry.ir))


def _check_backend(backend: str) -> str:
    if backend not in ("auto", "tpu", "host"):
        raise ValueError(f"backend must be 'auto', 'tpu' or 'host', got {backend!r}")
    return backend


def _check_on_error(on_error: str) -> str:
    if on_error not in ("raise", "skip", "null"):
        raise ValueError(
            f"on_error must be 'raise', 'skip' or 'null', got {on_error!r}"
        )
    return on_error


# -- error-policy layer (on_error="skip"/"null") ---------------------------
#
# The tolerant engine behind every public API call's ``on_error`` knob.
# Strategy is optimistic-fast-path: the batch decodes on its normal tier
# at full speed; only a MalformedAvro pays extra work. The native VM
# reports the FIRST bad row, so the resume loop decodes the known-good
# prefix in one pass, quarantines the offender, and re-enters the same
# tier on the remaining slice (single-VM-thread retries so the VM stops
# AT the error instead of decoding the whole tail per attempt — total
# work stays ~2 passes regardless of how many rows are poisoned). The
# device tier's error pass yields the FULL per-lane error-bit row mask
# (``MalformedAvro.indices``), so all offenders quarantine at once and
# the survivors decode in one extra launch. The pure-Python oracle is
# the per-record last resort for anything that fails without a usable
# row index. ``on_error="raise"`` (the default) never enters any of
# this — behavior and cost are exactly the pre-policy fast path.


def _enforce_max_datum(data) -> None:
    """The PYRUHVRO_TPU_MAX_DATUM_BYTES ceiling for ``on_error="raise"``
    paths on every tier. Free when the knob is unset (one env read)."""
    limit = max_datum_bytes()
    if not limit:
        return
    if hasattr(data, "lens"):
        # arrow-ingested datums: screen the offsets diff vectorized
        # instead of materializing ten million bytes objects
        lens = data.lens()
        if len(lens) and int(lens.max()) > limit:
            import numpy as np

            j = int(np.argmax(lens > limit))
            raise MalformedAvro(
                f"record {j}: datum of {int(lens[j])} bytes exceeds "
                f"PYRUHVRO_TPU_MAX_DATUM_BYTES={limit}",
                index=j, err_name="datum_too_large", tier="policy",
            )
        return
    for j, d in enumerate(data):
        if len(d) > limit:
            raise MalformedAvro(
                f"record {j}: datum of {len(d)} bytes exceeds "
                f"PYRUHVRO_TPU_MAX_DATUM_BYTES={limit}",
                index=j, err_name="datum_too_large", tier="policy",
            )


def _row_nullable(ir) -> bool:
    """True when every top-level field admits null — the schemas where
    ``on_error="null"`` can keep the row count (bad rows become all-null
    rows); anywhere else the policy degrades to skip, counted."""
    from .schema.model import Union as _Union

    return all(
        isinstance(f.type, _Union) and f.type.null_index is not None
        for f in ir.fields
    )


def _concat(batches: List[pa.RecordBatch], entry) -> pa.RecordBatch:
    batches = [b for b in batches if b.num_rows]
    if not batches:
        return rows_to_record_batch([], entry.ir, entry.arrow_schema)
    if len(batches) == 1:
        return batches[0]
    if hasattr(pa, "concat_batches"):
        return pa.concat_batches(batches)
    out = pa.Table.from_batches(batches).combine_chunks().to_batches()
    return out[0] if out else batches[0]


def _oracle_pairs(pairs, entry, quar) -> pa.RecordBatch:
    """Per-record last resort: every pair through the Python oracle,
    offenders into ``quar`` with their caller-assigned global indices.

    Covers BOTH poison classes: wire-level (stage 1 — captured per
    record by the reader) and value-level (stage 2 — wire-valid datums
    whose VALUES cannot build, e.g. invalid uuid text or a decimal
    beyond its declared precision; isolated by bisecting the Arrow
    build, which raises ValueError/ArrowInvalid without a row index)."""
    rows, errs = decode_pairs_tolerant(
        pairs, entry.ir, _host_reader(entry)
    )
    for gi, datum, name in errs:
        quar.append(quarantine.QuarantinedRecord(
            gi, datum, name, "fallback"))
    bad = {gi for gi, _d, _n in errs}
    triples = [
        (gi, d, v)
        for (gi, d), v in zip(
            [pr for pr in pairs if pr[0] not in bad], rows)
    ]

    def build(tris):
        return rows_to_record_batch(
            [v for _, _, v in tris], entry.ir, entry.arrow_schema)

    try:
        return build(triples)
    except (ValueError, OverflowError):
        pass

    def bisect(tris):
        if not tris:
            return []
        try:
            return [build(tris)]
        except (ValueError, OverflowError) as e:
            if len(tris) == 1:
                gi, d, _v = tris[0]
                quar.append(quarantine.QuarantinedRecord(
                    gi, d, "bad_value", "fallback"))
                return []
            mid = len(tris) // 2
            return bisect(tris[:mid]) + bisect(tris[mid:])

    return _concat(bisect(triples), entry)


def _tolerant_decode(tier, impl, entry, data, base):
    """Decode ``data`` on its routed tier under a tolerant policy →
    ``(batch_of_survivors, quarantine_entries)``; surviving rows keep
    their relative order, entries carry GLOBAL indices (``base`` +
    position)."""
    pairs = [(base + j, d) for j, d in enumerate(data)]
    quar: List[quarantine.QuarantinedRecord] = []
    limit = max_datum_bytes()
    if limit:
        keep = []
        for gi, d in pairs:
            if len(d) > limit:
                quar.append(quarantine.QuarantinedRecord(
                    gi, d, "datum_too_large", "policy"))
            else:
                keep.append((gi, d))
        pairs = keep
    if tier == "fallback" or impl is None:
        batch = _oracle_pairs(pairs, entry, quar)
        return batch, quar

    def tier_decode(items, first):
        if tier == "native" and not first:
            # one VM thread: the shard runner stops AT the first bad
            # record, so each resume attempt costs only the distance to
            # the next offender instead of a full pass over the tail
            return impl.decode(items, nthreads=1)
        return impl.decode(items)

    parts: List[pa.RecordBatch] = []
    first = True
    budget = 2 * len(pairs) + 16  # hard stop against no-progress loops
    while pairs:
        # each resume is a unit of work that can be skipped: a blown
        # wall-clock budget stops the salvage walk here, naming the
        # first record it never reached (a deadline is a call contract
        # and outranks the tolerant policy)
        deadline.check(index=pairs[0][0], site="tolerant.resume")
        budget -= 1
        if budget <= 0:
            parts.append(_oracle_pairs(pairs, entry, quar))
            break
        items = [d for _, d in pairs]
        try:
            parts.append(tier_decode(items, first))
            pairs = []
            break
        except MalformedAvro as e:
            first = False
            idxs = getattr(e, "indices", None)
            k = getattr(e, "index", None)
            if idxs and all(0 <= i < len(pairs) for i, _ in idxs):
                # device error pass: the full row mask in one shot
                names = {}
                for i, nm in idxs:
                    names.setdefault(i, nm)
                for i in sorted(names):
                    gi, d = pairs[i]
                    quar.append(quarantine.QuarantinedRecord(
                        gi, d, names[i] or "malformed", e.tier or tier))
                pairs = [p for j, p in enumerate(pairs)
                         if j not in names]
            elif k is not None and 0 <= k < len(pairs):
                # first-bad-index tiers: prefix is known good — decode
                # it in one pass, drop the offender, resume on the tail
                if k:
                    try:
                        parts.append(tier_decode(items[:k], True))
                    except DeadlineExceeded:
                        raise
                    except Exception:
                        parts.append(
                            _oracle_pairs(pairs[:k], entry, quar))
                gi, d = pairs[k]
                quar.append(quarantine.QuarantinedRecord(
                    gi, d, e.err_name or "malformed", e.tier or tier))
                pairs = pairs[k + 1:]
            else:
                parts.append(_oracle_pairs(pairs, entry, quar))
                break
        except DeadlineExceeded:
            raise
        except Exception:
            # non-wire failure (capacity convergence, backend fault):
            # the oracle serves the remainder per record
            parts.append(_oracle_pairs(pairs, entry, quar))
            break
    return _concat(parts, entry), quar


_ENC_ROW_ERRORS = (OverflowError, ValueError)  # decimal misfit, range,
# per-row value errors — NOT BatchTooLarge (a capacity condition that
# must keep propagating so callers split, exactly as under "raise")


def _encode_bisect(encode_fn, batch, base, quar, tier):
    """Isolate encode offenders by recursive halving (encode errors
    carry no row index): good halves encode whole, single-row failures
    quarantine (``datum=None`` — a row that never encoded has no wire
    bytes). Cost O(n) when clean, O(bad × log n) extra per offender."""
    try:
        return [encode_fn(batch)]
    except _ENC_ROW_ERRORS as e:
        if batch.num_rows <= 1:
            if batch.num_rows == 1:
                quar.append(quarantine.QuarantinedRecord(
                    base, None,
                    "encode_" + type(e).__name__.lower(), tier))
            return []
        mid = batch.num_rows // 2
        return (
            _encode_bisect(encode_fn, batch.slice(0, mid), base, quar,
                           tier)
            + _encode_bisect(encode_fn, batch.slice(mid), base + mid,
                             quar, tier)
        )


def _tolerant_encode(tier, impl, entry, batch, policy):
    """Encode under a tolerant policy → ``(binary_array, entries)``.
    Optimistic: the clean case is ONE normal encode. Under ``"null"``
    on an all-nullable schema the offending rows are re-encoded as
    all-null rows so the output row count matches the input."""
    if tier != "fallback" and impl is not None:
        encode_fn = impl.encode
    else:
        plan = entry.get_extra(
            "host_encode_plan", lambda: compile_encoder_plan(entry.ir)
        )

        def encode_fn(b):
            return pa.array(
                encode_record_batch(b, entry.ir, plan), pa.binary())

    quar: List[quarantine.QuarantinedRecord] = []
    arrays = _encode_bisect(encode_fn, batch, 0, quar, tier)
    if quar and policy == "null" and _row_nullable(entry.ir):
        bad = {e.index for e in quar}
        indices = [None if j in bad else j
                   for j in range(batch.num_rows)]
        try:
            repaired = batch.take(pa.array(indices, type=pa.int64()))
            return encode_fn(repaired), quar
        except _ENC_ROW_ERRORS + (pa.lib.ArrowNotImplementedError,
                                  pa.lib.ArrowInvalid):
            metrics.inc("encode.null_fallback_skip")
    if not arrays:
        return pa.array([], pa.binary()), quar
    return (arrays[0] if len(arrays) == 1
            else pa.concat_arrays(arrays)), quar


def _apply_null_policy(batch, entries, base, n, policy, entry):
    """Under ``on_error="null"`` re-inflate the survivor batch to ``n``
    rows with all-null rows at the quarantined positions (schemas whose
    top-level fields are all nullable); otherwise the skip shape."""
    if policy != "null" or not entries:
        return batch
    if not _row_nullable(entry.ir):
        metrics.inc("decode.null_unsupported_schema")
        return batch
    bad = {e.index - base for e in entries}
    indices: List[Optional[int]] = []
    k = 0
    for j in range(n):
        if j in bad:
            indices.append(None)
        else:
            indices.append(k)
            k += 1
    if k != batch.num_rows:  # survivor accounting mismatch: keep skip
        return batch
    try:
        return batch.take(pa.array(indices, type=pa.int64()))
    except (pa.lib.ArrowNotImplementedError, pa.lib.ArrowInvalid):
        # e.g. sparse-union columns predate take support: degrade to
        # skip rather than fail the tolerant call
        metrics.inc("decode.null_fallback_skip")
        return batch


# -- opt-in process-pool chunk fan-out (PYRUHVRO_TPU_POOL=process) ---------
#
# Host-tier chunked calls can fan chunks to a spawn-based process pool:
# each worker re-enters the public API for its slice (schema parse +
# native codec are per-process caches, warm after the first chunk) under
# a ``telemetry.worker_scope`` and ships its counter deltas + span tree
# back with the result, which ``map_chunks_proc`` merges — the parent's
# snapshot covers every worker's phases and rows, nothing is dropped on
# the process boundary. The device tier never fans out this way (its
# chunk axis is the device mesh, not host processes).


def _proc_decode_task(payload):
    schema, data, base, on_error, tp = payload
    with telemetry.worker_scope("pool.worker", rows=len(data),
                                op="decode", trace_ctx=tp) as w:
        # chaos seam INSIDE the spawned worker (the env-inherited fault
        # spec applies here too): kind=error fails the chunk, kind=exit
        # kills the worker process mid-fan-out
        faults.fire("pool_worker")
        try:
            if on_error == "raise":
                batch = deserialize_array(data, schema, backend="host")
                errs = []
            else:
                batch, errs = deserialize_array(
                    data, schema, backend="host", on_error=on_error,
                    return_errors=True,
                )
        except MalformedAvro as e:
            # the worker sees a chunk slice: re-base to the call's
            # GLOBAL row index before the error crosses the process
            # boundary (__reduce__ keeps the structured fields)
            raise shift_malformed(e, base) from None
    if errs:
        w.payload["quarantine"] = quarantine.rebase(errs, base)
    return batch, w.payload


def _proc_encode_task(payload):
    schema, batch, base, on_error, tp = payload
    with telemetry.worker_scope("pool.worker", rows=batch.num_rows,
                                op="encode", trace_ctx=tp) as w:
        faults.fire("pool_worker")
        if on_error == "raise":
            [arr] = serialize_record_batch(batch, schema, 1, backend="host")
            errs = []
        else:
            [arr], errs = serialize_record_batch(
                batch, schema, 1, backend="host", on_error=on_error,
                return_errors=True,
            )
    if errs:
        w.payload["quarantine"] = quarantine.rebase(errs, base)
    return arr, w.payload


def _proc_map(task, payloads, rows):
    """Fan out on the process pool; None = fall back to the thread path
    (counted): a pool INFRASTRUCTURE failure must degrade, never fail
    the call. A worker that died on a poison datum is not an
    infrastructure failure: its MalformedAvro re-raises directly — with
    the worker's original error name and the GLOBAL row index
    (``_proc_decode_task`` re-bases before pickling) — and counts as
    ``pool.worker_malformed``, not ``pool.process_fallback``."""
    try:
        return map_chunks_proc(task, payloads, rows=rows)
    except MalformedAvro:
        metrics.inc("pool.worker_malformed")
        raise
    except DeadlineExceeded:
        # the budget is spent: degrading to the thread path would just
        # blow it further — surface the structured expiry
        raise
    except Exception:
        metrics.inc("pool.process_fallback")
        return None


# -- differential-audit seams (ISSUE 18) -----------------------------------
#
# Called right AFTER router.observe on clean calls, still inside the
# root span / call_scope / deadline scope: the cost model never sees
# shadow seconds, the sampler and SLO feed subtract them via the audit
# TLS, and the caller's deadline bounds the shadow. The shadow always
# runs the pure-Python oracle — the one tier whose semantics every
# other tier is contractually equal to.


def _audit_shadow_decode(entry, data, bounds, on_error):
    """Re-decode the SAME rows per chunk through the oracle under the
    caller's policy; chunk bounds only matter for tolerant index bases
    (the digests are chunk-insensitive)."""
    reader = _host_reader(entry)
    out = []
    for a, b in bounds:
        deadline.check(site="audit.shadow")
        chunk = data[a:b]
        if on_error == "raise":
            out.append(decode_to_record_batch(
                chunk, entry.ir, entry.arrow_schema, reader,
                index_base=a))
        else:
            batch, quar = _tolerant_decode(
                "fallback", None, entry, chunk, a)
            out.append(_apply_null_policy(
                batch, quar, a, b - a, on_error, entry))
    return out


def _audit_shadow_roundtrip(entry, arrays):
    """The encode shadow: oracle-decode the produced wire bytes back —
    ``decode(encode(x))`` must equal ``x``."""
    reader = _host_reader(entry)
    out = []
    base = 0
    for arr in arrays:
        deadline.check(site="audit.shadow")
        datums = arr.to_pylist()
        out.append(decode_to_record_batch(
            datums, entry.ir, entry.arrow_schema, reader,
            index_base=base))
        base += len(datums)
    return out


def _maybe_audit_decode(dec, entry, data, bounds, on_error, result):
    if not audit.enabled():
        return
    batches = result if isinstance(result, list) else [result]
    audit.maybe_audit(
        dec, "decode",
        expected=lambda: batches,
        shadow=lambda: _audit_shadow_decode(entry, data, bounds,
                                            on_error),
        input_fn=lambda: coldigest.input_digest(data),
        chunks=len(bounds),
    )


def _maybe_audit_encode(dec, entry, batch, bounds, on_error, arrays,
                        quar):
    if not audit.enabled():
        return
    if quar is None and on_error != "raise":
        quar = quarantine.last()
    skip = None
    if quar:
        # survivor re-chunking / null re-encode breaks row alignment
        # between the input batch and the round-trip
        skip = "quarantine"
    elif not batch.schema.equals(entry.arrow_schema):
        # caller-typed batch: digests cover types, not coercions
        skip = "schema"
    audit.maybe_audit(
        dec, "encode",
        expected=lambda: [batch],
        shadow=lambda: _audit_shadow_roundtrip(entry, arrays),
        input_fn=lambda: coldigest.input_digest(batch),
        result_fn=lambda: (coldigest.array_digest(
            pa.chunked_array(arrays)) if arrays else ""),
        chunks=len(bounds),
        skip_reason=skip,
    )


def deserialize_array(
    data: Sequence[bytes], schema: str, *, backend: str = "auto",
    on_error: str = "raise", return_errors: bool = False,
    timeout_s: Optional[float] = None, tenant: Optional[str] = None,
    trace_ctx=None,
) -> pa.RecordBatch:
    """Decode Avro datums into a single RecordBatch
    (≙ ``deserialize_array``, ``src/lib.rs:56-71``).

    ``on_error``: ``"raise"`` (default — a corrupt datum aborts the
    call, exact pre-policy behavior), ``"skip"`` (corrupt rows are
    dropped and quarantined — see :func:`pyruhvro_tpu.last_quarantine`),
    or ``"null"`` (quarantined AND, where every top-level field is
    nullable, replaced by an all-null row so the row count is
    preserved). ``return_errors=True`` returns
    ``(batch, [QuarantinedRecord, ...])`` instead of the bare batch.

    ``timeout_s``: wall-clock budget for THIS call, enforced
    cooperatively at chunk boundaries, tolerant-decode resumes and
    device ladder rungs (:mod:`.runtime.deadline`); expiry raises a
    structured :class:`DeadlineExceeded` regardless of ``on_error``
    (a deadline is a call contract, not a data error). ``None`` defers
    to ``PYRUHVRO_TPU_DEADLINE_S``; ``0`` expires at the first
    checkpoint (the "would this call have blocked?" probe).

    ``data`` may also be a pyarrow ``BinaryArray``/``LargeBinaryArray``
    (or ``ChunkedArray`` of either) of datums — the shape
    :func:`serialize_record_batch` returns — in which case the native
    tier reads the array's offsets+data buffers directly (zero-copy
    ingestion lane; no per-datum Python object is created).

    ``tenant``: optional caller identity for memory/heavy-hitter
    attribution — lands on the call span and in the per-(tenant,
    schema) sketch behind ``telemetry mem-report`` (ISSUE 12);
    untagged calls pool under ``"-"``.

    ``trace_ctx``: optional distributed-trace parent (ISSUE 16) — a W3C
    ``traceparent`` string, a :class:`~.runtime.traceprop.TraceContext`,
    or a ``(trace_id, span_id)`` tuple. The call's root span JOINS that
    trace (its ``trace_id`` matches, its ``parent_span_id`` is the
    caller's span) instead of minting a fresh id; omitted, the ambient
    context (enclosing API call, then ``PYRUHVRO_TPU_TRACEPARENT``)
    applies, else a new 128-bit trace id is minted. The context rides
    into process-pool workers, quarantine records and the flight
    recorder, and out through the OTLP exporter."""
    _check_backend(backend)
    _check_on_error(on_error)
    data = as_datum_input(data)
    entry = get_or_parse_schema(schema)
    memacct.attribute(tenant, entry.fingerprint, "decode", len(data),
                      data)
    with telemetry.root_span("api.deserialize_array", rows=len(data),
                             trace_ctx=trace_ctx,
                             backend=backend, schema=entry.fingerprint,
                             **({"tenant": tenant} if tenant else {})), \
            sampling.call_scope("decode", entry.fingerprint,
                                len(data)) as smp, \
            deadline.scope(timeout_s, op="deserialize_array"):
        # inside the root span so a pressure event annotates THIS call
        memacct.tick()
        dec = _decide(entry, backend, len(data), op="decode")
        dec.sampled = smp.sampled
        try:
            # first checkpoint AFTER the routing decision: a timeout_s=0
            # probe still produces a ledgered error observation
            deadline.check(site="call_start")
            out = _deserialize_one(dec, entry, data, on_error,
                                   return_errors)
        except Exception as e:
            router.observe(dec, error=e)
            raise
        router.observe(dec)
        _maybe_audit_decode(dec, entry, data, [(0, len(data))],
                            on_error, out[0] if return_errors else out)
        return out


def _deserialize_one(dec, entry, data, on_error, return_errors):
    """The single-batch decode body, on the decided tier."""
    tier, impl = dec.tier, dec.impl
    if on_error == "raise":
        _enforce_max_datum(data)
        batch = None
        if tier != "fallback":
            try:
                batch = impl.decode(data)
            except Exception as e:
                # the native VM is a degradation seam like the device
                # tier (which degrades inside its codec): a runtime
                # fault falls back to the pure-Python oracle; data/
                # capacity/deadline errors propagate
                if tier != "native" or not _native_degradable(e):
                    raise
                _count_native_degrade(e)
        if batch is None:
            with telemetry.phase("fallback.decode_s", rows=len(data)):
                batch = decode_to_record_batch(
                    data, entry.ir, entry.arrow_schema,
                    _host_reader(entry),
                )
        return (batch, []) if return_errors else batch
    with quarantine.collecting() as quar:
        with telemetry.phase("decode.tolerant_s", rows=len(data),
                             tier=tier):
            batch, entries = _tolerant_decode(
                tier, impl, entry, data, 0)
        quar.extend(entries)
        batch = _apply_null_policy(
            batch, entries, 0, len(data), on_error, entry)
        quarantine.publish(quar, on_error)
    return (batch, quar) if return_errors else batch


def deserialize_array_threaded(
    data: Sequence[bytes], schema: str, num_chunks: int, *,
    backend: str = "auto", on_error: str = "raise",
    return_errors: bool = False, timeout_s: Optional[float] = None,
    tenant: Optional[str] = None, trace_ctx=None,
) -> List[pa.RecordBatch]:
    """Decode in ``num_chunks`` chunks → one RecordBatch per chunk
    (≙ ``deserialize_array_threaded``, ``src/lib.rs:73-89``).

    On the device path the chunk axis maps to the device mesh, not host
    threads: with multiple devices attached, chunks are decoded by
    ``shard_map`` over the mesh's ``"chunks"`` axis in one launch
    (``parallel/sharded.py``); on a single chip the whole input is
    decoded in one fused launch and sliced per chunk.

    ``on_error``/``return_errors``/``timeout_s``/``tenant``/
    ``trace_ctx`` and the pyarrow BinaryArray ingestion lane for
    ``data``: see :func:`deserialize_array`. On the process-pool arm
    the trace context ships to every worker, so chunk spans re-parent
    under the CALLER's trace id.
    Chunk boundaries are computed on the INPUT rows; under ``"skip"``
    a chunk's batch holds its surviving rows (``"null"`` preserves the
    per-chunk row count on all-nullable schemas)."""
    _check_backend(backend)
    _check_on_error(on_error)
    data = as_datum_input(data)
    entry = get_or_parse_schema(schema)
    memacct.attribute(tenant, entry.fingerprint, "decode", len(data),
                      data)
    bounds = chunk_bounds(len(data), num_chunks)
    with telemetry.root_span("api.deserialize_array_threaded",
                             rows=len(data), chunks=num_chunks,
                             trace_ctx=trace_ctx,
                             backend=backend, schema=entry.fingerprint,
                             **({"tenant": tenant} if tenant else {})), \
            sampling.call_scope("decode", entry.fingerprint,
                                len(data)) as smp, \
            deadline.scope(timeout_s, op="deserialize_array_threaded"):
        memacct.tick()  # inside the root span: pressure annotates it
        dec = _decide(entry, backend, len(data), op="decode",
                      chunks=len(bounds))
        dec.sampled = smp.sampled
        try:
            deadline.check(site="call_start")
            out = _deserialize_chunks(dec, entry, data, schema,
                                      num_chunks, bounds, on_error,
                                      return_errors)
        except Exception as e:
            router.observe(dec, error=e)
            raise
        router.observe(dec)
        _maybe_audit_decode(dec, entry, data, bounds, on_error,
                            out[0] if return_errors else out)
        return out


def _deserialize_chunks(dec, entry, data, schema, num_chunks, bounds,
                        on_error, return_errors):
    """The chunked decode body, on the decided (tier, pool) arm."""
    tier, impl = dec.tier, dec.impl
    use_proc = dec.pool == "process"  # router/env picked the spawn pool
    # the decided pool rides into the native codec as a placement hint
    # ("shard" = one-call C++ shard runner, "thread" = the serial
    # per-chunk loop); the other tiers' impls take no such hint
    native_kw = {"pool": dec.pool} if tier == "native" else {}
    # the caller's live trace context (the root span is already open),
    # shipped verbatim so worker chunk spans join the caller's trace
    tp = traceprop.current_traceparent()
    if on_error == "raise":
        _enforce_max_datum(data)
        if use_proc:
            out = _proc_map(
                _proc_decode_task,
                [(schema, list(data[a:b]), a, "raise", tp)
                 for a, b in bounds],
                rows=lambda p: len(p[1]),
            )
            if out is not None:
                return (out, []) if return_errors else out
            dec.degraded = True  # thread path serves a process-arm call
        if tier != "fallback":
            try:
                out = impl.decode_threaded(data, num_chunks, **native_kw)
                return (out, []) if return_errors else out
            except Exception as e:
                if tier != "native" or not _native_degradable(e):
                    raise
                _count_native_degrade(e)  # fallback chunks serve below
        ir, arrow = entry.ir, entry.arrow_schema
        reader = _host_reader(entry)

        def decode_chunk(ab):
            with telemetry.phase("fallback.decode_s",
                                 rows=ab[1] - ab[0]):
                return decode_to_record_batch(
                    data[ab[0]:ab[1]], ir, arrow, reader,
                    index_base=ab[0],
                )

        out = map_chunks(decode_chunk, bounds, rows=bounds_rows)
        return (out, []) if return_errors else out
    # tolerant policies: per-chunk isolation so one poisoned chunk
    # never forces another chunk off its fast path
    with quarantine.collecting() as quar:
        out = None
        if use_proc:
            # workers apply the policy on their own slice and ship
            # quarantine entries back with the telemetry payload
            # (merged into `quar` by telemetry.merge_worker)
            out = _proc_map(
                _proc_decode_task,
                [(schema, list(data[a:b]), a, on_error, tp)
                 for a, b in bounds],
                rows=lambda p: len(p[1]),
            )
            if out is None:
                dec.degraded = True
        if out is None:
            # a failed pool fan-out may have merged partial worker
            # results: the paths below redecode every chunk, so
            # start the collector clean
            quar.clear()
            quarantine.reset_merged()
            # optimistic fast path: a clean batch takes EXACTLY the
            # "raise" execution shape (one fused/sharded launch on
            # the device tier, the VM's per-chunk mode on native) —
            # only a failure drops to per-chunk isolation below.
            # With the MAX_DATUM_BYTES knob set, oversized datums
            # must quarantine even though the tiers would decode
            # them, so the screening per-chunk path serves instead.
            if tier != "fallback" and not max_datum_bytes():
                try:
                    out = impl.decode_threaded(data, num_chunks,
                                               **native_kw)
                except DeadlineExceeded:
                    raise  # a call contract, not a reason to re-decode
                except Exception:
                    out = None
        if out is None:
            def tolerant_chunk(ab):
                a, b = ab
                with telemetry.phase("decode.tolerant_s",
                                     rows=b - a, tier=tier):
                    batch, entries = _tolerant_decode(
                        tier, impl, entry, data[a:b], a)
                quar.extend(entries)
                return _apply_null_policy(
                    batch, entries, a, b - a, on_error, entry)

            if tier == "device":
                # the device decode is internally parallel (mesh /
                # VM shards); host-thread fan-out adds nothing
                out = [tolerant_chunk(ab) for ab in bounds]
            else:
                out = map_chunks(tolerant_chunk, bounds,
                                 rows=bounds_rows)
        quarantine.publish(quar, on_error)
    return (out, quar) if return_errors else out


def deserialize_array_threaded_spawn(
    data: Sequence[bytes], schema: str, num_chunks: int, *,
    backend: str = "auto", on_error: str = "raise",
    return_errors: bool = False, timeout_s: Optional[float] = None,
    tenant: Optional[str] = None, trace_ctx=None,
) -> List[pa.RecordBatch]:
    """Signature-parity alias of :func:`deserialize_array_threaded`
    (≙ ``src/lib.rs:108-128``; thread-pool flavor is a host-side detail)."""
    return deserialize_array_threaded(
        data, schema, num_chunks, backend=backend, on_error=on_error,
        return_errors=return_errors, timeout_s=timeout_s, tenant=tenant,
        trace_ctx=trace_ctx,
    )


def serialize_record_batch(
    batch: pa.RecordBatch, schema: str, num_chunks: int, *,
    backend: str = "auto", on_error: str = "raise",
    return_errors: bool = False, timeout_s: Optional[float] = None,
    tenant: Optional[str] = None, trace_ctx=None,
) -> List[pa.Array]:
    """Encode a RecordBatch into Avro datums, one BinaryArray per chunk
    (≙ ``serialize_record_batch``, ``src/lib.rs:91-106``).

    ``on_error``: ``"raise"`` (default, pre-policy behavior), ``"skip"``
    (rows whose values cannot encode — e.g. a decimal that does not fit
    its fixed size — are dropped and quarantined with ``datum=None``),
    or ``"null"`` (on all-nullable schemas the offending rows encode as
    all-null rows, preserving the row count). Under ``"skip"`` the
    chunked return re-chunks over the SURVIVING rows.
    ``trace_ctx``: see :func:`deserialize_array`."""
    _check_backend(backend)
    _check_on_error(on_error)
    entry = get_or_parse_schema(schema)
    if isinstance(batch, pa.Table):
        batches = batch.combine_chunks().to_batches()
        batch = (
            batches[0]
            if batches
            else pa.RecordBatch.from_pylist([], schema=batch.schema)
        )
    memacct.attribute(tenant, entry.fingerprint, "encode",
                      batch.num_rows, batch)
    bounds = chunk_bounds(batch.num_rows, num_chunks)
    with telemetry.root_span("api.serialize_record_batch",
                             rows=batch.num_rows, chunks=num_chunks,
                             trace_ctx=trace_ctx,
                             backend=backend, schema=entry.fingerprint,
                             **({"tenant": tenant} if tenant else {})), \
            sampling.call_scope("encode", entry.fingerprint,
                                batch.num_rows) as smp, \
            deadline.scope(timeout_s, op="serialize_record_batch"):
        memacct.tick()  # inside the root span: pressure annotates it
        dec = _decide(entry, backend, batch.num_rows, op="encode",
                      chunks=len(bounds), need_encode=True)
        dec.sampled = smp.sampled
        try:
            deadline.check(site="call_start")
            out = _serialize_chunks(dec, entry, batch, schema,
                                    num_chunks, bounds, on_error,
                                    return_errors)
        except Exception as e:
            router.observe(dec, error=e)
            raise
        router.observe(dec)
        _maybe_audit_encode(dec, entry, batch, bounds, on_error,
                            out[0] if return_errors else out,
                            out[1] if return_errors else None)
        return out


def _serialize_chunks(dec, entry, batch, schema, num_chunks, bounds,
                      on_error, return_errors):
    """The chunked encode body, on the decided (tier, pool) arm."""
    tier, impl = dec.tier, dec.impl
    use_proc = dec.pool == "process"  # router/env picked the spawn pool
    # placement hint for the native codec (see _deserialize_chunks)
    native_kw = {"pool": dec.pool} if tier == "native" else {}
    tp = traceprop.current_traceparent()  # ships the caller's trace
    if on_error == "raise":
        if use_proc:
            out = _proc_map(
                _proc_encode_task,
                [(schema, batch.slice(a, b - a), a, "raise", tp)
                 for a, b in bounds],
                rows=lambda p: p[1].num_rows,
            )
            if out is not None:
                return (out, []) if return_errors else out
            dec.degraded = True  # thread path serves a process-arm call
        if tier != "fallback":
            try:
                out = impl.encode_threaded(batch, num_chunks, **native_kw)
                return (out, []) if return_errors else out
            except Exception as e:
                # BatchTooLarge (a capacity contract) is not a
                # RuntimeError and propagates untouched
                if tier != "native" or not _native_degradable(e):
                    raise
                _count_native_degrade(e)  # fallback encode serves below
        ir = entry.ir
        plan = entry.get_extra(
            "host_encode_plan", lambda: compile_encoder_plan(ir)
        )

        def encode_chunk(ab):
            with telemetry.phase("fallback.encode_s",
                                 rows=ab[1] - ab[0]):
                datums = encode_record_batch(
                    batch.slice(ab[0], ab[1] - ab[0]), ir, plan
                )
                return pa.array(datums, pa.binary())

        out = map_chunks(encode_chunk, bounds, rows=bounds_rows)
        return (out, []) if return_errors else out
    with quarantine.collecting() as quar:
        out = None
        if use_proc:
            out = _proc_map(
                _proc_encode_task,
                [(schema, batch.slice(a, b - a), a, on_error, tp)
                 for a, b in bounds],
                rows=lambda p: p[1].num_rows,
            )
            if out is None:
                dec.degraded = True
            if out is not None and quar:
                # per-input-chunk survivor arrays → the documented
                # shape: ONE array re-chunked over surviving rows
                # (identical to the thread path's return)
                whole = pa.concat_arrays(out)
                out = [
                    whole.slice(a, b - a)
                    for a, b in chunk_bounds(len(whole), num_chunks)
                ]
        if out is None:
            quar.clear()
            quarantine.reset_merged()
            with telemetry.phase("encode.tolerant_s",
                                 rows=batch.num_rows, tier=tier):
                arr, entries = _tolerant_encode(
                    tier, impl, entry, batch, on_error)
            quar.extend(entries)
            out = [
                arr.slice(a, b - a)
                for a, b in chunk_bounds(len(arr), num_chunks)
            ]
        quarantine.publish(quar, on_error, op="encode")
    return (out, quar) if return_errors else out


def serialize_record_batch_spawn(
    batch: pa.RecordBatch, schema: str, num_chunks: int, *,
    backend: str = "auto", on_error: str = "raise",
    return_errors: bool = False, timeout_s: Optional[float] = None,
    tenant: Optional[str] = None, trace_ctx=None,
) -> List[pa.Array]:
    """Signature-parity alias of :func:`serialize_record_batch`
    (≙ ``src/lib.rs:130-147``)."""
    return serialize_record_batch(
        batch, schema, num_chunks, backend=backend, on_error=on_error,
        return_errors=return_errors, timeout_s=timeout_s, tenant=tenant,
        trace_ctx=trace_ctx,
    )
