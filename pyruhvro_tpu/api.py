"""Public API — drop-in parity with the reference's 5 functions.

≙ ``src/lib.rs:150-158``:

* ``deserialize_array(data, schema)`` → one ``pyarrow.RecordBatch``
* ``deserialize_array_threaded(data, schema, num_chunks)`` → ``list[RecordBatch]``
  (one per chunk, never concatenated — ``deserialize.rs:76-121``)
* ``deserialize_array_threaded_spawn`` — same result; the reference's
  spawn variant differs only in host thread-pool strategy
  (``src/lib.rs:108-128``), which has no analogue on the device path;
  kept for signature parity.
* ``serialize_record_batch(batch, schema, num_chunks)`` → ``list[BinaryArray]``
* ``serialize_record_batch_spawn`` — ditto.

One addition over the reference (the BASELINE.json north star):
``backend=`` on every function — ``"auto"`` (default), ``"tpu"`` (force
device; errors if unsupported), ``"host"`` (force the host path).

The host path itself is two-tiered, mirroring the reference's
fast/fallback split (``deserialize.rs:26-29``): schemas in the fast
subset decode through the **native C++ VM** (:mod:`.hostpath`, built on
demand); everything else through the pure-Python fallback decoder (the
differential oracle). ``backend="auto"`` picks device vs host by a
one-time interconnect probe: on a co-located accelerator the device
path wins from small batch sizes, while behind a high-latency tunnel
(~tens of ms RTT) the native host path wins at every size — forcing
``backend="tpu"`` always bypasses the probe. Override with
``PYRUHVRO_TPU_DEVICE_MIN_ROWS=<n>`` (device for batches ≥ n) and
disable the native VM entirely with ``PYRUHVRO_TPU_NO_NATIVE=1``.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence

import pyarrow as pa

from .gate import device_supported
from .ops import UnsupportedOnDevice
from .fallback.decoder import compile_reader, decode_to_record_batch
from .fallback.encoder import compile_encoder_plan, encode_record_batch
from .runtime import metrics, telemetry
from .runtime.chunking import bounds_rows, chunk_bounds
from .runtime.pool import map_chunks, map_chunks_proc, pool_mode
from .schema.cache import SchemaEntry, get_or_parse_schema

__all__ = [
    "deserialize_array",
    "deserialize_array_threaded",
    "deserialize_array_threaded_spawn",
    "serialize_record_batch",
    "serialize_record_batch_spawn",
]


def _device_codec_ex(entry: SchemaEntry, backend: str):
    """Resolve the TPU codec for this schema → ``(codec_or_None, reason)``.

    ``reason`` names why the device path was NOT taken (the routing
    explainer recorded on the call's span). backend="auto": device if
    the schema passes the fast gate AND a JAX device backend
    initializes; silently falls back otherwise (reference semantics).
    backend="tpu": device or raise. backend="host": None.
    """
    if backend == "host":
        return None, "backend_host"
    if backend == "auto" and entry._extras.get("device_failure") is not None:
        # device codec for THIS schema already blew up; don't re-pay the
        # failed (potentially seconds-long) init on every call. Other
        # schemas still get the device path. Counted per call so a
        # fallback storm is visible in snapshots, not just the one
        # RuntimeWarning at first failure.
        metrics.inc("route.device_failure")
        return None, "device_failure_cached"
    supported = device_supported(entry.ir)
    if backend == "auto" and not supported:
        return None, "gate_fail"
    if not supported:  # backend == "tpu"
        raise ValueError(
            "schema is outside the device subset (e.g. decimals beyond "
            "decimal128's 16 bytes / precision 38, or unknown logical "
            "types on fixed); use backend='auto' or backend='host'"
        )
    try:
        from .ops.codec import get_device_codec
    except ImportError as e:
        if backend == "tpu":
            raise RuntimeError(
                f"TPU backend is not available in this build: {e}"
            ) from e
        # missing module = deliberately host-only build, not a broken
        # backend: stay silent (reference fallback semantics)
        return None, "no_device_build"
    try:
        return get_device_codec(entry), None
    except UnsupportedOnDevice:
        # schema outside the *device* subset (e.g. nested repetition): the
        # silent fallback here mirrors the reference's unsupported-schema
        # gate (deserialize.rs:26-29)
        if backend == "tpu":
            raise
        metrics.inc("route.gate_reject")
        return None, "gate_reject"
    except Exception as e:
        # a *broken backend* is not the reference's silent-fallback case:
        # surface it once per schema, remember the failure, degrade in
        # 'auto' / raise in 'tpu'. Store only the repr — keeping the live
        # exception would pin its whole traceback (and every local in the
        # failed device init) in the process-lifetime schema cache.
        if backend == "tpu":
            raise
        with entry._lock:
            entry._extras["device_failure"] = repr(e)
        metrics.inc("route.device_failure")
        warnings.warn(
            f"pyruhvro_tpu device backend failed to initialize for this "
            f"schema; falling back to the (much slower) host path: {e!r}",
            RuntimeWarning,
            stacklevel=4,  # user -> api fn -> _route -> _device_codec_ex
        )
        return None, "device_failure"


def _device_codec(entry: SchemaEntry, backend: str):
    """Back-compat probe (bench/tests): the codec without the reason."""
    return _device_codec_ex(entry, backend)[0]


def _route(entry: SchemaEntry, backend: str, n_rows: int,
           *, need_encode: bool = False):
    """Resolve which tier serves this call → ``(tier, impl, reason)``.

    tier: ``"device"`` (impl = DeviceCodec), ``"native"`` (impl =
    NativeHostCodec) or ``"fallback"`` (impl = None, pure-Python path).
    ``reason`` is the routing explainer recorded on the call span — for
    host-side tiers it names why the device path was NOT taken."""
    codec = None
    reason = None
    if backend == "host":
        reason = "backend_host"
    elif need_encode and not _device_encode_available():
        # decided before constructing the (decode-lowering +
        # backend-probing) device codec, so serialize-only workloads in
        # a host-only build never pay for it
        if backend == "tpu":
            raise RuntimeError(
                "the device encode kernel is not available in this build"
            )
        reason = "no_device_encode"
    else:
        codec, reason = _device_codec_ex(entry, backend)
        if codec is not None and backend == "auto":
            host_reason = _auto_prefers_host(entry, n_rows)
            if host_reason:
                codec, reason = None, host_reason
    if codec is not None:
        return "device", codec, (
            "backend_tpu" if backend == "tpu" else "device_selected"
        )
    native = _native_host_codec(entry)
    if native is not None:
        return "native", native, reason
    return "fallback", None, reason


def _native_host_codec(entry: SchemaEntry):
    """The C++ host VM codec for this schema, or None (outside the fast
    subset, no toolchain, or disabled via PYRUHVRO_TPU_NO_NATIVE)."""
    import os

    if os.environ.get("PYRUHVRO_TPU_NO_NATIVE"):
        return None

    def make():
        try:
            from .hostpath import NativeHostCodec

            return NativeHostCodec(entry.ir, entry.arrow_schema)
        except Exception:
            # unsupported schema / missing toolchain: the Python
            # fallback serves the call (reference silent-gate semantics)
            return None

    return entry.get_extra("native_host_codec", make)


def _auto_prefers_host(entry: SchemaEntry, n_rows: int):
    """In ``backend="auto"`` with BOTH a device codec and the native host
    VM available: route to host when the device cannot win.

    Returns the routing reason (truthy string) when host should serve,
    else None: ``"device_min_rows"`` (env override), ``"devices_cpu_only"``
    or ``"interconnect_remote"`` — the verdict lands on the call span and
    in the ``route.reason.*`` counters.

    Two signals, cheapest first:

    1. platform: when every JAX device is a host CPU, the XLA pipeline
       is just a slower CPU program than the native VM (measured 60×
       slower at the 10M-row scale) — host wins at every size. The
       device pipeline exists for accelerators.
    2. a one-time interconnect RTT probe
       (:func:`.ops.codec.interconnect_rtt_s`): a co-located
       accelerator (sub-ms RTT) beats the single-core host VM from
       small sizes, so the device keeps the batch; a remote tunnel
       (tens of ms per round trip, ~30 MB/s) loses to the multi-M rec/s
       host VM at every batch size, so host serves ``auto`` and
       ``backend="tpu"`` remains the explicit override.

    ``PYRUHVRO_TPU_DEVICE_MIN_ROWS=<n>`` replaces both signals."""
    import os

    if _native_host_codec(entry) is None:
        return None
    env = os.environ.get("PYRUHVRO_TPU_DEVICE_MIN_ROWS")
    if env:
        return "device_min_rows" if n_rows < int(env) else None
    from .ops.codec import devices_cpu_only, interconnect_remote

    # safe: callers reach here only with a constructed device codec, so
    # the memoized backend probe has already resolved (never wedges)
    if devices_cpu_only():
        return "devices_cpu_only"
    if interconnect_remote():
        return "interconnect_remote"
    return None


# tri-state module global: None = not yet probed, else the cached bool
_device_encode_available_memo: Optional[bool] = None


def _device_encode_available() -> bool:
    """True when ``ops.encode`` exists (checked once, without importing
    JAX or building any codec)."""
    global _device_encode_available_memo
    if _device_encode_available_memo is None:
        import importlib.util

        _device_encode_available_memo = (
            importlib.util.find_spec("pyruhvro_tpu.ops.encode") is not None
        )
    return _device_encode_available_memo


def _host_reader(entry: SchemaEntry):
    """Per-schema memoized fallback wire reader (compile once, use on every
    call/chunk — the host analogue of the schema→kernel cache)."""
    return entry.get_extra("host_reader", lambda: compile_reader(entry.ir))


def _check_backend(backend: str) -> str:
    if backend not in ("auto", "tpu", "host"):
        raise ValueError(f"backend must be 'auto', 'tpu' or 'host', got {backend!r}")
    return backend


# -- opt-in process-pool chunk fan-out (PYRUHVRO_TPU_POOL=process) ---------
#
# Host-tier chunked calls can fan chunks to a spawn-based process pool:
# each worker re-enters the public API for its slice (schema parse +
# native codec are per-process caches, warm after the first chunk) under
# a ``telemetry.worker_scope`` and ships its counter deltas + span tree
# back with the result, which ``map_chunks_proc`` merges — the parent's
# snapshot covers every worker's phases and rows, nothing is dropped on
# the process boundary. The device tier never fans out this way (its
# chunk axis is the device mesh, not host processes).


def _proc_decode_task(payload):
    schema, data = payload
    with telemetry.worker_scope("pool.worker", rows=len(data),
                                op="decode") as w:
        batch = deserialize_array(data, schema, backend="host")
    return batch, w.payload


def _proc_encode_task(payload):
    schema, batch = payload
    with telemetry.worker_scope("pool.worker", rows=batch.num_rows,
                                op="encode") as w:
        [arr] = serialize_record_batch(batch, schema, 1, backend="host")
    return arr, w.payload


def _proc_map(task, payloads, rows):
    """Fan out on the process pool; None = fall back to the thread path
    (counted): a pool failure must degrade, never fail the call. A
    worker's own decode/encode error re-raises from the thread retry
    with its exact message."""
    try:
        return map_chunks_proc(task, payloads, rows=rows)
    except Exception:
        metrics.inc("pool.process_fallback")
        return None


def deserialize_array(
    data: Sequence[bytes], schema: str, *, backend: str = "auto"
) -> pa.RecordBatch:
    """Decode Avro datums into a single RecordBatch
    (≙ ``deserialize_array``, ``src/lib.rs:56-71``)."""
    _check_backend(backend)
    entry = get_or_parse_schema(schema)
    with telemetry.root_span("api.deserialize_array", rows=len(data),
                             backend=backend, schema=entry.fingerprint):
        tier, impl, reason = _route(entry, backend, len(data))
        telemetry.set_route(tier, reason)
        if tier != "fallback":
            return impl.decode(data)
        with telemetry.phase("fallback.decode_s", rows=len(data)):
            return decode_to_record_batch(
                data, entry.ir, entry.arrow_schema, _host_reader(entry)
            )


def deserialize_array_threaded(
    data: Sequence[bytes], schema: str, num_chunks: int, *, backend: str = "auto"
) -> List[pa.RecordBatch]:
    """Decode in ``num_chunks`` chunks → one RecordBatch per chunk
    (≙ ``deserialize_array_threaded``, ``src/lib.rs:73-89``).

    On the device path the chunk axis maps to the device mesh, not host
    threads: with multiple devices attached, chunks are decoded by
    ``shard_map`` over the mesh's ``"chunks"`` axis in one launch
    (``parallel/sharded.py``); on a single chip the whole input is
    decoded in one fused launch and sliced per chunk."""
    _check_backend(backend)
    entry = get_or_parse_schema(schema)
    bounds = chunk_bounds(len(data), num_chunks)
    with telemetry.root_span("api.deserialize_array_threaded",
                             rows=len(data), chunks=num_chunks,
                             backend=backend, schema=entry.fingerprint):
        tier, impl, reason = _route(entry, backend, len(data))
        telemetry.set_route(tier, reason)
        if tier != "device" and len(bounds) > 1 and pool_mode() == "process":
            out = _proc_map(
                _proc_decode_task,
                [(schema, list(data[a:b])) for a, b in bounds],
                rows=lambda p: len(p[1]),
            )
            if out is not None:
                return out
        if tier != "fallback":
            return impl.decode_threaded(data, num_chunks)
        ir, arrow, reader = entry.ir, entry.arrow_schema, _host_reader(entry)

        def decode_chunk(ab):
            with telemetry.phase("fallback.decode_s", rows=ab[1] - ab[0]):
                return decode_to_record_batch(
                    data[ab[0]:ab[1]], ir, arrow, reader
                )

        return map_chunks(decode_chunk, bounds, rows=bounds_rows)


def deserialize_array_threaded_spawn(
    data: Sequence[bytes], schema: str, num_chunks: int, *, backend: str = "auto"
) -> List[pa.RecordBatch]:
    """Signature-parity alias of :func:`deserialize_array_threaded`
    (≙ ``src/lib.rs:108-128``; thread-pool flavor is a host-side detail)."""
    return deserialize_array_threaded(data, schema, num_chunks, backend=backend)


def serialize_record_batch(
    batch: pa.RecordBatch, schema: str, num_chunks: int, *, backend: str = "auto"
) -> List[pa.Array]:
    """Encode a RecordBatch into Avro datums, one BinaryArray per chunk
    (≙ ``serialize_record_batch``, ``src/lib.rs:91-106``)."""
    _check_backend(backend)
    entry = get_or_parse_schema(schema)
    if isinstance(batch, pa.Table):
        batches = batch.combine_chunks().to_batches()
        batch = (
            batches[0]
            if batches
            else pa.RecordBatch.from_pylist([], schema=batch.schema)
        )
    bounds = chunk_bounds(batch.num_rows, num_chunks)
    with telemetry.root_span("api.serialize_record_batch",
                             rows=batch.num_rows, chunks=num_chunks,
                             backend=backend, schema=entry.fingerprint):
        tier, impl, reason = _route(entry, backend, batch.num_rows,
                                    need_encode=True)
        telemetry.set_route(tier, reason)
        if tier != "device" and len(bounds) > 1 and pool_mode() == "process":
            out = _proc_map(
                _proc_encode_task,
                [(schema, batch.slice(a, b - a)) for a, b in bounds],
                rows=lambda p: p[1].num_rows,
            )
            if out is not None:
                return out
        if tier != "fallback":
            return impl.encode_threaded(batch, num_chunks)
        ir = entry.ir
        plan = entry.get_extra(
            "host_encode_plan", lambda: compile_encoder_plan(ir)
        )

        def encode_chunk(ab):
            with telemetry.phase("fallback.encode_s", rows=ab[1] - ab[0]):
                datums = encode_record_batch(
                    batch.slice(ab[0], ab[1] - ab[0]), ir, plan
                )
                return pa.array(datums, pa.binary())

        return map_chunks(encode_chunk, bounds, rows=bounds_rows)


def serialize_record_batch_spawn(
    batch: pa.RecordBatch, schema: str, num_chunks: int, *, backend: str = "auto"
) -> List[pa.Array]:
    """Signature-parity alias of :func:`serialize_record_batch`
    (≙ ``src/lib.rs:130-147``)."""
    return serialize_record_batch(batch, schema, num_chunks, backend=backend)
