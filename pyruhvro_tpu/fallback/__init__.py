from .io import MalformedAvro
from .decoder import decode_to_record_batch, decode_records, compile_reader
from .encoder import encode_record_batch, compile_writer, extract_rows

__all__ = [
    "MalformedAvro",
    "decode_to_record_batch",
    "decode_records",
    "compile_reader",
    "encode_record_batch",
    "compile_writer",
    "extract_rows",
]
