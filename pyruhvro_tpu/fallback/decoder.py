"""General-path Avro → Arrow decoder (host, pure Python).

This is the analogue of the reference's ``Value``-tree baseline path
(``ruhvro/src/deserialize.rs:34-48`` + ``ruhvro/src/complex.rs``): it
covers the FULL Avro type surface (everything ``schema_translate.rs``
maps), serves as the runtime fallback for schemas outside the fast
subset, and — most importantly — is the **differential-test oracle** the
TPU fast path is validated against, exactly as the reference's fast
decoder is asserted equal to its baseline decoder
(``fast_decode.rs:945-953``).

Two stages, mirroring the reference:
1. per-datum wire decode into a Python value tree
   (≙ ``apache_avro::from_avro_datum`` → ``Value``), via per-schema
   compiled reader closures;
2. value-tree → Arrow builders (≙ ``complex.rs`` ``AvroToArrowBuilder``),
   finished into a ``pyarrow.RecordBatch``.

Value-tree conventions: null→None, record→dict, array→list,
map→list[(key, value)], union→(branch_index, value), enum→symbol str,
decimal→unscaled int.
"""

from __future__ import annotations

import decimal
import threading
import uuid as _uuid
from typing import Callable, List, Sequence, Tuple

import numpy as np
import pyarrow as pa

from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)
from ..schema.arrow_map import to_arrow_schema
from .io import (
    MAX_ZERO_WIDTH_ITEMS,
    MalformedAvro,
    max_datum_bytes,
    read_bool,
    read_bytes,
    read_double,
    read_float,
    read_long,
)

__all__ = [
    "compile_reader",
    "decode_records",
    "decode_records_tolerant",
    "decode_pairs_tolerant",
    "rows_to_record_batch",
    "MalformedAvro",
]


# ---------------------------------------------------------------------------
# Stage 1: wire bytes → value tree
# ---------------------------------------------------------------------------

# Hostile-input guard: the walker's recursion depth is bounded by the
# SCHEMA's nesting depth (the parser rejects recursive schemas), so the
# cap is enforced once at compile time rather than per datum. Default 64
# levels; PYRUHVRO_TPU_MAX_DEPTH overrides.
_DEFAULT_MAX_DEPTH = 64


def _max_depth() -> int:
    from ..runtime import knobs

    return knobs.get_int("PYRUHVRO_TPU_MAX_DEPTH")


# per-thread budget of zero-width array/map items for the datum being
# decoded (reset per datum by decode_records / decode_records_tolerant):
# null / empty-record items consume no wire bytes, so a claimed block
# count is the ONE quantity the remaining-bytes bound cannot limit
_zw_tls = threading.local()


def _reset_zw_budget() -> None:
    _zw_tls.budget = MAX_ZERO_WIDTH_ITEMS


def _charge_zero_width(count: int) -> None:
    left = getattr(_zw_tls, "budget", MAX_ZERO_WIDTH_ITEMS) - count
    _zw_tls.budget = left
    if left < 0:
        raise MalformedAvro(
            f"block claims more zero-width items than the per-datum cap "
            f"({MAX_ZERO_WIDTH_ITEMS})",
            err_name="zero_width_items",
        )


def compile_reader(t: AvroType, _depth: int = 0) -> Callable:
    """Build a ``reader(buf, pos) -> (value, pos)`` closure for ``t``."""
    if _depth > _max_depth():
        raise ValueError(
            f"schema nesting depth exceeds the walker cap "
            f"({_max_depth()}; PYRUHVRO_TPU_MAX_DEPTH overrides)"
        )
    if isinstance(t, Primitive):
        name = t.name
        if name == "null":
            return lambda buf, pos: (None, pos)
        if name == "boolean":
            return read_bool
        if name in ("int", "long"):
            if t.logical == "decimal":  # bytes-decimal handled under bytes
                raise NotImplementedError
            return read_long
        if name == "float":
            return read_float
        if name == "double":
            return read_double
        if name == "bytes":
            if t.logical == "decimal":
                def read_decimal(buf, pos):
                    raw, pos = read_bytes(buf, pos)
                    return int.from_bytes(raw, "big", signed=True), pos
                return read_decimal
            return read_bytes
        if name == "string":
            def read_string(buf, pos):
                raw, pos = read_bytes(buf, pos)
                try:
                    return raw.decode("utf-8"), pos
                except UnicodeDecodeError as e:
                    raise MalformedAvro(f"invalid UTF-8 in string: {e}",
                                        err_name="bad_utf8") from None
            return read_string
        raise NotImplementedError(name)

    if isinstance(t, Fixed):
        size = t.size
        if t.logical == "decimal":
            def read_fixed_decimal(buf, pos):
                if pos + size > len(buf):
                    raise MalformedAvro("truncated fixed", err_name="overrun")
                return (
                    int.from_bytes(buf[pos : pos + size], "big", signed=True),
                    pos + size,
                )
            return read_fixed_decimal

        def read_fixed(buf, pos):
            if pos + size > len(buf):
                raise MalformedAvro("truncated fixed", err_name="overrun")
            return bytes(buf[pos : pos + size]), pos + size
        return read_fixed

    if isinstance(t, Enum):
        symbols = t.symbols
        n = len(symbols)
        def read_enum(buf, pos):
            idx, pos = read_long(buf, pos)
            if not 0 <= idx < n:
                raise MalformedAvro(f"enum index {idx} out of range 0..{n}",
                                    err_name="bad_enum")
            return symbols[idx], pos
        return read_enum

    if isinstance(t, Array):
        item_reader = compile_reader(t.items, _depth + 1)
        def read_array(buf, pos):
            out = []
            while True:
                count, pos = read_long(buf, pos)
                if count == 0:
                    return out, pos
                if count < 0:
                    # negative block count: abs(count) items preceded by a
                    # byte-size long we can skip over (fast_decode.rs:689-700)
                    count = -count
                    _, pos = read_long(buf, pos)
                for k in range(count):
                    prev = pos
                    v, pos = item_reader(buf, pos)
                    out.append(v)
                    if k == 0 and pos == prev:
                        # zero-width items (null / empty record): the
                        # claimed count is unbounded by remaining bytes —
                        # charge it against the per-datum budget before
                        # materializing (hostile-input cap; the native VM
                        # applies the same rule)
                        _charge_zero_width(count)
        return read_array

    if isinstance(t, Map):
        value_reader = compile_reader(t.values, _depth + 1)
        def read_map(buf, pos):
            out = []
            while True:
                count, pos = read_long(buf, pos)
                if count == 0:
                    return out, pos
                if count < 0:
                    count = -count
                    _, pos = read_long(buf, pos)
                for _ in range(count):
                    raw, pos = read_bytes(buf, pos)
                    try:
                        k = raw.decode("utf-8")
                    except UnicodeDecodeError as e:
                        raise MalformedAvro(
                            f"invalid UTF-8 in map key: {e}",
                            err_name="bad_utf8",
                        ) from None
                    v, pos = value_reader(buf, pos)
                    out.append((k, v))
        return read_map

    if isinstance(t, Union):
        readers = tuple(compile_reader(v, _depth + 1) for v in t.variants)
        n = len(readers)
        def read_union(buf, pos):
            idx, pos = read_long(buf, pos)
            if not 0 <= idx < n:
                raise MalformedAvro(f"union branch {idx} out of range 0..{n}",
                                    err_name="bad_branch")
            v, pos = readers[idx](buf, pos)
            return (idx, v), pos
        return read_union

    if isinstance(t, Record):
        field_readers = tuple(
            (f.name, compile_reader(f.type, _depth + 1)) for f in t.fields
        )
        def read_record(buf, pos):
            row = {}
            for name, rd in field_readers:
                row[name], pos = rd(buf, pos)
            return row, pos
        return read_record

    raise NotImplementedError(f"no reader for {t!r}")


def _decode_one(datum, reader: Callable, limit: int):
    """One datum through the reader with the hostile-input guards: the
    PYRUHVRO_TPU_MAX_DATUM_BYTES ceiling fires before any decode work,
    the per-datum zero-width item budget resets, trailing bytes error."""
    if limit and len(datum) > limit:
        raise MalformedAvro(
            f"datum of {len(datum)} bytes exceeds "
            f"PYRUHVRO_TPU_MAX_DATUM_BYTES={limit}",
            err_name="datum_too_large",
        )
    _reset_zw_budget()
    value, pos = reader(datum, 0)
    if pos != len(datum):
        raise MalformedAvro(
            f"trailing bytes after datum: consumed {pos} of {len(datum)}",
            err_name="trailing",
        )
    return value


def decode_records(
    data: Sequence[bytes], t: AvroType, reader: Callable = None,
    index_base: int = 0,
) -> List[object]:
    """Decode each datum fully; trailing bytes are an error.

    Pass a precompiled ``reader`` (from :func:`compile_reader`, cached per
    schema via ``SchemaEntry.get_extra``) to skip per-call recompilation.
    Errors carry the GLOBAL row index (``index_base`` + position), so the
    chunked fallback path reports the same index as the native/device
    tiers (``record <i>: <why>``)."""
    if reader is None:
        reader = compile_reader(t)
    limit = max_datum_bytes()
    out = []
    for j, datum in enumerate(data):
        try:
            out.append(_decode_one(datum, reader, limit))
        except MalformedAvro as e:
            i = index_base + j
            raise MalformedAvro(
                f"record {i}: {e}", index=i,
                err_name=e.err_name, tier="fallback",
            ) from None
    return out


def decode_records_tolerant(
    data: Sequence[bytes], t: AvroType, reader: Callable = None,
    index_base: int = 0,
) -> Tuple[List[object], List[Tuple[int, bytes, str]]]:
    """Per-record error capture (the error-policy layer's last resort and
    the fallback tier's native mode): decode every datum independently,
    returning ``(surviving_value_trees, errors)`` where errors is
    ``[(global_index, raw_datum_bytes, err_name), ...]`` in row order.
    Surviving values keep their relative order."""
    return decode_pairs_tolerant(
        [(index_base + j, d) for j, d in enumerate(data)], t, reader
    )


def decode_pairs_tolerant(
    pairs: Sequence[Tuple[int, bytes]], t: AvroType, reader: Callable = None
) -> Tuple[List[object], List[Tuple[int, bytes, str]]]:
    """Like :func:`decode_records_tolerant` but over explicit
    ``(global_index, datum)`` pairs — the shape the error-policy resume
    loop holds after earlier offenders were already removed (survivor
    sets are not contiguous index ranges)."""
    if reader is None:
        reader = compile_reader(t)
    limit = max_datum_bytes()
    out: List[object] = []
    errors: List[Tuple[int, bytes, str]] = []
    for gi, datum in pairs:
        try:
            out.append(_decode_one(datum, reader, limit))
        except MalformedAvro as e:
            errors.append((gi, bytes(datum), e.err_name or "malformed"))
    return out, errors


# ---------------------------------------------------------------------------
# Stage 2: value trees → Arrow arrays
# ---------------------------------------------------------------------------

def _build_array(t: AvroType, dt: pa.DataType, values: List[object]) -> pa.Array:
    # unwrap nullable-pair unions: values are (branch, v) tuples
    if isinstance(t, Union) and t.is_nullable_pair:
        null_idx = t.null_index
        inner = [None if v is None or v[0] == null_idx else v[1] for v in values]
        return _build_array(t.non_null_variant, dt, inner)

    if isinstance(t, Primitive):
        if t.logical == "decimal":
            ctx = decimal.Context(prec=max(t.precision, 1))
            scale = t.scale
            vals = [
                None
                if v is None
                else ctx.create_decimal(v).scaleb(-scale, ctx)
                for v in values
            ]
            return pa.array(vals, type=dt)
        if t.logical == "uuid":
            vals = [
                None if v is None else _uuid.UUID(v).bytes for v in values
            ]
            return pa.array(vals, type=dt)
        return pa.array(values, type=dt)

    if isinstance(t, Fixed):
        if t.logical == "decimal":
            ctx = decimal.Context(prec=max(t.precision, 1))
            scale = t.scale
            vals = [
                None
                if v is None
                else ctx.create_decimal(v).scaleb(-scale, ctx)
                for v in values
            ]
            return pa.array(vals, type=dt)
        if t.logical == "duration":
            # avro duration fixed(12) = (months, days, millis) little-endian
            # u32; reference maps to Duration(ms). Months/days have no exact
            # ms length; we use the Arrow convention 1 day = 86_400_000 ms,
            # 1 month = 30 days, documenting the reference's lossy mapping.
            def to_ms(v):
                if v is None:
                    return None
                months = int.from_bytes(v[0:4], "little")
                days = int.from_bytes(v[4:8], "little")
                ms = int.from_bytes(v[8:12], "little")
                return ((months * 30 + days) * 86_400_000) + ms
            return pa.array([to_ms(v) for v in values], type=dt)
        return pa.array(values, type=dt)

    if isinstance(t, Enum):
        return pa.array(values, type=pa.string())

    if isinstance(t, Array):
        item_field = dt.value_field
        # null rows repeat the previous offset and set a validity bit; a null
        # in the offsets array itself would mark the WRONG row (the from_arrays
        # null-offset convention applies to the start position, which is the
        # previous row's end)
        offsets = [0]
        validity = []
        child_values = []
        n = 0
        for v in values:
            if v is None:
                validity.append(False)
            else:
                child_values.extend(v)
                n += len(v)
                validity.append(True)
            offsets.append(n)
        child = _build_array(t.items, item_field.type, child_values)
        mask = pa.array([not ok for ok in validity]) if not all(validity) else None
        return pa.ListArray.from_arrays(
            pa.array(offsets, pa.int32()), child, type=dt, mask=mask
        )

    if isinstance(t, Map):
        offsets = [0]
        validity = []
        keys: List[object] = []
        vals: List[object] = []
        n = 0
        for v in values:
            if v is None:
                validity.append(False)
            else:
                for k, item in v:
                    keys.append(k)
                    vals.append(item)
                n += len(v)
                validity.append(True)
            offsets.append(n)
        key_arr = pa.array(keys, pa.string())
        val_arr = _build_array(t.values, dt.item_type, vals)
        entries = pa.StructArray.from_arrays(
            [key_arr, val_arr], fields=[dt.key_field, dt.item_field]
        )
        if all(validity):
            vbuf, nulls = None, 0
        else:
            vbuf = pa.py_buffer(
                np.packbits(np.array(validity, bool), bitorder="little")
            )
            nulls = validity.count(False)
        return pa.Array.from_buffers(
            dt, len(values),
            [vbuf, pa.py_buffer(np.array(offsets, np.int32))],
            null_count=nulls, children=[entries],
        )

    if isinstance(t, Union):
        # sparse union: one child per variant, same length; non-selected
        # rows are null in every child (fast_decode.rs:643-668)
        n_var = len(t.variants)
        type_ids = []
        per_child: List[List[object]] = [[] for _ in range(n_var)]
        for v in values:
            idx, inner = (0, None) if v is None else v
            type_ids.append(idx)
            for c in range(n_var):
                per_child[c].append(inner if c == idx else None)
        children = []
        field_names = []
        for c, (vt, child_field) in enumerate(zip(t.variants, dt)):
            children.append(_build_array(vt, child_field.type, per_child[c]))
            field_names.append(child_field.name)
        return pa.UnionArray.from_sparse(
            pa.array(type_ids, pa.int8()),
            children,
            field_names=field_names,
            type_codes=list(dt.type_codes),
        )

    if isinstance(t, Record):
        validity = [v is not None for v in values]
        any_null = not all(validity)
        if not t.fields:
            # StructArray.from_arrays([]) would be length 0 regardless of
            # len(values); build the empty-struct rows explicitly
            return pa.array(
                [None if v is None else {} for v in values], pa.struct([])
            )
        children = []
        fields = []
        for i, f in enumerate(t.fields):
            child_field = dt.field(i)
            child_vals = [None if v is None else v[f.name] for v in values]
            children.append(_build_array(f.type, child_field.type, child_vals))
            fields.append(child_field)
        mask = pa.array([not v for v in validity]) if any_null else None
        return pa.StructArray.from_arrays(children, fields=fields, mask=mask)

    raise NotImplementedError(f"no builder for {t!r}")


def decode_to_record_batch(
    data: Sequence[bytes],
    t: AvroType,
    arrow_schema: pa.Schema = None,
    reader: Callable = None,
    index_base: int = 0,
) -> pa.RecordBatch:
    """Full fallback decode: ``list[bytes]`` → ``pa.RecordBatch``
    (≙ ``per_datum_deserialize_baseline``, ``deserialize.rs:34-48``).
    ``index_base`` offsets error indices so chunked callers report the
    GLOBAL position of a malformed datum."""
    if not isinstance(t, Record):
        raise ValueError("top-level Avro schema must be a record")
    if arrow_schema is None:
        arrow_schema = to_arrow_schema(t)
    rows = decode_records(data, t, reader, index_base)
    return rows_to_record_batch(rows, t, arrow_schema)


def rows_to_record_batch(
    rows: List[object], t: AvroType, arrow_schema: pa.Schema
) -> pa.RecordBatch:
    """Stage 2 alone: decoded value trees → ``pa.RecordBatch`` (used by
    the tolerant decode paths, which assemble from SURVIVING rows after
    per-record error capture)."""
    if not t.fields:
        # zero-column batch must still carry the row count
        return pa.RecordBatch.from_struct_array(
            pa.array([{}] * len(rows), pa.struct([]))
        )
    arrays = []
    for i, f in enumerate(t.fields):
        field = arrow_schema.field(i)
        col_vals = [row[f.name] for row in rows]
        arrays.append(_build_array(f.type, field.type, col_vals))
    return pa.RecordBatch.from_arrays(arrays, schema=arrow_schema)
