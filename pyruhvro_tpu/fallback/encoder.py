"""General-path Arrow → Avro encoder (host, pure Python).

Analogue of the reference's fallback serializer
(``ruhvro/src/serialization_containers.rs``): walks Arrow arrays
column-wise into per-row Python values (cursor-style, ≙ ``ContainerIter``),
then writes each row as one Avro datum. Reference semantics preserved:

* name-based column matching with a missing-column error
  (``serialization_containers.rs:248-267``)
* nullable fields encode as the original union with the correct null
  branch index (``NullInfo``, ``:364-396``)
* N-variant unions take the branch from the Arrow type_ids buffer
  (``:399-513``)
* arrays/maps emit the single-block form ``[count, items..., 0]``; empty
  emits just ``0`` (≙ ``fast_encode.rs:518-554``)
* enums encode the symbol's index; unknown symbols error
  (``fast_encode.rs:356-362``)
"""

from __future__ import annotations

import decimal
import uuid as _uuid
from typing import List

import numpy as np
import pyarrow as pa

from ..schema.arrow_map import to_arrow_field
from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)
from .io import (
    write_bool,
    write_bytes,
    write_double,
    write_float,
    write_long,
)

__all__ = ["encode_record_batch", "extract_rows", "compile_writer"]


# ---------------------------------------------------------------------------
# Arrow arrays → per-row value trees (same conventions as decoder.py)
# ---------------------------------------------------------------------------

def extract_rows(arr: pa.Array, t: AvroType) -> List[object]:
    """Decompose an Arrow array into the decoder's value-tree convention."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()

    if isinstance(t, Union) and t.is_nullable_pair:
        null_idx = t.null_index
        val_idx = 1 - null_idx
        inner = extract_rows(arr, t.non_null_variant)
        return [
            None if v is None else (val_idx, v) for v in inner
        ]

    if isinstance(t, Union):
        type_ids = np.frombuffer(
            arr.buffers()[1], np.int8, count=len(arr) + arr.offset
        )[arr.offset :]
        children_rows = [
            extract_rows(arr.field(i), vt) for i, vt in enumerate(t.variants)
        ]
        out = []
        for i in range(len(arr)):
            tid = int(type_ids[i])
            if not 0 <= tid < len(children_rows):
                raise ValueError(f"union type_id {tid} out of range")
            out.append((tid, children_rows[tid][i]))
        return out

    if isinstance(t, Record):
        validity = _validity(arr)
        children = [
            extract_rows(arr.field(i), f.type) for i, f in enumerate(t.fields)
        ]
        names = [f.name for f in t.fields]
        out = []
        for i in range(len(arr)):
            if validity is not None and not validity[i]:
                out.append(None)
            else:
                out.append({n: c[i] for n, c in zip(names, children)})
        return out

    if isinstance(t, Array):
        lists = arr.to_pylist() if _is_simple(t.items) else None
        if lists is not None:
            return lists
        validity = _validity(arr)
        offsets = arr.offsets.to_pylist()
        child = extract_rows(arr.values, t.items)
        out = []
        for i in range(len(arr)):
            if validity is not None and not validity[i]:
                out.append(None)
            else:
                out.append(child[offsets[i] : offsets[i + 1]])
        return out

    if isinstance(t, Map):
        validity = _validity(arr)
        offsets = arr.offsets.to_pylist()
        keys = arr.keys.to_pylist()
        vals = extract_rows(arr.items, t.values)
        out = []
        for i in range(len(arr)):
            if validity is not None and not validity[i]:
                out.append(None)
            else:
                out.append(
                    list(zip(keys[offsets[i] : offsets[i + 1]],
                             vals[offsets[i] : offsets[i + 1]]))
                )
        return out

    if isinstance(t, Primitive) and t.logical == "decimal":
        return [None if v is None else _unscaled(v, t.scale) for v in arr.to_pylist()]
    if isinstance(t, Fixed) and t.logical == "decimal":
        return [None if v is None else _unscaled(v, t.scale) for v in arr.to_pylist()]
    if isinstance(t, Primitive) and t.logical == "uuid":
        return [
            None if v is None else str(_uuid.UUID(bytes=v)) for v in arr.to_pylist()
        ]
    if isinstance(t, Fixed) and t.logical == "duration":
        def from_ms(ms):
            if ms is None:
                return None
            days, ms = divmod(ms, 86_400_000)
            months, days = divmod(days, 30)
            return (
                int(months).to_bytes(4, "little")
                + int(days).to_bytes(4, "little")
                + int(ms).to_bytes(4, "little")
            )
        vals = arr.cast(pa.int64()).to_pylist()
        return [from_ms(v) for v in vals]

    if (
        isinstance(t, Primitive)
        and t.logical in ("timestamp-millis", "timestamp-micros",
                          "local-timestamp-millis", "local-timestamp-micros",
                          "time-millis", "time-micros", "date")
    ):
        # pylist gives datetime objects; go through the raw integers instead
        target = pa.int32() if t.name == "int" else pa.int64()
        return arr.cast(target).to_pylist()

    return arr.to_pylist()


def _validity(arr: pa.Array):
    if arr.null_count == 0:
        return None
    return np.asarray(arr.is_valid())


def _is_simple(t: AvroType) -> bool:
    return isinstance(t, (Primitive, Enum)) and getattr(t, "logical", None) is None


# exact context for decimal128: the default context's prec=28 would silently
# round values with 29-38 significant digits (the reference's i128 path is exact)
_DEC_CTX = decimal.Context(prec=76)


def _unscaled(v, scale: int) -> int:
    return int(v.scaleb(scale, _DEC_CTX).to_integral_value())


# ---------------------------------------------------------------------------
# Value trees → wire bytes
# ---------------------------------------------------------------------------

def compile_writer(t: AvroType):
    """Build a ``writer(out: bytearray, value)`` closure for ``t``.

    Every non-union writer rejects ``None`` with a clear error (unions
    route nulls to their null branch; bare nulls elsewhere are a schema
    violation the wire format cannot express)."""
    w = _compile_writer(t)
    if isinstance(t, Union) or (isinstance(t, Primitive) and t.name == "null"):
        return w
    what = type(t).__name__.lower()
    if isinstance(t, Primitive):
        what = t.logical or t.name
    return _non_null(w, what)


def _compile_writer(t: AvroType):
    if isinstance(t, Primitive):
        name = t.name
        if name == "null":
            return lambda out, v: None
        if name == "boolean":
            return write_bool
        if name in ("int", "long"):
            return write_long
        if name == "float":
            return write_float
        if name == "double":
            return write_double
        if name == "bytes":
            if t.logical == "decimal":
                def write_decimal(out, v):
                    n = max((int(v).bit_length() + 8) // 8, 1)
                    write_bytes(out, int(v).to_bytes(n, "big", signed=True))
                return write_decimal
            return write_bytes
        if name == "string":
            return lambda out, v: write_bytes(out, v.encode("utf-8"))
        raise NotImplementedError(name)

    if isinstance(t, Fixed):
        size = t.size
        if t.logical == "decimal":
            def write_fixed_decimal(out, v):
                out += int(v).to_bytes(size, "big", signed=True)
            return write_fixed_decimal
        def write_fixed(out, v):
            if len(v) != size:
                raise ValueError(f"fixed size mismatch: {len(v)} != {size}")
            out += v
        return write_fixed

    if isinstance(t, Enum):
        index = {s: i for i, s in enumerate(t.symbols)}
        def write_enum(out, v):
            try:
                write_long(out, index[v])
            except KeyError:
                raise ValueError(
                    f"value {v!r} is not a symbol of enum {t.fullname}"
                ) from None
        return write_enum

    if isinstance(t, Array):
        item_writer = compile_writer(t.items)
        def write_array(out, v):
            if v:
                write_long(out, len(v))
                for item in v:
                    item_writer(out, item)
            write_long(out, 0)
        return write_array

    if isinstance(t, Map):
        value_writer = compile_writer(t.values)
        def write_map(out, v):
            if v:
                write_long(out, len(v))
                for k, item in v:
                    write_bytes(out, k.encode("utf-8"))
                    value_writer(out, item)
            write_long(out, 0)
        return write_map

    if isinstance(t, Union):
        writers = tuple(compile_writer(v) for v in t.variants)
        null_idx = t.null_index
        def write_union(out, v):
            if v is None:
                if null_idx is None:
                    raise ValueError("null value for union without null variant")
                write_long(out, null_idx)
                return
            idx, inner = v
            write_long(out, idx)
            writers[idx](out, inner)
        return write_union

    if isinstance(t, Record):
        field_writers = tuple((f.name, compile_writer(f.type)) for f in t.fields)
        def write_record(out, v):
            for name, w in field_writers:
                try:
                    fv = v[name]
                except KeyError:
                    raise ValueError(f"row missing record field {name!r}") from None
                w(out, fv)
        return write_record

    raise NotImplementedError(f"no writer for {t!r}")


def _types_compatible(actual: pa.DataType, expected: pa.DataType) -> bool:
    """Structural type equality ignoring *container child* field names, so
    e.g. a list child named "element" (Parquet convention) matches the
    expected "item". Struct children still match by name — record fields
    are name-matched, like the reference (``serialization_containers.rs:248-267``)."""
    if actual.equals(expected):
        return True
    if pa.types.is_list(actual) and pa.types.is_list(expected):
        return _types_compatible(actual.value_type, expected.value_type)
    if pa.types.is_map(actual) and pa.types.is_map(expected):
        return _types_compatible(
            actual.key_type, expected.key_type
        ) and _types_compatible(actual.item_type, expected.item_type)
    if pa.types.is_struct(actual) and pa.types.is_struct(expected):
        if actual.num_fields != expected.num_fields:
            return False
        return all(
            actual.field(i).name == expected.field(i).name
            and _types_compatible(actual.field(i).type, expected.field(i).type)
            for i in range(actual.num_fields)
        )
    if pa.types.is_union(actual) and pa.types.is_union(expected):
        if actual.mode != expected.mode:
            # dense vs sparse changes child indexing; extract_rows assumes sparse
            return False
        if actual.num_fields != expected.num_fields or list(
            actual.type_codes
        ) != list(expected.type_codes):
            return False
        return all(
            _types_compatible(actual.field(i).type, expected.field(i).type)
            for i in range(actual.num_fields)
        )
    return False


def _non_null(writer, what: str):
    """Nulls are representable only under a union with a null variant; the
    lenient type check admits nullable child fields (Parquet-style batches),
    so a null in a non-nullable Avro position must fail with a clear error
    rather than a crash deep in a wire writer."""
    def checked(out, v):
        if v is None:
            raise ValueError(
                f"null value for non-nullable Avro {what} "
                f"(no null union at this position in the schema)"
            )
        writer(out, v)
    return checked


def compile_encoder_plan(t: Record) -> List[tuple]:
    """Schema-only work of :func:`encode_record_batch`, computed once per
    schema and reusable across chunks/calls (cache it via
    ``SchemaEntry.get_extra``): per field
    ``(name, expected_arrow_type, avro_type, writer)``."""
    if not isinstance(t, Record):
        raise ValueError("top-level Avro schema must be a record")
    return [
        (f.name, to_arrow_field(f.type, name=f.name, nullable=False).type,
         f.type, compile_writer(f.type))
        for f in t.fields
    ]


def encode_record_batch(
    batch: pa.RecordBatch, t: Record, plan: List[tuple] = None
) -> List[bytes]:
    """Encode every row of ``batch`` as one Avro datum
    (≙ ``serialization_containers::serialize``, ``:13-22``).

    Columns are matched by name; a missing column is an error
    (``:248-267``). Extra columns in the batch are ignored.
    """
    if plan is None:
        plan = compile_encoder_plan(t)
    n = batch.num_rows
    cols = []
    for name, expected_type, ftype, writer in plan:
        idx = batch.schema.get_field_index(name)
        if idx == -1:
            raise ValueError(
                f"record batch is missing column {name!r} required by schema"
            )
        actual = batch.schema.field(idx).type
        if not _types_compatible(actual, expected_type):
            raise ValueError(
                f"column {name!r} has Arrow type {actual}, but the Avro "
                f"schema requires {expected_type}"
            )
        cols.append((name, extract_rows(batch.column(idx), ftype), writer))
    out: List[bytes] = []
    for i in range(n):
        buf = bytearray()
        for name, rows, writer in cols:
            try:
                writer(buf, rows[i])
            except ValueError as e:
                raise ValueError(f"column {name!r}, row {i}: {e}") from None
        out.append(bytes(buf))
    return out
