"""General-path Arrow → Avro encoder (host, pure Python).

Analogue of the reference's fallback serializer
(``ruhvro/src/serialization_containers.rs``): walks Arrow arrays
column-wise into per-row Python values (cursor-style, ≙ ``ContainerIter``),
then writes each row as one Avro datum. Reference semantics preserved:

* name-based column matching with a missing-column error
  (``serialization_containers.rs:248-267``)
* nullable fields encode as the original union with the correct null
  branch index (``NullInfo``, ``:364-396``)
* N-variant unions take the branch from the Arrow type_ids buffer
  (``:399-513``)
* arrays/maps emit the single-block form ``[count, items..., 0]``; empty
  emits just ``0`` (≙ ``fast_encode.rs:518-554``)
* enums encode the symbol's index; unknown symbols error
  (``fast_encode.rs:356-362``)
"""

from __future__ import annotations

import uuid as _uuid
from typing import List, Sequence

import numpy as np
import pyarrow as pa

from ..schema.arrow_map import to_arrow_field
from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)
from .io import (
    write_bool,
    write_bytes,
    write_double,
    write_float,
    write_long,
)

__all__ = ["encode_record_batch", "extract_rows", "compile_writer"]


# ---------------------------------------------------------------------------
# Arrow arrays → per-row value trees (same conventions as decoder.py)
# ---------------------------------------------------------------------------

def extract_rows(arr: pa.Array, t: AvroType) -> List[object]:
    """Decompose an Arrow array into the decoder's value-tree convention."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()

    if isinstance(t, Union) and t.is_nullable_pair:
        null_idx = t.null_index
        val_idx = 1 - null_idx
        inner = extract_rows(arr, t.non_null_variant)
        return [
            None if v is None else (val_idx, v) for v in inner
        ]

    if isinstance(t, Union):
        type_ids = np.frombuffer(
            arr.buffers()[1], np.int8, count=len(arr) + arr.offset
        )[arr.offset :]
        children_rows = [
            extract_rows(arr.field(i), vt) for i, vt in enumerate(t.variants)
        ]
        out = []
        for i in range(len(arr)):
            tid = int(type_ids[i])
            if not 0 <= tid < len(children_rows):
                raise ValueError(f"union type_id {tid} out of range")
            out.append((tid, children_rows[tid][i]))
        return out

    if isinstance(t, Record):
        validity = _validity(arr)
        children = [
            extract_rows(arr.field(i), f.type) for i, f in enumerate(t.fields)
        ]
        names = [f.name for f in t.fields]
        out = []
        for i in range(len(arr)):
            if validity is not None and not validity[i]:
                out.append(None)
            else:
                out.append({n: c[i] for n, c in zip(names, children)})
        return out

    if isinstance(t, Array):
        lists = arr.to_pylist() if _is_simple(t.items) else None
        if lists is not None:
            return lists
        validity = _validity(arr)
        offsets = arr.offsets.to_pylist()
        child = extract_rows(arr.values, t.items)
        out = []
        for i in range(len(arr)):
            if validity is not None and not validity[i]:
                out.append(None)
            else:
                out.append(child[offsets[i] : offsets[i + 1]])
        return out

    if isinstance(t, Map):
        validity = _validity(arr)
        offsets = arr.offsets.to_pylist()
        keys = arr.keys.to_pylist()
        vals = extract_rows(arr.items, t.values)
        out = []
        for i in range(len(arr)):
            if validity is not None and not validity[i]:
                out.append(None)
            else:
                out.append(
                    list(zip(keys[offsets[i] : offsets[i + 1]],
                             vals[offsets[i] : offsets[i + 1]]))
                )
        return out

    if isinstance(t, Primitive) and t.logical == "decimal":
        return [None if v is None else _unscaled(v, t.scale) for v in arr.to_pylist()]
    if isinstance(t, Fixed) and t.logical == "decimal":
        return [None if v is None else _unscaled(v, t.scale) for v in arr.to_pylist()]
    if isinstance(t, Primitive) and t.logical == "uuid":
        return [
            None if v is None else str(_uuid.UUID(bytes=v)) for v in arr.to_pylist()
        ]
    if isinstance(t, Fixed) and t.logical == "duration":
        def from_ms(ms):
            if ms is None:
                return None
            days, ms = divmod(ms, 86_400_000)
            months, days = divmod(days, 30)
            return (
                int(months).to_bytes(4, "little")
                + int(days).to_bytes(4, "little")
                + int(ms).to_bytes(4, "little")
            )
        vals = arr.cast(pa.int64()).to_pylist()
        return [from_ms(v) for v in vals]

    if (
        isinstance(t, Primitive)
        and t.logical in ("timestamp-millis", "timestamp-micros",
                          "local-timestamp-millis", "local-timestamp-micros",
                          "time-millis", "time-micros", "date")
    ):
        # pylist gives datetime objects; go through the raw integers instead
        target = pa.int32() if t.name == "int" else pa.int64()
        return arr.cast(target).to_pylist()

    return arr.to_pylist()


def _validity(arr: pa.Array):
    if arr.null_count == 0:
        return None
    return np.asarray(arr.is_valid())


def _is_simple(t: AvroType) -> bool:
    return isinstance(t, (Primitive, Enum)) and getattr(t, "logical", None) is None


def _unscaled(v, scale: int) -> int:
    return int(v.scaleb(scale).to_integral_value())


# ---------------------------------------------------------------------------
# Value trees → wire bytes
# ---------------------------------------------------------------------------

def compile_writer(t: AvroType):
    """Build a ``writer(out: bytearray, value)`` closure for ``t``."""
    if isinstance(t, Primitive):
        name = t.name
        if name == "null":
            return lambda out, v: None
        if name == "boolean":
            return write_bool
        if name in ("int", "long"):
            return write_long
        if name == "float":
            return write_float
        if name == "double":
            return write_double
        if name == "bytes":
            if t.logical == "decimal":
                def write_decimal(out, v):
                    n = max((int(v).bit_length() + 8) // 8, 1)
                    write_bytes(out, int(v).to_bytes(n, "big", signed=True))
                return write_decimal
            return write_bytes
        if name == "string":
            return lambda out, v: write_bytes(out, v.encode("utf-8"))
        raise NotImplementedError(name)

    if isinstance(t, Fixed):
        size = t.size
        if t.logical == "decimal":
            def write_fixed_decimal(out, v):
                out += int(v).to_bytes(size, "big", signed=True)
            return write_fixed_decimal
        def write_fixed(out, v):
            if len(v) != size:
                raise ValueError(f"fixed size mismatch: {len(v)} != {size}")
            out += v
        return write_fixed

    if isinstance(t, Enum):
        index = {s: i for i, s in enumerate(t.symbols)}
        def write_enum(out, v):
            try:
                write_long(out, index[v])
            except KeyError:
                raise ValueError(
                    f"value {v!r} is not a symbol of enum {t.fullname}"
                ) from None
        return write_enum

    if isinstance(t, Array):
        item_writer = compile_writer(t.items)
        def write_array(out, v):
            if v:
                write_long(out, len(v))
                for item in v:
                    item_writer(out, item)
            write_long(out, 0)
        return write_array

    if isinstance(t, Map):
        value_writer = compile_writer(t.values)
        def write_map(out, v):
            if v:
                write_long(out, len(v))
                for k, item in v:
                    write_bytes(out, k.encode("utf-8"))
                    value_writer(out, item)
            write_long(out, 0)
        return write_map

    if isinstance(t, Union):
        writers = tuple(compile_writer(v) for v in t.variants)
        null_idx = t.null_index
        def write_union(out, v):
            if v is None:
                if null_idx is None:
                    raise ValueError("null value for union without null variant")
                write_long(out, null_idx)
                return
            idx, inner = v
            write_long(out, idx)
            writers[idx](out, inner)
        return write_union

    if isinstance(t, Record):
        field_writers = tuple((f.name, compile_writer(f.type)) for f in t.fields)
        def write_record(out, v):
            for name, w in field_writers:
                try:
                    fv = v[name]
                except KeyError:
                    raise ValueError(f"row missing record field {name!r}") from None
                w(out, fv)
        return write_record

    raise NotImplementedError(f"no writer for {t!r}")


def encode_record_batch(batch: pa.RecordBatch, t: Record) -> List[bytes]:
    """Encode every row of ``batch`` as one Avro datum
    (≙ ``serialization_containers::serialize``, ``:13-22``).

    Columns are matched by name; a missing column is an error
    (``:248-267``). Extra columns in the batch are ignored.
    """
    if not isinstance(t, Record):
        raise ValueError("top-level Avro schema must be a record")
    n = batch.num_rows
    cols = []
    for f in t.fields:
        idx = batch.schema.get_field_index(f.name)
        if idx == -1:
            raise ValueError(
                f"record batch is missing column {f.name!r} required by schema"
            )
        expected = to_arrow_field(f.type, name=f.name, nullable=False)
        actual = batch.schema.field(idx).type
        if actual != expected.type:
            raise ValueError(
                f"column {f.name!r} has Arrow type {actual}, but the Avro "
                f"schema requires {expected.type}"
            )
        cols.append((f.name, extract_rows(batch.column(idx), f.type),
                     compile_writer(f.type)))
    out: List[bytes] = []
    for i in range(n):
        buf = bytearray()
        for _name, rows, writer in cols:
            writer(buf, rows[i])
        out.append(bytes(buf))
    return out
