"""Avro binary wire-format primitives (host, pure Python).

The byte-level readers/writers mirror the reference's
``fast_decode.rs:846-922`` (``read_zigzag_long``, ``read_f32/f64``,
``read_bool``, ``read_string``) and ``fast_encode.rs:586-599``
(``write_zigzag_long``, ``write_string``), with the same malformed-input
policy: bounds are checked and a ``ValueError`` is raised rather than
panicking.

Avro spec recap (wire format):
* int/long: little-endian base-128 varint of the zig-zag encoding
* float/double: 4/8 bytes IEEE-754 little-endian
* boolean: one byte 0/1
* bytes/string: length (long) then payload
* fixed: exactly N bytes
"""

from __future__ import annotations

import struct

__all__ = [
    "MalformedAvro",
    "malformed_record",
    "shift_malformed",
    "max_datum_bytes",
    "MAX_ZERO_WIDTH_ITEMS",
    "read_varint",
    "read_long",
    "read_float",
    "read_double",
    "read_bool",
    "read_bytes",
    "zigzag_encode",
    "zigzag_decode",
    "write_long",
    "write_float",
    "write_double",
    "write_bool",
    "write_bytes",
    "long_size",
]

_unpack_f32 = struct.Struct("<f").unpack_from
_unpack_f64 = struct.Struct("<d").unpack_from
_pack_f32 = struct.Struct("<f").pack
_pack_f64 = struct.Struct("<d").pack


class MalformedAvro(ValueError):
    """Raised on truncated or invalid Avro wire bytes.

    Structured fields back the error-policy layer (``on_error=`` in
    :mod:`..api`): ``index`` is the GLOBAL row index of the offending
    datum when the raiser knows it (None otherwise), ``err_name`` a
    short machine-stable slug (feeds ``decode.quarantine.<err_name>``
    counters), ``tier`` which decode tier detected it, and ``indices``
    — set only by the device tier's error pass — every bad row of the
    batch as ``[(index, err_name), ...]`` so tolerant callers isolate
    all offenders in one extra launch instead of one per row."""

    def __init__(self, message: str = "", index=None, err_name=None,
                 tier=None, indices=None):
        super().__init__(message)
        self.index = index
        self.err_name = err_name
        self.tier = tier
        self.indices = indices

    def __reduce__(self):
        # ValueError's default reduce rebuilds from args alone, which
        # would drop the structured fields on the process-pool boundary
        return (
            _rebuild_malformed,
            (self.args, self.index, self.err_name, self.tier, self.indices),
        )


def _rebuild_malformed(args, index, err_name, tier, indices):
    e = MalformedAvro(*args)
    e.index, e.err_name, e.tier, e.indices = index, err_name, tier, indices
    return e


def malformed_record(index: int, detail: str, err_name=None, tier=None,
                     indices=None) -> MalformedAvro:
    """The uniform cross-tier error shape: ``record <global_idx>: <why>``."""
    return MalformedAvro(
        f"record {index}: {detail}",
        index=index, err_name=err_name, tier=tier, indices=indices,
    )


def shift_malformed(e: MalformedAvro, base: int) -> MalformedAvro:
    """Re-base a chunk-local error to global row indices (``base`` added
    to ``index``/``indices``); the message is rewritten to match."""
    if not base or e.index is None:
        return e
    idx = e.index + base
    msg = str(e)
    prefix = f"record {e.index}: "
    detail = msg[len(prefix):] if msg.startswith(prefix) else msg
    return MalformedAvro(
        f"record {idx}: {detail}", index=idx, err_name=e.err_name,
        tier=e.tier,
        indices=None if e.indices is None
        else [(i + base, n) for i, n in e.indices],
    )


def max_datum_bytes() -> int:
    """The PYRUHVRO_TPU_MAX_DATUM_BYTES hostile-input ceiling (0 =
    unlimited, the default). A datum longer than this is rejected (or
    quarantined under a tolerant policy) before any decode work."""
    from ..runtime import knobs

    return knobs.get_int("PYRUHVRO_TPU_MAX_DATUM_BYTES")


# Zero-width array/map items (null / empty-record elements consume no
# wire bytes) are the one spot where a tiny datum can claim unbounded
# output: a 3-byte block header can demand 2^60 items. Items of any
# other type consume >= 1 byte each, so their counts are naturally
# bounded by the remaining datum bytes. This cap bounds the total
# zero-width items per DATUM; the native VM enforces the same constant
# (kMaxZeroWidthItems, host_vm_core.h) so all tiers agree on
# accept-vs-reject.
MAX_ZERO_WIDTH_ITEMS = 1 << 20


def zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else (n << 1)


def read_varint(buf, pos: int):
    """Read an unsigned base-128 varint; returns (value, new_pos)."""
    acc = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise MalformedAvro("truncated varint", err_name="overrun")
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            return acc, pos
        shift += 7
        if shift > 63:
            raise MalformedAvro("varint too long (max 10 bytes)", err_name="varint")


def read_long(buf, pos: int):
    """Read a zig-zag varint long; returns (value, new_pos)
    (≙ ``read_zigzag_long``, ``fast_decode.rs:855-869``)."""
    acc, pos = read_varint(buf, pos)
    # wrap to signed 64-bit like the reference's u64→i64 cast
    acc &= (1 << 64) - 1
    value = (acc >> 1) ^ -(acc & 1)
    if value >= 1 << 63:
        value -= 1 << 64
    elif value < -(1 << 63):
        value += 1 << 64
    return value, pos


def read_float(buf, pos: int):
    if pos + 4 > len(buf):
        raise MalformedAvro("truncated float", err_name="overrun")
    return _unpack_f32(buf, pos)[0], pos + 4


def read_double(buf, pos: int):
    if pos + 8 > len(buf):
        raise MalformedAvro("truncated double", err_name="overrun")
    return _unpack_f64(buf, pos)[0], pos + 8


def read_bool(buf, pos: int):
    if pos >= len(buf):
        raise MalformedAvro("truncated bool", err_name="overrun")
    b = buf[pos]
    if b > 1:
        raise MalformedAvro(f"invalid bool byte {b:#x}", err_name="bad_bool")
    return b == 1, pos + 1


def read_bytes(buf, pos: int):
    ln, pos = read_long(buf, pos)
    if ln < 0:
        raise MalformedAvro(f"negative bytes/string length {ln}", err_name="neg_len")
    if ln > 0x7FFFFFFF:
        # parity with the native VM's string_len_i32 guard (ISSUE 15,
        # host_vm_core.h rd_string): the host lens lanes and the Arrow
        # Binary offsets are int32, so a >2GiB single value is rejected
        # at the wire, never silently wrapped downstream
        raise MalformedAvro(
            f"bytes/string length {ln} exceeds int32 capacity",
            err_name="overrun")
    if pos + ln > len(buf):
        raise MalformedAvro("truncated bytes/string payload", err_name="overrun")
    return bytes(buf[pos : pos + ln]), pos + ln


def long_size(value: int) -> int:
    """Number of wire bytes of a zig-zag varint for ``value``."""
    z = zigzag_encode(value)
    size = 1
    while z >= 0x80:
        z >>= 7
        size += 1
    return size


def write_long(out: bytearray, value: int) -> None:
    if not -(1 << 63) <= value < (1 << 63):
        raise ValueError(f"value {value} out of int64 range for Avro long")
    z = zigzag_encode(value) & ((1 << 64) - 1)
    while z >= 0x80:
        out.append((z & 0x7F) | 0x80)
        z >>= 7
    out.append(z)


def write_float(out: bytearray, value: float) -> None:
    out += _pack_f32(value)


def write_double(out: bytearray, value: float) -> None:
    out += _pack_f64(value)


def write_bool(out: bytearray, value: bool) -> None:
    out.append(1 if value else 0)


def write_bytes(out: bytearray, value) -> None:
    write_long(out, len(value))
    out += value
