"""Jitted decode pipeline: pack → (walk + finalize, one launch) → host.

Per batch (SURVEY.md §7's two-pass size-then-scatter, organized for XLA
and for a high-latency host↔device interconnect):

1. host packs the datums dense (``concat_records``, C++ shim) and ships
   ONE flat byte buffer + per-record offsets,
2. one fused jit launch runs the lowered field program (the **walk**:
   numeric lanes, validity bytes, type ids, item counts, string
   ``(start, len)`` descriptors) and the **finalize** (prefix-sum
   offsets, compaction of strided item slots) and concatenates every
   output plus the data-dependent reductions into ONE uint8 blob,
3. one device→host transfer fetches the blob; the host splits it by the
   statically known layout and assembles pyarrow arrays
   (``arrow_build``) — string value bytes are gathered host-side from
   the host's own copy of the input and never cross the interconnect.

Variable-size outputs get **speculative static capacities**: item-slot
caps and per-region item totals are remembered per schema from previous
batches; when a batch exceeds them the launch is retried with bigger
(power-of-two bucketed) caps. Steady-state workloads therefore run
exactly one launch + one transfer and compile exactly once per
(schema, R, B) bucket (≙ the schema→kernel cache, SURVEY.md §2 row 5).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..fallback.io import MalformedAvro
from ..runtime.pack import bucket_len, concat_records
from .fieldprog import ROWS, Program, lower
from .varint import ERR_ITEM_OVERFLOW, ERR_NAMES

__all__ = ["DeviceDecoder", "DeviceCapacityExceeded"]

_DEFAULT_ITEM_CAP = 8
_DEFAULT_TOT_CAP = 8
# per-record item-slot ceiling: beyond this the strided buffers would not
# fit device memory; the codec falls back to the host path for the batch
_MAX_ITEM_CAP = 1 << 20
_cache_enabled = False


class DeviceCapacityExceeded(Exception):
    """Batch needs more per-record item slots than the device path
    supports; the caller decodes it on the host instead."""


def _enable_persistent_cache(jax) -> None:
    """Point XLA's persistent compilation cache at a user-cache dir (unless
    the user configured one), so each (schema, shape-bucket) kernel
    compiles once per machine instead of once per process. Disable with
    PYRUHVRO_TPU_NO_CACHE=1."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    import os

    if os.environ.get("PYRUHVRO_TPU_NO_CACHE"):
        return
    try:
        # CPU executables AOT-reload with machine-feature mismatches (XLA
        # warns about SIGILL); only accelerator backends cache safely.
        # Decide from the *configured* platform string — asking the backend
        # (jax.default_backend()) would initialize it, and a wedged device
        # plugin can block that indefinitely.
        plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
        first = plats.split(",")[0].strip().lower()
        if first in ("", "cpu"):
            return
        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.expanduser("~/.cache/pyruhvro_tpu/xla"),
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization; never fail construction
        pass


class DeviceDecoder:
    """Per-schema decode pipeline with compiled-kernel caches."""

    def __init__(self, ir, backend: str = None):
        import jax  # deferred: importing pyruhvro_tpu must stay JAX-free

        _enable_persistent_cache(jax)
        self._jax = jax
        self.prog: Program = lower(ir)
        self.backend = backend
        self._pipe_cache: Dict[tuple, tuple] = {}
        self._err_cache: Dict[tuple, object] = {}
        self._item_caps: List[int] = [0] + [
            _DEFAULT_ITEM_CAP for _ in self.prog.regions[1:]
        ]
        # per-region item-total caps, remembered per R bucket
        self._tot_cap_mem: Dict[Tuple[int, int], int] = {}
        self._lock = threading.Lock()

    # -- traced pieces -----------------------------------------------------

    def _trace_walk(self, R: int, item_caps, words, starts, lengths, n):
        jnp = self._jax.numpy
        prog = self.prog
        from .fieldprog import _Ctx
        from .varint import ERR_TRAILING

        def cap_of(region: int) -> int:
            return R if region == ROWS else R * item_caps[region]

        row = jnp.arange(R, dtype=jnp.int32)
        st = {"#cursor": starts, "#err": jnp.zeros(R, jnp.uint32)}
        for spec in prog.buffers.values():
            st[spec.key] = jnp.zeros(cap_of(spec.region), spec.dtype)
        ends = starts + lengths
        active = row < n
        cx = _Ctx(words, ends, item_caps)
        st = prog.emit(cx, st, active, None)
        st["#err"] = st["#err"] | jnp.where(
            active & (st["#cursor"] != ends),
            jnp.uint32(ERR_TRAILING),
            jnp.uint32(0),
        )
        return st

    # -- the fused pipeline ------------------------------------------------

    def _pipeline_fn(self, R: int, B: int, item_caps: Tuple[int, ...],
                     tot_caps: Tuple[int, ...]):
        """Compiled fused walk+finalize. Returns ``(fn, layout)`` where
        ``fn(words, starts, lengths, n)`` yields ONE uint8 blob and
        ``layout`` is ``[(key, dtype, length), ...]`` for the host split.
        The blob also carries the reductions (error flag, per-region item
        max/sum) so the steady state costs a single device round trip."""
        key = (R, B, item_caps, tot_caps)
        hit = self._pipe_cache.get(key)
        if hit is not None:
            return hit
        jax = self._jax
        jnp = jax.numpy
        lax = jax.lax
        prog = self.prog

        item_buffers = {
            rid: sorted(
                (s for s in prog.buffers.values() if s.region == rid),
                key=lambda s: s.key,
            )
            for rid in range(1, len(prog.regions))
        }

        def row_of(offsets, n_entries: int, cap: int):
            """For each position j < cap, the entry whose [offsets[i],
            offsets[i+1]) range contains j — one scatter-max + one cummax
            scan instead of a per-position binary search."""
            m = jnp.zeros(cap, jnp.int32)
            m = m.at[offsets[:n_entries]].max(
                jnp.arange(n_entries, dtype=jnp.int32), mode="drop"
            )
            return lax.cummax(m)

        def pipeline(words, starts, lengths, n):
            st = self._trace_walk(R, item_caps, words, starts, lengths, n)
            out = {}
            for rid in range(1, len(prog.regions)):
                path = prog.regions[rid]
                icap, tcap = item_caps[rid], tot_caps[rid]
                counts = st[path + "#count"]
                offsets = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32),
                     jnp.cumsum(counts, dtype=jnp.int32)]
                )
                out[path + "#offsets"] = offsets
                j = jnp.arange(tcap, dtype=jnp.int32)
                row = row_of(offsets, R, tcap)
                slot = row * icap + (j - jnp.take(offsets, row, mode="clip"))
                # entries past the region's true total are zeroed — their
                # lens feed host-side cumsums
                in_range = j < offsets[-1]
                for spec in item_buffers[rid]:
                    taken = jnp.take(st[spec.key], slot, mode="clip")
                    out[spec.key] = jnp.where(in_range, taken,
                                              jnp.zeros_like(taken))
                out["#red:max:" + path] = jnp.max(counts).reshape(1)
                out["#red:sum:" + path] = offsets[-1].reshape(1)
            for spec in prog.buffers.values():
                if spec.region == ROWS and spec.key.rpartition("#")[2] != "count":
                    out[spec.key] = st[spec.key]
            out["#red:err"] = (
                jnp.any((st["#err"] & ~jnp.uint32(ERR_ITEM_OVERFLOW)) != 0)
                .reshape(1)
                .astype(jnp.uint8)
            )
            # one blob, one transfer
            chunks = []
            for k in sorted(out):
                v = out[k]
                if v.dtype == jnp.uint8:
                    chunks.append(v)
                else:
                    chunks.append(
                        lax.bitcast_convert_type(v, jnp.uint8).reshape(-1)
                    )
            return jnp.concatenate(chunks)

        # the blob layout mirrors pipeline's sorted(out) order exactly
        sizes: Dict[str, tuple] = {}
        for rid in range(1, len(prog.regions)):
            path = prog.regions[rid]
            sizes[path + "#offsets"] = (np.int32, R + 1)
            for spec in item_buffers[rid]:
                sizes[spec.key] = (np.dtype(spec.dtype), tot_caps[rid])
            sizes["#red:max:" + path] = (np.int32, 1)
            sizes["#red:sum:" + path] = (np.int32, 1)
        for spec in prog.buffers.values():
            if spec.region == ROWS and spec.key.rpartition("#")[2] != "count":
                sizes[spec.key] = (np.dtype(spec.dtype), R)
        sizes["#red:err"] = (np.uint8, 1)
        layout = [(k,) + sizes[k] for k in sorted(sizes)]

        pair = (jax.jit(pipeline), layout)
        with self._lock:
            self._pipe_cache[key] = pair
        return pair

    def _err_fn(self, R: int, B: int, item_caps: Tuple[int, ...]):
        """Walk-only error lanes, compiled lazily — only a malformed batch
        ever pays for it."""
        key = (R, B, item_caps)
        fn = self._err_cache.get(key)
        if fn is None:
            fn = self._jax.jit(
                lambda words, starts, lengths, n: self._trace_walk(
                    R, item_caps, words, starts, lengths, n
                )["#err"]
            )
            with self._lock:
                self._err_cache[key] = fn
        return fn

    # -- orchestration -----------------------------------------------------

    def decode_to_columns(self, data: Sequence[bytes]):
        """Run the pipeline; returns ``(host_columns, n, meta)`` where meta
        carries per-region item totals and the raw datum bytes for the
        host-side assembly."""
        jax = self._jax
        n = len(data)
        flat, offsets = concat_records(data)
        total = int(offsets[-1])
        if total > (1 << 30):
            # int32 cursors: callers split giant batches (runtime/chunking)
            raise ValueError(
                "batch exceeds 1 GiB of datum bytes; split it into chunks"
            )
        B = bucket_len(max(total, 4), minimum=16)
        R = bucket_len(max(n, 1), minimum=8)
        if B != total:
            flat = np.concatenate([flat, np.zeros(B - total, np.uint8)])
        words = np.ascontiguousarray(flat).view(np.uint32)
        starts = np.full(R, B, np.int32)
        starts[:n] = offsets[:-1]
        lengths = np.zeros(R, np.int32)
        lengths[:n] = np.diff(offsets)

        words_d = jax.device_put(words)
        starts_d = jax.device_put(starts)
        lengths_d = jax.device_put(lengths)
        n_d = np.int32(n)

        prog = self.prog
        host = None
        # zero-byte items (null / empty-record) reveal their true count only
        # ~cap-at-a-time, so cap growth can take ~log2(_MAX_ITEM_CAP) rounds
        for _attempt in range(24):
            item_caps = tuple(self._item_caps)
            tot_caps = tuple(
                [0]
                + [
                    min(
                        self._tot_cap_mem.get((R, rid), _DEFAULT_TOT_CAP),
                        R * item_caps[rid],
                    )
                    for rid in range(1, len(prog.regions))
                ]
            )
            fn, layout = self._pipeline_fn(R, B, item_caps, tot_caps)
            blob = np.asarray(
                jax.device_get(fn(words_d, starts_d, lengths_d, n_d))
            )
            host = {}
            pos = 0
            for key, dt, ln in layout:
                nbytes = np.dtype(dt).itemsize * ln
                host[key] = blob[pos : pos + nbytes].view(dt)
                pos += nbytes
            assert pos == blob.nbytes, "pipeline layout mismatch"
            retry = False
            for rid, path in enumerate(prog.regions):
                if rid == ROWS:
                    continue
                maxc = int(host["#red:max:" + path][0])
                sumc = int(host["#red:sum:" + path][0])
                if maxc > item_caps[rid]:
                    if maxc > _MAX_ITEM_CAP:
                        raise DeviceCapacityExceeded(
                            f"{path!r} needs {maxc} item slots per record "
                            f"(device limit {_MAX_ITEM_CAP})"
                        )
                    self._item_caps[rid] = bucket_len(
                        maxc, minimum=_DEFAULT_ITEM_CAP
                    )
                    retry = True
                if sumc > tot_caps[rid]:
                    self._tot_cap_mem[(R, rid)] = bucket_len(
                        max(sumc, 1), minimum=_DEFAULT_TOT_CAP
                    )
                    retry = True
            if not retry:
                break
        else:
            raise MalformedAvro("array/map item capacity did not converge")

        if host["#red:err"][0]:
            err = np.asarray(
                jax.device_get(
                    self._err_fn(R, B, item_caps)(
                        words_d, starts_d, lengths_d, n_d
                    )
                )
            )[:n]
            bad = err & ~np.uint32(ERR_ITEM_OVERFLOW)
            i = int(np.flatnonzero(bad)[0])
            v = int(bad[i])
            bit = v & -v
            raise MalformedAvro(
                f"record {i}: {ERR_NAMES.get(bit, f'error bit {bit:#x}')}"
            )

        meta = {"item_totals": {}, "flat": flat}
        for rid, path in enumerate(prog.regions):
            if rid != ROWS:
                meta["item_totals"][path] = int(host["#red:sum:" + path][0])
        return host, n, meta
