"""Jitted decode pipeline: pack → (walk + finalize, one launch) → host.

Per batch (SURVEY.md §7's two-pass size-then-scatter, organized for XLA
and for a high-latency host↔device interconnect):

1. host packs the datums dense (``concat_records``, C++ shim) and ships
   ONE flat byte buffer + per-record offsets,
2. one fused jit launch runs the lowered field program (the **walk**:
   numeric lanes, validity bytes, type ids, item counts, string
   ``(start, len)`` descriptors) and the **finalize** (prefix-sum
   offsets, compaction of strided item slots) and concatenates every
   output plus the data-dependent reductions into ONE uint8 blob,
3. one device→host transfer fetches the blob; the host splits it by the
   statically known layout and assembles pyarrow arrays
   (``arrow_build``) — string value bytes are gathered host-side from
   the host's own copy of the input and never cross the interconnect.

Variable-size outputs get **speculative static capacities**: item-slot
caps and per-region item totals are remembered per schema from previous
batches; when a batch exceeds them the launch is retried with bigger
(power-of-two bucketed) caps. Steady-state workloads therefore run
exactly one launch + one transfer and compile exactly once per
(schema, R, B) bucket (≙ the schema→kernel cache, SURVEY.md §2 row 5).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

import numpy as np

import time

from ..fallback.io import MalformedAvro, malformed_record
from ..runtime import deadline, device_obs, faults, metrics, telemetry
from ..runtime.pack import bucket_len, concat_records
from .fieldprog import ROWS, Program, lower
from .varint import ERR_ITEM_OVERFLOW, ERR_NAMES, ERR_SLUGS

__all__ = [
    "DeviceDecoder",
    "DeviceCapacityExceeded",
    "BatchTooLarge",
    "split_blob",
    "pad_views",
    "pack_launch_input",
]


def split_blob(blob: np.ndarray, layout) -> Dict[str, np.ndarray]:
    """Split one transferred uint8 blob back into named host views by the
    pipeline's static ``[(key, dtype, length), ...]`` layout."""
    host: Dict[str, np.ndarray] = {}
    pos = 0
    for key, dt, ln in layout:
        nbytes = np.dtype(dt).itemsize * ln
        host[key] = blob[pos : pos + nbytes].view(dt)
        pos += nbytes
    assert pos == blob.nbytes, "pipeline layout mismatch"
    return host


def _region_counts(ir, batch, path: str):
    """Per-row item counts of the repeated field at ``path`` in an Arrow
    batch (host-side, for cap seeding). Path components are record field
    names or union-arm indices; nullable pairs are transparent (Arrow
    folds them into field nullability)."""
    from ..schema.model import Array as _Arr, Map as _Map, Record, Union

    t = ir
    arr = None
    for comp in path.split("/"):
        while isinstance(t, Union) and t.is_nullable_pair:
            t = t.non_null_variant
        if isinstance(t, Record):
            names = [f.name for f in t.fields]
            i = names.index(comp)
            arr = batch.column(comp) if arr is None else arr.field(i)
            t = t.fields[i].type
        elif isinstance(t, Union):
            k = int(comp)
            arr = arr.field(k)
            t = t.variants[k]
        else:
            return None
    while isinstance(t, Union) and t.is_nullable_pair:
        t = t.non_null_variant
    if arr is None or not isinstance(t, (_Arr, _Map)):
        return None
    counts = np.diff(np.asarray(arr.offsets))
    if arr.null_count:
        counts = np.where(
            arr.is_valid().to_numpy(zero_copy_only=False), counts, 0
        )
    return counts


def pad_views(flat: np.ndarray, offsets: np.ndarray, n: int, R: int, B: int):
    """Shape one packed record run into launch inputs: ``flat`` padded to
    ``B`` bytes viewed as LE u32 ``words``, plus ``starts``/``lengths``
    lane vectors padded to ``R`` (inactive lanes: start=B, length=0).
    Returns ``(words, starts, lengths, flat_padded)``."""
    total = int(offsets[-1])
    if B != total:
        flat = np.concatenate([flat, np.zeros(B - total, np.uint8)])
    words = np.ascontiguousarray(flat).view(np.uint32)
    starts = np.full(R, B, np.int32)
    starts[:n] = offsets[:-1]
    lengths = np.zeros(R, np.int32)
    lengths[:n] = np.diff(offsets).astype(np.int32)
    return words, starts, lengths, flat


def pack_launch_input(words, starts, lengths, n: int) -> np.ndarray:
    """Fuse the four launch inputs into ONE uint32 host buffer
    ``[words | starts | lengths | n]`` — a single ``device_put`` per
    decode call (each extra array is an extra transfer; see
    ``_pipeline_fn``)."""
    return np.concatenate([
        words,
        starts.view(np.uint32),
        lengths.view(np.uint32),
        np.array([n], np.uint32),
    ])


def unpack_launch_input(jnp, lax, buf, W: int, R: int):
    """Traced inverse of :func:`pack_launch_input` — the single place
    that knows the packed layout (used by the single-device jit wrapper
    and the ``shard_map`` per-shard body)."""
    words = buf[:W]
    starts = lax.bitcast_convert_type(buf[W : W + R], jnp.int32)
    lengths = lax.bitcast_convert_type(buf[W + R : W + 2 * R], jnp.int32)
    n = lax.bitcast_convert_type(buf[W + 2 * R], jnp.int32)
    return words, starts, lengths, n

def _bucket_label(R: int, B: int, item_caps=(), tot_caps=(),
                  compact: bool = True) -> str:
    """Human-readable shape-bucket id for the jit-cache registry (one
    label per compiled executable)."""
    label = f"R{R},B{B}"
    if len(item_caps) > 1:
        label += ",i" + "/".join(str(c) for c in item_caps[1:])
    if len(tot_caps) > 1:
        label += ",t" + "/".join(str(c) for c in tot_caps[1:])
    if not compact:
        label += ",full"
    return label


_DEFAULT_ITEM_CAP = 8
_DEFAULT_TOT_CAP = 8
# per-record item-slot ceiling: beyond this the strided buffers would not
# fit device memory; ``grow_caps`` raises DeviceCapacityExceeded and the
# codec serves that batch from the host path (codec.py catches it)
_MAX_ITEM_CAP = 1 << 20
_cache_enabled = False


class DeviceCapacityExceeded(Exception):
    """Batch needs more per-record item slots than the device path
    supports; the caller decodes it on the host instead."""


class BatchTooLarge(Exception):
    """Batch exceeds the single-launch byte budget (int32 cursors);
    the codec splits it and decodes the pieces (still on device)."""


def _enable_persistent_cache(jax) -> None:
    """Point XLA's persistent compilation cache at a user-cache dir (unless
    the user configured one), so each (schema, shape-bucket) kernel
    compiles once per machine instead of once per process. Disable with
    PYRUHVRO_TPU_NO_CACHE=1."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    import os

    if os.environ.get("PYRUHVRO_TPU_NO_CACHE"):
        return
    try:
        # CPU executables AOT-reload with machine-feature mismatches (XLA
        # warns about SIGILL); only accelerator backends cache safely.
        # Decide from the *configured* platform string — asking the backend
        # (jax.default_backend()) would initialize it, and a wedged device
        # plugin can block that indefinitely.
        plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
        first = plats.split(",")[0].strip().lower()
        if first in ("", "cpu"):
            return
        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.expanduser("~/.cache/pyruhvro_tpu/xla"),
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization; never fail construction
        pass


class DeviceDecoder:
    """Per-schema decode pipeline with compiled-kernel caches."""

    def __init__(self, ir, backend: str = None,
                 fingerprint: str = None):
        import jax  # deferred: importing pyruhvro_tpu must stay JAX-free

        _enable_persistent_cache(jax)
        self._jax = jax
        self.prog: Program = lower(ir)
        self.backend = backend
        # schema id for the jit-cache registry / recompile-churn guard
        # (codec.py passes the SchemaEntry fingerprint down)
        self.fingerprint = fingerprint or "?"
        self._pipe_cache: Dict[tuple, tuple] = {}
        self._err_cache: Dict[tuple, object] = {}
        self._item_caps: List[int] = [0] + [
            _DEFAULT_ITEM_CAP for _ in self.prog.regions[1:]
        ]
        # per-region item-total caps, remembered per R bucket
        self._tot_cap_mem: Dict[Tuple[int, int], int] = {}
        # (R, B) buckets whose string lens overflowed the compact
        # descriptor budget — remembered so they go straight to the
        # full-width layout (see build_pipeline blob shrinking)
        self._str_full: set = set()
        self._seed_tried: set = set()  # (R, rid) sampling attempts
        self._lock = threading.Lock()

    # -- traced pieces -----------------------------------------------------

    def _trace_walk(self, R: int, item_caps, words, starts, lengths, n):
        jnp = self._jax.numpy
        prog = self.prog
        from .fieldprog import _Ctx
        from .varint import ERR_TRAILING

        def cap_of(region: int) -> int:
            # strided slot space: product of item caps down the ancestry
            cap = R
            while region != ROWS:
                cap *= item_caps[region]
                region = prog.region_parents[region]
            return cap

        row = jnp.arange(R, dtype=jnp.int32)
        st = {"#cursor": starts, "#err": jnp.zeros(R, jnp.uint32)}
        for spec in prog.buffers.values():
            st[spec.key] = jnp.zeros(cap_of(spec.region), spec.dtype)
        ends = starts + lengths
        active = row < n
        cx = _Ctx(words, ends, item_caps)
        st = prog.emit(cx, st, active, None)
        st["#err"] = st["#err"] | jnp.where(
            active & (st["#cursor"] != ends),
            jnp.uint32(ERR_TRAILING),
            jnp.uint32(0),
        )
        return st

    # -- the fused pipeline ------------------------------------------------

    def build_pipeline(self, R: int, B: int, item_caps: Tuple[int, ...],
                       tot_caps: Tuple[int, ...],
                       compact_strings: bool = True):
        """Build the (unjitted) fused walk+finalize. Returns
        ``(fn, layout)`` where ``fn(words, starts, lengths, n)`` yields
        ONE uint8 blob and ``layout`` is ``[(key, dtype, length), ...]``
        for the host split. The blob also carries the reductions (error
        flag, per-region item max/sum) so the steady state costs a single
        device round trip.

        Blob shrinking (the d2h direction is the expensive one —
        BENCH_NOTES.md): string ``(start, len)`` descriptor pairs are
        the bulk of the blob, so with ``compact_strings`` they ship as
        ONE u32 ``start | len << 21`` when ``B ≤ 2^20`` (lens < 2^11,
        "sl32" mode) or with u16 lens otherwise (lens < 2^16, "len16"
        mode); a ``#red:strfit`` reduction reports when a batch's lens
        exceed the mode's budget and the caller retries with
        ``compact_strings=False`` (same ladder as capacity growth).
        Validity and boolean lanes always bit-pack 8:1 (``…@bits``).
        :meth:`expand_host` undoes all of it after the transfer.

        The raw callable is what :mod:`..parallel` ``shard_map``s over a
        device mesh (each mesh shard runs it on its chunk) and what
        ``__graft_entry__.entry()`` hands the driver for compile checks;
        single-device callers use :meth:`_pipeline_fn` (jit + cache)."""
        jax = self._jax
        jnp = jax.numpy
        lax = jax.lax
        prog = self.prog
        str_mode = None
        if compact_strings and prog.string_cols:
            str_mode = "sl32" if B <= (1 << 20) else "len16"
        len_limit = (1 << 11) if str_mode == "sl32" else (1 << 16)

        item_buffers = {
            rid: sorted(
                (s for s in prog.buffers.values() if s.region == rid),
                key=lambda s: s.key,
            )
            for rid in range(1, len(prog.regions))
        }

        def row_of(offsets, n_entries: int, cap: int):
            """For each position j < cap, the entry whose [offsets[i],
            offsets[i+1]) range contains j — one scatter-max + one cummax
            scan instead of a per-position binary search."""
            m = jnp.zeros(cap, jnp.int32)
            m = m.at[offsets[:n_entries]].max(
                jnp.arange(n_entries, dtype=jnp.int32), mode="drop"
            )
            return lax.cummax(m)

        def pipeline(words, starts, lengths, n):
            st = self._trace_walk(R, item_caps, words, starts, lengths, n)
            out = {}
            # compaction cascades parent-first (region ids are in DFS
            # order): a nested region's counts live in its parent's
            # STRIDED slot space and are first gathered through the
            # parent's compaction map
            slot_maps = {}  # rid -> (strided slot per compact idx, in_range)
            for rid in range(1, len(prog.regions)):
                path = prog.regions[rid]
                parent = prog.region_parents[rid]
                icap, tcap = item_caps[rid], tot_caps[rid]
                counts_raw = st[path + "#count"]
                if parent == ROWS:
                    n_entries = R
                    counts_c = counts_raw
                    parent_slot = jnp.arange(R, dtype=jnp.int32)
                else:
                    parent_slot, parent_in = slot_maps[parent]
                    n_entries = tot_caps[parent]
                    taken = jnp.take(counts_raw, parent_slot, mode="clip")
                    counts_c = jnp.where(parent_in, taken, 0)
                offsets = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32),
                     jnp.cumsum(counts_c, dtype=jnp.int32)]
                )
                out[path + "#offsets"] = offsets
                j = jnp.arange(tcap, dtype=jnp.int32)
                ent = row_of(offsets, n_entries, tcap)
                slot = (
                    jnp.take(parent_slot, ent, mode="clip") * icap
                    + (j - jnp.take(offsets, ent, mode="clip"))
                )
                # entries past the region's true total are zeroed — their
                # lens feed host-side cumsums
                in_range = j < offsets[-1]
                slot_maps[rid] = (slot, in_range)
                for spec in item_buffers[rid]:
                    taken = jnp.take(st[spec.key], slot, mode="clip")
                    out[spec.key] = jnp.where(in_range, taken,
                                              jnp.zeros_like(taken))
                out["#red:max:" + path] = jnp.max(counts_c).reshape(1)
                out["#red:sum:" + path] = offsets[-1].reshape(1)
            for spec in prog.buffers.values():
                if spec.region == ROWS and spec.key.rpartition("#")[2] != "count":
                    out[spec.key] = st[spec.key]
            out["#red:err"] = (
                jnp.any((st["#err"] & ~jnp.uint32(ERR_ITEM_OVERFLOW)) != 0)
                .reshape(1)
                .astype(jnp.uint8)
            )
            # blob shrinking (see docstring): compact string descriptors…
            if str_mode is not None:
                fit = jnp.bool_(True)
                for sc in prog.string_cols:
                    fit = fit & (
                        jnp.max(out[sc.path + "#len"]) < len_limit
                    )
                out["#red:strfit"] = fit.reshape(1).astype(jnp.uint8)
                for sc in prog.string_cols:
                    s = out.pop(sc.path + "#start")
                    ln = out.pop(sc.path + "#len")
                    if str_mode == "sl32":
                        out[sc.path + "#sl"] = (
                            s.astype(jnp.uint32)
                            | (ln.astype(jnp.uint32) << 21)
                        )
                    else:
                        out[sc.path + "#start"] = s
                        out[sc.path + "#lenc"] = ln.astype(jnp.uint16)
            # …and bit-pack every u8 payload lane (validity, booleans)
            for k in list(out):
                if not k.startswith("#red:") and out[k].dtype == jnp.uint8:
                    out[k + "@bits"] = jnp.packbits(
                        out.pop(k), bitorder="little"
                    )
            # one blob, one transfer
            chunks = []
            for k in sorted(out):
                v = out[k]
                if v.dtype == jnp.uint8:
                    chunks.append(v)
                else:
                    chunks.append(
                        lax.bitcast_convert_type(v, jnp.uint8).reshape(-1)
                    )
            return jnp.concatenate(chunks)

        # the blob layout mirrors pipeline's sorted(out) order exactly
        sizes: Dict[str, tuple] = {}
        for rid in range(1, len(prog.regions)):
            path = prog.regions[rid]
            parent = prog.region_parents[rid]
            n_entries = R if parent == ROWS else tot_caps[parent]
            sizes[path + "#offsets"] = (np.int32, n_entries + 1)
            for spec in item_buffers[rid]:
                sizes[spec.key] = (np.dtype(spec.dtype), tot_caps[rid])
            sizes["#red:max:" + path] = (np.int32, 1)
            sizes["#red:sum:" + path] = (np.int32, 1)
        for spec in prog.buffers.values():
            if spec.region == ROWS and spec.key.rpartition("#")[2] != "count":
                sizes[spec.key] = (np.dtype(spec.dtype), R)
        sizes["#red:err"] = (np.uint8, 1)
        # mirror the pipeline's blob-shrinking transforms exactly
        if str_mode is not None:
            sizes["#red:strfit"] = (np.uint8, 1)
            for sc in prog.string_cols:
                _dt, ln_s = sizes.pop(sc.path + "#start")
                sizes.pop(sc.path + "#len")
                if str_mode == "sl32":
                    sizes[sc.path + "#sl"] = (np.uint32, ln_s)
                else:
                    sizes[sc.path + "#start"] = (np.int32, ln_s)
                    sizes[sc.path + "#lenc"] = (np.uint16, ln_s)
        for k in list(sizes):
            dt, ln = sizes[k]
            if not k.startswith("#red:") and np.dtype(dt) == np.uint8:
                del sizes[k]
                sizes[k + "@bits"] = (np.uint8, ln // 8)
        layout = [(k,) + sizes[k] for k in sorted(sizes)]
        return pipeline, layout

    @staticmethod
    def expand_host(host: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Undo :meth:`build_pipeline`'s blob shrinking on the host dict
        (vectorized, µs-scale) so the Arrow assembly sees the standard
        ``#start``/``#len``/u8-lane keys."""
        for k in list(host):
            if k.endswith("@bits"):
                host[k[:-5]] = np.unpackbits(host[k], bitorder="little")
            elif k.endswith("#sl"):
                v = host[k]
                p = k[: -len("#sl")]
                host[p + "#start"] = (
                    v & np.uint32((1 << 21) - 1)
                ).astype(np.int32)
                host[p + "#len"] = (v >> np.uint32(21)).astype(np.int32)
            elif k.endswith("#lenc"):
                host[k[: -len("#lenc")] + "#len"] = host[k].astype(np.int32)
        return host

    def _pipeline_fn(self, R: int, B: int, item_caps: Tuple[int, ...],
                     tot_caps: Tuple[int, ...],
                     compact_strings: bool = True):
        """Jitted-and-cached :meth:`build_pipeline` (one compile per
        (R, B, caps) bucket for the process, ≙ the schema→kernel cache).

        The jitted callable takes ONE packed uint32 buffer
        ``[words | starts | lengths | n]`` (see :func:`pack_launch_input`)
        instead of four arrays: each separate jit argument is a separate
        transfer, and on a high-latency interconnect a fresh numpy
        scalar argument alone costs a full synchronous round trip
        (measured ~65 ms through a device tunnel — BENCH_NOTES.md)."""
        key = (R, B, item_caps, tot_caps, compact_strings)
        hit = self._pipe_cache.get(key)
        if hit is not None:
            return hit
        pipeline, layout = self.build_pipeline(
            R, B, item_caps, tot_caps, compact_strings
        )
        jnp = self._jax.numpy
        lax = self._jax.lax
        W = B // 4

        def packed(buf):
            return pipeline(*unpack_launch_input(jnp, lax, buf, W, R))

        # jit-cache telemetry (ISSUE 5): each cache entry is one
        # executable; the wrapper splits its first call into an explicit
        # lower+compile (device.compile_s) and times every later call as
        # device.launch_s, feeding the per-(fingerprint, bucket) registry
        # and the recompile-churn guard
        fn = device_obs.InstrumentedJit(
            self._jax, self._jax.jit(packed), kind="decode.pipeline",
            bucket=_bucket_label(R, B, item_caps, tot_caps,
                                 compact_strings),
            fingerprint=self.fingerprint, family="decode",
        )
        pair = (fn, layout)
        with self._lock:
            self._pipe_cache[key] = pair
        return pair

    def _err_fn(self, R: int, B: int, item_caps: Tuple[int, ...]):
        """Walk-only error lanes, compiled lazily — only a malformed batch
        ever pays for it."""
        key = (R, B, item_caps)
        fn = self._err_cache.get(key)
        if fn is None:
            fn = device_obs.InstrumentedJit(
                self._jax,
                self._jax.jit(
                    lambda words, starts, lengths, n: self._trace_walk(
                        R, item_caps, words, starts, lengths, n
                    )["#err"]
                ),
                kind="decode.err",
                bucket=_bucket_label(R, B, item_caps),
                fingerprint=self.fingerprint, family="decode",
            )
            with self._lock:
                self._err_cache[key] = fn
        return fn

    # -- capacity bookkeeping (shared with parallel.ShardedDecoder) --------

    def seed_caps_from_sample(self, data: Sequence[bytes], R: int) -> None:
        """Estimate item caps for a fresh ``R`` bucket from a small
        host-decoded sample, so the first device launch compiles ONCE
        instead of climbing the retry ladder (each rung is a recompile —
        and with remote compile, a tunnel round trip). Estimates only:
        the ladder still catches under-estimates; sampling errors
        (malformed head records) are ignored and left to the device
        pass, which reports exact per-record errors."""
        prog = self.prog
        if len(prog.regions) <= 1:
            return
        with self._lock:
            need = [
                rid
                for rid in range(1, len(prog.regions))
                if (R, rid) not in self._tot_cap_mem
                and (R, rid) not in self._seed_tried
            ]
            # one sampling attempt per (R, region) — a region the sample
            # can't resolve (e.g. nested repetition) must not re-pay the
            # host scan on every steady-state decode
            self._seed_tried.update((R, rid) for rid in need)
        if not need:
            return
        k = min(len(data), 128)
        try:
            from ..fallback.decoder import decode_to_record_batch
            from ..schema.arrow_map import to_arrow_schema

            with telemetry.phase("device.seed_s", rows=k):
                sample = decode_to_record_batch(
                    data[:k], prog.ir, to_arrow_schema(prog.ir)
                )
        except Exception:
            return
        for rid in need:
            counts = _region_counts(prog.ir, sample, prog.regions[rid])
            if counts is None or counts.size == 0:
                continue
            mx = int(counts.max(initial=0))
            avg = float(counts.mean())
            with self._lock:
                self._item_caps[rid] = max(
                    self._item_caps[rid],
                    bucket_len(mx + (mx >> 1) + 1,
                               minimum=_DEFAULT_ITEM_CAP),
                )
                est = int(R * avg * 1.25) + 16
                self._tot_cap_mem[(R, rid)] = max(
                    self._tot_cap_mem.get((R, rid), 0),
                    bucket_len(est, minimum=_DEFAULT_TOT_CAP),
                )

    def caps_snapshot(self, R: int):
        """Atomic snapshot of ``(item_caps, tot_caps)`` for an R bucket.

        A region's item total is bounded by (parent's entry total ×
        items/entry cap); parents precede children in region order, so
        one forward sweep resolves the nested bounds."""
        prog = self.prog
        with self._lock:
            item_caps = tuple(self._item_caps)
            tot_caps = [0]
            for rid in range(1, len(prog.regions)):
                parent = prog.region_parents[rid]
                parent_total = R if parent == ROWS else tot_caps[parent]
                tot_caps.append(
                    min(
                        self._tot_cap_mem.get((R, rid), _DEFAULT_TOT_CAP),
                        parent_total * item_caps[rid],
                    )
                )
            tot_caps = tuple(tot_caps)
        return item_caps, tot_caps

    def grow_caps(self, R, item_caps, tot_caps, red_max, red_sum) -> bool:
        """Grow remembered caps from observed per-region reductions
        (max items/record, total items). Returns True when any cap grew
        (→ the caller retries the launch with the bigger bucket).

        ``red_max`` / ``red_sum`` are ``{rid: int}`` — for sharded
        launches, already max-reduced across shards."""
        retry = False
        with self._lock:  # cap growth is monotonic; max() keeps it so
            for rid in red_max:
                maxc, sumc = red_max[rid], red_sum[rid]
                if maxc > item_caps[rid]:
                    if maxc > _MAX_ITEM_CAP:
                        raise DeviceCapacityExceeded(
                            f"{self.prog.regions[rid]!r} needs {maxc} item "
                            f"slots per record (device limit {_MAX_ITEM_CAP})"
                        )
                    self._item_caps[rid] = max(
                        self._item_caps[rid],
                        bucket_len(maxc, minimum=_DEFAULT_ITEM_CAP),
                    )
                    retry = True
                if sumc > tot_caps[rid]:
                    self._tot_cap_mem[(R, rid)] = max(
                        self._tot_cap_mem.get((R, rid), 0),
                        bucket_len(max(sumc, 1), minimum=_DEFAULT_TOT_CAP),
                    )
                    retry = True
        return retry

    # -- orchestration -----------------------------------------------------

    def decode_to_columns(self, data: Sequence[bytes]):
        """Run the pipeline; returns ``(host_columns, n, meta)`` where meta
        carries per-region item totals and the raw datum bytes for the
        host-side assembly.

        ``device.pipeline_s`` spans the whole device phase; its children
        (pack → h2d → compile/launch → d2h, plus seed/retry rungs)
        decompose it — the ISSUE 5 acceptance contract asserts >= 90%
        coverage on the kafka 10k run."""
        with telemetry.phase("device.pipeline_s", rows=len(data),
                             op="decode"):
            return self._decode_to_columns(data)

    def _decode_to_columns(self, data: Sequence[bytes]):
        jax = self._jax
        n = len(data)
        with telemetry.phase("decode.pack_s", rows=n):
            flat, offsets = concat_records(data)
        total = int(offsets[-1])
        if total > (1 << 30):
            # int32 cursors bound one launch to 1 GiB of datum bytes; the
            # codec catches this and auto-splits the batch (codec.py)
            raise BatchTooLarge(n, total)
        B = bucket_len(max(total, 4), minimum=16)
        R = bucket_len(max(n, 1), minimum=8)
        self.seed_caps_from_sample(data, R)
        words, starts, lengths, flat = pad_views(flat, offsets, n, R, B)
        packed = pack_launch_input(words, starts, lengths, n)

        with telemetry.phase("decode.h2d_s", bytes=packed.nbytes):
            faults.fire("h2d")
            packed_d = jax.device_put(packed)
        metrics.inc("decode.h2d_bytes", packed.nbytes)
        metrics.inc("device.h2d_bytes", packed.nbytes)

        prog = self.prog
        host = None
        # zero-byte items (null / empty-record) reveal their true count only
        # ~cap-at-a-time, so cap growth can take ~log2(_MAX_ITEM_CAP) rounds
        for _attempt in range(24):
            # each capacity-ladder rung is a compile + launch: a
            # deadline-bounded call stops climbing when the budget is
            # spent instead of paying rungs it can no longer afford
            deadline.check(site="device.capacity_ladder")
            item_caps, tot_caps = self.caps_snapshot(R)
            compact = (R, B) not in self._str_full
            fn, layout = self._pipeline_fn(R, B, item_caps, tot_caps,
                                           compact)
            # the wrapper splits device.compile_s (first call per shape
            # bucket, explicit lower+compile) from device.launch_s
            # (block_until_ready-bounded unless behind a remote
            # interconnect — device_obs.sync_mode); d2h_s carries any
            # remaining wait
            res = fn(packed_d)
            with telemetry.phase("decode.d2h_s"):
                blob = np.asarray(jax.device_get(res))
            metrics.inc("decode.d2h_bytes", blob.nbytes)
            metrics.inc("device.d2h_bytes", blob.nbytes)
            host = split_blob(blob, layout)
            if compact and "#red:strfit" in host and not host["#red:strfit"][0]:
                # a string overflowed the compact descriptor budget:
                # remember and relaunch this bucket full-width
                self._str_full.add((R, B))
                metrics.inc("device.retries")
                telemetry.observe(
                    "device.retry_s", 0.0,
                    reason="str_descriptor_overflow", attempt=_attempt,
                    capacity=_bucket_label(R, B, item_caps, tot_caps,
                                           compact),
                )
                continue
            red_max = {
                rid: int(host["#red:max:" + path][0])
                for rid, path in enumerate(prog.regions)
                if rid != ROWS
            }
            red_sum = {
                rid: int(host["#red:sum:" + path][0])
                for rid, path in enumerate(prog.regions)
                if rid != ROWS
            }
            t0 = time.perf_counter()
            if not self.grow_caps(R, item_caps, tot_caps, red_max, red_sum):
                break
            # each retry-ladder rung is a child span carrying WHY the
            # relaunch happened and the capacity that proved too small
            metrics.inc("device.retries")
            telemetry.observe(
                "device.retry_s", time.perf_counter() - t0,
                reason="cap_growth", attempt=_attempt,
                capacity=_bucket_label(R, B, item_caps, tot_caps, compact),
                need_items=max(red_max.values(), default=0),
                need_total=max(red_sum.values(), default=0),
            )
        else:
            raise MalformedAvro("array/map item capacity did not converge")

        # per-device memory watermarks where the backend exposes them
        # (TPU/GPU memory_stats(); graceful no-op on CPU)
        device_obs.note_memory(jax)

        host = self.expand_host(host)
        if host["#red:err"][0]:
            # rare path (malformed batch): re-put the unpacked inputs for
            # the walk-only error pass
            err = np.asarray(
                jax.device_get(
                    self._err_fn(R, B, item_caps)(
                        jax.device_put(words),
                        jax.device_put(starts),
                        jax.device_put(lengths),
                        np.int32(n),
                    )
                )
            )[:n]
            bad = err & ~np.uint32(ERR_ITEM_OVERFLOW)
            bad_rows = np.flatnonzero(bad)
            # the walk computed error bits for EVERY lane — surface the
            # full row mask so a tolerant caller (api.py on_error=skip/
            # null) isolates all offenders in ONE extra pass instead of
            # re-launching once per bad record
            indices = []
            for r in bad_rows:
                v = int(bad[int(r)])
                b = v & -v
                indices.append((int(r), ERR_SLUGS.get(b, f"bit_{b:#x}")))
            i = int(bad_rows[0])
            v = int(bad[i])
            bit = v & -v
            raise malformed_record(
                i, ERR_NAMES.get(bit, f"error bit {bit:#x}"),
                err_name=ERR_SLUGS.get(bit, f"bit_{bit:#x}"),
                tier="device", indices=indices,
            )

        meta = {"item_totals": {}, "flat": flat}
        for rid, path in enumerate(prog.regions):
            if rid != ROWS:
                meta["item_totals"][path] = int(host["#red:sum:" + path][0])
        return host, n, meta
