"""Jitted decode pipeline: pack → (walk + finalize, one launch) → host.

Per batch (SURVEY.md §7's two-pass size-then-scatter, organized for XLA
and for a high-latency host↔device interconnect):

1. host packs the datums dense (``concat_records``, C++ shim) and ships
   ONE flat byte buffer + per-record offsets,
2. one fused jit launch runs the lowered field program (the **walk**:
   numeric lanes, validity bytes, type ids, item counts, string
   ``(start, len)`` descriptors) and the **finalize** (prefix-sum
   offsets, compaction of strided item slots) and concatenates every
   output plus the data-dependent reductions into ONE uint8 blob,
3. one device→host transfer fetches the blob; the host splits it by the
   statically known layout and assembles pyarrow arrays
   (``arrow_build``) — string value bytes are gathered host-side from
   the host's own copy of the input and never cross the interconnect.

Variable-size outputs get **speculative static capacities**: item-slot
caps and per-region item totals are remembered per schema from previous
batches; when a batch exceeds them the launch is retried with bigger
(power-of-two bucketed) caps. Steady-state workloads therefore run
exactly one launch + one transfer and compile exactly once per
(schema, R, B) bucket (≙ the schema→kernel cache, SURVEY.md §2 row 5).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

import numpy as np

import time

from ..fallback.io import MalformedAvro, malformed_record
from ..runtime import (
    capacity,
    deadline,
    device_obs,
    faults,
    metrics,
    schedtest,
    telemetry,
)
from ..runtime.pack import bucket_len, concat_records
from .fieldprog import ROWS, Program, lower
from .varint import ERR_ITEM_OVERFLOW, ERR_NAMES, ERR_SLUGS

__all__ = [
    "DeviceDecoder",
    "DeviceCapacityExceeded",
    "BatchTooLarge",
    "split_blob",
    "pad_views",
    "pack_launch_input",
    "pack_launch_into",
    "overlap_chunks",
]


def raise_aggregated_malformed(indices) -> None:
    """Raise ONE :class:`MalformedAvro` for a multi-chunk / multi-shard
    decode: the message names the FIRST bad global row, ``indices``
    carries every ``(global index, slug)`` pair — the shape the
    tolerant api path consumes to quarantine all offenders in a single
    relaunch. Shared by the overlap path and ``parallel/sharded.py``."""
    indices = sorted(indices)
    i0, slug0 = indices[0]
    bit0 = {v: k for k, v in ERR_SLUGS.items()}.get(slug0, 0)
    raise malformed_record(
        i0, ERR_NAMES.get(bit0, slug0), err_name=slug0,
        tier="device", indices=indices,
    )


def _ready(res) -> bool:
    """Has an in-flight device result completed? (Conservative: an
    array without ``is_ready`` counts as done, so overlap accounting
    can only undercount on backends missing the API.)"""
    try:
        return bool(res.is_ready())
    except AttributeError:
        return True


def overlap_chunks(n_rows: int) -> int:
    """How many sub-batches the double-buffered h2d/compute overlap
    path should pipeline a decode through (1 = stay on the single-launch
    path). ``PYRUHVRO_TPU_OVERLAP=0`` disables; ``PYRUHVRO_TPU_OVERLAP_ROWS``
    (default 4096) is the minimum rows per chunk — chunks below it
    would pay more per-launch overhead than the overlap hides."""
    from ..runtime import knobs

    if not knobs.get_bool("PYRUHVRO_TPU_OVERLAP"):
        return 1
    min_rows = max(1, knobs.get_int("PYRUHVRO_TPU_OVERLAP_ROWS"))
    return max(1, min(8, n_rows // min_rows))


def split_blob(blob: np.ndarray, layout) -> Dict[str, np.ndarray]:
    """Split one transferred uint8 blob back into named host views by the
    pipeline's static ``[(key, dtype, length), ...]`` layout."""
    host: Dict[str, np.ndarray] = {}
    pos = 0
    for key, dt, ln in layout:
        nbytes = np.dtype(dt).itemsize * ln
        host[key] = blob[pos : pos + nbytes].view(dt)
        pos += nbytes
    assert pos == blob.nbytes, "pipeline layout mismatch"
    return host


def _region_counts(ir, batch, path: str):
    """Per-row item counts of the repeated field at ``path`` in an Arrow
    batch (host-side, for cap seeding). Path components are record field
    names or union-arm indices; nullable pairs are transparent (Arrow
    folds them into field nullability)."""
    from ..schema.model import Array as _Arr, Map as _Map, Record, Union

    t = ir
    arr = None
    for comp in path.split("/"):
        while isinstance(t, Union) and t.is_nullable_pair:
            t = t.non_null_variant
        if isinstance(t, Record):
            names = [f.name for f in t.fields]
            i = names.index(comp)
            arr = batch.column(comp) if arr is None else arr.field(i)
            t = t.fields[i].type
        elif isinstance(t, Union):
            k = int(comp)
            arr = arr.field(k)
            t = t.variants[k]
        else:
            return None
    while isinstance(t, Union) and t.is_nullable_pair:
        t = t.non_null_variant
    if arr is None or not isinstance(t, (_Arr, _Map)):
        return None
    counts = np.diff(np.asarray(arr.offsets))
    if arr.null_count:
        counts = np.where(
            arr.is_valid().to_numpy(zero_copy_only=False), counts, 0
        )
    return counts


def pad_views(flat: np.ndarray, offsets: np.ndarray, n: int, R: int, B: int):
    """Shape one packed record run into launch inputs: ``flat`` padded to
    ``B`` bytes viewed as LE u32 ``words``, plus ``starts``/``lengths``
    lane vectors padded to ``R`` (inactive lanes: start=B, length=0).
    Returns ``(words, starts, lengths, flat_padded)``."""
    total = int(offsets[-1])
    if B != total:
        flat = np.concatenate([flat, np.zeros(B - total, np.uint8)])
    words = np.ascontiguousarray(flat).view(np.uint32)
    starts = np.full(R, B, np.int32)
    starts[:n] = offsets[:-1]
    lengths = np.zeros(R, np.int32)
    lengths[:n] = np.diff(offsets).astype(np.int32)
    return words, starts, lengths, flat


def pack_launch_input(words, starts, lengths, n: int) -> np.ndarray:
    """Fuse the four launch inputs into ONE uint32 host buffer
    ``[words | starts | lengths | n]`` — a single ``device_put`` per
    decode call (each extra array is an extra transfer; see
    ``_pipeline_fn``)."""
    return np.concatenate([
        words,
        starts.view(np.uint32),
        lengths.view(np.uint32),
        np.array([n], np.uint32),
    ])


def pack_launch_into(out: np.ndarray, flat: np.ndarray,
                     offsets: np.ndarray, n: int, R: int, B: int
                     ) -> np.ndarray:
    """In-place :func:`pack_launch_input`: write the packed
    ``[words | starts | lengths | n]`` launch buffer for one record run
    directly into ``out`` (a persistent per-(R, B) host arena, length
    ``B // 4 + 2 * R + 1`` u32) — the warm path allocates nothing.
    ``flat``/``offsets`` are :func:`..runtime.pack.concat_records`
    output (or a slice of one: ``offsets`` may start non-zero)."""
    W = B // 4
    base = int(offsets[0])
    total = int(offsets[-1]) - base
    u8 = out[:W].view(np.uint8)
    u8[:total] = flat[:total]
    u8[total:] = 0
    starts = out[W : W + R].view(np.int32)
    starts[:] = B
    # subtract in int64 BEFORE the int32 store: a shard whose absolute
    # base offset crosses 2 GiB would overflow an in-place int32 -=
    # (numpy 2.x raises); the shard-local results always fit int32
    starts[:n] = offsets[:-1] - base
    lengths = out[W + R : W + 2 * R].view(np.int32)
    lengths[n:] = 0
    np.subtract(offsets[1:], offsets[:-1], out=lengths[:n],
                casting="unsafe")
    out[W + 2 * R] = n
    return out


def unpack_launch_input(jnp, lax, buf, W: int, R: int):
    """Traced inverse of :func:`pack_launch_input` — the single place
    that knows the packed layout (used by the single-device jit wrapper
    and the ``shard_map`` per-shard body)."""
    words = buf[:W]
    starts = lax.bitcast_convert_type(buf[W : W + R], jnp.int32)
    lengths = lax.bitcast_convert_type(buf[W + R : W + 2 * R], jnp.int32)
    n = lax.bitcast_convert_type(buf[W + 2 * R], jnp.int32)
    return words, starts, lengths, n

def _bucket_label(R: int, B: int, item_caps=(), tot_caps=(),
                  compact: bool = True) -> str:
    """Human-readable shape-bucket id for the jit-cache registry (one
    label per compiled executable)."""
    label = f"R{R},B{B}"
    if len(item_caps) > 1:
        label += ",i" + "/".join(str(c) for c in item_caps[1:])
    if len(tot_caps) > 1:
        label += ",t" + "/".join(str(c) for c in tot_caps[1:])
    if not compact:
        label += ",full"
    return label


_DEFAULT_ITEM_CAP = 8
_DEFAULT_TOT_CAP = 8
# per-record item-slot ceiling: beyond this the strided buffers would not
# fit device memory; ``grow_caps`` raises DeviceCapacityExceeded and the
# codec serves that batch from the host path (codec.py catches it)
_MAX_ITEM_CAP = 1 << 20
_cache_enabled = False


class DeviceCapacityExceeded(Exception):
    """Batch needs more per-record item slots than the device path
    supports; the caller decodes it on the host instead."""


class BatchTooLarge(Exception):
    """Batch exceeds the single-launch byte budget (int32 cursors);
    the codec splits it and decodes the pieces (still on device)."""


def _enable_persistent_cache(jax) -> None:
    """Point XLA's persistent compilation cache at a user-cache dir (unless
    the user configured one), so each (schema, shape-bucket) kernel
    compiles once per machine instead of once per process. Disable with
    PYRUHVRO_TPU_NO_CACHE=1."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    import os

    from ..runtime import knobs

    if knobs.get_bool("PYRUHVRO_TPU_NO_CACHE"):
        return
    try:
        # CPU executables AOT-reload with machine-feature mismatches (XLA
        # warns about SIGILL); only accelerator backends cache safely.
        # Decide from the *configured* platform string — asking the backend
        # (jax.default_backend()) would initialize it, and a wedged device
        # plugin can block that indefinitely.
        plats = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
        first = plats.split(",")[0].strip().lower()
        if first in ("", "cpu"):
            return
        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.expanduser("~/.cache/pyruhvro_tpu/xla"),
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization; never fail construction
        pass


class DeviceDecoder:
    """Per-schema decode pipeline with compiled-kernel caches."""

    def __init__(self, ir, backend: str = None,
                 fingerprint: str = None):
        import jax  # deferred: importing pyruhvro_tpu must stay JAX-free

        _enable_persistent_cache(jax)
        self._jax = jax
        self.prog: Program = lower(ir)
        self.backend = backend
        # schema id for the jit-cache registry / recompile-churn guard
        # (codec.py passes the SchemaEntry fingerprint down). Decoders
        # built straight from an IR (ShardedDecoder(ir), tests, bench
        # scripts) get a stable IR-derived fallback so the capacity
        # planner can still key their learned rungs across processes.
        if not fingerprint:
            import hashlib

            fingerprint = "ir:" + hashlib.sha1(
                repr(ir).encode()
            ).hexdigest()[:12]
        self.fingerprint = fingerprint
        self._pipe_cache: Dict[tuple, tuple] = {}
        self._err_cache: Dict[tuple, object] = {}
        self._item_caps: List[int] = [0] + [
            _DEFAULT_ITEM_CAP for _ in self.prog.regions[1:]
        ]
        # per-region item-total caps, remembered per R bucket
        self._tot_cap_mem: Dict[Tuple[int, int], int] = {}
        # (R, B) buckets whose string lens overflowed the compact
        # descriptor budget — remembered so they go straight to the
        # full-width layout (see build_pipeline blob shrinking)
        self._str_full: set = set()
        self._seed_tried: set = set()  # (R, rid) sampling attempts
        # persistent host input arenas: (R, B, slot) -> u32 buffer the
        # packer refills in place (slot alternates 0/1 on the
        # double-buffered overlap path; the single-launch path uses 0)
        self._arenas: Dict[tuple, np.ndarray] = {}
        self._arena_used: Dict[tuple, float] = {}  # LRU clock per arena
        # R buckets whose converged rung was already taught to the
        # capacity planner (re-harvest only after a cap actually grows)
        self._planned: set = set()
        self._lock = threading.Lock()
        # lifecycle planes (ISSUE 12): executables + arenas enumerate
        # and evict through the weak holder registry
        device_obs.track_holder(self)

    def _jit_caches(self):
        return [self._pipe_cache, self._err_cache]

    def _arena(self, R: int, B: int, slot: int = 0) -> np.ndarray:
        """The persistent packed-input host buffer for an (R, B) bucket
        — identity-stable across warm calls (no per-call allocation;
        the donation/arena-reuse test asserts on ``ctypes.data``).

        Keyed by thread too: the codec is memoized per schema for the
        process lifetime, so two threads decoding same-bucket batches
        concurrently would otherwise overwrite each other's packed
        bytes between pack and ``device_put`` (the pre-arena code was
        race-free by allocating per call; per-thread arenas restore
        that invariant at per-thread cost)."""
        key = (R, B, slot, threading.get_ident())
        schedtest.yp("arena.checkout")
        with self._lock:
            buf = self._arenas.get(key)
            if buf is None:
                # bound lifetime growth: a decoder lives as long as the
                # process, so keep only the LARGEST B per (R, slot,
                # thread) — smaller byte buckets of the same row bucket
                # are superseded, and this thread cannot be mid-call on
                # one (calls are synchronous per thread)
                for old in [k for k in self._arenas
                            if k[0] == R and k[2] == slot
                            and k[3] == key[3] and k[1] < B]:
                    del self._arenas[old]
                    self._arena_used.pop(old, None)
                buf = self._arenas[key] = np.empty(
                    B // 4 + 2 * R + 1, np.uint32
                )
                metrics.inc("device.arena.misses")
            else:
                metrics.inc("device.arena.hits")
            self._arena_used[key] = time.monotonic()
        return buf

    # -- traced pieces -----------------------------------------------------

    def _trace_walk(self, R: int, item_caps, words, starts, lengths, n):
        jnp = self._jax.numpy
        prog = self.prog
        from .fieldprog import _Ctx
        from .varint import ERR_TRAILING

        def cap_of(region: int) -> int:
            # strided slot space: product of item caps down the ancestry
            cap = R
            while region != ROWS:
                cap *= item_caps[region]
                region = prog.region_parents[region]
            return cap

        row = jnp.arange(R, dtype=jnp.int32)
        st = {"#cursor": starts, "#err": jnp.zeros(R, jnp.uint32)}
        for spec in prog.buffers.values():
            st[spec.key] = jnp.zeros(cap_of(spec.region), spec.dtype)
        ends = starts + lengths
        active = row < n
        cx = _Ctx(words, ends, item_caps)
        st = prog.emit(cx, st, active, None)
        st["#err"] = st["#err"] | jnp.where(
            active & (st["#cursor"] != ends),
            jnp.uint32(ERR_TRAILING),
            jnp.uint32(0),
        )
        return st

    # -- the fused pipeline ------------------------------------------------

    def build_pipeline(self, R: int, B: int, item_caps: Tuple[int, ...],
                       tot_caps: Tuple[int, ...],
                       compact_strings: bool = True):
        """Build the (unjitted) fused walk+finalize. Returns
        ``(fn, layout)`` where ``fn(words, starts, lengths, n)`` yields
        ONE uint8 blob and ``layout`` is ``[(key, dtype, length), ...]``
        for the host split. The blob also carries the reductions (error
        flag, per-region item max/sum) so the steady state costs a single
        device round trip.

        Blob shrinking (the d2h direction is the expensive one —
        BENCH_NOTES.md): string ``(start, len)`` descriptor pairs are
        the bulk of the blob, so with ``compact_strings`` they ship as
        ONE u32 ``start | len << 21`` when ``B ≤ 2^20`` (lens < 2^11,
        "sl32" mode) or with u16 lens otherwise (lens < 2^16, "len16"
        mode); a ``#red:strfit`` reduction reports when a batch's lens
        exceed the mode's budget and the caller retries with
        ``compact_strings=False`` (same ladder as capacity growth).
        Validity and boolean lanes always bit-pack 8:1 (``…@bits``).
        :meth:`expand_host` undoes all of it after the transfer.

        The raw callable is what :mod:`..parallel` ``shard_map``s over a
        device mesh (each mesh shard runs it on its chunk) and what
        ``__graft_entry__.entry()`` hands the driver for compile checks;
        single-device callers use :meth:`_pipeline_fn` (jit + cache)."""
        jax = self._jax
        jnp = jax.numpy
        lax = jax.lax
        prog = self.prog
        str_mode = None
        if compact_strings and prog.string_cols:
            str_mode = "sl32" if B <= (1 << 20) else "len16"
        len_limit = (1 << 11) if str_mode == "sl32" else (1 << 16)

        item_buffers = {
            rid: sorted(
                (s for s in prog.buffers.values() if s.region == rid),
                key=lambda s: s.key,
            )
            for rid in range(1, len(prog.regions))
        }

        def row_of(offsets, n_entries: int, cap: int):
            """For each position j < cap, the entry whose [offsets[i],
            offsets[i+1]) range contains j — one scatter-max + one cummax
            scan instead of a per-position binary search."""
            m = jnp.zeros(cap, jnp.int32)
            m = m.at[offsets[:n_entries]].max(
                jnp.arange(n_entries, dtype=jnp.int32), mode="drop"
            )
            return lax.cummax(m)

        def pipeline(words, starts, lengths, n):
            st = self._trace_walk(R, item_caps, words, starts, lengths, n)
            out = {}
            # compaction cascades parent-first (region ids are in DFS
            # order): a nested region's counts live in its parent's
            # STRIDED slot space and are first gathered through the
            # parent's compaction map
            slot_maps = {}  # rid -> (strided slot per compact idx, in_range)
            for rid in range(1, len(prog.regions)):
                path = prog.regions[rid]
                parent = prog.region_parents[rid]
                icap, tcap = item_caps[rid], tot_caps[rid]
                counts_raw = st[path + "#count"]
                if parent == ROWS:
                    n_entries = R
                    counts_c = counts_raw
                    parent_slot = jnp.arange(R, dtype=jnp.int32)
                else:
                    parent_slot, parent_in = slot_maps[parent]
                    n_entries = tot_caps[parent]
                    taken = jnp.take(counts_raw, parent_slot, mode="clip")
                    counts_c = jnp.where(parent_in, taken, 0)
                offsets = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32),
                     jnp.cumsum(counts_c, dtype=jnp.int32)]
                )
                out[path + "#offsets"] = offsets
                j = jnp.arange(tcap, dtype=jnp.int32)
                ent = row_of(offsets, n_entries, tcap)
                slot = (
                    jnp.take(parent_slot, ent, mode="clip") * icap
                    + (j - jnp.take(offsets, ent, mode="clip"))
                )
                # entries past the region's true total are zeroed — their
                # lens feed host-side cumsums
                in_range = j < offsets[-1]
                slot_maps[rid] = (slot, in_range)
                for spec in item_buffers[rid]:
                    taken = jnp.take(st[spec.key], slot, mode="clip")
                    out[spec.key] = jnp.where(in_range, taken,
                                              jnp.zeros_like(taken))
                out["#red:max:" + path] = jnp.max(counts_c).reshape(1)
                out["#red:sum:" + path] = offsets[-1].reshape(1)
            for spec in prog.buffers.values():
                if spec.region == ROWS and spec.key.rpartition("#")[2] != "count":
                    out[spec.key] = st[spec.key]
            out["#red:err"] = (
                jnp.any((st["#err"] & ~jnp.uint32(ERR_ITEM_OVERFLOW)) != 0)
                .reshape(1)
                .astype(jnp.uint8)
            )
            # blob shrinking (see docstring): compact string descriptors…
            if str_mode is not None:
                fit = jnp.bool_(True)
                for sc in prog.string_cols:
                    fit = fit & (
                        jnp.max(out[sc.path + "#len"]) < len_limit
                    )
                out["#red:strfit"] = fit.reshape(1).astype(jnp.uint8)
                for sc in prog.string_cols:
                    s = out.pop(sc.path + "#start")
                    ln = out.pop(sc.path + "#len")
                    if str_mode == "sl32":
                        out[sc.path + "#sl"] = (
                            s.astype(jnp.uint32)
                            | (ln.astype(jnp.uint32) << 21)
                        )
                    else:
                        out[sc.path + "#start"] = s
                        out[sc.path + "#lenc"] = ln.astype(jnp.uint16)
            # …and bit-pack every u8 payload lane (validity, booleans)
            for k in list(out):
                if not k.startswith("#red:") and out[k].dtype == jnp.uint8:
                    out[k + "@bits"] = jnp.packbits(
                        out.pop(k), bitorder="little"
                    )
            # one blob, one transfer
            chunks = []
            for k in sorted(out):
                v = out[k]
                if v.dtype == jnp.uint8:
                    chunks.append(v)
                else:
                    chunks.append(
                        lax.bitcast_convert_type(v, jnp.uint8).reshape(-1)
                    )
            return jnp.concatenate(chunks)

        # the blob layout mirrors pipeline's sorted(out) order exactly
        sizes: Dict[str, tuple] = {}
        for rid in range(1, len(prog.regions)):
            path = prog.regions[rid]
            parent = prog.region_parents[rid]
            n_entries = R if parent == ROWS else tot_caps[parent]
            sizes[path + "#offsets"] = (np.int32, n_entries + 1)
            for spec in item_buffers[rid]:
                sizes[spec.key] = (np.dtype(spec.dtype), tot_caps[rid])
            sizes["#red:max:" + path] = (np.int32, 1)
            sizes["#red:sum:" + path] = (np.int32, 1)
        for spec in prog.buffers.values():
            if spec.region == ROWS and spec.key.rpartition("#")[2] != "count":
                sizes[spec.key] = (np.dtype(spec.dtype), R)
        sizes["#red:err"] = (np.uint8, 1)
        # mirror the pipeline's blob-shrinking transforms exactly
        if str_mode is not None:
            sizes["#red:strfit"] = (np.uint8, 1)
            for sc in prog.string_cols:
                _dt, ln_s = sizes.pop(sc.path + "#start")
                sizes.pop(sc.path + "#len")
                if str_mode == "sl32":
                    sizes[sc.path + "#sl"] = (np.uint32, ln_s)
                else:
                    sizes[sc.path + "#start"] = (np.int32, ln_s)
                    sizes[sc.path + "#lenc"] = (np.uint16, ln_s)
        for k in list(sizes):
            dt, ln = sizes[k]
            if not k.startswith("#red:") and np.dtype(dt) == np.uint8:
                del sizes[k]
                sizes[k + "@bits"] = (np.uint8, ln // 8)
        layout = [(k,) + sizes[k] for k in sorted(sizes)]
        return pipeline, layout

    @staticmethod
    def expand_host(host: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Undo :meth:`build_pipeline`'s blob shrinking on the host dict
        (vectorized, µs-scale) so the Arrow assembly sees the standard
        ``#start``/``#len``/u8-lane keys."""
        for k in list(host):
            if k.endswith("@bits"):
                host[k[:-5]] = np.unpackbits(host[k], bitorder="little")
            elif k.endswith("#sl"):
                v = host[k]
                p = k[: -len("#sl")]
                host[p + "#start"] = (
                    v & np.uint32((1 << 21) - 1)
                ).astype(np.int32)
                host[p + "#len"] = (v >> np.uint32(21)).astype(np.int32)
            elif k.endswith("#lenc"):
                host[k[: -len("#lenc")] + "#len"] = host[k].astype(np.int32)
        return host

    def _pipeline_fn(self, R: int, B: int, item_caps: Tuple[int, ...],
                     tot_caps: Tuple[int, ...],
                     compact_strings: bool = True):
        """Jitted-and-cached :meth:`build_pipeline` (one compile per
        (R, B, caps) bucket for the process, ≙ the schema→kernel cache).

        The jitted callable takes ONE packed uint32 buffer
        ``[words | starts | lengths | n]`` (see :func:`pack_launch_input`)
        instead of four arrays: each separate jit argument is a separate
        transfer, and on a high-latency interconnect a fresh numpy
        scalar argument alone costs a full synchronous round trip
        (measured ~65 ms through a device tunnel — BENCH_NOTES.md)."""
        key = (R, B, item_caps, tot_caps, compact_strings)
        hit = self._pipe_cache.get(key)
        if hit is not None:
            return hit
        pipeline, layout = self.build_pipeline(
            R, B, item_caps, tot_caps, compact_strings
        )
        jnp = self._jax.numpy
        lax = self._jax.lax
        W = B // 4

        def packed(buf):
            return pipeline(*unpack_launch_input(jnp, lax, buf, W, R))

        # donate_argnums: the packed input buffer is consumed by the
        # launch, so XLA recycles its device memory for the outputs
        # instead of allocating a fresh blob per call (ISSUE 10 —
        # callers must treat the device input as dead after the call;
        # the capacity ladder re-puts from the host arena on a retry
        # rung). Where donation cannot be used XLA only warns, and the
        # InstrumentedJit compile paths scope that warning away.
        # jit-cache telemetry (ISSUE 5): each cache entry is one
        # executable; the wrapper splits its first call into an explicit
        # lower+compile (device.compile_s) and times every later call as
        # device.launch_s, feeding the per-(fingerprint, bucket) registry
        # and the recompile-churn guard
        fn = device_obs.InstrumentedJit(
            self._jax, self._jax.jit(packed, donate_argnums=0),
            kind="decode.pipeline",
            bucket=_bucket_label(R, B, item_caps, tot_caps,
                                 compact_strings),
            fingerprint=self.fingerprint, family="decode",
        )
        pair = (fn, layout)
        with self._lock:
            self._pipe_cache[key] = pair
        return pair

    def _err_fn(self, R: int, B: int, item_caps: Tuple[int, ...]):
        """Walk-only error lanes, compiled lazily — only a malformed batch
        ever pays for it."""
        key = (R, B, item_caps)
        fn = self._err_cache.get(key)
        if fn is None:
            fn = device_obs.InstrumentedJit(
                self._jax,
                self._jax.jit(
                    lambda words, starts, lengths, n: self._trace_walk(
                        R, item_caps, words, starts, lengths, n
                    )["#err"]
                ),
                kind="decode.err",
                bucket=_bucket_label(R, B, item_caps),
                fingerprint=self.fingerprint, family="decode",
            )
            with self._lock:
                self._err_cache[key] = fn
        return fn

    # -- capacity bookkeeping (shared with parallel.ShardedDecoder) --------

    def seed_caps_from_sample(self, data: Sequence[bytes], R: int) -> None:
        """Estimate item caps for a fresh ``R`` bucket from a small
        host-decoded sample, so the first device launch compiles ONCE
        instead of climbing the retry ladder (each rung is a recompile —
        and with remote compile, a tunnel round trip). Estimates only:
        the ladder still catches under-estimates; sampling errors
        (malformed head records) are ignored and left to the device
        pass, which reports exact per-record errors."""
        prog = self.prog
        if len(prog.regions) <= 1:
            return
        with self._lock:
            need = [
                rid
                for rid in range(1, len(prog.regions))
                if (R, rid) not in self._tot_cap_mem
                and (R, rid) not in self._seed_tried
            ]
            # one sampling attempt per (R, region) — a region the sample
            # can't resolve (e.g. nested repetition) must not re-pay the
            # host scan on every steady-state decode
            self._seed_tried.update((R, rid) for rid in need)
        if not need:
            return
        k = min(len(data), 128)
        try:
            from ..fallback.decoder import decode_to_record_batch
            from ..schema.arrow_map import to_arrow_schema

            with telemetry.phase("device.seed_s", rows=k):
                sample = decode_to_record_batch(
                    data[:k], prog.ir, to_arrow_schema(prog.ir)
                )
        except Exception:
            return
        for rid in need:
            counts = _region_counts(prog.ir, sample, prog.regions[rid])
            if counts is None or counts.size == 0:
                continue
            mx = int(counts.max(initial=0))
            avg = float(counts.mean())
            with self._lock:
                self._item_caps[rid] = max(
                    self._item_caps[rid],
                    bucket_len(mx + (mx >> 1) + 1,
                               minimum=_DEFAULT_ITEM_CAP),
                )
                est = int(R * avg * 1.25) + 16
                self._tot_cap_mem[(R, rid)] = max(
                    self._tot_cap_mem.get((R, rid), 0),
                    bucket_len(est, minimum=_DEFAULT_TOT_CAP),
                )

    def caps_snapshot(self, R: int):
        """Atomic snapshot of ``(item_caps, tot_caps)`` for an R bucket.

        A region's item total is bounded by (parent's entry total ×
        items/entry cap); parents precede children in region order, so
        one forward sweep resolves the nested bounds."""
        prog = self.prog
        with self._lock:
            item_caps = tuple(self._item_caps)
            tot_caps = [0]
            for rid in range(1, len(prog.regions)):
                parent = prog.region_parents[rid]
                parent_total = R if parent == ROWS else tot_caps[parent]
                tot_caps.append(
                    min(
                        self._tot_cap_mem.get((R, rid), _DEFAULT_TOT_CAP),
                        parent_total * item_caps[rid],
                    )
                )
            tot_caps = tuple(tot_caps)
        return item_caps, tot_caps

    def grow_caps(self, R, item_caps, tot_caps, red_max, red_sum) -> bool:
        """Grow remembered caps from observed per-region reductions
        (max items/record, total items). Returns True when any cap grew
        (→ the caller retries the launch with the bigger bucket).

        ``red_max`` / ``red_sum`` are ``{rid: int}`` — for sharded
        launches, already max-reduced across shards."""
        retry = False
        with self._lock:  # cap growth is monotonic; max() keeps it so
            for rid in red_max:
                maxc, sumc = red_max[rid], red_sum[rid]
                if maxc > item_caps[rid]:
                    if maxc > _MAX_ITEM_CAP:
                        raise DeviceCapacityExceeded(
                            f"{self.prog.regions[rid]!r} needs {maxc} item "
                            f"slots per record (device limit {_MAX_ITEM_CAP})"
                        )
                    self._item_caps[rid] = max(
                        self._item_caps[rid],
                        bucket_len(maxc, minimum=_DEFAULT_ITEM_CAP),
                    )
                    retry = True
                if sumc > tot_caps[rid]:
                    self._tot_cap_mem[(R, rid)] = max(
                        self._tot_cap_mem.get((R, rid), 0),
                        bucket_len(max(sumc, 1), minimum=_DEFAULT_TOT_CAP),
                    )
                    retry = True
        return retry

    # -- orchestration -----------------------------------------------------

    def decode_to_columns(self, data: Sequence[bytes]):
        """Run the pipeline; returns ``(host_columns, n, meta)`` where meta
        carries per-region item totals and the raw datum bytes for the
        host-side assembly.

        ``device.pipeline_s`` spans the whole device phase; its children
        (pack → h2d → compile/launch → d2h, plus seed/retry rungs)
        decompose it — the ISSUE 5 acceptance contract asserts >= 90%
        coverage on the kafka 10k run."""
        with telemetry.phase("device.pipeline_s", rows=len(data),
                             op="decode"):
            return self._decode_to_columns(data)

    def seed_from_plan(self, R: int) -> bool:
        """Warm-start an R bucket from the capacity planner's learned
        rung (ISSUE 10): a schema any decoder has converged before —
        this process or, via ROUTING_PROFILE.json, a previous one —
        compiles once and launches with ``device.retries == 0`` from
        its very first call. Returns True on a plan hit (the host
        sample probe is skipped too; the plan replaces it). A hit also
        marks the bucket planned, so the overlap path streams ALL
        chunks from the first call instead of sync-laddering chunk 0
        against a rung the planner already proved."""
        hit = capacity.seed_decoder(self, R)
        if hit:
            with self._lock:
                self._planned.add(R)
        return hit

    def _harvest_plan(self, R: int, grew: bool) -> None:
        """Teach the planner this bucket's converged rung (once per
        bucket unless a cap actually grew) and arm profile persistence
        when capacity persistence is enabled."""
        with self._lock:
            fresh = R not in self._planned
            self._planned.add(R)
        if not (fresh or grew):
            return
        capacity.harvest_decoder(self, R)
        if capacity.persist_enabled():
            from ..runtime import costmodel

            costmodel.arm_persistence()

    def _arena_views(self, arena: np.ndarray, R: int, B: int):
        """(words, starts, lengths) views over a packed arena — the
        rare error pass re-puts these individually."""
        W = B // 4
        return (arena[:W], arena[W : W + R].view(np.int32),
                arena[W + R : W + 2 * R].view(np.int32))

    def _put_packed(self, arena: np.ndarray):
        """One transfer of the packed arena (h2d span + byte counters)."""
        jax = self._jax
        with telemetry.phase("decode.h2d_s", bytes=arena.nbytes):
            faults.fire("h2d")
            packed_d = jax.device_put(arena)
        metrics.inc("decode.h2d_bytes", arena.nbytes)
        metrics.inc("device.h2d_bytes", arena.nbytes)
        return packed_d

    def _run_ladder(self, arena: np.ndarray, R: int, B: int,
                    packed_d=None):
        """Launch the pipeline for one packed arena, climbing the
        capacity ladder until the reductions converge. Returns the
        split-but-unexpanded host dict. ``packed_d`` (optional) is an
        already-transferred device buffer for the FIRST rung; donation
        consumes it, so retry rungs re-put from the host arena."""
        jax = self._jax
        prog = self.prog
        host = None
        grew = False
        # zero-byte items (null / empty-record) reveal their true count
        # only ~cap-at-a-time, so cap growth can take ~log2(_MAX_ITEM_CAP)
        # rounds
        for _attempt in range(24):
            # each capacity-ladder rung is a compile + launch: a
            # deadline-bounded call stops climbing when the budget is
            # spent instead of paying rungs it can no longer afford
            deadline.check(site="device.capacity_ladder")
            item_caps, tot_caps = self.caps_snapshot(R)
            compact = (R, B) not in self._str_full
            fn, layout = self._pipeline_fn(R, B, item_caps, tot_caps,
                                           compact)
            if packed_d is None or getattr(packed_d, "is_deleted",
                                           lambda: True)():
                # the previous rung's donated input was consumed (or
                # this is the first rung): transfer from the host arena
                packed_d = self._put_packed(arena)
            # the wrapper splits device.compile_s (first call per shape
            # bucket, explicit lower+compile) from device.launch_s
            # (block_until_ready-bounded unless behind a remote
            # interconnect — device_obs.sync_mode); d2h_s carries any
            # remaining wait
            res = fn(packed_d)
            packed_d = None  # donated: dead after the launch
            with telemetry.phase("decode.d2h_s"):
                blob = np.asarray(jax.device_get(res))
            metrics.inc("decode.d2h_bytes", blob.nbytes)
            metrics.inc("device.d2h_bytes", blob.nbytes)
            host = split_blob(blob, layout)
            if compact and "#red:strfit" in host and not host["#red:strfit"][0]:
                # a string overflowed the compact descriptor budget:
                # remember and relaunch this bucket full-width
                self._str_full.add((R, B))
                grew = True
                metrics.inc("device.retries")
                telemetry.observe(
                    "device.retry_s", 0.0,
                    reason="str_descriptor_overflow", attempt=_attempt,
                    capacity=_bucket_label(R, B, item_caps, tot_caps,
                                           compact),
                )
                continue
            red_max = {
                rid: int(host["#red:max:" + path][0])
                for rid, path in enumerate(prog.regions)
                if rid != ROWS
            }
            red_sum = {
                rid: int(host["#red:sum:" + path][0])
                for rid, path in enumerate(prog.regions)
                if rid != ROWS
            }
            t0 = time.perf_counter()
            if not self.grow_caps(R, item_caps, tot_caps, red_max, red_sum):
                break
            grew = True
            # each retry-ladder rung is a child span carrying WHY the
            # relaunch happened and the capacity that proved too small
            metrics.inc("device.retries")
            telemetry.observe(
                "device.retry_s", time.perf_counter() - t0,
                reason="cap_growth", attempt=_attempt,
                capacity=_bucket_label(R, B, item_caps, tot_caps, compact),
                need_items=max(red_max.values(), default=0),
                need_total=max(red_sum.values(), default=0),
            )
        else:
            raise MalformedAvro("array/map item capacity did not converge")
        self._harvest_plan(R, grew)
        return host

    def _raise_row_errors(self, arena, R, B, n, base_row: int = 0,
                          collect=None):
        """Run the walk-only error pass for one packed arena and either
        raise (default) or append ``(global_index, slug)`` pairs into
        ``collect`` (the overlap path aggregates across chunks first)."""
        jax = self._jax
        item_caps, _tot = self.caps_snapshot(R)
        words, starts, lengths = self._arena_views(arena, R, B)
        err = np.asarray(
            jax.device_get(
                self._err_fn(R, B, item_caps)(
                    jax.device_put(words),
                    jax.device_put(starts),
                    jax.device_put(lengths),
                    np.int32(n),
                )
            )
        )[:n]
        bad = err & ~np.uint32(ERR_ITEM_OVERFLOW)
        bad_rows = np.flatnonzero(bad)
        # the walk computed error bits for EVERY lane — surface the
        # full row mask so a tolerant caller (api.py on_error=skip/
        # null) isolates all offenders in ONE extra pass instead of
        # re-launching once per bad record
        indices = []
        for r in bad_rows:
            v = int(bad[int(r)])
            b = v & -v
            indices.append(
                (base_row + int(r), ERR_SLUGS.get(b, f"bit_{b:#x}"))
            )
        if collect is not None:
            collect.extend(indices)
            return
        if not indices:  # pragma: no cover — err flag implies a bad lane
            raise MalformedAvro("device reported a malformed record")
        i = int(bad_rows[0])
        v = int(bad[i])
        bit = v & -v
        raise malformed_record(
            base_row + i, ERR_NAMES.get(bit, f"error bit {bit:#x}"),
            err_name=ERR_SLUGS.get(bit, f"bit_{bit:#x}"),
            tier="device", indices=indices,
        )

    def _finish_host(self, host, n, flat):
        """Expand a converged host dict and build the (host, n, meta)
        triple — shared by the single-launch and overlap paths."""
        prog = self.prog
        host = self.expand_host(host)
        meta = {"item_totals": {}, "flat": flat}
        for rid, path in enumerate(prog.regions):
            if rid != ROWS:
                meta["item_totals"][path] = int(host["#red:sum:" + path][0])
        return host, n, meta

    def _decode_to_columns(self, data: Sequence[bytes]):
        jax = self._jax
        n = len(data)
        with telemetry.phase("decode.pack_s", rows=n):
            flat, offsets = concat_records(data)
        total = int(offsets[-1])
        if total > (1 << 30):
            # int32 cursors bound one launch to 1 GiB of datum bytes; the
            # codec catches this and auto-splits the batch (codec.py)
            raise BatchTooLarge(n, total)
        B = bucket_len(max(total, 4), minimum=16)
        R = bucket_len(max(n, 1), minimum=8)
        if not self.seed_from_plan(R):
            self.seed_caps_from_sample(data, R)
        arena = self._arena(R, B)
        pack_launch_into(arena, flat, offsets, n, R, B)

        host = self._run_ladder(arena, R, B)

        # per-device memory watermarks where the backend exposes them
        # (TPU/GPU memory_stats(); graceful no-op on CPU)
        device_obs.note_memory(jax)

        if host["#red:err"][0]:
            # rare path (malformed batch): re-put the arena views for
            # the walk-only error pass
            self._raise_row_errors(arena, R, B, n)
        return self._finish_host(host, n, flat)

    # -- double-buffered h2d/compute overlap (ISSUE 10) --------------------

    def decode_to_columns_overlapped(self, data: Sequence[bytes],
                                     n_chunks: int):
        """Pipelined chunked decode: pack + ``device_put`` of chunk
        N+1 runs on the host while chunk N's launch is in flight
        (async dispatch; the only blocking point is each chunk's d2h).
        Returns one ``(host_columns, rows, meta)`` triple per chunk.

        ``device.overlap_s`` accumulates the host-side pack/h2d seconds
        spent while at least one launch was in flight — the overlap the
        serialized pipeline of PR 5's spans could only *measure*;
        ``device.overlap_frac`` (per call, on the span) is that time
        over the whole pipeline wall."""
        with telemetry.phase("device.pipeline_s", rows=len(data),
                             op="decode", overlap_chunks=n_chunks):
            return self._decode_overlapped(data, n_chunks)

    def _decode_overlapped(self, data: Sequence[bytes], n_chunks: int):
        from ..runtime.chunking import chunk_bounds

        jax = self._jax
        n_all = len(data)
        t_wall0 = time.perf_counter()
        with telemetry.phase("decode.pack_s", rows=n_all):
            flat_all, offsets_all = concat_records(data)
        bounds = chunk_bounds(n_all, n_chunks)
        chunk_rows = max(b - a for a, b in bounds)
        chunk_bytes = max(
            int(offsets_all[b] - offsets_all[a]) for a, b in bounds
        )
        if int(offsets_all[-1]) > (1 << 30) or chunk_bytes > (1 << 30):
            raise BatchTooLarge(n_all, int(offsets_all[-1]))
        R = bucket_len(max(chunk_rows, 1), minimum=8)
        B = bucket_len(max(chunk_bytes, 4), minimum=16)
        if not self.seed_from_plan(R):
            self.seed_caps_from_sample(data, R)

        chunk_arenas: dict = {}  # chunk index -> its (reused) arena

        def pack_chunk(i: int) -> np.ndarray:
            a, b = bounds[i]
            arena = self._arena(R, B, slot=i % 2)
            base = int(offsets_all[a])
            pack_launch_into(
                arena, flat_all[base : int(offsets_all[b])],
                offsets_all[a : b + 1], b - a, R, B,
            )
            chunk_arenas[i] = arena
            return arena

        def chunk_flat(i: int) -> np.ndarray:
            a, b = bounds[i]
            return flat_all[int(offsets_all[a]) : int(offsets_all[b])]

        triples = [None] * len(bounds)
        bad_indices: list = []
        # COLD bucket: chunk 0 converges the capacity ladder
        # synchronously first (the cold rungs must not be pipelined —
        # every later chunk reuses its compiled executable and caps).
        # WARM bucket (converged before, or planner-seeded): chunk 0
        # joins the async stream too, so even a 2-chunk call overlaps
        # pack/h2d with a launch in flight.
        with self._lock:
            warm = R in self._planned
        start = 0
        if not warm:
            arena0 = pack_chunk(0)
            host0 = self._run_ladder(arena0, R, B)
            if host0["#red:err"][0]:
                self._raise_row_errors(
                    arena0, R, B, bounds[0][1] - bounds[0][0],
                    base_row=0, collect=bad_indices,
                )
            triples[0] = self._finish_host(
                host0, bounds[0][1] - bounds[0][0], chunk_flat(0)
            )
            start = 1

        overlap_s = 0.0
        # (chunk index, in-flight result, layout/caps AT DISPATCH TIME —
        # a later rerun may grow the shared caps under a pending chunk)
        pending: list = []

        def collect_one():
            """Block on the OLDEST in-flight chunk and post-process it
            (rare per-chunk cap overflow re-runs the ladder — its input
            arena is still intact: only chunk i+2 would reuse the slot,
            and it is never packed before chunk i is collected)."""
            i, res, layout, item_caps, tot_caps, compact = pending.pop(0)
            a, b = bounds[i]
            with telemetry.phase("decode.d2h_s"):
                blob = np.asarray(jax.device_get(res))
            metrics.inc("decode.d2h_bytes", blob.nbytes)
            metrics.inc("device.d2h_bytes", blob.nbytes)
            host = split_blob(blob, layout)
            prog = self.prog
            needs_rerun = (
                compact and "#red:strfit" in host
                and not host["#red:strfit"][0]
            )
            if needs_rerun:
                # record the overflow NOW so the rerun ladder goes
                # straight to the full-width layout instead of paying
                # one more known-failing compact launch
                self._str_full.add((R, B))
                metrics.inc("device.retries")
                telemetry.observe(
                    "device.retry_s", 0.0,
                    reason="str_descriptor_overflow",
                    capacity=_bucket_label(R, B, item_caps, tot_caps,
                                           compact),
                )
            if not needs_rerun:
                red_max = {
                    rid: int(host["#red:max:" + path][0])
                    for rid, path in enumerate(prog.regions)
                    if rid != ROWS
                }
                red_sum = {
                    rid: int(host["#red:sum:" + path][0])
                    for rid, path in enumerate(prog.regions)
                    if rid != ROWS
                }
                if self.grow_caps(R, item_caps, tot_caps,
                                  red_max, red_sum):
                    # heterogeneous chunk overflowed chunk 0's rung: a
                    # genuine retry relaunch — counted HERE, because
                    # the rerun ladder starts at the already-grown caps
                    # and would record nothing itself
                    needs_rerun = True
                    metrics.inc("device.retries")
                    telemetry.observe(
                        "device.retry_s", 0.0, reason="cap_growth",
                        capacity=_bucket_label(R, B, item_caps,
                                               tot_caps, compact),
                        need_items=max(red_max.values(), default=0),
                        need_total=max(red_sum.values(), default=0),
                    )
            if needs_rerun:
                host = self._run_ladder(chunk_arenas[i], R, B)
            if host["#red:err"][0]:
                self._raise_row_errors(
                    chunk_arenas[i], R, B, b - a,
                    base_row=a, collect=bad_indices,
                )
            triples[i] = self._finish_host(host, b - a, chunk_flat(i))

        for i in range(start, len(bounds)):
            t0 = time.perf_counter()
            arena = pack_chunk(i)
            packed_d = self._put_packed(arena)
            t_host = time.perf_counter() - t0
            if any(not _ready(res) for _j, res, *_rest in pending):
                # a launch is STILL in flight after this chunk's whole
                # pack+h2d finished: every one of those host seconds ran
                # concurrently with device compute. (Checking AFTER the
                # host work undercounts the tail — a launch completing
                # mid-pack — so the figure is conservative, never
                # fiction.)
                overlap_s += t_host
            item_caps, tot_caps = self.caps_snapshot(R)
            compact = (R, B) not in self._str_full
            fn, layout = self._pipeline_fn(R, B, item_caps, tot_caps,
                                           compact)
            # call_async skips the sync_mode block: the launch stays in
            # flight while the next chunk packs; collect_one's d2h
            # carries the wait
            res = fn.call_async(packed_d)
            pending.append((i, res, layout, item_caps, tot_caps, compact))
            if len(pending) >= 2:
                collect_one()
        while pending:
            collect_one()

        device_obs.note_memory(jax)
        if bad_indices:
            raise_aggregated_malformed(bad_indices)
        wall = time.perf_counter() - t_wall0
        if overlap_s:
            metrics.inc("device.overlap_s", overlap_s)
            metrics.inc("device.overlap_calls")
            telemetry.annotate(
                overlap_s=round(overlap_s, 6),
                overlap_frac=round(min(overlap_s / wall, 1.0), 4)
                if wall > 0 else 0.0,
            )
        return triples
