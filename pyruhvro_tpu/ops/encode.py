"""Jitted encode pipeline: Arrow columns → Avro wire bytes, one launch.

TPU-native counterpart of the reference's fast encoder
(``ruhvro/src/fast_encode.rs:27-599``), designed from the format rather
than translated: the reference writes each row sequentially into a
reused buffer (``fast_encode.rs:44-52``); on TPU the key observation is
that **encoding, unlike decoding, needs no sequential walk at all** —
every output byte's position is computable ahead of time:

1. a vectorized **size pass** computes the exact wire size of every
   element of every region (rows; flat array/map item axes) — varint
   widths from value magnitudes, string lengths from Arrow offsets,
   per-row item sums via one segment-sum,
2. **prefix sums** turn sizes into exact byte positions: row offsets
   over the batch, item offsets within each row's block,
3. a fully parallel **scatter pass** writes every field of every row at
   its precomputed position — no loop-carried cursor anywhere; string
   payload bytes are copied by one bulk gather/scatter per column.

One launch returns one blob (output bytes + per-row sizes); the host
wraps it zero-copy into a ``pyarrow`` BinaryArray whose value buffer IS
the device output. Wire form matches the host oracle byte-for-byte:
minimal zig-zag varints, arrays/maps in single-block form
``[count, items..., 0]`` with bare ``0`` for empty
(≙ ``fast_encode.rs:518-554``), nullable branch indices per the schema's
union order, enum symbol indices (``fast_encode.rs:356-362``).

Output capacity is static per launch: the host computes a cheap upper
bound (max varint widths + exact string byte totals), bucketed so the
jit cache stays small. No retry ladder is ever needed — encode sizes,
unlike decode item counts, are boundable before launch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
from jax import lax

from . import UnsupportedOnDevice
from .fieldprog import ROWS, _BIG
from ..gate import device_supported
from ..runtime.pack import bucket_len
from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)

__all__ = ["DeviceEncoder", "lower_encoder"]

I32 = jnp.int32
U32 = jnp.uint32


# ---------------------------------------------------------------------------
# traced helpers
# ---------------------------------------------------------------------------

def _zigzag32(v):
    """Zig-zag of an int32 lane vector as a (lo, hi=0) u32 pair."""
    x = v.astype(I32)
    z = jnp.bitwise_xor(x << 1, x >> 31)  # arithmetic >> on int32
    return lax.bitcast_convert_type(z, U32), jnp.zeros_like(v, dtype=U32)


def _zigzag64(lo, hi):
    """Zig-zag of an int64 carried as (lo, hi) u32 words."""
    slo = lo << 1
    shi = (hi << 1) | lax.shift_right_logical(lo, U32(31))
    m = jnp.zeros_like(hi) - lax.shift_right_logical(hi, U32(31))  # 0/~0
    return slo ^ m, shi ^ m


def _varint_size(zlo, zhi):
    """Wire bytes of an unsigned LEB128 varint given as a u32 pair."""
    size = jnp.ones(zlo.shape, I32)
    for k in range(1, 10):
        bits = 7 * k
        if bits < 32:
            ge = (zhi != U32(0)) | (zlo >= U32(1 << bits))
        else:
            ge = zhi >= U32(1 << (bits - 32))
        size = size + ge.astype(I32)
    return size


def _put_byte(out, idx, byte, mask):
    safe = jnp.where(mask, idx, I32(_BIG))
    return out.at[safe].set(byte.astype(jnp.uint8), mode="drop")


def _put_varint(out, cursor, zlo, zhi, nbytes, mask):
    """Scatter one varint per active lane at its cursor."""
    for k in range(10):
        bits = 7 * k
        if bits < 32:
            g = lax.shift_right_logical(zlo, U32(bits))
            if bits + 7 > 32:
                g = g | (zhi << U32(32 - bits))
        else:
            g = lax.shift_right_logical(zhi, U32(bits - 32))
        g = jnp.bitwise_and(g, U32(0x7F))
        byte = jnp.where(k < nbytes - 1, g | U32(0x80), g)
        out = _put_byte(out, cursor + k, byte, mask & (k < nbytes))
    return out


def _row_of(offsets, n_entries: int, cap: int):
    """entry index owning each position j < cap, given entry start
    ``offsets`` (same scatter-max + cummax trick as the decoder)."""
    m = jnp.zeros(cap, I32)
    m = m.at[offsets[:n_entries]].max(
        jnp.arange(n_entries, dtype=I32), mode="drop"
    )
    return lax.cummax(m)


# ---------------------------------------------------------------------------
# lowering: schema IR → size/write emitter tree
# ---------------------------------------------------------------------------

@dataclass
class _StrCol:
    path: str
    region: int


@dataclass
class EncProgram:
    ir: Record
    regions: List[str]           # rid → path of the repeated field ("" = rows)
    string_cols: List[_StrCol]
    size: Callable               # size(cx) -> per-row i32 [R]
    write: Callable              # write(cx, cursor, mask) -> None


class _Cx:
    """Traced state threaded through the emitters."""

    __slots__ = ("dv", "out", "sizes", "str_dst", "item")

    def __init__(self, dv, out):
        self.dv = dv          # device input dict
        self.out = out        # u8 output buffer (functionally updated)
        self.sizes = {}       # path -> memoized size vector
        self.str_dst = {}     # path -> (dst_start vec, write mask)
        self.item = {}        # rid -> dict(row, within_base, active, total)


class _EncLowering:
    def __init__(self) -> None:
        self.regions: List[str] = [""]
        self.string_cols: List[_StrCol] = []

    def lower_type(self, t: AvroType, path: str, region: int):
        """Return ``(size, write)`` emitters for one value of ``t``.

        ``size(cx) -> i32 vec`` over the region axis (garbage at masked
        lanes — parents mask before aggregating). ``write(cx, cursor,
        mask)`` scatters the value bytes at per-lane cursors."""
        if isinstance(t, Primitive):
            return self.lower_primitive(t, path, region)
        if isinstance(t, Fixed):
            if t.logical == "decimal":
                return self.lower_decimal(path, region, fixed_size=t.size)
            return self.lower_fixed(t, path, region)
        if isinstance(t, Enum):
            return self.lower_varint_leaf(path + "#v", path, wide=False)
        if isinstance(t, Record):
            return self.lower_record(t, path, region)
        if isinstance(t, Union):
            if t.is_nullable_pair:
                return self.lower_nullable(t, path, region)
            return self.lower_union(t, path, region)
        if isinstance(t, (Array, Map)):
            return self.lower_repeated(t, path, region)
        raise UnsupportedOnDevice(f"type {type(t).__name__} at {path!r}")

    # -- leaves -----------------------------------------------------------

    def lower_varint_leaf(self, key: str, path: str, wide: bool):
        """int / long / enum-index: one zig-zag varint."""

        def pair(cx):
            if wide:
                return _zigzag64(cx.dv[key + ":lo"], cx.dv[key + ":hi"])
            return _zigzag32(cx.dv[key])

        def size(cx):
            s = cx.sizes.get(path)
            if s is None:
                s = cx.sizes[path] = _varint_size(*pair(cx))
            return s

        def write(cx, cursor, mask):
            zlo, zhi = pair(cx)
            cx.out = _put_varint(cx.out, cursor, zlo, zhi, size(cx), mask)

        return size, write

    def lower_primitive(self, t: Primitive, path: str, region: int):
        name = t.name
        if name == "null":
            zero = lambda cx: jnp.zeros_like(  # noqa: E731
                cx.dv["#active:%d" % region], dtype=I32
            )
            return zero, (lambda cx, cursor, mask: None)

        if name in ("int", "long"):
            return self.lower_varint_leaf(path + "#v", path, wide=name == "long")

        if name == "float":

            def size_f32(cx):
                return jnp.full(cx.dv[path + "#v"].shape, 4, I32)

            def write_f32(cx, cursor, mask):
                w = lax.bitcast_convert_type(cx.dv[path + "#v"], U32)
                for k in range(4):
                    b = jnp.bitwise_and(
                        lax.shift_right_logical(w, U32(8 * k)), U32(0xFF)
                    )
                    cx.out = _put_byte(cx.out, cursor + k, b, mask)

            return size_f32, write_f32

        if name == "double":

            def size_f64(cx):
                return jnp.full(cx.dv[path + "#v:lo"].shape, 8, I32)

            def write_f64(cx, cursor, mask):
                for half, word in enumerate((":lo", ":hi")):
                    w = cx.dv[path + "#v" + word]
                    for k in range(4):
                        b = jnp.bitwise_and(
                            lax.shift_right_logical(w, U32(8 * k)), U32(0xFF)
                        )
                        cx.out = _put_byte(
                            cx.out, cursor + 4 * half + k, b, mask
                        )

            return size_f64, write_f64

        if name == "boolean":

            def size_b(cx):
                return jnp.ones(cx.dv[path + "#v"].shape, I32)

            def write_b(cx, cursor, mask):
                cx.out = _put_byte(cx.out, cursor, cx.dv[path + "#v"], mask)

            return size_b, write_b

        if name == "bytes" and t.logical == "decimal":
            return self.lower_decimal(path, region, fixed_size=None)

        if name in ("string", "bytes"):
            # Binary shares Utf8's wire form (len varint + payload);
            # uuid arrives from the extractor already rendered as
            # canonical text in the same column layout
            self.string_cols.append(_StrCol(path, region))

            def size_s(cx):
                s = cx.sizes.get(path)
                if s is None:
                    lens = cx.dv[path + "#len"]
                    zlo, zhi = _zigzag32(lens)
                    s = cx.sizes[path] = _varint_size(zlo, zhi) + lens
                return s

            def write_s(cx, cursor, mask):
                lens = cx.dv[path + "#len"]
                zlo, zhi = _zigzag32(lens)
                ns = _varint_size(zlo, zhi)
                cx.out = _put_varint(cx.out, cursor, zlo, zhi, ns, mask)
                # payload bytes go in one bulk scatter after the walk
                cx.str_dst[path] = (cursor + ns, mask)

            return size_s, write_s

        raise UnsupportedOnDevice(f"primitive {name!r} at {path!r}")

    def lower_fixed(self, t: Fixed, path: str, region: int):
        """Plain ``fixed`` (incl. duration, pre-converted to its wire
        12 bytes by the extractor): a constant-size raw run. Rides the
        bulk payload scatter exactly like strings — the extractor emits
        the same ``#src``/``#len``/``#bytes`` column layout with
        constant lens, so no per-byte unrolled writes are needed
        (size-independent compile)."""
        self.string_cols.append(_StrCol(path, region))
        size_c = t.size

        def size(cx):
            return jnp.full(cx.dv[path + "#len"].shape, size_c, I32)

        def write(cx, cursor, mask):
            cx.str_dst[path] = (cursor, mask)

        return size, write

    def lower_decimal(self, path: str, region: int,
                      fixed_size: Optional[int]):
        """Decimal over bytes (minimal-length big-endian two's
        complement, length varint prefix) or over fixed (constant
        size). The byte LENGTH per entry is data-dependent but cheap —
        the extractor computes it host-side, vectorized, as ``#dlen``
        (≙ the oracle's ``max((bits + 8) // 8, 1)``); the device writes
        the BE bytes by reversing the 16-byte-LE ``#dec`` words, with
        sign fill past byte 16 (n = 17 happens at the int128 minimum)."""

        def n_of(cx):
            if fixed_size is not None:
                return jnp.full(cx.dv["#active:%d" % region].shape,
                                fixed_size, I32)
            return cx.dv[path + "#dlen"]

        def write_bytes(cx, at, mask, n):
            dec = cx.dv[path + "#dec"]
            ent = jnp.arange(n.shape[0], dtype=I32) * 16
            msb = jnp.take(dec, ent + 15, mode="clip").astype(U32)
            fill = jnp.where(msb >= U32(0x80), U32(0xFF), U32(0))
            kmax = 17 if fixed_size is None else fixed_size
            for k in range(kmax):
                le = n - 1 - k
                in16 = (le >= 0) & (le < 16)
                b = jnp.where(
                    in16,
                    jnp.take(
                        dec, ent + jnp.clip(le, 0, 15), mode="clip"
                    ).astype(U32),
                    fill,
                )
                cx.out = _put_byte(cx.out, at + k, b, mask & (k < n))

        if fixed_size is not None:

            def size(cx):
                return n_of(cx)

            def write(cx, cursor, mask):
                write_bytes(cx, cursor, mask, n_of(cx))

            return size, write

        def size(cx):
            s = cx.sizes.get(path)
            if s is None:
                n = n_of(cx)
                zlo, zhi = _zigzag32(n)
                s = cx.sizes[path] = _varint_size(zlo, zhi) + n
            return s

        def write(cx, cursor, mask):
            n = n_of(cx)
            zlo, zhi = _zigzag32(n)
            ns = _varint_size(zlo, zhi)
            cx.out = _put_varint(cx.out, cursor, zlo, zhi, ns, mask)
            write_bytes(cx, cursor + ns, mask, n)

        return size, write

    # -- composites -------------------------------------------------------

    def lower_record(self, t: Record, path: str, region: int):
        prefix = path + "/" if path else ""
        fields = [
            self.lower_type(f.type, prefix + f.name, region) for f in t.fields
        ]

        def size(cx):
            s = cx.sizes.get(path + "#rec")
            if s is None:
                s = jnp.zeros(cx.dv["#active:%d" % region].shape, I32)
                for fsize, _ in fields:
                    s = s + fsize(cx)
                cx.sizes[path + "#rec"] = s
            return s

        def write(cx, cursor, mask):
            for fsize, fwrite in fields:
                fwrite(cx, cursor, mask)
                cursor = cursor + jnp.where(mask, fsize(cx), 0)

        return size, write

    def _branch_varint(self, branch):
        """Branch indices are tiny non-negative ints."""
        zlo, zhi = _zigzag32(branch)
        return zlo, zhi, _varint_size(zlo, zhi)

    def lower_nullable(self, t: Union, path: str, region: int):
        """``["null", T]`` → branch varint + masked inner
        (≙ ``build_nullable_encoder``, ``fast_encode.rs:285``)."""
        null_idx = t.null_index
        val_idx = 1 - null_idx
        inner_size, inner_write = self.lower_type(
            t.non_null_variant, path, region
        )

        def branch(cx):
            valid = cx.dv[path + "#valid"].astype(bool)
            return valid, jnp.where(valid, I32(val_idx), I32(null_idx))

        def size(cx):
            s = cx.sizes.get(path + "#nul")
            if s is None:
                valid, b = branch(cx)
                _, _, ns = self._branch_varint(b)
                s = ns + jnp.where(valid, inner_size(cx), 0)
                cx.sizes[path + "#nul"] = s
            return s

        def write(cx, cursor, mask):
            valid, b = branch(cx)
            zlo, zhi, ns = self._branch_varint(b)
            cx.out = _put_varint(cx.out, cursor, zlo, zhi, ns, mask)
            inner_write(cx, cursor + ns, mask & valid)

        return size, write

    def lower_union(self, t: Union, path: str, region: int):
        """N-variant union: branch from the Arrow type_ids
        (≙ ``build_union_encoder``, ``fast_encode.rs:258``)."""
        arms = []
        for k, v in enumerate(t.variants):
            if v.is_null():
                arms.append(None)
            else:
                arms.append(self.lower_type(v, f"{path}/{k}", region))

        def size(cx):
            s = cx.sizes.get(path + "#uni")
            if s is None:
                tid = cx.dv[path + "#tid"]
                _, _, ns = self._branch_varint(tid)
                s = ns
                for k, arm in enumerate(arms):
                    if arm is not None:
                        s = s + jnp.where(tid == k, arm[0](cx), 0)
                cx.sizes[path + "#uni"] = s
            return s

        def write(cx, cursor, mask):
            tid = cx.dv[path + "#tid"]
            zlo, zhi, ns = self._branch_varint(tid)
            cx.out = _put_varint(cx.out, cursor, zlo, zhi, ns, mask)
            for k, arm in enumerate(arms):
                if arm is not None:
                    arm[1](cx, cursor + ns, mask & (tid == k))

        return size, write

    def lower_repeated(self, t, path: str, region: int = ROWS):
        """Array/map single-block form ``[count, items..., 0]`` / ``0``.

        Item positions come from one within-row prefix sum over the flat
        item axis — the TPU replacement for the reference's per-item
        sequential writes (``fast_encode.rs:518-554``). Nesting composes
        for free: an inner repeated field's counts live on the OUTER
        item axis (``region``), and its flat item axis is the Arrow
        grandchild — the same prefix-sum machinery, one level down
        (≙ recursive encoders, ``fast_encode.rs:518-554``)."""
        rid = len(self.regions)
        self.regions.append(path)
        if isinstance(t, Array):
            items = [self.lower_type(t.items, path + "/@item", rid)]
        else:
            items = [
                self.lower_type(Primitive("string"), path + "/@key", rid),
                self.lower_type(t.values, path + "/@val", rid),
            ]

        def axis(cx):
            """Per-region item-axis bookkeeping, computed once."""
            info = cx.item.get(rid)
            if info is None:
                counts = cx.dv[path + "#count"]
                R = counts.shape[0]
                off = jnp.concatenate(
                    [jnp.zeros(1, I32), jnp.cumsum(counts, dtype=I32)]
                )
                T = cx.dv["#active:%d" % rid].shape[0]
                j = jnp.arange(T, dtype=I32)
                row = _row_of(off, R, T)
                active = j < off[-1]
                # exact per-item size over the flat axis
                isize = jnp.zeros(T, I32)
                for s, _ in items:
                    isize = isize + s(cx)
                isize = jnp.where(active, isize, 0)
                cum = jnp.cumsum(isize)
                ex = cum - isize  # exclusive
                row_first = jnp.take(off, row, mode="clip")
                within = ex - jnp.take(ex, row_first, mode="clip")
                per_row = jnp.zeros(R, I32).at[row].add(
                    jnp.where(active, isize, 0), mode="drop"
                )
                info = cx.item[rid] = {
                    "counts": counts, "row": row, "active": active,
                    "within": within, "per_row": per_row, "isize": isize,
                }
            return info

        def size(cx):
            s = cx.sizes.get(path + "#rep")
            if s is None:
                info = axis(cx)
                counts = info["counts"]
                zlo, zhi = _zigzag32(counts)
                ns = _varint_size(zlo, zhi)
                # [count, items..., 0] — or a bare 0 byte when empty
                s = jnp.where(counts > 0, ns + info["per_row"] + 1, 1)
                cx.sizes[path + "#rep"] = s
            return s

        def write(cx, cursor, mask):
            info = axis(cx)
            counts = info["counts"]
            zlo, zhi = _zigzag32(counts)
            ns = _varint_size(zlo, zhi)
            nonempty = mask & (counts > 0)
            cx.out = _put_varint(cx.out, cursor, zlo, zhi, ns, nonempty)
            # terminator 0 is the block's last byte (also the only byte
            # of an empty block)
            cx.out = _put_byte(
                cx.out, cursor + size(cx) - 1, jnp.zeros_like(zlo), mask
            )
            # items: data begins after the count varint
            data_start = cursor + ns
            item_cursor = (
                jnp.take(data_start, info["row"], mode="clip")
                + info["within"]
            )
            item_mask = info["active"] & jnp.take(
                nonempty, info["row"], mode="clip"
            )
            icur = item_cursor
            for s, w in items:
                w(cx, icur, item_mask)
                icur = icur + jnp.where(item_mask, s(cx), 0)

        return size, write


def lower_encoder(ir: AvroType) -> EncProgram:
    """Lower a top-level record schema to its device encode program.
    Subset = the decode subset (``gate.device_supported``), so both
    directions gate identically — the FULL reference type surface,
    beyond the reference's own fast-encode subset
    (``fast_encode.rs:22-24``)."""
    if not device_supported(ir):
        raise UnsupportedOnDevice("schema is outside the device subset")
    lo = _EncLowering()
    size, write = lo.lower_record(ir, "", ROWS)
    return EncProgram(
        ir=ir,
        regions=lo.regions,
        string_cols=lo.string_cols,
        size=size,
        write=write,
    )


# ---------------------------------------------------------------------------
# bulk string payload scatter (after the walk)
# ---------------------------------------------------------------------------

def _write_string_bytes(cx: _Cx, col: _StrCol):
    """Copy one column's payload bytes: for every source byte, find its
    element (scatter-max + cummax over element starts), then scatter to
    ``dst_start[elem] + position``. One gather + one scatter per column
    regardless of row count."""
    path = col.path
    dst, mask = cx.str_dst[path]
    src = cx.dv[path + "#src"]     # element start offsets (monotone)
    lens = cx.dv[path + "#len"]
    words = cx.dv[path + "#bytes"]
    n_el = src.shape[0]
    V = words.shape[0] * 4
    j = jnp.arange(V, dtype=I32)
    elem = _row_of(src, n_el, V)
    pos = j - jnp.take(src, elem, mode="clip")
    ok = (
        (pos >= 0)
        & (pos < jnp.take(lens, elem, mode="clip"))
        & jnp.take(mask, elem, mode="clip")
    )
    byte = jnp.bitwise_and(
        lax.shift_right_logical(
            jnp.take(words, lax.shift_right_logical(j, 2), mode="clip"),
            (jnp.bitwise_and(j, 3) << 3).astype(U32),
        ),
        U32(0xFF),
    )
    out_idx = jnp.take(dst, elem, mode="clip") + pos
    cx.out = _put_byte(cx.out, out_idx, byte, ok)


# ---------------------------------------------------------------------------
# host side: Arrow batch → device inputs
# ---------------------------------------------------------------------------



class _Extractor:
    """Walk the schema IR + Arrow arrays, producing the device input
    dict (same path keys the lowering registered) and a byte-capacity
    upper bound. Validity/shape errors match the host oracle's
    (``fallback/encoder.py``): nulls at non-nullable positions, unknown
    enum symbols and out-of-range union type_ids raise ``ValueError``.

    A ``parent`` validity chain (None = all rows live) tracks which
    lanes the encoder will actually read — nulls are only an error where
    the chain is live (a null under a null struct or a non-selected
    union arm is never encoded, so never an error; same as the oracle,
    which never visits masked values)."""

    def __init__(self, host_mode: bool = False) -> None:
        self.arrays: Dict[str, Tuple[np.ndarray, int]] = {}  # key → (arr, region)
        self.byte_bufs: Dict[str, np.ndarray] = {}           # key → u8 buffer
        self.region_len: Dict[int, int] = {}
        self.regions: List[str] = [""]
        self.bound = 0
        # host_mode: produce the native VM's input layout — whole int64/
        # float64 ``#v64`` arrays (no u32 lane split) read zero-copy off
        # the Arrow values buffers, with NO fill_null materialization:
        # the VM consumes-but-never-emits dead entries, so whatever bytes
        # a null slot holds are fine (Arrow defines the buffer exists,
        # not its content there). Device mode keeps defined zeros — the
        # vectorized size pass reads every lane before masking.
        self.host_mode = host_mode

    def put(self, key: str, arr: np.ndarray, region: int) -> None:
        self.arrays[key] = (np.ascontiguousarray(arr), region)

    # -- leaf readers (offset-aware) --------------------------------------

    @staticmethod
    def _valid(arr: pa.Array) -> Optional[np.ndarray]:
        if arr.null_count == 0:
            return None
        vbuf = arr.buffers()[0]
        if vbuf is None:  # null_count > 0 without a bitmap: NullArray etc.
            return arr.is_valid().to_numpy(zero_copy_only=False)
        n = len(arr)
        bits = np.frombuffer(
            vbuf, np.uint8, count=(arr.offset + n + 7) // 8
        )
        return np.unpackbits(bits, bitorder="little")[
            arr.offset : arr.offset + n
        ].astype(bool)

    @staticmethod
    def _raw_fixed_width(arr: pa.Array, np_dtype) -> Optional[np.ndarray]:
        """Zero-copy view of a fixed-width values buffer when the Arrow
        physical layout matches ``np_dtype``'s width (int32/date32,
        int64/timestamp/time64, float32, float64 — NOT boolean, whose
        values are bit-packed). None → caller takes the cast path."""
        t = arr.type
        try:
            w = t.byte_width
        except (ValueError, AttributeError):
            return None
        if w != np.dtype(np_dtype).itemsize or pa.types.is_boolean(t):
            return None
        buf = arr.buffers()[1]
        if buf is None:
            return np.zeros(len(arr), np_dtype)
        return np.frombuffer(
            buf, np_dtype, count=len(arr) + arr.offset
        )[arr.offset:]

    @staticmethod
    def _ints(arr: pa.Array, target: pa.DataType, dtype) -> np.ndarray:
        import pyarrow.compute as pc

        a = arr if arr.type.equals(target) else arr.cast(target)
        if a.null_count:
            a = pc.fill_null(a, 0)
        return a.to_numpy(zero_copy_only=False).astype(dtype, copy=False)

    def _require_valid(self, arr: pa.Array, path: str,
                       parent: Optional[np.ndarray]) -> None:
        """Error on nulls the encoder would actually read."""
        if not arr.null_count:
            return
        dead = ~self._valid(arr)
        if parent is not None:
            dead = dead & parent
        if dead.any():
            i = int(np.flatnonzero(dead)[0])
            raise ValueError(
                f"row {i}: null value for non-nullable Avro position "
                f"{path or '<top>'!r} (no null union there in the schema)"
            )

    # -- recursive walk ---------------------------------------------------

    def extract(self, t: AvroType, arr: pa.Array, path: str,
                region: int, parent: Optional[np.ndarray]) -> None:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()

        if isinstance(t, Union) and t.is_nullable_pair:
            valid = self._valid(arr)
            if valid is None:
                valid = np.ones(len(arr), bool)
            self.put(path + "#valid", valid.astype(np.uint8), region)
            self.bound += len(arr)  # 1-byte branch varint
            sub = valid if parent is None else (valid & parent)
            self.extract(t.non_null_variant, arr, path, region, sub)
            return

        self._require_valid(arr, path, parent)

        if isinstance(t, Primitive):
            self._extract_primitive(t, arr, path, region, parent)
            return
        if isinstance(t, Enum):
            self._extract_enum(t, arr, path, region, parent)
            return
        if isinstance(t, Record):
            prefix = path + "/" if path else ""
            sub = parent
            v = self._valid(arr)
            if v is not None:
                sub = v if sub is None else (v & sub)
            for i, f in enumerate(t.fields):
                self.extract(f.type, arr.field(i), prefix + f.name,
                             region, sub)
            return
        if isinstance(t, Union):
            tids = np.frombuffer(
                arr.buffers()[1], np.int8, count=len(arr) + arr.offset
            )[arr.offset:].astype(np.int32)
            live_bad = (tids < 0) | (tids >= len(t.variants))
            if parent is not None:
                live_bad = live_bad & parent
            if live_bad.any():
                bad = int(tids[live_bad][0])
                raise ValueError(f"union type_id {bad} out of range")
            self.put(path + "#tid", tids, region)
            self.bound += 5 * len(arr)
            for k, v in enumerate(t.variants):
                if not v.is_null():
                    sel = tids == k
                    sub = sel if parent is None else (sel & parent)
                    self.extract(v, arr.field(k), f"{path}/{k}", region,
                                 sub)
            return
        if isinstance(t, (Array, Map)):
            self._extract_repeated(t, arr, path, region, parent)
            return
        if isinstance(t, Fixed):
            self._extract_fixed(t, arr, path, region, parent)
            return
        raise UnsupportedOnDevice(f"type {type(t).__name__} at {path!r}")

    def _extract_fixed(self, t, arr, path, region,
                       parent=None) -> None:
        """Avro ``fixed`` → one raw byte run (size per entry); a
        ``duration`` Arrow input (Duration(ms) int64) converts back to
        the wire's (months, days, ms) u32-LE triple with the oracle's
        divmod arithmetic (``fallback/encoder.py``)."""
        n = len(arr)
        size = t.size
        if t.logical == "decimal":
            self._extract_decimal(arr, path, region, fixed_size=size,
                                  parent=parent)
            return
        if t.logical == "duration":
            import pyarrow.compute as pc

            ms = (
                pc.fill_null(arr.cast(pa.int64()), 0)
                .to_numpy(zero_copy_only=False)
                .astype(np.int64)
            )
            days_total, ms_r = np.divmod(ms, 86_400_000)
            months, days = np.divmod(days_total, 30)
            live = self._valid(arr)
            if parent is not None:
                live = parent if live is None else (live & parent)
            for name, v in (("months", months), ("days", days),
                            ("ms", ms_r)):
                # only lanes the encoder will read can error — dead
                # slots (nulls, non-selected union arms) hold garbage
                bad = (v < 0) | (v >= (1 << 32))
                if live is not None:
                    bad = bad & live
                if bad.any():
                    raise ValueError(
                        f"duration {name} component out of uint32 range "
                        f"at row {int(np.flatnonzero(bad)[0])}"
                    )
            raw = np.ascontiguousarray(
                np.stack(
                    [months.astype(np.uint32), days.astype(np.uint32),
                     ms_r.astype(np.uint32)],
                    axis=1,
                )
            ).view(np.uint8).reshape(-1)
        else:
            buf = arr.buffers()[1]
            if buf is None:
                raw = np.zeros(n * size, np.uint8)
            else:
                raw = np.frombuffer(
                    buf, np.uint8, count=(arr.offset + n) * size
                )[arr.offset * size:]
        if self.host_mode:
            self.put(path + "#fix", raw, region)  # the VM's dense column
        else:
            # device encode writes fixed runs through the bulk payload
            # scatter: same #src/#len/#bytes layout as strings, with
            # constant lens (see _EncLowering.lower_fixed)
            self.put(
                path + "#src",
                (np.arange(n, dtype=np.int64) * size).astype(np.int32),
                region,
            )
            self.put(path + "#len", np.full(n, size, np.int32), region)
            self.byte_bufs[path + "#bytes"] = np.ascontiguousarray(raw)
        self.bound += size * n

    def _extract_primitive(self, t: Primitive, arr, path, region,
                           parent=None) -> None:
        name = t.name
        if name == "null":
            return
        if name == "int":
            raw = self._raw_fixed_width(arr, np.int32) if self.host_mode else None
            self.put(
                path + "#v",
                raw if raw is not None
                else self._ints(arr, pa.int32(), np.int32),
                region,
            )
            self.bound += 5 * len(arr)
        elif name == "long":
            raw = self._raw_fixed_width(arr, np.int64) if self.host_mode else None
            v = raw if raw is not None else self._ints(arr, pa.int64(), np.int64)
            if self.host_mode:
                self.put(path + "#v64", v, region)
            else:
                u = v.view(np.uint64)
                self.put(path + "#v:lo", (u & 0xFFFFFFFF).astype(np.uint32), region)
                self.put(path + "#v:hi", (u >> 32).astype(np.uint32), region)
            self.bound += 10 * len(arr)
        elif name == "float":
            raw = self._raw_fixed_width(arr, np.float32) if self.host_mode else None
            if raw is None:
                import pyarrow.compute as pc

                a = pc.fill_null(arr, 0.0) if arr.null_count else arr
                raw = a.to_numpy(zero_copy_only=False).astype(
                    np.float32, copy=False
                )
            self.put(path + "#v", raw, region)
            self.bound += 4 * len(arr)
        elif name == "double":
            raw = self._raw_fixed_width(arr, np.float64) if self.host_mode else None
            if raw is None:
                import pyarrow.compute as pc

                a = pc.fill_null(arr, 0.0) if arr.null_count else arr
                raw = a.to_numpy(zero_copy_only=False).astype(
                    np.float64, copy=False
                )
            if self.host_mode:
                self.put(path + "#v64", raw, region)
            else:
                u = raw.view(np.uint64)
                self.put(path + "#v:lo", (u & 0xFFFFFFFF).astype(np.uint32), region)
                self.put(path + "#v:hi", (u >> 32).astype(np.uint32), region)
            self.bound += 8 * len(arr)
        elif name == "boolean":
            self.put(path + "#v", self._ints(arr, pa.uint8(), np.uint8), region)
            self.bound += len(arr)
        elif name == "string":
            if t.logical == "uuid":
                self._extract_uuid(arr, path, region)
            else:
                self._extract_string(arr, path, region)
        elif name == "bytes":
            if t.logical == "decimal":
                self._extract_decimal(arr, path, region, parent=parent)
            else:
                # Binary shares Utf8's offsets+data layout
                self._extract_string(arr, path, region)
        else:
            raise UnsupportedOnDevice(f"primitive {name!r} at {path!r}")

    _HEXCHARS = np.frombuffer(b"0123456789abcdef", np.uint8)

    def _extract_uuid(self, arr, path, region) -> None:
        """FixedSizeBinary(16) → canonical lowercase uuid text (what the
        oracle writes: ``str(UUID(bytes=v))``), vectorized, emitted in
        the string column layout the encode VM consumes."""
        n = len(arr)
        buf = arr.buffers()[1]
        if buf is None:
            raw = np.zeros((n, 16), np.uint8)
        else:
            raw = np.frombuffer(
                buf, np.uint8, count=(arr.offset + n) * 16
            )[arr.offset * 16:].reshape(n, 16)
        from ..runtime.native.build import loaded_host_codec_with

        mod = loaded_host_codec_with("uuid_text")
        if mod is not None and n:
            out = np.frombuffer(
                mod.uuid_text(np.ascontiguousarray(raw.reshape(-1)), n),
                np.uint8,
            ).reshape(n, 36)
        else:
            chars = np.empty((n, 32), np.uint8)
            chars[:, 0::2] = self._HEXCHARS[raw >> 4]
            chars[:, 1::2] = self._HEXCHARS[raw & 0xF]
            out = np.empty((n, 36), np.uint8)
            out[:, [8, 13, 18, 23]] = ord("-")
            out[:, 0:8] = chars[:, 0:8]
            out[:, 9:13] = chars[:, 8:12]
            out[:, 14:18] = chars[:, 12:16]
            out[:, 19:23] = chars[:, 16:20]
            out[:, 24:36] = chars[:, 20:32]
        # int32 like every #src: n*36 would wrap past ~59.6M rows, but
        # the byte bound (37n < 2^30) splits such batches before any
        # consumer sees these offsets
        self.put(
            path + "#src",
            (np.arange(n, dtype=np.int64) * 36).astype(np.int32),
            region,
        )
        self.put(path + "#len", np.full(n, 36, np.int32), region)
        self.byte_bufs[path + "#bytes"] = np.ascontiguousarray(
            out
        ).reshape(-1)
        self.bound += 37 * n  # 36 chars + 1-byte length varint

    @staticmethod
    def _bitlen64(x: np.ndarray) -> np.ndarray:
        """Vectorized bit length of a uint64 array."""
        bits = np.zeros(x.shape, np.int32)
        v = x.copy()
        for s in (32, 16, 8, 4, 2, 1):
            ge = v >= (np.uint64(1) << np.uint64(s))
            bits += np.where(ge, s, 0).astype(np.int32)
            v = np.where(ge, v >> np.uint64(s), v)
        return bits + (v > 0).astype(np.int32)

    def _extract_decimal(self, arr, path, region, fixed_size=None,
                         parent=None) -> None:
        """Decimal128 values buffer: 16 bytes LE per entry (what the
        encode VM's OP_DEC ops consume). Device mode additionally
        derives per-entry wire byte lengths (``#dlen``, the oracle's
        ``max((abs_bit_length + 8) // 8, 1)``) for bytes-decimals, and
        pre-checks fixed-decimals against their size — both vectorized
        over the u64 halves; only LIVE entries are checked (a null slot
        holds undefined buffer bytes)."""
        n = len(arr)
        buf = arr.buffers()[1]
        if buf is None:
            raw = np.zeros(n * 16, np.uint8)
        else:
            raw = np.frombuffer(
                buf, np.uint8, count=(arr.offset + n) * 16
            )[arr.offset * 16:]
        self.put(path + "#dec", raw, region)
        if not self.host_mode and n:
            w = np.ascontiguousarray(raw).view(np.uint64).reshape(n, 2)
            lo, hi = w[:, 0], w[:, 1]
            neg = (hi >> np.uint64(63)) != 0
            lo_a = np.where(neg, (~lo) + np.uint64(1), lo)
            hi_a = np.where(neg, (~hi) + (lo == 0).astype(np.uint64), hi)
            if fixed_size is None:
                bits = np.where(
                    hi_a > 0, 64 + self._bitlen64(hi_a), self._bitlen64(lo_a)
                )
                self.put(
                    path + "#dlen",
                    np.maximum((bits + 8) // 8, 1).astype(np.int32),
                    region,
                )
            elif fixed_size < 16:
                # signed-range fit: |v| < 2^(8s-1), or == for the most
                # negative value (≙ the VM's check / int.to_bytes)
                live = self._valid(arr)
                if parent is not None:
                    live = parent if live is None else (live & parent)
                sbits = 8 * fixed_size - 1
                if sbits >= 64:
                    l_hi = np.uint64(1) << np.uint64(sbits - 64)
                    l_lo = np.uint64(0)
                else:
                    l_hi = np.uint64(0)
                    l_lo = np.uint64(1) << np.uint64(sbits)
                over = (hi_a > l_hi) | ((hi_a == l_hi) & (lo_a > l_lo)) | (
                    (~neg) & (hi_a == l_hi) & (lo_a == l_lo)
                )
                if live is not None:
                    over = over & live
                if over.any():
                    raise OverflowError(
                        "decimal value does not fit its fixed size"
                    )
        elif not self.host_mode and fixed_size is None:
            self.put(path + "#dlen", np.zeros(0, np.int32), region)
        self.bound += 18 * n  # ≤16 value bytes + length varint

    @staticmethod
    def _utf8_view(arr):
        """(offs, values, lens) numpy views of a Utf8/Binary array's
        buffers, offset-aware and tolerant of absent buffers (legal for
        all-null arrays per the Arrow C data interface). ``offs`` are
        ABSOLUTE positions into ``values``' underlying buffer (a sliced
        array's offs[0] is nonzero); ``values`` covers [0, offs[-1])."""
        n = len(arr)
        off_buf = arr.buffers()[1]
        if off_buf is None:
            offs = np.zeros(n + 1, np.int32)
        else:
            offs = np.frombuffer(off_buf, np.int32,
                                 count=n + arr.offset + 1)[arr.offset:]
        end = int(offs[-1])
        val_buf = arr.buffers()[2]
        values = (
            np.frombuffer(val_buf, np.uint8, count=end)
            if val_buf is not None and end
            else np.zeros(0, np.uint8)
        )
        return offs, values, np.diff(offs).astype(np.int32)

    def _extract_string(self, arr, path, region) -> None:
        n = len(arr)
        offs, values, lens = self._utf8_view(arr)
        base, end = int(offs[0]), int(offs[-1])
        vals = values[base:end]
        src = (offs[:-1] - base).astype(np.int32)
        self.put(path + "#src", src, region)
        self.put(path + "#len", lens, region)
        self.byte_bufs[path + "#bytes"] = vals
        self.bound += 5 * n + int(lens.sum())

    def _extract_enum(self, t: Enum, arr, path, region,
                      parent: Optional[np.ndarray]) -> None:
        n = len(arr)
        if pa.types.is_string(arr.type) and n:
            # vectorized symbol match on the raw utf8 buffers: per
            # symbol, one length filter + one (cand, L) byte compare —
            # replaces pc.index_in's generic hash kernel (~8x on the
            # kafka enum cell). Distinct symbols can't share bytes, so
            # each row matches at most once.
            offs, values, lens = self._utf8_view(arr)
            idx = np.full(n, -1, np.int32)
            L0 = int(lens[0])
            if bool((lens == L0).all()):
                # uniform value width (the typical enum column): dense
                # row-matrix compares, no candidate fancy-indexing —
                # numpy's per-op overhead dominates at this size.
                # Uniform lens ⇒ offsets are a ramp from offs[0], so the
                # slice's bytes are one contiguous [n, L0] block (a
                # sliced array's offs[0] is nonzero).
                base = int(offs[0])
                m = (values[base: base + n * L0].reshape(n, L0)
                     if L0 else None)
                for k, sym in enumerate(t.symbols):
                    sb = np.frombuffer(sym.encode("utf-8"), np.uint8)
                    if len(sb) != L0:
                        continue
                    if L0 == 0:
                        idx[:] = k  # at most one zero-length symbol
                    elif L0 == 1:
                        idx[m[:, 0] == sb[0]] = k
                    else:
                        idx[(m == sb).all(axis=1)] = k
            else:
                for k, sym in enumerate(t.symbols):
                    sb = np.frombuffer(sym.encode("utf-8"), np.uint8)
                    L = len(sb)
                    cand = np.flatnonzero(lens == L)
                    if not cand.size:
                        continue
                    if L == 0:
                        idx[cand] = k
                        continue
                    m = values[
                        offs[:-1][cand, None].astype(np.int64)
                        + np.arange(L)
                    ]
                    idx[cand[(m == sb).all(axis=1)]] = k
            missing = idx < 0
            valid = self._valid(arr)
            if valid is not None:
                missing = missing & valid
            if parent is not None:
                missing = missing & parent
            if missing.any():
                i = int(np.flatnonzero(missing)[0])
                raise ValueError(
                    f"value {arr[i].as_py()!r} is not a symbol of enum "
                    f"{t.fullname}"
                )
            np.maximum(idx, 0, out=idx)
            if valid is not None:
                # null slots may own garbage bytes that happen to match
                # a symbol; the fallback path emits 0 for them — keep
                # the two paths byte-identical
                idx[~valid] = 0
            self.put(path + "#v", idx, region)
            self.bound += 5 * n
            return
        import pyarrow.compute as pc

        idx = pc.index_in(arr, value_set=pa.array(list(t.symbols), pa.utf8()))
        missing = pc.and_(pc.is_null(idx), arr.is_valid()).to_numpy(
            zero_copy_only=False
        )
        if parent is not None:
            missing = missing & parent
        if missing.any():
            i = int(np.flatnonzero(missing)[0])
            raise ValueError(
                f"value {arr[i].as_py()!r} is not a symbol of enum "
                f"{t.fullname}"
            )
        self.put(
            path + "#v",
            pc.fill_null(idx, 0).to_numpy(zero_copy_only=False)
            .astype(np.int32, copy=False),
            region,
        )
        self.bound += 5 * len(arr)

    def _extract_repeated(self, t, arr, path, region: int,
                          parent: Optional[np.ndarray]) -> None:
        rid = len(self.regions)
        self.regions.append(path)
        n = len(arr)
        offs = np.frombuffer(
            arr.offsets.buffers()[1], np.int32,
            count=n + arr.offsets.offset + 1,
        )[arr.offsets.offset:]
        # RAW counts: the device derives the flat item-axis mapping from
        # cumsum(counts), which must mirror the Arrow child layout even
        # at rows the walk later masks out (a null row may still own a
        # nonzero offset range). For nested repetition the counts live on
        # the OUTER item axis (``region``).
        counts = np.diff(offs).astype(np.int32)
        base, end = int(offs[0]), int(offs[-1])
        self.put(path + "#count", counts, region)
        self.region_len[rid] = end - base
        self.bound += 7 * n  # count varint (≤5) + terminator + slack
        # lift the row validity chain onto the item axis
        live = self._valid(arr)
        if parent is not None:
            live = parent if live is None else (live & parent)
        item_parent = (
            None if live is None
            else np.repeat(live, counts)
        )
        if isinstance(t, Array):
            child = arr.values.slice(base, end - base)
            self.extract(t.items, child, path + "/@item", rid, item_parent)
        else:
            keys = arr.keys.slice(base, end - base)
            vals = arr.items.slice(base, end - base)
            self._require_valid(keys, path + "/@key", item_parent)
            self._extract_string(keys, path + "/@key", rid)
            self.extract(t.values, vals, path + "/@val", rid, item_parent)


def batch_to_struct(ir: Record, batch: pa.RecordBatch) -> pa.StructArray:
    """Column-match an Arrow batch against the schema → one StructArray
    mirroring the IR's field order. Columns are matched by NAME
    (missing → error, extras ignored), exactly like the oracle and the
    reference (``serialization_containers.rs:248-267``). Shared by the
    Python extractor walk below and the Arrow-native C++ extractor
    (``hostpath/codec.py`` exports this struct through the Arrow C data
    interface)."""
    from ..fallback.encoder import _types_compatible
    from ..schema.arrow_map import to_arrow_field

    cols = []
    for f in ir.fields:
        idx = batch.schema.get_field_index(f.name)
        if idx == -1:
            raise ValueError(
                f"record batch is missing column {f.name!r} required by "
                f"schema"
            )
        expected = to_arrow_field(f.type, name=f.name, nullable=False).type
        actual = batch.schema.field(idx).type
        if not _types_compatible(actual, expected):
            raise ValueError(
                f"column {f.name!r} has Arrow type {actual}, but the Avro "
                f"schema requires {expected}"
            )
        cols.append(batch.column(idx))
    return pa.StructArray.from_arrays(
        cols, names=[f.name for f in ir.fields]
    ) if cols else pa.array([{}] * batch.num_rows, pa.struct([]))


def run_extractor(ir: Record, batch: pa.RecordBatch,
                  host_mode: bool = False) -> "_Extractor":
    """Walk a column-matched Arrow batch into per-path numpy arrays
    (shared by the device encoder and the native host encoder)."""
    ex = _Extractor(host_mode)
    ex.extract(ir, batch_to_struct(ir, batch), "", ROWS, None)
    return ex


def extract_batch(prog: EncProgram, batch: pa.RecordBatch,
                  ir: Record) -> Tuple[Dict[str, np.ndarray], int]:
    """Arrow batch → padded device-input dict + output byte bound."""
    ex = run_extractor(ir, batch)

    if ex.regions != prog.regions:  # pragma: no cover — same walk order
        raise AssertionError("extractor/lowering region mismatch")

    n = batch.num_rows
    ex.region_len[ROWS] = n
    dv: Dict[str, np.ndarray] = {}
    pads = {
        rid: bucket_len(max(ln, 1), minimum=8) for rid, ln in ex.region_len.items()
    }
    for rid, ln in ex.region_len.items():
        act = np.zeros(pads[rid], np.uint8)
        act[:ln] = 1
        dv["#active:%d" % rid] = act
    for key, (arr, rid) in ex.arrays.items():
        # per-entry arrays pad to the region bucket; multi-byte-per-
        # entry arrays (#dec 16/entry, #fix size/entry) exceed it and
        # pad to their own power-of-two bucket so jit shapes stay stable
        P = pads[rid] if len(arr) <= pads[rid] else bucket_len(len(arr))
        if len(arr) < P:
            if key.endswith("#src"):
                # pad with an out-of-range sentinel so padded elements
                # never win the byte→element scatter-max mapping
                padded = np.full(P, _BIG, arr.dtype)
            else:
                padded = np.zeros(P, arr.dtype)
            padded[: len(arr)] = arr
            arr = padded
        dv[key] = arr
    for key, buf in ex.byte_bufs.items():
        V = bucket_len(max(len(buf), 4), minimum=16)
        if len(buf) < V:
            buf = np.concatenate([buf, np.zeros(V - len(buf), np.uint8)])
        dv[key] = np.ascontiguousarray(buf).view(np.uint32)
    return dv, max(ex.bound, 16)


# ---------------------------------------------------------------------------
# packed-input protocol (shared by the single-device and sharded paths)
# ---------------------------------------------------------------------------

def input_entries(dv: Dict[str, np.ndarray], axis: int = 0) -> tuple:
    """The static packed-buffer layout: sorted (key, dtype, length)
    per input array (``axis`` selects the per-shard length axis for
    ``[D, ...]``-stacked inputs). The single source of input ordering
    for :func:`unpack_input_entries` and both packers."""
    return tuple(
        sorted((k, str(v.dtype), v.shape[axis]) for k, v in dv.items())
    )


def unpack_input_entries(jnp, lax, buf, entries: tuple) -> Dict[str, object]:
    """Traced inverse of the packers: split one uint8 buffer back into
    the input dict by the static ``entries`` layout."""
    dv = {}
    pos = 0
    for k, dt, ln in entries:
        nb = np.dtype(dt).itemsize * ln
        seg = buf[pos : pos + nb]
        if dt != "uint8":
            seg = lax.bitcast_convert_type(
                seg.reshape(ln, np.dtype(dt).itemsize), jnp.dtype(dt)
            )
        dv[k] = seg
        pos += nb
    return dv


# ---------------------------------------------------------------------------
# the encoder object
# ---------------------------------------------------------------------------

class DeviceEncoder:
    """Per-schema encode pipeline: one jitted launch per (shape-bucket)."""

    def __init__(self, ir: Record, arrow_schema: pa.Schema,
                 fingerprint: str = None):
        import jax  # deferred, like DeviceDecoder

        from .decode import _enable_persistent_cache

        _enable_persistent_cache(jax)
        self._jax = jax
        self.ir = ir
        self.arrow_schema = arrow_schema
        self.fingerprint = fingerprint or "?"  # jit-cache registry id
        self.prog = lower_encoder(ir)  # raises UnsupportedOnDevice
        self._packed_cache: Dict[tuple, object] = {}
        from ..runtime import device_obs as _dobs

        _dobs.track_holder(self)  # executable lifecycle (ISSUE 12)

    def _jit_caches(self):
        return [self._packed_cache]

    def _program(self):
        prog = self.prog
        jax = self._jax

        def run(dv, cap: int):
            out = jnp.zeros(cap, jnp.uint8)
            cx = _Cx(dv, out)
            active = dv["#active:0"].astype(bool)
            row_sizes = jnp.where(active, prog.size(cx), 0)
            cum = jnp.cumsum(row_sizes, dtype=I32)
            start = cum - row_sizes
            prog.write(cx, start, active)
            for col in prog.string_cols:
                _write_string_bytes(cx, col)
            return jnp.concatenate(
                [cx.out, lax.bitcast_convert_type(row_sizes, jnp.uint8)
                 .reshape(-1)]
            )

        return run

    def _packed_fn(self, entries: tuple, cap: int):
        """Jitted program taking ONE uint8 buffer that concatenates every
        input array (static ``entries`` = sorted (key, dtype, length)):
        a dict input would be one transfer per leaf — ~30 serialized
        round trips on a high-latency interconnect (BENCH_NOTES.md) —
        and a packed buffer is one."""
        key = (entries, cap)
        hit = self._packed_cache.get(key)
        if hit is not None:
            return hit
        run = self._program()
        lax = self._jax.lax

        def run_packed(buf):
            return run(unpack_input_entries(jnp, lax, buf, entries), cap)

        import hashlib

        from ..runtime import device_obs

        total = sum(np.dtype(dt).itemsize * ln for _k, dt, ln in entries)
        # the short entries digest keeps the registry bucket unique per
        # executable: two different input layouts can share (total, cap)
        # but are distinct compiles (the cache key is (entries, cap))
        eh = hashlib.sha1(repr(entries).encode()).hexdigest()[:6]
        fn = device_obs.InstrumentedJit(
            self._jax, self._jax.jit(run_packed), kind="encode.pipeline",
            bucket=f"in{total},cap{cap},e{eh}",
            fingerprint=self.fingerprint, family="encode",
        )
        self._packed_cache[key] = fn
        return fn

    def encode(self, batch: pa.RecordBatch) -> pa.Array:
        """Encode every row as one Avro datum → BinaryArray whose value
        buffer is the device output, zero-copy
        (≙ ``serialize_chunk``, ``fast_encode.rs:27-52``)."""
        from ..runtime import telemetry

        n = batch.num_rows
        if n == 0:
            return pa.array([], pa.binary())
        with telemetry.phase("device.pipeline_s", rows=n, op="encode"):
            return self._encode(batch, n)

    def _encode(self, batch: pa.RecordBatch, n: int) -> pa.Array:
        from ..runtime import device_obs, metrics, telemetry

        with telemetry.phase("encode.extract_s", rows=n):
            dv, bound = extract_batch(self.prog, batch, self.ir)
        if bound >= (1 << 30):
            # int32 cursors AND the _BIG drop-sentinel both require the
            # output to stay under 2^30 bytes; the codec splits the batch
            from .decode import BatchTooLarge

            raise BatchTooLarge(n, bound)
        cap = bucket_len(bound, minimum=64)
        jax = self._jax
        entries = input_entries(dv)
        packed = np.concatenate(
            [dv[k].view(np.uint8) for k, _dt, _ln in entries]
        )
        metrics.inc("encode.h2d_bytes", packed.nbytes)
        metrics.inc("device.h2d_bytes", packed.nbytes)
        fn = self._packed_fn(entries, cap)
        with telemetry.phase("encode.h2d_s", bytes=packed.nbytes):
            packed_d = jax.device_put(packed)
        # the wrapper records device.compile_s (first call per shape
        # bucket) vs device.launch_s; d2h carries any remaining wait
        res = fn(packed_d)
        with telemetry.phase("encode.d2h_s"):
            blob = np.asarray(jax.device_get(res))
        metrics.inc("encode.d2h_bytes", blob.nbytes)
        metrics.inc("device.d2h_bytes", blob.nbytes)
        device_obs.note_memory(jax)
        R = dv["#active:0"].shape[0]
        sizes = blob[cap : cap + 4 * R].view(np.int32)[:n]
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum(sizes, out=offsets[1:])
        total = int(offsets[-1])
        return pa.Array.from_buffers(
            pa.binary(), n,
            [None, pa.py_buffer(offsets),
             pa.py_buffer(np.ascontiguousarray(blob[:total]))],
        )
