"""Schema IR → static field program (the device decode lowering).

The reference walks each record with a tree of boxed per-field decoders
driven by runtime dispatch (``FieldDecoder`` ``ruhvro/src/fast_decode.rs:67-420``).
On TPU there is no cheap per-row dispatch — instead the schema is lowered
**once** into a static program of vectorized steps, unrolled at JAX trace
time: each step decodes one schema position for *all records at once*
(one lane per record), masks composing nullable branches, union arms and
array blocks. Data-dependent control flow exists only where the wire
format forces it — the array/map block protocol — as a single
``lax.while_loop`` whose body decodes one item per active lane
(≙ ``read_block_count`` semantics, ``fast_decode.rs:689-700``).

Output layout (the "column specs"):

* every leaf writes fixed-size device buffers keyed by a path string
  (``"address/street#start"``); ``#``-suffixed buffer names cannot clash
  with Avro identifiers,
* repeated fields (array/map) write items into **strided slots**
  ``row * item_cap + i`` of a separate *region*; a too-small statically
  chosen ``item_cap`` is detected per lane (ERR_ITEM_OVERFLOW) and the
  host retries with a bigger cap — see ``ops/decode.py``,
* variable-width bytes (string values) are not moved during the walk at
  all: the walk records ``(start, len)`` only, and the finalize pass
  (``ops/decode.py``) gathers value bytes once sizes are known.

Device subset = the reference's fast subset (``fast_decode.rs:38-61``),
including nested repetition: an array/map inside another array/map's
items becomes a child *region* whose strided slots are indexed by the
parent item's slot (≙ the recursive ``ListDecoder``/``MapDecoder``,
``fast_decode.rs:125-167,689-786``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from . import UnsupportedOnDevice
from .varint import (
    ERR_BAD_BRANCH,
    ERR_BAD_ENUM,
    ERR_ITEM_OVERFLOW,
    ERR_NEG_LEN,
    ERR_OVERRUN,
    U32,
    read_bool_byte,
    read_f32,
    read_f64_pair as _read_f64_pair,
    read_varint32,
    read_varint64,
    zigzag_decode_pair,
)
from ..gate import device_supported
from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)

__all__ = ["Program", "lower", "ROWS"]

ROWS = 0  # region id of the per-row region
_BIG = 1 << 30  # out-of-range scatter index → dropped (mode="drop")
I32 = jnp.int32


@dataclass
class BufSpec:
    key: str
    dtype: object  # jnp dtype
    region: int


@dataclass
class StringCol:
    """A string-valued column whose bytes are gathered in finalize."""

    path: str          # buffers at path#start / path#len
    region: int


@dataclass
class Program:
    """Lowered, schema-static decode program."""

    ir: Record
    buffers: Dict[str, BufSpec]
    regions: List[str]          # region id → path of the repeated field ("" = rows)
    region_parents: List[int]   # region id → parent region (-1 for rows)
    string_cols: List[StringCol]
    emit: Callable              # emit(cx, st, mask, out_idx) -> st  (top record)

    def region_of(self, path: str) -> int:
        return self.buffers[path + "#count"].region


class _Ctx:
    """Runtime (traced) values threaded through emitters.

    ``item_put`` (optional) overrides how strided item-region writes
    lower: the default is a masked scatter (XLA handles those well in
    HBM); the Pallas kernel supplies a 2D one-hot select strategy
    instead, because Mosaic does not lower vector-index scatters
    (``ops/pallas_decode.py``).

    ``reduce_max`` (optional) overrides how the repeated emitter's
    scalar loop-bound reduction lowers: the default ``jnp.max`` is an
    integer ``reduce_max``, which Mosaic refuses to lower (the 12
    failures recorded in PALLAS_LOWER_STATS.json pre-ISSUE-10); the
    Pallas kernel supplies a float32 round trip instead — exact for the
    record-local byte spans the bound is computed over (≤ BW·4 ≤ 2 KiB,
    far inside float32's 2^24 integer range)."""

    __slots__ = ("words", "ends", "item_caps", "item_put", "reduce_max")

    def __init__(self, words, ends, item_caps: Tuple[int, ...],
                 item_put=None, reduce_max=None):
        self.words = words
        self.ends = ends          # absolute end index per row lane
        self.item_caps = item_caps  # static cap per region (item_caps[0] unused)
        self.item_put = item_put
        self.reduce_max = reduce_max if reduce_max is not None else jnp.max


def _put(st, key, idx, val, mask, cx=None):
    """Masked write of one lane-vector into a column buffer.

    ``idx=None`` means the writes are lane-aligned (row region, one slot
    per lane) and lower to a select — XLA compiles piles of selects far
    faster than piles of scatters, and every top-level field write is one.
    Item-region writes (strided slots) are true masked scatters, unless
    the context supplies an ``item_put`` strategy (see :class:`_Ctx`)."""
    buf = st[key]
    if idx is None:
        st[key] = jnp.where(mask, val.astype(buf.dtype), buf)
    elif cx is not None and cx.item_put is not None:
        st[key] = cx.item_put(buf, idx, val.astype(buf.dtype), mask)
    else:
        safe = jnp.where(mask, idx, I32(_BIG))
        st[key] = buf.at[safe].set(val.astype(buf.dtype), mode="drop")
    return st


def _acc_err(st, bits):
    st["#err"] = st["#err"] | bits
    return st


def _err_where(st, mask, bit):
    return _acc_err(st, jnp.where(mask, jnp.uint32(bit), jnp.uint32(0)))


class _Lowering:
    def __init__(self) -> None:
        self.buffers: Dict[str, BufSpec] = {}
        self.regions: List[str] = [""]
        self.region_parents: List[int] = [-1]
        self.string_cols: List[StringCol] = []

    def buf(self, key: str, dtype, region: int) -> None:
        self.buffers[key] = BufSpec(key, dtype, region)

    # -- emitters ---------------------------------------------------------

    def lower_type(self, t: AvroType, path: str, region: int) -> Callable:
        """Return ``emit(cx, st, mask, out_idx) -> st`` for one value of
        ``t`` at ``path``, registering its output buffers."""
        if isinstance(t, Primitive):
            return self.lower_primitive(t, path, region)
        if isinstance(t, Fixed):
            return self.lower_fixed(t, path, region)
        if isinstance(t, Enum):
            return self.lower_enum(t, path, region)
        if isinstance(t, Record):
            return self.lower_record(t, path, region)
        if isinstance(t, Union):
            if t.is_nullable_pair:
                return self.lower_nullable(t, path, region)
            return self.lower_union(t, path, region)
        if isinstance(t, (Array, Map)):
            return self.lower_repeated(t, path, region)
        raise UnsupportedOnDevice(f"type {type(t).__name__} at {path!r}")

    def lower_primitive(self, t: Primitive, path: str, region: int) -> Callable:
        name = t.name
        if name == "null":
            return lambda cx, st, mask, out_idx: st

        if name in ("int", "long"):
            wide = name == "long"
            if wide:
                self.buf(path + "#lo", U32, region)
                self.buf(path + "#hi", U32, region)
            else:
                self.buf(path + "#v", I32, region)

            def emit_varint(cx, st, mask, out_idx):
                lo, hi, cur, verr = read_varint64(cx.words, st["#cursor"], mask)
                lo, hi = zigzag_decode_pair(lo, hi)
                st["#cursor"] = cur
                st = _acc_err(st, verr)
                if wide:
                    st = _put(st, path + "#lo", out_idx, lo, mask, cx)
                    st = _put(st, path + "#hi", out_idx, hi, mask, cx)
                else:
                    st = _put(st, path + "#v", out_idx, lo.astype(I32), mask, cx)
                return st

            return emit_varint

        if name == "float":
            self.buf(path + "#v", jnp.float32, region)

            def emit_f32(cx, st, mask, out_idx):
                v, cur = read_f32(cx.words, st["#cursor"], mask)
                st["#cursor"] = cur
                return _put(st, path + "#v", out_idx, v, mask, cx)

            return emit_f32

        if name == "double":
            self.buf(path + "#lo", U32, region)
            self.buf(path + "#hi", U32, region)

            def emit_f64(cx, st, mask, out_idx):
                lo, hi, cur = _read_f64_pair(cx.words, st["#cursor"], mask)
                st["#cursor"] = cur
                st = _put(st, path + "#lo", out_idx, lo, mask, cx)
                return _put(st, path + "#hi", out_idx, hi, mask, cx)

            return emit_f64

        if name == "boolean":
            self.buf(path + "#v", jnp.uint8, region)

            def emit_bool(cx, st, mask, out_idx):
                b, cur, berr = read_bool_byte(cx.words, st["#cursor"], mask)
                st["#cursor"] = cur
                st = _acc_err(st, berr)
                return _put(st, path + "#v", out_idx, b, mask, cx)

            return emit_bool

        if name in ("string", "bytes"):
            # one wire form, three Arrow destinations: Utf8 (string,
            # incl. uuid text), Binary (bytes), Decimal128 (decimal over
            # bytes). The walk only records (start, len) descriptors;
            # the shared host assembly does the per-type conversion
            # (``arrow_build._string_values`` / ``._decimal`` / ``._uuid``)
            self.buf(path + "#start", I32, region)
            self.buf(path + "#len", I32, region)
            self.string_cols.append(StringCol(path, region))

            def emit_string(cx, st, mask, out_idx):
                lo, hi, cur, verr = read_varint32(cx.words, st["#cursor"], mask)
                lo, hi = zigzag_decode_pair(lo, hi)
                slen = lo.astype(I32)
                bad = mask & ((slen < 0) | (hi != 0))
                st = _acc_err(st, verr)
                st = _err_where(st, bad, ERR_NEG_LEN)
                slen = jnp.where(bad, 0, slen)
                new_cur = cur + jnp.where(mask, slen, 0)
                st = _err_where(st, mask & (new_cur > cx.ends), ERR_OVERRUN)
                st = _put(st, path + "#start", out_idx, cur, mask, cx)
                st = _put(st, path + "#len", out_idx, slen, mask, cx)
                st["#cursor"] = new_cur
                return st

            return emit_string

        raise UnsupportedOnDevice(f"primitive {name!r} at {path!r}")

    def lower_fixed(self, t: Fixed, path: str, region: int) -> Callable:
        """Avro ``fixed`` (incl. duration = fixed(12) and decimal over
        fixed): a static-size byte run — the walk records the start only
        (the length is the schema constant) and the host assembly gathers
        + converts (``arrow_build._fixed`` / ``._decimal``)."""
        self.buf(path + "#start", I32, region)
        size = t.size

        def emit_fixed(cx, st, mask, out_idx):
            cur = st["#cursor"]
            new_cur = cur + jnp.where(mask, I32(size), 0)
            st = _err_where(st, mask & (new_cur > cx.ends), ERR_OVERRUN)
            st = _put(st, path + "#start", out_idx, cur, mask, cx)
            st["#cursor"] = new_cur
            return st

        return emit_fixed

    def lower_enum(self, t: Enum, path: str, region: int) -> Callable:
        self.buf(path + "#v", I32, region)
        n = len(t.symbols)

        def emit_enum(cx, st, mask, out_idx):
            lo, hi, cur, verr = read_varint32(cx.words, st["#cursor"], mask)
            lo, hi = zigzag_decode_pair(lo, hi)
            idx = lo.astype(I32)
            st["#cursor"] = cur
            st = _acc_err(st, verr)
            st = _err_where(
                st, mask & ((hi != 0) | (idx < 0) | (idx >= n)), ERR_BAD_ENUM
            )
            return _put(st, path + "#v", out_idx, idx, mask, cx)

        return emit_enum

    def lower_record(self, t: Record, path: str, region: int) -> Callable:
        prefix = path + "/" if path else ""
        emitters = [
            self.lower_type(f.type, prefix + f.name, region) for f in t.fields
        ]

        def emit_record(cx, st, mask, out_idx):
            for e in emitters:
                st = e(cx, st, mask, out_idx)
            return st

        return emit_record

    def _read_branch(self, cx, st, mask):
        """Read a small non-negative varint (union branch). Any value with
        a nonzero high word is out of range for every caller — reject it
        rather than silently truncating to the low 32 bits."""
        lo, hi, cur, verr = read_varint32(cx.words, st["#cursor"], mask)
        lo, hi = zigzag_decode_pair(lo, hi)
        st["#cursor"] = cur
        st = _acc_err(st, verr)
        st = _err_where(st, mask & (hi != 0), ERR_BAD_BRANCH)
        return lo.astype(I32), st

    def lower_nullable(self, t: Union, path: str, region: int) -> Callable:
        """2-variant ``["null", T]`` union → validity bitmap + masked inner
        decode (≙ ``make_nullable_decoder``, ``fast_decode.rs:270``)."""
        self.buf(path + "#valid", jnp.uint8, region)
        null_idx = t.null_index
        inner = self.lower_type(t.non_null_variant, path, region)

        def emit_nullable(cx, st, mask, out_idx):
            branch, st = self._read_branch(cx, st, mask)
            present = mask & (branch == (1 - null_idx))
            absent = mask & (branch == null_idx)
            st = _err_where(st, mask & ~(present | absent), ERR_BAD_BRANCH)
            # i32 constant on purpose: _put casts to the buffer dtype,
            # and a literal u8 constant is unlowerable in Mosaic (the
            # Pallas kernel widens u8 buffers to i32 in-kernel)
            st = _put(st, path + "#valid", out_idx,
                      jnp.full_like(branch, 1, dtype=I32), present, cx)
            return inner(cx, st, present, out_idx)

        return emit_nullable

    def lower_union(self, t: Union, path: str, region: int) -> Callable:
        """N-variant sparse union → type_ids + per-arm masked decode
        (≙ ``UnionDecoder``, ``fast_decode.rs:642-684``)."""
        self.buf(path + "#tid", I32, region)
        n = len(t.variants)
        arms: List[Optional[Callable]] = []
        for k, v in enumerate(t.variants):
            if v.is_null():
                arms.append(None)
            else:
                arms.append(self.lower_type(v, f"{path}/{k}", region))

        def emit_union(cx, st, mask, out_idx):
            branch, st = self._read_branch(cx, st, mask)
            st = _err_where(st, mask & ((branch < 0) | (branch >= n)),
                            ERR_BAD_BRANCH)
            st = _put(st, path + "#tid", out_idx, branch, mask, cx)
            for k, arm in enumerate(arms):
                if arm is not None:
                    st = arm(cx, st, mask & (branch == k), out_idx)
            return st

        return emit_union

    def lower_repeated(self, t, path: str, region: int = ROWS) -> Callable:
        """Array/map block protocol as one vectorized ``lax.while_loop``:
        each iteration reads pending block headers and decodes at most one
        item per active lane into strided slots ``parent_slot * item_cap
        + i``. Negative block counts (item-count with byte-size prefix,
        ``fast_decode.rs:689-700``) consume and discard the size.

        Nested repetition (``region != ROWS``, ≙ the reference's
        recursive ``ListDecoder``/``MapDecoder``,
        ``fast_decode.rs:125-167,689-786``) composes naturally: the
        inner repeated emitter runs its own while_loop inside the outer
        body, indexed by the outer item's strided slot; the finalize
        pass (``ops/decode.py``) cascades the compaction parent-first."""
        rid = len(self.regions)
        self.regions.append(path)
        self.region_parents.append(region)
        self.buf(path + "#count", I32, region)
        if isinstance(t, Array):
            item_emitters = [self.lower_type(t.items, path + "/@item", rid)]
        else:  # Map: key string + value
            item_emitters = [
                self.lower_type(
                    Primitive("string"), path + "/@key", rid
                ),
                self.lower_type(t.values, path + "/@val", rid),
            ]

        # only the buffers the loop writes travel in the while carry: this
        # region's, plus any nested region's (their loops run inside this
        # body); the rest of the (large) state dict stays outside — this
        # keeps the XLA loop body small, which dominates compile time
        loop_keys = None

        def emit_repeated(cx, st, mask, out_idx):
            nonlocal loop_keys
            if loop_keys is None:
                rids = {rid}
                for r in range(rid + 1, len(self.regions)):
                    if self.region_parents[r] in rids:
                        rids.add(r)
                loop_keys = sorted(
                    k for k, s in self.buffers.items() if s.region in rids
                ) + ["#cursor", "#err"]
            icap = cx.item_caps[rid]
            base = (
                jnp.arange(st["#cursor"].shape[0], dtype=I32)
                if out_idx is None
                else out_idx
            )
            # worst-case legitimate iterations: one per wire byte of the
            # longest row (headers and ≥1-byte items) plus one per item slot
            # (zero-byte items: null/empty-record items consume no bytes,
            # bounded by the per-record cap — an overflowing cap retries
            # with a larger one, see ops/decode.py)
            row_span = cx.ends - st["#cursor"]
            max_iters = cx.reduce_max(jnp.where(mask, row_span, 0)) + icap + 2

            def cond(carry):
                _st, _rem, done, _cnt, it = carry
                return jnp.any(~done) & (it < max_iters)

            def body(carry):
                sub, rem, done, cnt, it = carry
                st = dict(sub)  # item emitters only touch loop_keys
                # 1) lanes needing a block header
                need = (~done) & (rem == 0)
                lo, hi, cur, verr = read_varint32(cx.words, st["#cursor"], need)
                lo, hi = zigzag_decode_pair(lo, hi)
                b = lo.astype(I32)
                st = _acc_err(st, verr)
                # a count whose high word is neither a zero- nor a
                # sign-extension of the low word would truncate silently
                bad_count = need & ~(
                    ((hi == 0) & (b >= 0))
                    | ((hi == jnp.uint32(0xFFFFFFFF)) & (b < 0))
                )
                st = _err_where(st, bad_count, ERR_OVERRUN)
                b = jnp.where(bad_count, 0, b)
                neg = need & (b < 0)
                # negative count: a byte-size long follows; skip it
                _slo, _shi, cur, serr = read_varint32(cx.words, cur, neg)
                st = _acc_err(st, serr)
                b = jnp.where(neg, -b, b)
                st["#cursor"] = cur
                ended = need & (b == 0)
                done = done | ended
                rem = jnp.where(need, jnp.where(ended, 0, b), rem)
                st = _err_where(st, (~done) & (st["#cursor"] > cx.ends),
                                ERR_OVERRUN)
                done = done | ((~done) & (st["#cursor"] > cx.ends))
                # 2) decode one item per lane that has items pending
                can = (~done) & (rem > 0)
                over = can & (cnt >= icap)
                st = _err_where(st, over, ERR_ITEM_OVERFLOW)
                # overflow lanes still *decode* (into dropped slots) so the
                # cursor walk stays exact; the host retries with a larger cap
                slot = jnp.where(cnt < icap, base * icap + cnt, I32(_BIG))
                for e in item_emitters:
                    st = e(cx, st, can, slot)
                rem = rem - can.astype(I32)
                cnt = cnt + can.astype(I32)
                return {k: st[k] for k in loop_keys}, rem, done, cnt, it + 1

            zero = jnp.zeros_like(st["#cursor"])
            sub0 = {k: st[k] for k in loop_keys}
            sub, _rem, done, cnt, it = lax.while_loop(
                cond, body, (sub0, zero, ~mask, zero, I32(0))
            )
            st = dict(st)
            st.update(sub)
            # ran out of iterations with lanes still open → malformed
            st = _err_where(st, ~done, ERR_OVERRUN)
            return _put(st, path + "#count", out_idx, cnt, mask, cx)

        return emit_repeated


def lower(ir: AvroType) -> Program:
    """Lower a top-level record schema to its device field program.

    Raises :class:`UnsupportedOnDevice` when outside the device subset —
    a strict SUPERSET of the reference's fast subset
    (``fast_decode.rs:38-61``): the full reference type surface,
    including bytes/fixed/decimal/uuid/duration/time-* which the
    reference serves only via its Value-tree fallback. Nested repetition
    included — ``lower_repeated`` recurses, with the inner region's
    strided slots indexed by the outer item's slot.
    """
    if not device_supported(ir):
        raise UnsupportedOnDevice("schema is outside the device subset")
    lo = _Lowering()
    emit = lo.lower_record(ir, "", ROWS)
    return Program(
        ir=ir,
        buffers=lo.buffers,
        regions=lo.regions,
        region_parents=lo.region_parents,
        string_cols=lo.string_cols,
        emit=emit,
    )
