"""Device codec: the object ``api.py`` routes to behind the fast gate.

``get_device_codec(entry)`` is the TPU analogue of the reference's gate
target (``fast_decode::decode_with_arrow_schema``,
``ruhvro/src/fast_decode.rs:815``): construction performs the one-time
schema lowering + backend probe, memoized on the ``SchemaEntry`` so a
schema string maps to its compiled kernels for the process lifetime
(≙ the schema cache + shared-Arc amortization, ``src/lib.rs:35-54``,
``deserialize.rs:83-89``).

Raises :class:`UnsupportedOnDevice` for schemas outside the device
subset (silent host fallback in ``backend='auto'``, like
``deserialize.rs:26-29``); any other exception means the backend itself
is broken and is surfaced by ``api.py``.
"""

from __future__ import annotations

from typing import List, Sequence

import pyarrow as pa

from ..fallback.io import MalformedAvro, shift_malformed
from ..schema.cache import SchemaEntry
from . import UnsupportedOnDevice
from .arrow_build import compact_union_slices
from .decode import (
    BatchTooLarge,
    DeviceCapacityExceeded,
    DeviceDecoder,
    overlap_chunks,
)

__all__ = ["DeviceCodec", "get_device_codec"]

from ..runtime import knobs as _knobs

_PROBE_TIMEOUT_S = _knobs.get_float("PYRUHVRO_TPU_PROBE_TIMEOUT")
_probe_result: list = []  # memoized: [devices] or [exception]
_rtt_result: list = []    # memoized: [seconds]


def interconnect_rtt_s() -> float:
    """One-time host↔device round-trip probe (64 KB up, tiny compute,
    64 KB down, best of 3). Distinguishes a co-located accelerator
    (sub-ms) from a remote device tunnel (tens of ms) — the signal
    ``backend="auto"`` uses to place small batches. Memoized per
    process; costs at most a few RTTs, and only runs when both the
    device codec and the native host VM are candidates."""
    if _rtt_result:
        return _rtt_result[0]
    import threading
    import time

    import numpy as np

    def run(box):
        try:
            # backend init first, under its own (configurable,
            # PYRUHVRO_TPU_PROBE_TIMEOUT) watchdog — slow-but-healthy
            # runtime bring-up must not read as a remote interconnect
            _probe_backend()
            import jax

            x = np.random.default_rng(0).integers(
                0, 1 << 32, 16384, dtype=np.uint32
            )
            f = jax.jit(lambda v: v + np.uint32(1))
            best = float("inf")
            for _ in range(3):
                x[0] ^= 1  # defeat any transport-level result caching
                t0 = time.perf_counter()
                np.asarray(jax.device_get(f(jax.device_put(x))))
                best = min(best, time.perf_counter() - t0)
            box.append(best)
        except Exception:
            box.append(float("inf"))  # no usable device: infinitely far

    # watchdog thread: a transport can wedge (not fail) mid-probe — the
    # probe must degrade to "remote" rather than hang the caller. Budget:
    # the backend-init allowance plus slack for the tiny jit + 3 RTTs.
    box: list = []
    t = threading.Thread(target=run, args=(box,), daemon=True)
    t.start()
    t.join(_PROBE_TIMEOUT_S + 30.0)
    best = box[0] if box else float("inf")
    _rtt_result.append(best)
    return best


def reset_failed_probe() -> None:
    """Forget a FAILED backend probe (and the RTT figure derived while
    it was failing) so the next construction re-probes —
    ``api._device_codec_ex`` calls this when a schema's device-failure
    backoff grants a retry. A successful probe memo is never cleared."""
    if _probe_result and isinstance(_probe_result[0], BaseException):
        _probe_result.clear()
        _rtt_result.clear()


def _degradable(e: BaseException) -> bool:
    """Failures that justify degrading a device call to the host path —
    the shared fault-domain taxonomy (``runtime.faults.degradable``)."""
    from ..runtime import faults

    return faults.degradable(e)


def _device_call_failed(e: BaseException) -> None:
    """Record one call-time device failure: counted, span-annotated and
    fed to the ``device_backend`` breaker — enough consecutive failures
    open it and the router stops offering device arms until the
    half-open probe proves the backend back."""
    from ..runtime import breaker, metrics, telemetry

    metrics.inc("device.call_failure")
    telemetry.annotate(device_degraded=type(e).__name__)
    breaker.get("device_backend").record_failure()


def _device_call_ok() -> None:
    """A device call completed: reset the breaker's failure streak (and
    close it when this call was the half-open probe)."""
    from ..runtime import breaker

    breaker.get("device_backend").record_success()


def devices_cpu_only() -> bool:
    """True when the RESOLVED backend probe found only host-CPU devices
    — the routing signal ``backend="auto"`` uses to skip the device
    pipeline entirely (an XLA walk on CPU is just a slower CPU program
    than the native VM). Reads the memo only: callers must have built a
    device codec first (which runs the probe), so this never wedges."""
    devs = _probe_result[0] if _probe_result else None
    return (devs is not None and not isinstance(devs, BaseException)
            and len(devs) > 0
            and all(d.platform == "cpu" for d in devs))


def interconnect_remote(threshold_s: float = 0.010) -> bool:
    """True when the accelerator sits behind a high-latency transport
    (RTT above ``threshold_s``), where per-call round trips dominate any
    kernel win and the native host VM is the faster production path."""
    return interconnect_rtt_s() > threshold_s


def _probe_backend() -> None:
    """Initialize the JAX backend once, with a timeout.

    Backend init can hang (not fail) when a device transport is wedged;
    running it on a watchdog thread turns that hang into a RuntimeError so
    ``backend='auto'`` degrades to the host path with a warning instead of
    blocking the caller indefinitely."""
    import threading

    if _probe_result:
        out = _probe_result[0]
        if isinstance(out, BaseException):
            raise RuntimeError(f"JAX backend unavailable: {out!r}") from out
        return

    def run():
        try:
            import jax

            _probe_result.append(jax.devices())
        except BaseException as e:  # noqa: BLE001 — reported to caller
            _probe_result.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(_PROBE_TIMEOUT_S)
    if not _probe_result:
        e = TimeoutError(
            f"JAX backend initialization did not finish within "
            f"{_PROBE_TIMEOUT_S:.0f}s (wedged device transport?)"
        )
        _probe_result.append(e)
    out = _probe_result[0]
    # a FRESH probe verdict is backend-wide evidence (no schema in
    # sight), so it feeds the shared breaker directly — memo re-reads
    # above must not re-count the same broken state
    from ..runtime import breaker

    if isinstance(out, BaseException):
        breaker.get("device_backend").record_failure()
        raise RuntimeError(f"JAX backend unavailable: {out!r}") from out
    breaker.get("device_backend").record_success()


class DeviceCodec:
    """Schema-bound decode/encode pipelines on the default JAX backend."""

    def __init__(self, entry: SchemaEntry, pallas: str | None = None):
        self.entry = entry
        self.ir = entry.ir
        self.arrow_schema = entry.arrow_schema
        # opt-in: run the decode walk as the Pallas kernel instead of
        # the XLA pipeline for schemas it supports (v2: row-level
        # array/map included; nested repetition stays on XLA)
        # — same lowered field program, explicit-kernel execution
        # (ops/pallas_decode.py). The XLA pipeline stays the default:
        # its fused single-blob transfer is tuned for high-latency
        # interconnects, and it covers repeated fields. The mode comes
        # from the caller (``get_device_codec`` reads the env ONCE and
        # folds the same value into its memo key — re-reading here could
        # cache a codec under a key that doesn't match its decoder).
        pallas_flag = (_pallas_mode() if pallas is None else pallas)
        self.decoder = None
        if pallas_flag in ("mosaic", "interpret"):
            try:
                from .pallas_decode import PallasKernelDecoder

                self.decoder = PallasKernelDecoder(
                    entry.ir, interpret=pallas_flag == "interpret",
                    fingerprint=entry.fingerprint,
                )
            except UnsupportedOnDevice:
                pass  # nested repetition: the XLA pipeline serves it
        if self.decoder is None:
            self.decoder = DeviceDecoder(entry.ir,
                                         fingerprint=entry.fingerprint)
        self._encoder = None
        self._sharded = None  # lazily: ShardedDecoder | False (single-chip)
        # probe the backend now: a missing/broken device must fail at
        # construction (where api.py distinguishes it from unsupported
        # schemas), not on the first decode call. The probe is
        # time-bounded: a wedged device transport must degrade to the
        # host path, not hang every backend='auto' caller forever.
        _probe_backend()

    def _host_decode(self, data: Sequence[bytes]) -> pa.RecordBatch:
        """Host-path decode for batches the device path hands back
        (capacity exceeded, oversized single datum): the native VM when
        available, else the Python fallback reader (same per-schema
        memoization as ``api._host_reader``/``api._native_host_codec``)."""
        from ..api import _native_host_codec

        native = _native_host_codec(self.entry)
        if native is not None:
            return native.decode(data)
        from ..fallback.decoder import compile_reader, decode_to_record_batch

        reader = self.entry.get_extra(
            "host_reader", lambda: compile_reader(self.ir)
        )
        return decode_to_record_batch(
            data, self.ir, self.arrow_schema, reader
        )

    def _decode_triples(self, data: Sequence[bytes]):
        """One or more ``(host_columns, rows, meta)`` triples: large
        batches on the XLA pipeline run the double-buffered overlap
        path (pack+h2d of chunk N+1 concurrent with chunk N's launch —
        ISSUE 10, ``PYRUHVRO_TPU_OVERLAP`` / ``_OVERLAP_ROWS`` knobs);
        everything else stays single-launch."""
        dec = self.decoder
        if isinstance(dec, DeviceDecoder):
            k = overlap_chunks(len(data))
            if k > 1:
                return dec.decode_to_columns_overlapped(data, k)
        return [dec.decode_to_columns(data)]

    def decode(self, data: Sequence[bytes]) -> pa.RecordBatch:
        if len(data) == 0:
            # empty launch has no shapes to compile; build directly
            return self._host_decode([])
        try:
            triples = self._decode_triples(data)
        except BatchTooLarge:
            # one launch is bounded to 1 GiB of datum bytes (int32
            # cursors): recursively halve the batch — each half still
            # decodes on device — and concatenate the results, so the
            # public API never surfaces the launch-size limit
            if len(data) < 2:
                # one giant datum can't be split: serve it from the host
                # path like any other beyond-device-capacity batch
                return self._host_decode(data)
            mid = len(data) // 2
            left = self.decode(data[:mid])
            try:
                right = self.decode(data[mid:])
            except MalformedAvro as e:
                # the right half reports half-local row indices; re-base
                # so the public API always names the GLOBAL position
                raise shift_malformed(e, mid) from None
            return _concat_batches([left, right])
        except DeviceCapacityExceeded:
            # a batch whose per-record item counts exceed device capacity
            # is still valid Avro: serve it from the general path (the
            # same degradation the reference applies to unsupported
            # schemas, deserialize.rs:26-29 — here per batch)
            return self._host_decode(data)
        except UnsupportedOnDevice:
            # per-batch limits of an alternative walk (e.g. the Pallas
            # kernel's per-record tile budget): host path, silently
            return self._host_decode(data)
        except Exception as e:
            # a transient backend fault (wedged launch, injected chaos)
            # degrades THIS call to the host path and feeds the
            # device_backend breaker; data errors / deadlines propagate
            if not _degradable(e):
                raise
            _device_call_failed(e)
            return self._host_decode(data)
        from .arrow_build import build_record_batch

        _device_call_ok()
        batches = [
            build_record_batch(self.ir, self.arrow_schema, host, n, meta)
            for host, n, meta in triples
        ]
        return batches[0] if len(batches) == 1 else _concat_batches(batches)

    def _sharded_decoder(self):
        """The mesh-sharded decoder when >1 device is attached, else None
        (single chip: the fused single-launch path is already optimal)."""
        if self._sharded is None:
            if not isinstance(self.decoder, DeviceDecoder):
                # alternative walks (Pallas opt-in) run single-device
                self._sharded = False
                return None
            import jax

            devs = jax.devices()
            if len(devs) > 1:
                from ..parallel import ShardedDecoder

                self._sharded = ShardedDecoder(base=self.decoder,
                                               devices=devs)
            else:
                self._sharded = False
        return self._sharded or None

    def decode_threaded(self, data: Sequence[bytes],
                        num_chunks: int) -> List[pa.RecordBatch]:
        """Chunked decode → one RecordBatch per chunk (≙ the threaded
        entry, ``deserialize.rs:76-121``).

        With a multi-device mesh and ``num_chunks`` == mesh size, chunks
        map 1:1 onto devices in one sharded launch (the TPU-native
        analogue of one thread per chunk). Any other chunk count decodes
        once — sharded when possible — and slices the result, preserving
        the exact chunk boundaries of the reference."""
        from ..runtime.chunking import chunk_bounds

        bounds = chunk_bounds(len(data), num_chunks)
        sd = self._sharded_decoder() if len(data) else None
        if sd is not None:
            try:
                batches = sd.decode(data, self.ir, self.arrow_schema)
            except BatchTooLarge:
                batches = None  # per-shard byte budget blown: split below
            except DeviceCapacityExceeded:
                from ..runtime.pool import map_chunks

                return map_chunks(
                    lambda ab: self._host_decode(data[ab[0]:ab[1]]), bounds
                )
            except Exception as e:
                if not _degradable(e):
                    raise
                # sharded launch fault: fall through to the single-chip
                # fused path (which carries its own host fallback)
                _device_call_failed(e)
                batches = None
            if batches is not None:
                if len(batches) == len(bounds):
                    # mesh shards used reference slicing too → exact match
                    return batches
                whole = _concat_batches(batches)
                return [
                    compact_union_slices(whole.slice(a, b - a))
                    for a, b in bounds
                ]
        batch = self.decode(data)
        return [
            compact_union_slices(batch.slice(a, b - a)) for a, b in bounds
        ]

    def encode_threaded(self, batch: pa.RecordBatch,
                        num_chunks: int) -> List[pa.Array]:
        """Encode the WHOLE batch in one launch and slice the resulting
        BinaryArray per chunk — one compile per shape bucket and one
        device round trip regardless of the chunk count (mirrors
        ``decode_threaded``; encoding each chunk slice separately would
        re-bucket every slice into its own shape → compile, VERDICT r03
        weakness 2). ≙ ``serialize.rs:38-66``'s one-pass-then-slice."""
        from ..runtime.chunking import chunk_bounds

        bounds = chunk_bounds(batch.num_rows, num_chunks)
        arr = self.encode(batch)
        return [arr.slice(a, b - a) for a, b in bounds]

    def encode(self, batch: pa.RecordBatch) -> pa.Array:
        if self._encoder is None:
            from .encode import DeviceEncoder

            try:
                self._encoder = DeviceEncoder(
                    self.ir, self.arrow_schema,
                    fingerprint=self.entry.fingerprint,
                )
            except UnsupportedOnDevice:
                # encode subset narrower than decode's for this schema:
                # serve serialize from the host path (silent fallback,
                # ≙ serialize.rs:53-56)
                self._encoder = False
        if self._encoder is False:
            return self._host_encode(batch)
        try:
            out = self._encoder.encode(batch)
            _device_call_ok()
            return out
        except BatchTooLarge:
            # output would blow the 2^30-byte launch budget: halve the
            # batch (still on device), or for one giant row go host
            if batch.num_rows < 2:
                return self._host_encode(batch)
            mid = batch.num_rows // 2
            try:
                return pa.concat_arrays([
                    self.encode(batch.slice(0, mid)),
                    self.encode(batch.slice(mid)),
                ])
            except pa.lib.ArrowInvalid:
                # halves fit individually but their concatenation blows
                # int32 offsets (≙ hostpath _encode_split)
                raise BatchTooLarge(batch.num_rows, -1) from None
        except Exception as e:
            # same degradation contract as decode(): backend faults go
            # host-side and feed the breaker; value errors (the
            # tolerant-encode bisect relies on them), capacity and
            # deadline expiry propagate
            if not _degradable(e):
                raise
            _device_call_failed(e)
            return self._host_encode(batch)

    def _host_encode(self, batch: pa.RecordBatch) -> pa.Array:
        """Host-path encode for schemas/batches the device encoder hands
        back: the native VM when available (mirrors ``_host_decode`` —
        the widened device-decode subset routes schemas here whose
        serialize previously never built a codec, and they must keep
        their native-VM speed), else the Python fallback encoder."""
        from ..api import _native_host_codec

        native = _native_host_codec(self.entry)
        if native is not None:
            from .decode import BatchTooLarge as _BTL

            try:
                return native.encode(batch)
            except _BTL:
                if batch.num_rows < 2:
                    # a single record that alone blows int32 offsets
                    # cannot be split, and the interpreted fallback
                    # below cannot represent it either — surface the
                    # library's BatchTooLarge contract instead of
                    # burning time on a doomed pyarrow build (ADVICE r04)
                    raise
                mid = batch.num_rows // 2
                try:
                    return pa.concat_arrays([
                        self._host_encode(batch.slice(0, mid)),
                        self._host_encode(batch.slice(mid)),
                    ])
                except pa.lib.ArrowInvalid:
                    # halves fit individually but their concatenation
                    # blows int32 offsets: no split can make this batch
                    # one BinaryArray (≙ hostpath _encode_split)
                    raise BatchTooLarge(batch.num_rows, -1) from None
        from ..fallback.encoder import (
            compile_encoder_plan,
            encode_record_batch,
        )

        plan = self.entry.get_extra(
            "host_encode_plan", lambda: compile_encoder_plan(self.ir)
        )
        return pa.array(
            encode_record_batch(batch, self.ir, plan), pa.binary()
        )


def _concat_batches(batches: List[pa.RecordBatch]) -> pa.RecordBatch:
    """Concatenate RecordBatches into one (pyarrow-version tolerant)."""
    if hasattr(pa, "concat_batches"):
        return pa.concat_batches(batches)
    table = pa.Table.from_batches(batches).combine_chunks()
    out = table.to_batches()
    return out[0] if out else batches[0]


def _pallas_mode() -> str:
    """Normalize PYRUHVRO_TPU_PALLAS to its three semantic states:
    ``"mosaic"`` ("1"/"true" — compiled kernel), ``"interpret"``, or
    ``"off"`` (anything else, incl. the conventional "0")."""
    from ..runtime import knobs

    raw = knobs.get_raw("PYRUHVRO_TPU_PALLAS").lower()
    if raw in ("1", "true", "mosaic"):
        return "mosaic"
    if raw == "interpret":
        return "interpret"
    return "off"


def get_device_codec(entry: SchemaEntry) -> DeviceCodec:
    """Memoized per-schema codec (≙ ``get_or_parse_schema`` + the Arc-shared
    Arrow schema, ``src/lib.rs:44``/``deserialize.rs:85-89``).

    The (normalized) PYRUHVRO_TPU_PALLAS mode is part of the memo key:
    toggling the flag between calls must yield a codec honoring the new
    value, not silently return the first-built one (ADVICE r04). The
    mode is read ONCE here and passed down, so the cached codec always
    matches its key even if the env mutates mid-construction."""
    mode = _pallas_mode()
    return entry.get_extra(
        f"device_codec:pallas={mode}",
        lambda: DeviceCodec(entry, pallas=mode),
    )
