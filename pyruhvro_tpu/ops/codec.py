"""Device codec: the object ``api.py`` routes to behind the fast gate.

``get_device_codec(entry)`` is the TPU analogue of the reference's gate
target (``fast_decode::decode_with_arrow_schema``,
``ruhvro/src/fast_decode.rs:815``): construction performs the one-time
schema lowering + backend probe, memoized on the ``SchemaEntry`` so a
schema string maps to its compiled kernels for the process lifetime
(≙ the schema cache + shared-Arc amortization, ``src/lib.rs:35-54``,
``deserialize.rs:83-89``).

Raises :class:`UnsupportedOnDevice` for schemas outside the device
subset (silent host fallback in ``backend='auto'``, like
``deserialize.rs:26-29``); any other exception means the backend itself
is broken and is surfaced by ``api.py``.
"""

from __future__ import annotations

from typing import List, Sequence

import pyarrow as pa

from ..schema.cache import SchemaEntry
from . import UnsupportedOnDevice
from .decode import DeviceDecoder

__all__ = ["DeviceCodec", "get_device_codec"]

_PROBE_TIMEOUT_S = float(__import__("os").environ.get(
    "PYRUHVRO_TPU_PROBE_TIMEOUT", "60"))
_probe_result: list = []  # memoized: [devices] or [exception]


def _probe_backend() -> None:
    """Initialize the JAX backend once, with a timeout.

    Backend init can hang (not fail) when a device transport is wedged;
    running it on a watchdog thread turns that hang into a RuntimeError so
    ``backend='auto'`` degrades to the host path with a warning instead of
    blocking the caller indefinitely."""
    import threading

    if _probe_result:
        out = _probe_result[0]
        if isinstance(out, BaseException):
            raise RuntimeError(f"JAX backend unavailable: {out!r}") from out
        return

    def run():
        try:
            import jax

            _probe_result.append(jax.devices())
        except BaseException as e:  # noqa: BLE001 — reported to caller
            _probe_result.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(_PROBE_TIMEOUT_S)
    if not _probe_result:
        e = TimeoutError(
            f"JAX backend initialization did not finish within "
            f"{_PROBE_TIMEOUT_S:.0f}s (wedged device transport?)"
        )
        _probe_result.append(e)
    out = _probe_result[0]
    if isinstance(out, BaseException):
        raise RuntimeError(f"JAX backend unavailable: {out!r}") from out


class DeviceCodec:
    """Schema-bound decode/encode pipelines on the default JAX backend."""

    def __init__(self, entry: SchemaEntry):
        self.entry = entry
        self.ir = entry.ir
        self.arrow_schema = entry.arrow_schema
        self.decoder = DeviceDecoder(entry.ir)
        self._encoder = None
        # probe the backend now: a missing/broken device must fail at
        # construction (where api.py distinguishes it from unsupported
        # schemas), not on the first decode call. The probe is
        # time-bounded: a wedged device transport must degrade to the
        # host path, not hang every backend='auto' caller forever.
        _probe_backend()

    def decode(self, data: Sequence[bytes]) -> pa.RecordBatch:
        if len(data) == 0:
            # empty launch has no shapes to compile; build directly
            from ..fallback.decoder import decode_to_record_batch

            return decode_to_record_batch([], self.ir, self.arrow_schema)
        from .decode import DeviceCapacityExceeded

        try:
            host, n, meta = self.decoder.decode_to_columns(data)
        except DeviceCapacityExceeded:
            # a batch whose per-record item counts exceed device capacity
            # is still valid Avro: serve it from the general path (the
            # same degradation the reference applies to unsupported
            # schemas, deserialize.rs:26-29 — here per batch)
            from ..fallback.decoder import decode_to_record_batch

            return decode_to_record_batch(data, self.ir, self.arrow_schema)
        from .arrow_build import build_record_batch

        return build_record_batch(self.ir, self.arrow_schema, host, n, meta)

    def encode(self, batch: pa.RecordBatch) -> pa.Array:
        if self._encoder is None:
            from .encode import DeviceEncoder

            self._encoder = DeviceEncoder(self.ir, self.arrow_schema)
        return self._encoder.encode(batch)


def get_device_codec(entry: SchemaEntry) -> DeviceCodec:
    """Memoized per-schema codec (≙ ``get_or_parse_schema`` + the Arc-shared
    Arrow schema, ``src/lib.rs:44``/``deserialize.rs:85-89``)."""
    return entry.get_extra("device_codec", lambda: DeviceCodec(entry))
