"""Vectorized Avro wire-format primitives (JAX, int32-native).

These are the TPU-first building blocks of the decode kernel: every
helper operates on **vectors of per-record cursors** — one lane per
record — so the inherently sequential byte walk of a single Avro datum
(≙ ``read_zigzag_long`` ``ruhvro/src/fast_decode.rs:855-869``) becomes a
data-parallel sweep across all records at once.

Design rules (see /opt/skills/guides/pallas_guide.md and SURVEY.md §7):

* All arithmetic is 32-bit. The TPU VPU lane is 32 bits wide and int64
  is emulated; 64-bit quantities (Avro ``long``, ``double``) are carried
  as ``(lo, hi)`` uint32 pairs and recombined on the host (a free numpy
  ``view``), never on device.
* The byte stream is stored as little-endian uint32 **words**; a byte
  load is a word gather + shift, so XLA moves 32-bit lanes, not bytes.
* Every reader takes a ``mask`` lane vector and advances cursors only
  where the lane is active — masking is how nullable branches, union
  arms, and array-block loops compose without divergence.
* Reads never fault: gathers are clipped to the buffer; malformed input
  surfaces as per-lane error bits checked on the host afterwards.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = [
    "U32",
    "get_byte",
    "load_window",
    "read_varint64",
    "read_varint32",
    "zigzag_decode_pair",
    "read_f32",
    "read_f64_pair",
    "read_bool_byte",
    "ERR_VARINT",
    "ERR_NEG_LEN",
    "ERR_OVERRUN",
    "ERR_BAD_BRANCH",
    "ERR_BAD_ENUM",
    "ERR_TRAILING",
    "ERR_BAD_BOOL",
    "ERR_ITEM_OVERFLOW",
    "ERR_NAMES",
    "ERR_SLUGS",
]

U32 = jnp.uint32

# per-lane error bits, OR-accumulated during the walk and checked on host
ERR_VARINT = 1 << 0        # varint longer than the wire maximum (10 bytes)
ERR_NEG_LEN = 1 << 1       # negative string/bytes length
ERR_OVERRUN = 1 << 2       # cursor ran past the record's end
ERR_BAD_BRANCH = 1 << 3    # union branch index out of range
ERR_BAD_ENUM = 1 << 4      # enum index out of range
ERR_TRAILING = 1 << 5      # datum not fully consumed (trailing bytes)
ERR_BAD_BOOL = 1 << 6      # boolean byte not 0/1
ERR_ITEM_OVERFLOW = 1 << 7 # array/map items exceeded the slot cap (retry)
ERR_DEC_RANGE = 1 << 8     # decimal outside decimal128 (host VM only)

ERR_NAMES = {
    ERR_VARINT: "varint longer than 10 bytes",
    ERR_NEG_LEN: "negative string/bytes length",
    ERR_OVERRUN: "value runs past end of datum",
    ERR_BAD_BRANCH: "union branch index out of range",
    ERR_BAD_ENUM: "enum index out of range",
    ERR_TRAILING: "trailing bytes after datum",
    ERR_BAD_BOOL: "invalid boolean byte",
    ERR_ITEM_OVERFLOW: "array/map item capacity overflow",
    ERR_DEC_RANGE: "decimal outside decimal128 range",
}

# short machine-stable slugs for the quarantine channel
# (decode.quarantine.<slug> counters + QuarantinedRecord.error); the
# fallback tier's raise sites use the same names
ERR_SLUGS = {
    ERR_VARINT: "varint",
    ERR_NEG_LEN: "neg_len",
    ERR_OVERRUN: "overrun",
    ERR_BAD_BRANCH: "bad_branch",
    ERR_BAD_ENUM: "bad_enum",
    ERR_TRAILING: "trailing",
    ERR_BAD_BOOL: "bad_bool",
    ERR_ITEM_OVERFLOW: "item_overflow",
    ERR_DEC_RANGE: "dec_range",
}


def _take_words(words, widx):
    """One word per lane at word index ``widx`` (clip semantics).

    ``words`` is either a flat u32 array (XLA pipeline: one gather) or
    any object exposing ``take_words(widx)`` — the seam that lets the
    SAME field program run inside a Pallas kernel, where the word source
    is a VMEM-resident record tile read without a gather
    (``ops/pallas_decode.py``)."""
    take = getattr(words, "take_words", None)
    if take is not None:
        return take(widx)
    return jnp.take(words, widx, mode="clip")


def get_byte(words, idx: jnp.ndarray) -> jnp.ndarray:
    """Byte ``idx`` of the little-endian u32-word buffer, as uint32 lanes.

    Out-of-range indices clip to the last word (callers mask the result);
    negative clip to 0.
    """
    w = _take_words(words, lax.shift_right_logical(idx, 2))
    shift = (jnp.bitwise_and(idx, 3) << 3).astype(U32)
    return jnp.bitwise_and(lax.shift_right_logical(w, shift), U32(0xFF))


def load_window(words, cursor, nwords: int):
    """Gather ``nwords`` consecutive u32 words at ``cursor``'s word and
    funnel-shift them into ``nwords - 1`` words whose byte 0 IS the byte at
    ``cursor``. One gather per word; everything after is register ALU —
    this keeps the XLA gather chain short, which dominates both compile
    time and TPU issue rate (the VPU moves 32-bit lanes, never bytes).
    """
    wbase = lax.shift_right_logical(cursor, 2)
    win = [_take_words(words, wbase + k) for k in range(nwords)]
    a = (jnp.bitwise_and(cursor, 3) << 3).astype(U32)  # bit offset 0/8/16/24
    nz = a != U32(0)
    inv = (U32(32) - a) & U32(31)
    out = []
    for k in range(nwords - 1):
        hi = jnp.where(nz, win[k + 1] << inv, U32(0))
        out.append(lax.shift_right_logical(win[k], a) | hi)
    return out


def _window_byte(aligned, k: int):
    """Byte ``k`` (static) of the funnel-aligned window."""
    return jnp.bitwise_and(
        lax.shift_right_logical(aligned[k >> 2], U32((k & 3) * 8)), U32(0xFF)
    )


def _read_varint(words, cursor, mask, max_bytes: int):
    aligned = load_window(words, cursor, (max_bytes + 3) // 4 + 1)
    lo = jnp.zeros_like(cursor, dtype=U32)
    hi = jnp.zeros_like(cursor, dtype=U32)
    more = mask
    nbytes = jnp.zeros_like(cursor)
    for k in range(max_bytes):
        b = _window_byte(aligned, k)
        g = jnp.bitwise_and(b, U32(0x7F))
        s = 7 * k
        if s < 32:
            lo = lo | jnp.where(more, g << s, U32(0))
            if s + 7 > 32:  # the straddling group (k=4, bits 28..34)
                hi = hi | jnp.where(
                    more, lax.shift_right_logical(g, U32(32 - s)), U32(0)
                )
        else:
            hi = hi | jnp.where(more, g << (s - 32), U32(0))
        nbytes = nbytes + more.astype(cursor.dtype)
        more = more & (b >= U32(0x80))
    err = jnp.where(more, jnp.uint32(ERR_VARINT), jnp.uint32(0))
    return lo, hi, cursor + nbytes, err


def read_varint64(words, cursor, mask):
    """Read one unsigned LEB128 varint (≤10 bytes) per active lane.

    Returns ``(lo u32, hi u32, new_cursor i32, err u32)``; cursors advance
    only where ``mask``. ≙ the byte loop of ``fast_decode.rs:855-869``,
    unrolled to the wire format's static 10-byte maximum (4 word gathers).
    """
    return _read_varint(words, cursor, mask, 10)


# Varint for quantities that must fit 32 bits after decode — union
# branches, enum indices, string lengths, array/map block counts. The
# full 10-byte wire maximum is read, exactly like the host path's
# ``read_long`` (and the reference's ``read_zigzag_long``,
# ``fast_decode.rs:855-869``), so legal-but-non-minimal LEB128 encodings
# (zero-padded small values) decode instead of erroring; out-of-range
# *values* are rejected by each caller's ``hi``-word check. Deliberately
# the same reader as read_varint64 — the distinct name marks call sites
# whose callers enforce a 32-bit range.
read_varint32 = read_varint64


def zigzag_decode_pair(lo, hi):
    """Zig-zag decode a u32 pair: ``(n >> 1) ^ -(n & 1)`` in 64-bit
    two's-complement carried as two u32 words (≙ ``fast_decode.rs:867``)."""
    sign = jnp.bitwise_and(lo, U32(1))
    lo1 = lax.shift_right_logical(lo, U32(1)) | (hi << 31)
    hi1 = lax.shift_right_logical(hi, U32(1))
    m = jnp.zeros_like(lo) - sign  # 0x00000000 or 0xFFFFFFFF
    return lo1 ^ m, hi1 ^ m


def read_f32(words, cursor, mask):
    """IEEE-754 float32, little-endian (≙ ``read_f32`` ``fast_decode.rs:872``):
    one funnel-aligned word, bitcast."""
    (v,) = load_window(words, cursor, 2)
    return (
        lax.bitcast_convert_type(v, jnp.float32),
        cursor + jnp.where(mask, 4, 0),
    )


def read_f64_pair(words, cursor, mask):
    """IEEE-754 float64 as a (lo, hi) u32 pair — recombined and bitcast on
    the host (≙ ``read_f64`` ``fast_decode.rs:882``)."""
    lo, hi = load_window(words, cursor, 3)
    return lo, hi, cursor + jnp.where(mask, 8, 0)


def read_bool_byte(words, cursor, mask):
    """One boolean byte; bytes >1 set ERR_BAD_BOOL
    (≙ ``read_bool`` ``fast_decode.rs:893-900``)."""
    b = get_byte(words, cursor)
    err = jnp.where(mask & (b > U32(1)), jnp.uint32(ERR_BAD_BOOL), jnp.uint32(0))
    return b.astype(jnp.uint8), cursor + jnp.where(mask, 1, 0), err
