"""Device decode outputs → ``pyarrow`` arrays (host assembly).

The finalize pass returns dense numpy-compatible buffers in Arrow's own
layout — int32 offsets, contiguous value bytes, per-lane validity bytes,
strided 64-bit halves — so assembly is ``pa.Array.from_buffers`` over
zero-copy views plus three cheap vectorized host ops the device should
not do: recombining (lo, hi) u32 pairs into int64/float64 (a numpy
``view``), bit-packing validity/boolean bytes (``np.packbits``), and
expanding enum indices through the symbol table. This replaces the
reference's Arrow C-data FFI handoff (``src/lib.rs:70,88,104``) — same
boundary, columnar buffers instead of builder objects.

Null semantics mirror the fallback oracle exactly (and through it the
reference): children under a null struct are null, non-selected sparse
union children are null (``fast_decode.rs:643-668``), and a null parent
forces nulls all the way down — implemented by threading ``parent_valid``
through the recursion.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pyarrow as pa

from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)

__all__ = ["build_record_batch"]


def _validity(valid: Optional[np.ndarray], count: int):
    """(buffer, null_count) for an optional boolean lane vector."""
    if valid is None:
        return None, 0
    nulls = count - int(valid.sum())
    if nulls == 0:
        return None, 0
    return pa.py_buffer(np.packbits(valid, bitorder="little")), nulls


def _and(a: Optional[np.ndarray], b: Optional[np.ndarray]):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _check_utf8(values: np.ndarray, voff: np.ndarray, path: str) -> None:
    """Validate a whole string column's bytes in one pass, matching the
    host oracle (which raises :class:`MalformedAvro` on invalid UTF-8 in
    ``fallback/decoder.py``). The reference deliberately skips this check
    (``fast_decode.rs:914-921``); we keep the device path byte-for-byte
    equal to our own fallback instead — the differential contract wins.

    Cost: the overwhelmingly common all-ASCII column is settled by one
    vectorized ``max`` (SIMD, ~memory speed); only columns containing
    high bytes pay the real decode. Per-string validity follows from two
    whole-column facts: (a) the concatenation decodes as UTF-8, and
    (b) no string starts on a continuation byte (0x80–0xBF). Any string
    boundary that splits a multi-byte sequence makes the next string
    start on a continuation byte, and a dangling lead byte at a string's
    end makes the concatenation invalid — so (a) ∧ (b) ⟺ every string
    is valid."""
    if values.size == 0 or int(values.max(initial=0)) < 0x80:
        return  # pure ASCII — necessarily valid, and start-bytes too
    try:
        values.tobytes().decode("utf-8")
    except UnicodeDecodeError as e:
        from ..fallback.io import MalformedAvro

        raise MalformedAvro(f"invalid UTF-8 in string column {path!r}: {e}")
    firsts = values[voff[:-1][voff[:-1] < voff[1:]].astype(np.int64)]
    if firsts.size and bool(((firsts & 0xC0) == 0x80).any()):
        from ..fallback.io import MalformedAvro

        raise MalformedAvro(
            f"invalid UTF-8 in string column {path!r}: string begins on a "
            f"continuation byte"
        )


def _combine64(lo: np.ndarray, hi: np.ndarray, view) -> np.ndarray:
    out = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    return out.view(view)


class _Assembler:
    def __init__(self, host: Dict[str, np.ndarray], meta):
        self.host = host
        self.item_totals = meta["item_totals"]
        self.flat = meta["flat"]  # original datum bytes (value-gather source)

    def col(self, key: str, count: int) -> np.ndarray:
        return np.ascontiguousarray(self.host[key][:count])

    def build(
        self,
        t: AvroType,
        dt: pa.DataType,
        path: str,
        count: int,
        parent_valid: Optional[np.ndarray],
    ) -> pa.Array:
        if isinstance(t, Union) and t.is_nullable_pair:
            own = self.host[path + "#valid"][:count].astype(bool)
            return self.build(
                t.non_null_variant, dt, path, count, _and(parent_valid, own)
            )

        if isinstance(t, Primitive):
            return self._primitive(t, dt, path, count, parent_valid)
        if isinstance(t, Fixed):
            return self._fixed(t, dt, path, count, parent_valid)
        if isinstance(t, Enum):
            return self._enum(t, path, count, parent_valid)
        if isinstance(t, Record):
            return self._struct(t, dt, path, count, parent_valid)
        if isinstance(t, Union):
            return self._union(t, dt, path, count, parent_valid)
        if isinstance(t, (Array, Map)):
            return self._repeated(t, dt, path, count, parent_valid)
        raise NotImplementedError(repr(t))

    def _primitive(self, t, dt, path, count, valid):
        vbuf, nulls = _validity(valid, count)
        name = t.name
        if name == "null":
            return pa.nulls(count, pa.null())
        if name == "bytes" and t.logical == "decimal":
            return self._decimal(dt, path, count, vbuf, nulls)
        if name in ("string", "bytes"):
            lens = self.host[path + "#len"][:count]
            total = int(lens.sum(dtype=np.int64))
            if total >= (1 << 31):
                # int32 offsets would wrap; the oracle's pa.array raises
                # the same error class here
                raise pa.lib.ArrowCapacityError(
                    f"column {path!r} carries {total} value bytes — over "
                    f"the 2 GiB Binary/Utf8 capacity; split the batch"
                )
            voff = np.zeros(count + 1, np.int32)
            np.cumsum(lens, out=voff[1:])
            if path + "#bytes" in self.host:
                # the native host VM copies value bytes contiguously
                # during its walk; use them directly
                values = self.host[path + "#bytes"][:total]
            else:
                # device walk ships (start, len) only: values are
                # gathered here, on the host, from the original datum
                # bytes — they never cross the device interconnect
                starts = self.host[path + "#start"][:count]
                src = np.repeat(
                    starts.astype(np.int64) - voff[:-1], lens
                ) + np.arange(total, dtype=np.int64)
                values = self.flat[src]
            if name == "string":
                _check_utf8(values, voff, path)
            return pa.Array.from_buffers(
                dt, count,
                [vbuf, pa.py_buffer(voff), pa.py_buffer(values)],
                null_count=nulls,
            )
        if name == "boolean":
            bits = np.packbits(
                self.col(path + "#v", count).astype(bool), bitorder="little"
            )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(bits)], null_count=nulls
            )
        if name == "int":
            arr = self.col(path + "#v", count)
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        if name == "long":
            # device walk ships (lo, hi) u32 lanes; the native host VM
            # writes int64 directly under "#v64"
            if path + "#v64" in self.host:
                arr = self.col(path + "#v64", count)
            else:
                arr = _combine64(
                    self.col(path + "#lo", count),
                    self.col(path + "#hi", count),
                    np.int64,
                )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        if name == "float":
            arr = self.col(path + "#v", count)
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        if name == "double":
            if path + "#v64" in self.host:
                arr = self.col(path + "#v64", count)
            else:
                arr = _combine64(
                    self.col(path + "#lo", count),
                    self.col(path + "#hi", count),
                    np.float64,
                )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        raise NotImplementedError(name)

    def _decimal(self, dt, path, count, vbuf, nulls):
        """Decimal128 from the host VM's 16-byte-LE #dec words (the
        exact Arrow decimal128 buffer layout)."""
        raw = np.ascontiguousarray(self.host[path + "#dec"][: count * 16])
        return pa.Array.from_buffers(
            dt, count, [vbuf, pa.py_buffer(raw)], null_count=nulls
        )

    def _fixed(self, t, dt, path, count, valid):
        """Avro ``fixed`` from the host VM's raw #fix byte column;
        ``duration`` converts fixed(12) (months, days, ms u32-LE) to
        Duration(ms) with the oracle's 30-day-month convention
        (``fallback/decoder.py``)."""
        vbuf, nulls = _validity(valid, count)
        if t.logical == "decimal":
            return self._decimal(dt, path, count, vbuf, nulls)
        raw = self.host[path + "#fix"][: count * t.size]
        if t.logical == "duration":
            u = np.ascontiguousarray(raw).view(np.uint32).reshape(count, 3)
            # uint64 holds the wire maximum ((2^32·30 + 2^32)·86400000 +
            # 2^32 < 2^64); values past int64 overflow Duration(ms) like
            # the oracle's pa.array does
            ms = (
                (u[:, 0].astype(np.uint64) * 30 + u[:, 1]) * 86_400_000
                + u[:, 2]
            )
            if bool((ms > np.uint64(np.iinfo(np.int64).max)).any()):
                raise OverflowError(
                    f"duration at {path!r} exceeds Duration(ms) int64"
                )
            return pa.Array.from_buffers(
                dt, count,
                [vbuf, pa.py_buffer(ms.astype(np.int64))],
                null_count=nulls,
            )
        return pa.Array.from_buffers(
            dt, count,
            [vbuf, pa.py_buffer(np.ascontiguousarray(raw))],
            null_count=nulls,
        )

    def _enum(self, t, path, count, valid):
        """Enum indices → Utf8 through the symbol table, vectorized."""
        vbuf, nulls = _validity(valid, count)
        idx = self.col(path + "#v", count)
        sym_bytes = np.frombuffer("".join(t.symbols).encode("utf-8"), np.uint8)
        sym_lens = np.array([len(s.encode("utf-8")) for s in t.symbols], np.int32)
        sym_starts = np.zeros(len(t.symbols), np.int32)
        np.cumsum(sym_lens[:-1], out=sym_starts[1:])
        lens = sym_lens[idx]
        offsets = np.zeros(count + 1, np.int32)
        np.cumsum(lens, out=offsets[1:])
        total = int(offsets[count])
        pos = np.arange(total, dtype=np.int64) - np.repeat(offsets[:-1], lens)
        src = np.repeat(sym_starts[idx], lens) + pos
        values = sym_bytes[src]
        return pa.Array.from_buffers(
            pa.utf8(), count,
            [vbuf, pa.py_buffer(offsets), pa.py_buffer(values)],
            null_count=nulls,
        )

    def _struct(self, t, dt, path, count, valid):
        vbuf, nulls = _validity(valid, count)
        prefix = path + "/" if path else ""
        children = [
            self.build(f.type, dt.field(i).type, prefix + f.name, count, valid)
            for i, f in enumerate(t.fields)
        ]
        return pa.Array.from_buffers(
            dt, count, [vbuf], null_count=nulls, children=children
        )

    def _union(self, t, dt, path, count, parent_valid):
        tid = self.col(path + "#tid", count)
        if parent_valid is not None:
            # a null parent renders as branch 0 + null child, like the oracle
            tid = np.where(parent_valid, tid, 0).astype(tid.dtype)
        children = []
        names = []
        for k, v in enumerate(t.variants):
            child_field = dt.field(k)
            names.append(child_field.name)
            sel = _and(parent_valid, tid == k)
            if v.is_null():
                children.append(pa.nulls(count, pa.null()))
            else:
                children.append(
                    self.build(v, child_field.type, f"{path}/{k}", count, sel)
                )
        return pa.UnionArray.from_sparse(
            pa.array(tid.astype(np.int8), pa.int8()),
            children,
            field_names=names,
            type_codes=list(dt.type_codes),
        )

    def _repeated(self, t, dt, path, count, valid):
        vbuf, nulls = _validity(valid, count)
        offsets = self.col(path + "#offsets", count + 1)
        total = self.item_totals[path]
        if isinstance(t, Array):
            child = self.build(
                t.items, dt.value_field.type, path + "/@item", total, None
            )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(offsets)],
                null_count=nulls, children=[child],
            )
        keys = self._primitive(
            Primitive("string"), pa.utf8(), path + "/@key", total, None
        )
        vals = self.build(t.values, dt.item_type, path + "/@val", total, None)
        entries = pa.StructArray.from_arrays(
            [keys, vals], fields=[dt.key_field, dt.item_field]
        )
        return pa.Array.from_buffers(
            dt, count, [vbuf, pa.py_buffer(offsets)],
            null_count=nulls, children=[entries],
        )


def build_record_batch(
    ir: Record,
    arrow_schema: pa.Schema,
    host: Dict[str, np.ndarray],
    n: int,
    meta,
) -> pa.RecordBatch:
    asm = _Assembler(host, meta)
    arrays = [
        asm.build(f.type, arrow_schema.field(i).type, f.name, n, None)
        for i, f in enumerate(ir.fields)
    ]
    if not arrays:
        return pa.RecordBatch.from_struct_array(
            pa.array([{}] * n, pa.struct([]))
        )
    return pa.RecordBatch.from_arrays(arrays, schema=arrow_schema)
