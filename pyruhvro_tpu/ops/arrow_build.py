"""Device decode outputs → ``pyarrow`` arrays (host assembly).

The finalize pass returns dense numpy-compatible buffers in Arrow's own
layout — int32 offsets, contiguous value bytes, per-lane validity bytes,
strided 64-bit halves — so assembly is ``pa.Array.from_buffers`` over
zero-copy views plus three cheap vectorized host ops the device should
not do: recombining (lo, hi) u32 pairs into int64/float64 (a numpy
``view``), bit-packing validity/boolean bytes (``np.packbits``), and
expanding enum indices through the symbol table. This replaces the
reference's Arrow C-data FFI handoff (``src/lib.rs:70,88,104``) — same
boundary, columnar buffers instead of builder objects.

Null semantics mirror the fallback oracle exactly (and through it the
reference): children under a null struct are null, non-selected sparse
union children are null (``fast_decode.rs:643-668``), and a null parent
forces nulls all the way down — implemented by threading ``parent_valid``
through the recursion.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import pyarrow as pa

from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)

__all__ = [
    "build_record_batch",
    "build_fused_record_batch",
    "compact_union_slices",
]


def _contains_union(dt: pa.DataType) -> bool:
    if pa.types.is_union(dt):
        return True
    if pa.types.is_struct(dt) or pa.types.is_map(dt):
        return any(_contains_union(dt.field(i).type)
                   for i in range(dt.num_fields))
    if pa.types.is_list(dt) or pa.types.is_large_list(dt):
        return _contains_union(dt.value_type)
    return False


def compact_union_slices(batch: pa.RecordBatch) -> pa.RecordBatch:
    """Repair a SLICED batch whose columns contain sparse unions:
    pyarrow's scalar access mis-reads a sparse union reached through a
    non-zero offset when its children hold validity bitmaps
    (``to_pylist``/``as_py`` return null for every row — reproducible on
    a pure ``pa.UnionArray.from_sparse(...).slice(...)`` with pyarrow
    22, and equally through a sliced struct PARENT, where the offset
    lives on the struct and the union child still mis-resolves).
    ``pa.concat_arrays`` of the single slice compacts it back to offset
    0 — children included — copying only the union-bearing columns;
    every other column stays the zero-copy slice. A batch with no
    union-bearing columns (or no offset) is returned untouched — this
    keeps the reference's slice-per-chunk shape (``deserialize.rs:57-68``)
    while making the returned chunks render correctly."""
    if not any(_contains_union(f.type) for f in batch.schema):
        return batch
    cols = [
        pa.concat_arrays([c]) if _contains_union(c.type) and c.offset
        else c
        for c in batch.columns
    ]
    return pa.RecordBatch.from_arrays(cols, schema=batch.schema)


def _validity(valid: Optional[np.ndarray], count: int):
    """(buffer, null_count) for an optional boolean lane vector."""
    if valid is None:
        return None, 0
    nulls = count - int(np.count_nonzero(valid))
    if nulls == 0:
        return None, 0
    return pa.py_buffer(np.packbits(valid, bitorder="little")), nulls


def _and(a: Optional[np.ndarray], b: Optional[np.ndarray]):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _check_utf8(values: np.ndarray, voff: np.ndarray, path: str) -> None:
    """Validate a whole string column's bytes in one pass, matching the
    host oracle (which raises :class:`MalformedAvro` on invalid UTF-8 in
    ``fallback/decoder.py``). The reference deliberately skips this check
    (``fast_decode.rs:914-921``); we keep the device path byte-for-byte
    equal to our own fallback instead — the differential contract wins.

    Cost: the overwhelmingly common all-ASCII column is settled by one
    vectorized ``max`` (SIMD, ~memory speed); only columns containing
    high bytes pay the real decode. Per-string validity follows from two
    whole-column facts: (a) the concatenation decodes as UTF-8, and
    (b) no string starts on a continuation byte (0x80–0xBF). Any string
    boundary that splits a multi-byte sequence makes the next string
    start on a continuation byte, and a dangling lead byte at a string's
    end makes the concatenation invalid — so (a) ∧ (b) ⟺ every string
    is valid."""
    if values.size == 0 or int(values.max(initial=0)) < 0x80:
        return  # pure ASCII — necessarily valid, and start-bytes too
    try:
        values.tobytes().decode("utf-8")
    except UnicodeDecodeError as e:
        from ..fallback.io import MalformedAvro

        raise MalformedAvro(f"invalid UTF-8 in string column {path!r}: {e}")
    firsts = values[voff[:-1][voff[:-1] < voff[1:]].astype(np.int64)]
    if firsts.size and bool(((firsts & 0xC0) == 0x80).any()):
        from ..fallback.io import MalformedAvro

        raise MalformedAvro(
            f"invalid UTF-8 in string column {path!r}: string begins on a "
            f"continuation byte"
        )


def _combine64(lo: np.ndarray, hi: np.ndarray, view) -> np.ndarray:
    out = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    return out.view(view)


# uuid text positions that carry hex nibbles (dashes at 8/13/18/23)
_UUID_KEEP = np.delete(np.arange(36), [8, 13, 18, 23])


def _native_mod(symbol: str):
    from ..runtime.native.build import loaded_host_codec_with

    return loaded_host_codec_with(symbol)


def _native_cumsum():
    return _native_mod("cumsum0")


def cumsum0(lens: np.ndarray) -> np.ndarray:
    """Arrow offsets (leading 0) from an int32 length vector.

    Prefix sums are inherently sequential — numpy's scalar loop costs
    ~3 ns/element — so the native module's C version is used when it is
    ALREADY loaded (never triggering a JIT g++ build from the assembly
    hot path — a device-only process may legitimately have no .so).
    Callers guard the int32 total themselves; the native path would
    raise OverflowError, the numpy path would wrap."""
    mod = _native_cumsum()
    if mod is not None:
        return np.frombuffer(
            mod.cumsum0(np.ascontiguousarray(lens, np.int32)), np.int32
        )
    voff = np.zeros(len(lens) + 1, np.int32)
    np.cumsum(lens, out=voff[1:])
    return voff


class _Assembler:
    def __init__(self, host: Dict[str, np.ndarray], meta):
        self.host = host
        self.item_totals = meta["item_totals"]
        self.flat = meta["flat"]  # original datum bytes (value-gather source)

    def col(self, key: str, count: int) -> np.ndarray:
        return np.ascontiguousarray(self.host[key][:count])

    def build(
        self,
        t: AvroType,
        dt: pa.DataType,
        path: str,
        count: int,
        parent_valid: Optional[np.ndarray],
    ) -> pa.Array:
        if isinstance(t, Union) and t.is_nullable_pair:
            own = self.host[path + "#valid"][:count].astype(bool)
            return self.build(
                t.non_null_variant, dt, path, count, _and(parent_valid, own)
            )

        if isinstance(t, Primitive):
            return self._primitive(t, dt, path, count, parent_valid)
        if isinstance(t, Fixed):
            return self._fixed(t, dt, path, count, parent_valid)
        if isinstance(t, Enum):
            return self._enum(t, path, count, parent_valid)
        if isinstance(t, Record):
            return self._struct(t, dt, path, count, parent_valid)
        if isinstance(t, Union):
            return self._union(t, dt, path, count, parent_valid)
        if isinstance(t, (Array, Map)):
            return self._repeated(t, dt, path, count, parent_valid)
        raise NotImplementedError(repr(t))

    def _primitive(self, t, dt, path, count, valid):
        vbuf, nulls = _validity(valid, count)
        name = t.name
        if name == "null":
            return pa.nulls(count, pa.null())
        if name == "bytes" and t.logical == "decimal":
            return self._decimal(t, dt, path, count, vbuf, nulls, valid)
        if name == "string" and t.logical == "uuid":
            return self._uuid(dt, path, count, vbuf, nulls, valid)
        if name in ("string", "bytes"):
            values, voff, _lens = self._string_values(path, count)
            if name == "string":
                _check_utf8(values, voff, path)
            return pa.Array.from_buffers(
                dt, count,
                [vbuf, pa.py_buffer(voff), pa.py_buffer(values)],
                null_count=nulls,
            )
        if name == "boolean":
            bits = np.packbits(
                self.col(path + "#v", count).astype(bool), bitorder="little"
            )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(bits)], null_count=nulls
            )
        if name == "int":
            arr = self.col(path + "#v", count)
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        if name == "long":
            # device walk ships (lo, hi) u32 lanes; the native host VM
            # writes int64 directly under "#v64"
            if path + "#v64" in self.host:
                arr = self.col(path + "#v64", count)
            else:
                arr = _combine64(
                    self.col(path + "#lo", count),
                    self.col(path + "#hi", count),
                    np.int64,
                )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        if name == "float":
            arr = self.col(path + "#v", count)
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        if name == "double":
            if path + "#v64" in self.host:
                arr = self.col(path + "#v64", count)
            else:
                arr = _combine64(
                    self.col(path + "#lo", count),
                    self.col(path + "#hi", count),
                    np.float64,
                )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(arr)], null_count=nulls
            )
        raise NotImplementedError(name)

    def _string_values(self, path: str, count: int):
        """Materialize one string-ish column's ``(values, voff, lens)``
        from either layout — the host VM's contiguous ``#bytes`` or the
        device walk's ``(start, len)`` descriptors gathered from the
        original datum bytes — with the 2 GiB int32-offset guard (the
        oracle's ``pa.array`` raises the same error class)."""
        lens = self.host[path + "#len"][:count]
        # the native cumsum0 raises OverflowError past int32 itself, so
        # the common path needs no separate whole-column sum; the numpy
        # fallback would wrap silently and keeps the explicit guard
        if _native_cumsum() is not None:
            try:
                voff = cumsum0(lens)
            except OverflowError:
                raise pa.lib.ArrowCapacityError(
                    f"column {path!r} carries over 2 GiB of value bytes "
                    f"— over the Binary/Utf8 capacity; split the batch"
                ) from None
            total = int(voff[-1]) if len(voff) else 0
        else:
            total = int(lens.sum(dtype=np.int64))
            if total >= (1 << 31):
                raise pa.lib.ArrowCapacityError(
                    f"column {path!r} carries {total} value bytes — over "
                    f"the 2 GiB Binary/Utf8 capacity; split the batch"
                )
            voff = cumsum0(lens)
        if path + "#bytes" in self.host:
            values = self.host[path + "#bytes"][:total]
        else:
            starts = self.host[path + "#start"][:count]
            src = np.repeat(
                starts.astype(np.int64) - voff[:-1], lens
            ) + np.arange(total, dtype=np.int64)
            values = self.flat[src]
        return values, voff, lens

    # char → nibble; 0xFF marks non-hex
    _HEX_LUT = np.full(256, 0xFF, np.uint8)
    for i, ch in enumerate(b"0123456789abcdef"):
        _HEX_LUT[ch] = i
    for i, ch in enumerate(b"ABCDEF"):
        _HEX_LUT[ch] = 10 + i
    del i, ch

    def _uuid(self, dt, path, count, vbuf, nulls, valid):
        """uuid text → FixedSizeBinary(16). Live rows in the canonical
        36-char form (dashes at 8/13/18/23) convert vectorized; anything
        else goes through the stdlib ``uuid.UUID`` — the oracle's own
        parser (``fallback/decoder.py``), so exotic-but-accepted forms
        and error behavior match by construction. Dead rows (nulls,
        non-selected union arms) emit zero bytes."""
        values, voff, lens = self._string_values(path, count)
        _check_utf8(values, voff, path)

        live = (
            np.ones(count, bool) if valid is None else valid.astype(bool)
        )
        mod = _native_mod("uuid16")
        if mod is not None and count:
            # native scalar parse of the canonical form (the dominant
            # cost of this column type was the numpy LUT-gather here);
            # converges to the shared stdlib-fallback tail below
            out_b, okb = mod.uuid16(
                np.ascontiguousarray(values), voff, count
            )
            out = np.frombuffer(bytearray(out_b), np.uint8).reshape(
                count, 16
            )
            canonical = np.frombuffer(okb, np.uint8).astype(bool) & live
            if not bool(live.all()):
                out[~live] = 0  # dead rows emit zeros, whatever parsed
        else:
            out = np.zeros((count, 16), np.uint8)
            canonical = np.zeros(count, bool)
            cand = np.flatnonzero(live & (lens == 36))
            if cand.size:
                if cand.size == count and values.size == count * 36:
                    # every row live and 36 chars: the value bytes are
                    # one dense (count, 36) block — zero-copy reshape
                    # instead of the fancy-index gather
                    m = values.reshape(count, 36)
                else:
                    m = values[
                        voff[:-1][cand, None].astype(np.int64)
                        + np.arange(36)
                    ]
                nib = self._HEX_LUT[m[:, _UUID_KEEP]]
                ok = (m[:, [8, 13, 18, 23]] == ord("-")).all(axis=1) & (
                    nib != 0xFF
                ).all(axis=1)
                rows = cand[ok]
                out[rows] = (nib[ok, 0::2] << 4) | nib[ok, 1::2]
                canonical[rows] = True
        rest = np.flatnonzero(live & ~canonical)
        if rest.size:
            import uuid as _uuid_mod

            for i in rest:
                s = values[voff[i] : voff[i + 1]].tobytes().decode("utf-8")
                out[i] = np.frombuffer(_uuid_mod.UUID(s).bytes, np.uint8)
        return pa.Array.from_buffers(
            dt, count,
            [vbuf, pa.py_buffer(np.ascontiguousarray(out).reshape(-1))],
            null_count=nulls,
        )

    def _decimal_raw_from_descriptors(self, t, path, count, valid):
        """Device-walk layout → 16-byte-LE decimal128 words: gather each
        row's big-endian two's-complement run from the datum bytes via
        its ``(start, len)`` descriptor and sign-extend to 16 bytes.
        Over-long encodings (len > 16, or a fixed size > 16) are legal
        when the leading bytes are pure sign fill — exactly the values
        ``int.from_bytes`` accepts in the oracle; anything wider than
        128 bits necessarily exceeds precision ≤ 38 and raises the
        oracle's error class. Dead rows (len 0) emit zeros."""
        live = np.ones(count, bool) if valid is None else valid.astype(bool)
        starts = np.where(live, self.host[path + "#start"][:count], 0
                          ).astype(np.int64)
        if path + "#len" in self.host:
            lens = np.where(live, self.host[path + "#len"][:count], 0
                            ).astype(np.int64)
        else:  # decimal over fixed: static size
            lens = np.where(live, t.size, 0).astype(np.int64)
        hi = np.int64(max(len(self.flat) - 1, 0))
        first = self.flat[np.clip(starts, 0, hi)]
        fill = np.where(
            (lens > 0) & ((first & 0x80) != 0), 0xFF, 0
        ).astype(np.uint8)
        take = np.minimum(lens, 16)
        j = np.arange(16)
        pos = np.clip(starts[:, None] + lens[:, None] - 1 - j, 0, hi)
        out = np.where(j < take[:, None], self.flat[pos], fill[:, None])
        over = lens > 16
        if bool(over.any()):
            extra = np.where(over, lens - 16, 0)
            total = int(extra.sum())
            off = np.zeros(count + 1, np.int64)
            np.cumsum(extra, out=off[1:])
            src = np.repeat(starts - off[:-1], extra) + np.arange(
                total, dtype=np.int64
            )
            lead_ok = np.ones(count, bool)
            np.logical_and.at(
                lead_ok,
                np.repeat(np.arange(count), extra),
                self.flat[np.clip(src, 0, hi)] == np.repeat(fill, extra),
            )
            sign_ok = ((out[:, 15] & 0x80) != 0) == (fill == 0xFF)
            bad = over & ~(lead_ok & sign_ok)
            if bool(bad.any()):
                i = int(np.flatnonzero(bad)[0])
                raise pa.lib.ArrowInvalid(
                    f"decimal at {path!r} row {i} exceeds precision "
                    f"{t.precision}"
                )
        return np.ascontiguousarray(out.astype(np.uint8).reshape(-1))

    def _decimal(self, t, dt, path, count, vbuf, nulls, valid):
        """Decimal128 from either layout — the host VM's ready 16-byte-LE
        ``#dec`` words, or the device walk's ``(start, len)`` descriptors
        (``_decimal_raw_from_descriptors``) — validating live values
        against the declared precision; the oracle's ``pa.array``
        raises ArrowInvalid for over-precision values, and
        ``from_buffers`` would silently accept them."""
        if path + "#dec" in self.host:
            raw = np.ascontiguousarray(self.host[path + "#dec"][: count * 16])
        else:
            raw = self._decimal_raw_from_descriptors(t, path, count, valid)
        mod = _native_mod("dec128_check")
        if count and mod is not None:
            # dead rows carry all-zero words (both layouts), so checking
            # every row natively matches the live-masked numpy check
            bound = 10 ** t.precision
            bad = mod.dec128_check(
                raw, count, bound >> 64, bound & ((1 << 64) - 1)
            )
            if bad >= 0:
                raise pa.lib.ArrowInvalid(
                    f"decimal at {path!r} row {bad} exceeds precision "
                    f"{t.precision}"
                )
        elif count:
            words = raw.view(np.uint64).reshape(count, 2)
            lo, hi = words[:, 0], words[:, 1]
            neg = (hi >> np.uint64(63)) != 0
            # |v| over two u64 halves (two's-complement negate)
            lo_a = np.where(neg, (~lo) + np.uint64(1), lo)
            hi_a = np.where(neg, (~hi) + (lo == 0).astype(np.uint64), hi)
            bound = 10 ** t.precision
            b_hi = np.uint64(bound >> 64)
            b_lo = np.uint64(bound & ((1 << 64) - 1))
            fits = (hi_a < b_hi) | ((hi_a == b_hi) & (lo_a < b_lo))
            live = (
                np.ones(count, bool) if valid is None
                else valid.astype(bool)
            )
            bad = live & ~fits
            if bad.any():
                i = int(np.flatnonzero(bad)[0])
                raise pa.lib.ArrowInvalid(
                    f"decimal at {path!r} row {i} exceeds precision "
                    f"{t.precision}"
                )
        return pa.Array.from_buffers(
            dt, count, [vbuf, pa.py_buffer(raw)], null_count=nulls
        )

    def _fixed(self, t, dt, path, count, valid):
        """Avro ``fixed`` from the host VM's raw #fix byte column;
        ``duration`` converts fixed(12) (months, days, ms u32-LE) to
        Duration(ms) with the oracle's 30-day-month convention
        (``fallback/decoder.py``)."""
        vbuf, nulls = _validity(valid, count)
        if t.logical == "decimal":
            return self._decimal(t, dt, path, count, vbuf, nulls, valid)
        if path + "#fix" in self.host:
            raw = self.host[path + "#fix"][: count * t.size]
        else:
            # device-walk layout: gather the static-size run per row from
            # the datum bytes; dead rows (null/non-selected arm) → zeros
            # like the host VM's builder
            live = (
                np.ones(count, bool) if valid is None else valid.astype(bool)
            )
            starts = self.host[path + "#start"][:count].astype(np.int64)
            hi = np.int64(max(len(self.flat) - 1, 0))
            pos = np.clip(starts[:, None] + np.arange(t.size), 0, hi)
            raw = np.where(
                live[:, None], self.flat[pos], np.uint8(0)
            ).astype(np.uint8).reshape(-1)
        if t.logical == "duration":
            u = np.ascontiguousarray(raw).view(np.uint32).reshape(count, 3)
            # uint64 holds the wire maximum ((2^32·30 + 2^32)·86400000 +
            # 2^32 < 2^64); values past int64 overflow Duration(ms) like
            # the oracle's pa.array does
            ms = (
                (u[:, 0].astype(np.uint64) * 30 + u[:, 1]) * 86_400_000
                + u[:, 2]
            )
            if bool((ms > np.uint64(np.iinfo(np.int64).max)).any()):
                raise OverflowError(
                    f"duration at {path!r} exceeds Duration(ms) int64"
                )
            return pa.Array.from_buffers(
                dt, count,
                [vbuf, pa.py_buffer(ms.astype(np.int64))],
                null_count=nulls,
            )
        return pa.Array.from_buffers(
            dt, count,
            [vbuf, pa.py_buffer(np.ascontiguousarray(raw))],
            null_count=nulls,
        )

    def _enum(self, t, path, count, valid):
        """Enum indices → Utf8 through the symbol table, vectorized."""
        vbuf, nulls = _validity(valid, count)
        idx = self.col(path + "#v", count)
        sym_bytes = np.frombuffer("".join(t.symbols).encode("utf-8"), np.uint8)
        sym_lens = np.array([len(s.encode("utf-8")) for s in t.symbols], np.int32)
        if count and int(sym_lens.max()) == int(sym_lens.min()):
            # uniform symbol width L (the typical enum): offsets are a
            # ramp and the values one (count, L) table gather — replaces
            # the repeat/arange expansion below (~4x on this hot cell)
            L = int(sym_lens[0])
            if count * L >= (1 << 31):
                raise pa.lib.ArrowCapacityError(
                    f"enum column {path!r} expands to {count * L} symbol "
                    f"bytes — over the 2 GiB Utf8 capacity; split the batch"
                )
            offsets = (np.arange(count + 1, dtype=np.int64) * L).astype(
                np.int32
            )
            values = sym_bytes.reshape(len(t.symbols), L)[idx].reshape(-1)
            return pa.Array.from_buffers(
                pa.utf8(), count,
                [vbuf, pa.py_buffer(offsets), pa.py_buffer(values)],
                null_count=nulls,
            )
        sym_starts = np.zeros(len(t.symbols), np.int32)
        np.cumsum(sym_lens[:-1], out=sym_starts[1:])
        lens = sym_lens[idx]
        total = int(lens.sum(dtype=np.int64))
        if total >= (1 << 31):
            raise pa.lib.ArrowCapacityError(
                f"enum column {path!r} expands to {total} symbol bytes — "
                f"over the 2 GiB Utf8 capacity; split the batch"
            )
        offsets = cumsum0(lens)
        pos = np.arange(total, dtype=np.int64) - np.repeat(offsets[:-1], lens)
        src = np.repeat(sym_starts[idx], lens) + pos
        values = sym_bytes[src]
        return pa.Array.from_buffers(
            pa.utf8(), count,
            [vbuf, pa.py_buffer(offsets), pa.py_buffer(values)],
            null_count=nulls,
        )

    def _struct(self, t, dt, path, count, valid):
        vbuf, nulls = _validity(valid, count)
        prefix = path + "/" if path else ""
        children = [
            self.build(f.type, dt.field(i).type, prefix + f.name, count, valid)
            for i, f in enumerate(t.fields)
        ]
        return pa.Array.from_buffers(
            dt, count, [vbuf], null_count=nulls, children=children
        )

    def _union(self, t, dt, path, count, parent_valid):
        tid = self.col(path + "#tid", count)
        if parent_valid is not None:
            # a null parent renders as branch 0 + null child, like the oracle
            tid = np.where(parent_valid, tid, 0).astype(tid.dtype)
        children = []
        names = []
        for k, v in enumerate(t.variants):
            child_field = dt.field(k)
            names.append(child_field.name)
            sel = _and(parent_valid, tid == k)
            if v.is_null():
                children.append(pa.nulls(count, pa.null()))
            else:
                children.append(
                    self.build(v, child_field.type, f"{path}/{k}", count, sel)
                )
        return pa.UnionArray.from_sparse(
            pa.array(tid.astype(np.int8), pa.int8()),
            children,
            field_names=names,
            type_codes=list(dt.type_codes),
        )

    def _repeated(self, t, dt, path, count, valid):
        vbuf, nulls = _validity(valid, count)
        offsets = self.col(path + "#offsets", count + 1)
        total = self.item_totals[path]
        if isinstance(t, Array):
            child = self.build(
                t.items, dt.value_field.type, path + "/@item", total, None
            )
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(offsets)],
                null_count=nulls, children=[child],
            )
        keys = self._primitive(
            Primitive("string"), pa.utf8(), path + "/@key", total, None
        )
        vals = self.build(t.values, dt.item_type, path + "/@val", total, None)
        entries = pa.StructArray.from_arrays(
            [keys, vals], fields=[dt.key_field, dt.item_field]
        )
        return pa.Array.from_buffers(
            dt, count, [vbuf, pa.py_buffer(offsets)],
            null_count=nulls, children=[entries],
        )


class _FusedNodes:
    """Positional cursor over the fused decoder's flat node list
    (``runtime/native/arrow_decode_core.h``) — both sides walk the same
    schema tree pre-order, so entries carry no keys."""

    __slots__ = ("nodes", "i")

    def __init__(self, nodes):
        self.nodes = nodes
        self.i = 0

    def next(self):
        e = self.nodes[self.i]
        self.i += 1
        return e


def _fused_build(t: AvroType, dt: pa.DataType, count: int,
                 it: _FusedNodes) -> pa.Array:
    """One schema node from its finished native buffers — the fused
    mirror of ``_Assembler.build``: every buffer arrives in final Arrow
    layout (validity bitmaps, leading-0 offsets, int8 type ids), so
    this walk is pure ``pa.Array.from_buffers`` composition; no numpy
    op exists anywhere on this path."""
    if isinstance(t, Union) and t.is_nullable_pair:
        # the native pass folded the wrapper's validity into the child
        return _fused_build(t.non_null_variant, dt, count, it)

    if isinstance(t, Primitive):
        name = t.name
        if name == "null":
            return pa.nulls(count, pa.null())
        if name in ("string", "bytes") and t.logical != "uuid" \
                and t.logical != "decimal":
            nc, vb, offs, vals = it.next()
            return pa.Array.from_buffers(
                dt, count,
                [None if vb is None else pa.py_buffer(vb),
                 pa.py_buffer(offs), pa.py_buffer(vals)],
                null_count=nc,
            )
        # uuid / decimal / numeric / boolean: one value buffer
        nc, vb, data = it.next()
        return pa.Array.from_buffers(
            dt, count,
            [None if vb is None else pa.py_buffer(vb), pa.py_buffer(data)],
            null_count=nc,
        )
    if isinstance(t, (Fixed, Enum)):
        if isinstance(t, Enum):
            nc, vb, offs, vals = it.next()
            return pa.Array.from_buffers(
                pa.utf8(), count,
                [None if vb is None else pa.py_buffer(vb),
                 pa.py_buffer(offs), pa.py_buffer(vals)],
                null_count=nc,
            )
        nc, vb, data = it.next()
        return pa.Array.from_buffers(
            dt, count,
            [None if vb is None else pa.py_buffer(vb), pa.py_buffer(data)],
            null_count=nc,
        )
    if isinstance(t, Record):
        nc, vb = it.next()
        children = [
            _fused_build(f.type, dt.field(i).type, count, it)
            for i, f in enumerate(t.fields)
        ]
        return pa.Array.from_buffers(
            dt, count,
            [None if vb is None else pa.py_buffer(vb)],
            null_count=nc, children=children,
        )
    if isinstance(t, Union):
        (tid8,) = it.next()
        tid_arr = pa.Array.from_buffers(
            pa.int8(), count, [None, pa.py_buffer(tid8)]
        )
        children = []
        names = []
        for k, v in enumerate(t.variants):
            child_field = dt.field(k)
            names.append(child_field.name)
            if v.is_null():
                children.append(pa.nulls(count, pa.null()))
            else:
                children.append(
                    _fused_build(v, child_field.type, count, it)
                )
        return pa.UnionArray.from_sparse(
            tid_arr, children,
            field_names=names, type_codes=list(dt.type_codes),
        )
    if isinstance(t, (Array, Map)):
        nc, vb, offs, total = it.next()
        vbuf = None if vb is None else pa.py_buffer(vb)
        if isinstance(t, Array):
            child = _fused_build(t.items, dt.value_field.type, total, it)
            return pa.Array.from_buffers(
                dt, count, [vbuf, pa.py_buffer(offs)],
                null_count=nc, children=[child],
            )
        knc, kvb, koffs, kvals = it.next()  # map keys: a string entry
        keys = pa.Array.from_buffers(
            pa.utf8(), total,
            [None if kvb is None else pa.py_buffer(kvb),
             pa.py_buffer(koffs), pa.py_buffer(kvals)],
            null_count=knc,
        )
        vals = _fused_build(t.values, dt.item_type, total, it)
        entries = pa.StructArray.from_arrays(
            [keys, vals], fields=[dt.key_field, dt.item_field]
        )
        return pa.Array.from_buffers(
            dt, count, [vbuf, pa.py_buffer(offs)],
            null_count=nc, children=[entries],
        )
    raise NotImplementedError(repr(t))


def _empty_fields_batch(n: int) -> pa.RecordBatch:
    """An n-row batch for a zero-field schema, built without an n-long
    Python list (shared by both assembly engines)."""
    return pa.RecordBatch.from_struct_array(
        pa.Array.from_buffers(pa.struct([]), n, [None], children=[])
    )


def build_fused_record_batch(
    ir: Record,
    arrow_schema: pa.Schema,
    nodes,
    n: int,
) -> pa.RecordBatch:
    """RecordBatch from the fused native decoder's node list — the
    zero-copy handoff: every ``pa.py_buffer`` wraps the returned bytes
    objects in place. Raises if the node list and schema disagree
    (a contract violation, not a data error)."""
    it = _FusedNodes(nodes)
    arrays = [
        _fused_build(f.type, arrow_schema.field(i).type, n, it)
        for i, f in enumerate(ir.fields)
    ]
    if it.i != len(nodes):
        # the positional protocol's one failure mode is a silent walk
        # desync — unconsumed entries must never pass as a valid batch
        raise ValueError(
            f"fused decode walk desync: {len(nodes) - it.i} node "
            f"entr{'y' if len(nodes) - it.i == 1 else 'ies'} unconsumed"
        )
    if not arrays:
        return _empty_fields_batch(n)
    return pa.RecordBatch.from_arrays(arrays, schema=arrow_schema)


def build_record_batch(
    ir: Record,
    arrow_schema: pa.Schema,
    host: Dict[str, np.ndarray],
    n: int,
    meta,
) -> pa.RecordBatch:
    asm = _Assembler(host, meta)
    arrays = [
        asm.build(f.type, arrow_schema.field(i).type, f.name, n, None)
        for i, f in enumerate(ir.fields)
    ]
    if not arrays:
        return _empty_fields_batch(n)
    return pa.RecordBatch.from_arrays(arrays, schema=arrow_schema)
