"""Pallas TPU walk kernel: the field program executed from VMEM tiles.

The XLA pipeline (``ops/decode.py``) runs the lowered field program
(``ops/fieldprog.py``) as one traced XLA computation whose byte reads are
gathers into the flat HBM word buffer. This module runs the **same
program** — same lowering, same emitters, same error bits — inside a
``pl.pallas_call`` kernel (SURVEY.md §7 step 4's "Pallas kernel: one
record per grid element"; ≙ the hot loop being replaced,
``ruhvro/src/fast_decode.rs:806-834``):

* records are packed **row-padded** ``[R, BW]`` little-endian u32 words
  (one row per record, ``BW`` = bucketed max record words) instead of the
  flat+offsets layout, so one grid step's tile ``[TILE_R, BW]`` is a
  contiguous VMEM block,
* per-lane cursors are **record-local** byte positions; the word source
  resolves ``take_words(widx)`` as a **one-hot masked row-reduction**
  over the tile — compare + select + sum, all dense VPU work with log
  reduction depth (v1 used a BW-deep sequential select chain, VERDICT
  r04 weak #3), no gather, nothing Mosaic refuses to lower,
* repeated fields (array/map — v2, VERDICT r04 #3) run the field
  program's own block-protocol ``lax.while_loop``; the strided
  item-region writes that XLA lowers as scatters become **2D one-hot
  selects** over ``[TILE_R, icap]`` views via the program's pluggable
  ``item_put`` strategy (``fieldprog._Ctx``) — Mosaic does not lower
  vector-index scatters,
* outputs are the program's buffers, blocked per grid step (u8 lanes
  widened to i32 in-kernel, cast back outside); row-region string
  ``#start`` descriptors are rebased in-kernel to global byte offsets
  into the row-major padded buffer; item-region descriptors stay
  record-local and are rebased during the host-side compaction, which
  also turns strided slots into the dense item arrays + ``#offsets``
  the Arrow assembly expects (the XLA pipeline compacts on device for
  transfer economics; the kernel path keeps the walk on device and the
  cheap vectorized numpy compaction on host).

Scope (v2): schemas whose repeated regions all sit at ROW level
(no array-inside-array nesting) — the kafka headline schema qualifies.
Item capacities follow the same ERR_ITEM_OVERFLOW retry ladder as the
XLA pipeline. The gate mirrors ``deserialize.rs:26-29``: callers fall
back transparently.

``interpret=True`` runs the kernel on CPU for the differential suite;
on hardware the same call compiles via Mosaic, and
``scripts/pallas_lower_check.py`` AOT-lowers it for the TPU target in
CI so lowering regressions surface without a chip.
"""

from __future__ import annotations

import threading
from typing import Dict, Sequence, Tuple

import numpy as np

from ..fallback.io import MalformedAvro
from ..runtime import device_obs, metrics, telemetry
from ..runtime.pack import bucket_len, concat_records
from . import UnsupportedOnDevice
from .fieldprog import ROWS, Program, _Ctx, lower
from .varint import ERR_ITEM_OVERFLOW, ERR_NAMES, ERR_TRAILING

__all__ = ["PallasKernelDecoder", "pallas_supported"]

_LANE = 128           # TPU lane width; TILE_R is always a multiple
_VMEM_TILE_BYTES = 1 << 21  # ~2 MiB tile budget (VMEM is ~16 MiB/core)
_MAX_BW = 512         # beyond 2 KiB/record the one-hot reads get silly;
                      # such batches stay on the XLA pipeline
_MAX_CAP = 1 << 10    # item-cap ladder ceiling (per record, per region)


def pallas_supported(prog: Program) -> bool:
    """Can this lowered program run as the Pallas walk kernel (v2)?
    Repeated regions are supported when they all hang off the row
    region (single-level; nested repetition stays on the XLA path)."""
    return all(p == ROWS for p in prog.region_parents[1:])


class _TileWords:
    """Word source over a ``[TILE_R, BW]`` VMEM tile: lane ``l`` reads
    word ``widx[l]`` of ITS OWN row as a one-hot masked row-reduction
    (see module docstring)."""

    def __init__(self, tile, jax):
        self._tile = tile
        self._jax = jax

    def take_words(self, widx):
        jax = self._jax
        jnp = jax.numpy
        tile = self._tile
        tile_r, bw = tile.shape
        w = jnp.clip(widx, 0, bw - 1)
        col = jax.lax.broadcasted_iota(jnp.int32, (tile_r, bw), 1)
        hot = col == w[:, None]
        # Mosaic lowers no integer reductions at all (reduce_sum over
        # i32 was the bulk of the 12 PALLAS_LOWER_STATS failures), so
        # the one-hot row-reduction runs as TWO float32 sums over the
        # word's 16-bit halves: each half is < 2^16 and exactly one
        # term per row is non-zero, so both f32 sums are bit-exact and
        # recombine to the original u32 word. (f32→i32 casts lower;
        # f32→u32 does not — keep the integer math in i32 throughout.)
        ti = jax.lax.bitcast_convert_type(tile, jnp.int32)
        lo = jnp.where(hot, ti & 0xFFFF, 0)
        hi = jnp.where(hot, jax.lax.shift_right_logical(ti, 16), 0)
        slo = jnp.sum(lo.astype(jnp.float32), axis=1).astype(jnp.int32)
        shi = jnp.sum(hi.astype(jnp.float32), axis=1).astype(jnp.int32)
        return jax.lax.bitcast_convert_type(
            slo | (shi << 16), jnp.uint32
        )


class PallasKernelDecoder:
    """Per-schema Pallas decode kernel (row-level repeated regions).

    Same public contract as :class:`ops.decode.DeviceDecoder`'s
    ``decode_to_columns`` (host column dict + meta), so the Arrow
    assembly and the differential tests are shared verbatim.
    """

    def __init__(self, ir, interpret: bool = False,
                 fingerprint: str = None):
        import jax  # deferred, like the rest of the package

        self._jax = jax
        self.fingerprint = fingerprint or "?"  # jit-cache registry id
        self.prog = lower(ir)
        if not pallas_supported(self.prog):
            raise UnsupportedOnDevice(
                "pallas walk kernel v2 covers row-level array/map "
                "(nested repetition runs on the XLA pipeline)"
            )
        self.interpret = interpret
        self._caps = None  # remembered successful cap-ladder rung
        self._cache: Dict[Tuple, object] = {}
        self._lock = threading.Lock()
        device_obs.track_holder(self)  # executable lifecycle (ISSUE 12)
        self.n_regions = len(self.prog.regions)
        # sorted buffer keys define the output tuple order
        self.out_keys = sorted(self.prog.buffers) + ["#err"]
        self._widened = {
            k: self.prog.buffers[k].dtype for k in sorted(self.prog.buffers)
        }

    def _jit_caches(self):
        return [self._cache]

    # -- kernel construction ------------------------------------------------

    def _row_bytes(self, BW: int, caps: Tuple[int, ...]) -> int:
        """Per-record VMEM footprint of one grid step: the input words
        plus EVERY output buffer's share — item-region buffers cost
        ``icap`` elements per record, which is what bounds the upper
        cap-ladder rungs (ignoring them would blow VMEM on hardware at
        high caps while interpret-mode tests sail through)."""
        total = BW * 4 + 4 + 4  # words + lens + act
        for key, spec in self.prog.buffers.items():
            per = 1 if spec.region == ROWS else caps[spec.region]
            total += 4 * per  # widened lanes are all 32-bit in-kernel
        total += 4 + 4 + 4  # #cursor, #err, slack
        return total

    def _tile_rows(self, BW: int, caps: Tuple[int, ...] = ()) -> int:
        full_caps = caps or tuple(0 for _ in range(self.n_regions))
        rows = _VMEM_TILE_BYTES // max(self._row_bytes(BW, full_caps), 1)
        rows = min(1024, (rows // _LANE) * _LANE)
        return rows  # 0 = this rung cannot fit even one lane row

    def _buf_len(self, key: str, tile_r: int, caps: Tuple[int, ...]) -> int:
        region = self.prog.buffers[key].region
        return tile_r if region == ROWS else tile_r * caps[region]

    def _build(self, grid_r: int, tile_r: int, BW: int,
               caps: Tuple[int, ...]):
        """One compiled pallas_call for a (grid, TILE_R, BW, caps)
        bucket."""
        jax = self._jax
        jnp = jax.numpy
        from jax.experimental import pallas as pl

        prog = self.prog
        out_keys = self.out_keys
        widened = self._widened
        # row-region descriptor starts rebase in-kernel to global offsets
        # into the row-major padded buffer; item-region starts rebase
        # host-side during compaction (rows are known there for free)
        row_start_keys = [
            k for k, s in prog.buffers.items()
            if s.region == ROWS and k.endswith("#start")
        ]

        def item_put(buf, idx, val, mask):
            """Strided item write as a 2D one-hot select: buf is a
            [tile_r * icap] region buffer, idx = lane * icap + cnt (or
            _BIG for cap-overflow lanes, which must drop)."""
            icap = buf.shape[0] // tile_r
            b2 = buf.reshape(tile_r, icap)
            lane = jax.lax.broadcasted_iota(jnp.int32, (tile_r,), 0)
            col = idx - lane * icap  # == cnt, or huge for dropped slots
            cc = jax.lax.broadcasted_iota(jnp.int32, (tile_r, icap), 1)
            sel = (cc == col[:, None]) & mask[:, None]
            return jnp.where(sel, val[:, None], b2).reshape(-1)

        def kernel(words_ref, lens_ref, act_ref, *out_refs):
            tile = words_ref[...]                      # [TILE_R, BW] u32
            lens = lens_ref[...]                       # [TILE_R] i32
            active = act_ref[...] != 0
            cursors = jnp.zeros_like(lens)             # record-local bytes
            st = {"#cursor": cursors,
                  "#err": jnp.zeros_like(lens).astype(jnp.uint32)}
            for key in sorted(prog.buffers):
                dt = widened[key]
                kdt = jnp.int32 if jnp.dtype(dt) == jnp.uint8 else dt
                st[key] = jnp.zeros(
                    self._buf_len(key, tile_r, caps), kdt
                )
            def reduce_max_f32(v):
                # scalar loop-bound max over record-local byte spans
                # (≤ BW·4 ≤ 2 KiB — exact in float32); Mosaic refuses
                # the integer reduce_max this replaces
                return jnp.max(v.astype(jnp.float32)).astype(jnp.int32)

            cx = _Ctx(_TileWords(tile, jax), lens, item_caps=caps,
                      item_put=item_put, reduce_max=reduce_max_f32)
            st = prog.emit(cx, st, active, None)
            st["#err"] = st["#err"] | jnp.where(
                active & (st["#cursor"] != lens),
                jnp.uint32(ERR_TRAILING),
                jnp.uint32(0),
            )
            if row_start_keys:
                lane = jax.lax.broadcasted_iota(
                    jnp.int32, (tile_r, 1), 0
                ).squeeze(-1)
                row = pl.program_id(0) * tile_r + lane
                for k in row_start_keys:
                    st[k] = jnp.where(active, st[k] + row * (BW * 4), 0)
            for i, key in enumerate(out_keys):
                v = st[key]
                if v.dtype == jnp.uint8:  # defensive; state is widened
                    v = v.astype(jnp.int32)
                out_refs[i][...] = v

        out_shapes = []
        out_specs = []
        for key in out_keys:
            dt = jnp.uint32 if key == "#err" else widened[key]
            if jnp.dtype(dt) == jnp.uint8:
                dt = jnp.int32  # widened in-kernel, cast back outside
            blk = (tile_r if key == "#err"
                   else self._buf_len(key, tile_r, caps))
            out_shapes.append(
                jax.ShapeDtypeStruct((grid_r * blk,), dt)
            )
            out_specs.append(pl.BlockSpec((blk,), lambda i: (i,)))

        call = pl.pallas_call(
            kernel,
            grid=(grid_r,),
            in_specs=[
                pl.BlockSpec((tile_r, BW), lambda i: (i, 0)),
                pl.BlockSpec((tile_r,), lambda i: (i,)),
                pl.BlockSpec((tile_r,), lambda i: (i,)),
            ],
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=self.interpret,
        )

        def fn(words2d, lens, act):
            outs = call(words2d, lens, act)
            res = []
            for key, v in zip(out_keys, outs):
                want = jnp.uint32 if key == "#err" else widened[key]
                res.append(v.astype(want))
            return tuple(res)

        return jax.jit(fn)

    def _fn(self, grid_r: int, tile_r: int, BW: int, caps: Tuple[int, ...]):
        key = (grid_r, tile_r, BW, caps)
        # get-or-build under the lock: concurrent callers must not both
        # compile the same bucket (ADVICE r04 — wasted compile time).
        # Different buckets serialize their builds too, which is fine:
        # builds are rare (per shape bucket) and correctness-neutral.
        with self._lock:
            fn = self._cache.get(key)
            if fn is None:
                fn = device_obs.InstrumentedJit(
                    self._jax, self._build(grid_r, tile_r, BW, caps),
                    kind="decode.pallas",
                    bucket=f"g{grid_r},tile{tile_r},BW{BW},"
                           f"caps{'/'.join(map(str, caps))}",
                    fingerprint=self.fingerprint, family="decode",
                )
                self._cache[key] = fn
        return fn

    # -- host orchestration ---------------------------------------------------

    def decode_to_columns(self, data: Sequence[bytes]):
        """Row-padded pack → kernel (item-cap retry ladder) → host
        compaction → host columns (same contract as
        ``DeviceDecoder.decode_to_columns``)."""
        with telemetry.phase("device.pipeline_s", rows=len(data),
                             op="decode", kernel="pallas"):
            return self._decode_to_columns(data)

    def _decode_to_columns(self, data: Sequence[bytes]):
        jax = self._jax
        n = len(data)
        with telemetry.phase("decode.pack_s", rows=n, kernel="pallas"):
            flat, offsets = concat_records(data)
        lens_np = np.diff(offsets).astype(np.int32)
        max_b = int(lens_np.max(initial=1))
        BW = bucket_len(max(-(-max_b // 4), 1), minimum=4)
        if BW > _MAX_BW:
            raise UnsupportedOnDevice(
                f"record of {max_b} bytes exceeds the pallas tile budget"
            )

        def pack(R: int):
            # row-padded layout: record i's bytes at [i, 0:len_i], built
            # by one vectorized scatter of the packed run
            padded = np.zeros((R, BW * 4), np.uint8)
            total = int(offsets[-1])
            rows = np.repeat(np.arange(n), lens_np)
            cols = np.arange(total, dtype=np.int64) - np.repeat(
                offsets[:-1].astype(np.int64), lens_np
            )
            padded[rows, cols] = flat[:total]
            lens = np.zeros(R, np.int32)
            lens[:n] = lens_np
            act = np.zeros(R, np.int32)
            act[:n] = 1
            return padded, lens, act

        # item-cap retry ladder, remembered per decoder so a steady-state
        # workload pays the ladder once (≙ the XLA pipeline's seeded
        # caps): ERR_ITEM_OVERFLOW lanes mean a region's per-record cap
        # was too small — double and rerun; any other error bit is
        # malformed input. Only #err transfers until a rung is clean.
        caps = getattr(self, "_caps", None) or tuple(
            0 if r == 0 else 8 for r in range(self.n_regions)
        )
        err_i = self.out_keys.index("#err")
        padded = None
        prev_R = None
        while True:
            tile_r = self._tile_rows(BW, caps)
            if tile_r < _LANE:
                raise UnsupportedOnDevice(
                    f"pallas tile cannot fit caps={max(caps)} in VMEM; "
                    f"use the XLA pipeline"
                )
            grid_r = max(1, -(-n // tile_r))
            R = grid_r * tile_r
            if R * (BW * 4) > (1 << 30):
                # descriptor starts rebase to int32 global offsets, and
                # row padding amplifies skewed batches; same 1 GiB
                # launch budget as the XLA pipeline — callers split
                from .decode import BatchTooLarge

                raise BatchTooLarge(n, R * BW * 4)
            if R != prev_R:
                padded, lens, act = pack(R)
                prev_R = R
                h2d_nbytes = padded.nbytes + lens.nbytes + act.nbytes
                with telemetry.phase("decode.h2d_s", bytes=h2d_nbytes):
                    args = (jax.device_put(padded.view(np.uint32)),
                            jax.device_put(lens), jax.device_put(act))
                metrics.inc("decode.h2d_bytes", h2d_nbytes)
                metrics.inc("device.h2d_bytes", h2d_nbytes)
            fn = self._fn(grid_r, tile_r, BW, caps)
            # device.compile_s / device.launch_s split by the wrapper
            dev_outs = fn(*args)
            err_np = np.asarray(jax.device_get(dev_outs[err_i]))
            if not (err_np[:n] & ERR_ITEM_OVERFLOW).any():
                break
            if max(caps) >= _MAX_CAP:
                raise UnsupportedOnDevice(
                    f"array/map items exceed the pallas cap ladder "
                    f"({_MAX_CAP}/record); use the XLA pipeline"
                )
            metrics.inc("device.retries")
            telemetry.observe(
                "device.retry_s", 0.0, reason="item_cap_overflow",
                capacity=f"caps{'/'.join(map(str, caps))}",  # too small
            )
            caps = tuple(0 if c == 0 else c * 2 for c in caps)
        self._caps = caps
        with telemetry.phase("decode.d2h_s"):
            outs = [
                err_np if i == err_i
                else np.asarray(jax.device_get(v))
                for i, v in enumerate(dev_outs)
            ]
        metrics.inc("decode.d2h_bytes", sum(v.nbytes for v in outs))
        metrics.inc("device.d2h_bytes", sum(v.nbytes for v in outs))
        device_obs.note_memory(jax)

        host = dict(zip(self.out_keys, outs))
        err = host.pop("#err")[:n]
        if err.any():
            i = int(np.flatnonzero(err)[0])
            bit = int(err[i]) & -int(err[i])
            raise MalformedAvro(
                f"record {i}: {ERR_NAMES.get(bit, f'error bit {bit:#x}')}"
            )
        meta = {"item_totals": {}, "flat": padded.reshape(-1)}
        self._compact_regions(host, n, caps, BW, meta)
        return host, n, meta

    def _compact_regions(self, host: Dict[str, np.ndarray], n: int,
                         caps: Tuple[int, ...], BW: int, meta) -> None:
        """Strided item slots → dense arrays + ``#offsets`` (the layout
        ``arrow_build`` consumes — the host-side mirror of the XLA
        pipeline's on-device compaction). Item-region ``#start``
        descriptors rebase to global offsets here, where each dense
        item's row is known for free."""
        from .arrow_build import cumsum0
        from .decode import BatchTooLarge

        prog = self.prog
        for rid in range(1, self.n_regions):
            path = prog.regions[rid]
            icap = caps[rid]
            counts = np.ascontiguousarray(
                host[path + "#count"][:n], np.int32
            )
            # int32 offsets are a hard bound (zero-byte items — arrays
            # of null/empty records — are NOT bounded by wire bytes, so
            # this can genuinely overflow): cumsum0's native path raises
            # past int32; the numpy fallback is guarded explicitly
            if int(counts.sum(dtype=np.int64)) >= (1 << 31):
                raise BatchTooLarge(n, -1)
            try:
                offsets = cumsum0(counts)
            except OverflowError:
                raise BatchTooLarge(n, -1) from None
            total = int(offsets[-1])
            rows = np.repeat(np.arange(n, dtype=np.int64), counts)
            within = np.arange(total, dtype=np.int64) - np.repeat(
                offsets[:-1].astype(np.int64), counts
            )
            src = rows * icap + within
            for key, spec in prog.buffers.items():
                if spec.region != rid or key == path + "#count":
                    continue
                dense = host[key][src]
                if key.endswith("#start"):
                    dense = (dense + rows * (BW * 4)).astype(dense.dtype)
                host[key] = dense
            host[path + "#offsets"] = offsets
            meta["item_totals"][path] = total

    def decode(self, data: Sequence[bytes], arrow_schema):
        """Straight to a RecordBatch (test/bench convenience)."""
        from .arrow_build import build_record_batch

        host, n, meta = self.decode_to_columns(data)
        return build_record_batch(self.prog.ir, arrow_schema, host, n, meta)
