"""Pallas TPU walk kernel: the field program executed from VMEM tiles.

The XLA pipeline (``ops/decode.py``) runs the lowered field program
(``ops/fieldprog.py``) as one traced XLA computation whose byte reads are
gathers into the flat HBM word buffer. This module runs the **same
program** — same lowering, same emitters, same error bits — inside a
``pl.pallas_call`` kernel (SURVEY.md §7 step 4's "Pallas kernel: one
record per grid element"; ≙ the hot loop being replaced,
``ruhvro/src/fast_decode.rs:806-834``):

* records are packed **row-padded** ``[R, BW]`` little-endian u32 words
  (one row per record, ``BW`` = bucketed max record words) instead of the
  flat+offsets layout, so one grid step's tile ``[TILE_R, BW]`` is a
  contiguous VMEM block,
* per-lane cursors are **record-local** byte positions; the word source
  handed to the shared readers resolves ``take_words(widx)`` as a
  clip-clamped **select chain over the tile's static columns** — pure
  VPU ALU on VMEM-resident data, no gather, no reshape, nothing Mosaic
  struggles to lower,
* outputs are the program's row-region buffers, blocked ``[TILE_R]`` per
  grid step (u8 lanes widened to i32 in-kernel, cast back outside);
  string ``#start`` descriptors are rebased to global byte offsets into
  the row-major padded buffer so the host finalize (``arrow_build``)
  gathers value bytes exactly like the XLA path.

Scope (v1): schemas whose field program has **no repeated regions**
(array/map) — those need the block-protocol ``while_loop`` + strided
scatters, which stay on the XLA pipeline (``fast_decode.rs:689-786``'s
territory). The gate mirrors ``deserialize.rs:26-29``: callers fall back
transparently.

``interpret=True`` runs the kernel on CPU for the differential suite;
on hardware the same call compiles via Mosaic.
"""

from __future__ import annotations

import threading
from typing import Dict, Sequence, Tuple

import numpy as np

from ..fallback.io import MalformedAvro
from ..runtime import metrics
from ..runtime.pack import bucket_len, concat_records
from . import UnsupportedOnDevice
from .fieldprog import ROWS, Program, _Ctx, lower
from .varint import ERR_NAMES, ERR_TRAILING

__all__ = ["PallasKernelDecoder", "pallas_supported"]

_LANE = 128           # TPU lane width; TILE_R is always a multiple
_VMEM_TILE_BYTES = 1 << 21  # ~2 MiB tile budget (VMEM is ~16 MiB/core)
_MAX_BW = 512         # beyond 2 KiB/record the select chain is silly;
                      # such batches stay on the XLA pipeline


def pallas_supported(prog: Program) -> bool:
    """Can this lowered program run as the Pallas walk kernel (v1)?"""
    return len(prog.regions) == 1


class _TileWords:
    """Word source over a ``[TILE_R, BW]`` VMEM tile: lane ``l`` reads
    word ``widx[l]`` of ITS OWN row via a clip-clamped select chain over
    the ``BW`` static columns (see module docstring)."""

    def __init__(self, tile, jnp):
        self._tile = tile
        self._jnp = jnp

    def take_words(self, widx):
        jnp = self._jnp
        bw = self._tile.shape[1]
        w = jnp.clip(widx, 0, bw - 1)
        acc = self._tile[:, 0]
        for k in range(1, bw):
            acc = jnp.where(w == k, self._tile[:, k], acc)
        return acc


class PallasKernelDecoder:
    """Per-schema Pallas decode kernel (flat-schema subset).

    Same public contract as :class:`ops.decode.DeviceDecoder`'s
    ``decode_to_columns`` (host column dict + meta), so the Arrow
    assembly and the differential tests are shared verbatim.
    """

    def __init__(self, ir, interpret: bool = False):
        import jax  # deferred, like the rest of the package

        self._jax = jax
        self.prog = lower(ir)
        if not pallas_supported(self.prog):
            raise UnsupportedOnDevice(
                "pallas walk kernel v1 covers schemas without array/map "
                "(repeated regions run on the XLA pipeline)"
            )
        self.interpret = interpret
        self._cache: Dict[Tuple[int, int, int], object] = {}
        self._lock = threading.Lock()
        # sorted row-region output keys define the output tuple order
        self.out_keys = sorted(self.prog.buffers) + ["#err"]
        self._widened = {
            k: self.prog.buffers[k].dtype for k in sorted(self.prog.buffers)
        }

    # -- kernel construction ------------------------------------------------

    def _tile_rows(self, BW: int) -> int:
        rows = _VMEM_TILE_BYTES // (BW * 4)
        rows = max(_LANE, min(1024, (rows // _LANE) * _LANE))
        return rows

    def _build(self, grid_r: int, tile_r: int, BW: int):
        """One compiled pallas_call for a (grid, TILE_R, BW) bucket."""
        jax = self._jax
        jnp = jax.numpy
        from jax.experimental import pallas as pl

        prog = self.prog
        out_keys = self.out_keys
        widened = self._widened
        # every descriptor start must rebase to a global offset into the
        # row-major padded buffer: string/bytes/decimal-bytes descriptors
        # AND the fixed-family's static-run starts (all end in "#start")
        start_keys = [k for k in prog.buffers if k.endswith("#start")]

        def kernel(words_ref, lens_ref, act_ref, *out_refs):
            tile = words_ref[...]                      # [TILE_R, BW] u32
            lens = lens_ref[...]                       # [TILE_R] i32
            active = act_ref[...] != 0
            cursors = jnp.zeros_like(lens)             # record-local bytes
            st = {"#cursor": cursors, "#err": jnp.zeros_like(lens).astype(jnp.uint32)}
            for key in sorted(prog.buffers):
                dt = widened[key]
                kdt = jnp.int32 if jnp.dtype(dt) == jnp.uint8 else dt
                st[key] = jnp.zeros(tile_r, kdt)
            cx = _Ctx(_TileWords(tile, jnp), lens, item_caps=(0,))
            st = prog.emit(cx, st, active, None)
            st["#err"] = st["#err"] | jnp.where(
                active & (st["#cursor"] != lens),
                jnp.uint32(ERR_TRAILING),
                jnp.uint32(0),
            )
            # rebase descriptor starts: record-local -> global byte offset
            # in the row-major [R, BW*4] padded buffer the host gathers
            # from (the caller guards R * BW * 4 against int32)
            if start_keys:
                lane = jax.lax.broadcasted_iota(
                    jnp.int32, (tile_r, 1), 0
                ).squeeze(-1)
                row = pl.program_id(0) * tile_r + lane
                for k in start_keys:
                    st[k] = jnp.where(active, st[k] + row * (BW * 4), 0)
            for i, key in enumerate(out_keys):
                v = st[key]
                if v.dtype == jnp.uint8:  # defensive; state is widened
                    v = v.astype(jnp.int32)
                out_refs[i][...] = v

        out_shapes = []
        out_specs = []
        for key in out_keys:
            dt = jnp.uint32 if key == "#err" else widened[key]
            if jnp.dtype(dt) == jnp.uint8:
                dt = jnp.int32  # widened in-kernel, cast back outside
            out_shapes.append(
                jax.ShapeDtypeStruct((grid_r * tile_r,), dt)
            )
            out_specs.append(pl.BlockSpec((tile_r,), lambda i: (i,)))

        call = pl.pallas_call(
            kernel,
            grid=(grid_r,),
            in_specs=[
                pl.BlockSpec((tile_r, BW), lambda i: (i, 0)),
                pl.BlockSpec((tile_r,), lambda i: (i,)),
                pl.BlockSpec((tile_r,), lambda i: (i,)),
            ],
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=self.interpret,
        )

        def fn(words2d, lens, act):
            outs = call(words2d, lens, act)
            res = []
            for key, v in zip(out_keys, outs):
                want = jnp.uint32 if key == "#err" else widened[key]
                res.append(v.astype(want))
            return tuple(res)

        return jax.jit(fn)

    def _fn(self, grid_r: int, tile_r: int, BW: int):
        key = (grid_r, tile_r, BW)
        # get-or-build under the lock: concurrent callers must not both
        # compile the same bucket (ADVICE r04 — wasted compile time).
        # Different buckets serialize their builds too, which is fine:
        # builds are rare (per shape bucket) and correctness-neutral.
        with self._lock:
            fn = self._cache.get(key)
            if fn is None:
                fn = self._build(grid_r, tile_r, BW)
                self._cache[key] = fn
        return fn

    # -- host orchestration ---------------------------------------------------

    def decode_to_columns(self, data: Sequence[bytes]):
        """Row-padded pack → kernel → host columns (same contract as
        ``DeviceDecoder.decode_to_columns``)."""
        jax = self._jax
        n = len(data)
        with metrics.timer("decode.pack_s"):
            flat, offsets = concat_records(data)
        lens_np = np.diff(offsets).astype(np.int32)
        max_b = int(lens_np.max(initial=1))
        BW = bucket_len(max(-(-max_b // 4), 1), minimum=4)
        if BW > _MAX_BW:
            raise UnsupportedOnDevice(
                f"record of {max_b} bytes exceeds the pallas tile budget"
            )
        tile_r = self._tile_rows(BW)
        grid_r = max(1, -(-n // tile_r))
        R = grid_r * tile_r
        if R * (BW * 4) > (1 << 30):
            # descriptor starts rebase to int32 global offsets, and row
            # padding amplifies skewed batches (R × max record size);
            # same 1 GiB launch budget as the XLA pipeline — callers
            # split or take the XLA path
            from .decode import BatchTooLarge

            raise BatchTooLarge(n, R * BW * 4)

        # row-padded layout: record i's bytes at [i, 0:len_i], built by
        # one vectorized scatter of the packed run
        padded = np.zeros((R, BW * 4), np.uint8)
        total = int(offsets[-1])
        rows = np.repeat(np.arange(n), lens_np)
        cols = np.arange(total, dtype=np.int64) - np.repeat(
            offsets[:-1].astype(np.int64), lens_np
        )
        padded[rows, cols] = flat[:total]
        words2d = padded.view(np.uint32)
        lens = np.zeros(R, np.int32)
        lens[:n] = lens_np
        act = np.zeros(R, np.int32)
        act[:n] = 1

        fn = self._fn(grid_r, tile_r, BW)
        with metrics.timer("decode.h2d_s"):
            args = (jax.device_put(words2d), jax.device_put(lens),
                    jax.device_put(act))
        metrics.inc("decode.h2d_bytes", words2d.nbytes + lens.nbytes + act.nbytes)
        with metrics.timer("decode.launch_s"):
            outs = fn(*args)
        with metrics.timer("decode.d2h_s"):
            outs = [np.asarray(jax.device_get(v)) for v in outs]
        metrics.inc("decode.d2h_bytes", sum(v.nbytes for v in outs))

        host = dict(zip(self.out_keys, outs))
        err = host.pop("#err")[:n]
        if err.any():
            i = int(np.flatnonzero(err)[0])
            bit = int(err[i]) & -int(err[i])
            raise MalformedAvro(
                f"record {i}: {ERR_NAMES.get(bit, f'error bit {bit:#x}')}"
            )
        meta = {"item_totals": {}, "flat": padded.reshape(-1)}
        return host, n, meta

    def decode(self, data: Sequence[bytes], arrow_schema):
        """Straight to a RecordBatch (test/bench convenience)."""
        from .arrow_build import build_record_batch

        host, n, meta = self.decode_to_columns(data)
        return build_record_batch(self.prog.ir, arrow_schema, host, n, meta)
