"""Device (TPU) codec ops: vectorized Avro wire-format kernels in JAX.

Submodules (imported lazily so that merely importing :mod:`pyruhvro_tpu`
never pays the JAX startup cost — the reference's host-only import path
is similarly cheap):

* :mod:`.varint`    — vectorized zig-zag varint read primitives
* :mod:`.fieldprog` — Avro schema IR → static field program (output specs)
* :mod:`.decode`    — the jitted record-walk decode kernel
* :mod:`.arrow_build` — device outputs → ``pyarrow`` arrays
* :mod:`.encode`    — the jitted encode kernel (Arrow → wire bytes)
* :mod:`.codec`     — ``get_device_codec(entry)``, the object ``api.py`` uses
"""

__all__ = ["UnsupportedOnDevice"]


class UnsupportedOnDevice(ValueError):
    """Schema is valid but outside the requested fast path's subset.

    The device subset covers the FULL reference type surface
    (``gate.device_supported``) — the only exclusion is fixed decimals
    wider than decimal128's 16 bytes; the Pallas walk additionally
    excludes repeated fields (array/map). ``backend='auto'`` falls back
    silently, matching the reference's unsupported-schema gate
    (``deserialize.rs:26-29``)."""
