"""Device (TPU) codec ops: vectorized Avro wire-format kernels in JAX.

Submodules (imported lazily so that merely importing :mod:`pyruhvro_tpu`
never pays the JAX startup cost — the reference's host-only import path
is similarly cheap):

* :mod:`.varint`    — vectorized zig-zag varint read primitives
* :mod:`.fieldprog` — Avro schema IR → static field program (output specs)
* :mod:`.decode`    — the jitted record-walk decode kernel
* :mod:`.arrow_build` — device outputs → ``pyarrow`` arrays
* :mod:`.encode`    — the jitted encode kernel (Arrow → wire bytes)
* :mod:`.codec`     — ``get_device_codec(entry)``, the object ``api.py`` uses
"""

__all__ = ["UnsupportedOnDevice"]


class UnsupportedOnDevice(ValueError):
    """Schema is valid but outside the *device* kernel's subset (the
    fast-path subset: bytes/fixed/decimal/uuid/duration/time-* are
    host-only). ``backend='auto'`` falls back to the host path silently,
    matching the reference's unsupported-schema gate
    (``deserialize.rs:26-29``)."""
