"""Verifier-backed opcode superoptimizer (host tier round 3).

Peephole rewrites over compiled host programs (:mod:`.program`), in
the AwkwardForth tradition of optimizing a batched-record DSL program
rather than the decoder: the schema is already lowered to a flat
opcode array, so adjacent fixed-layout field walks can be fused into
bulk ops (``OP_FIXED_RUN`` — the SFVInt-style span-checked member run),
validity tests can be elided under unconditional record chains
(``FLAG_ALWAYS_PRESENT``), and array/map string-item loops can be
pre-decided at compile time (``FLAG_STR_ITEMS``).

Every rewrite is PROOF-CARRYING: the optimized program is re-verified
against the original's effects by the PR 14 abstract interpreter
(:func:`..analysis.irverify.verify_optimized`) — flatten-equality back
to the raw program plus re-derivation of every flag's claim. A program
that fails the oracle is rejected and COUNTED (``optimize.rejected``),
never run; the caller keeps the raw program. The raw program also
stays the source of truth for the specializer and the encode bound
(hostpath/codec.py keeps both).

The rewrites are pure tree transforms: parse the flat array into the
subtree structure ``nops`` already encodes, rewrite nodes, re-flatten.
:func:`strip_optimizations` is the exact inverse the oracle uses —
dropping every ``OP_FIXED_RUN`` header and clearing the flag bits must
reproduce the original array byte-for-byte.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from . import program as hp

__all__ = ["optimize_program", "strip_optimizations", "OptimizeStats"]

# leaves a fused run may absorb: fixed wire layout, no aux, no subtree
_FUSABLE_MIN_WIRE = {
    hp.OP_INT: 1, hp.OP_LONG: 1, hp.OP_FLOAT: 4, hp.OP_DOUBLE: 8,
    hp.OP_BOOL: 1,
}
# exact-width members (wire bytes == min_wire always): only an
# all-exact run may take the engines' bulk lane, because one upfront
# span check must justify every unchecked member read that follows —
# a varint member (int/long) can legally exceed its floor
_EXACT_WIDTH = (hp.OP_FLOAT, hp.OP_DOUBLE, hp.OP_BOOL)


@dataclass
class _Node:
    kind: int
    a: int
    b: int
    col: int
    pad: int
    aux: Optional[tuple]
    children: List["_Node"] = field(default_factory=list)


def _parse(ops, op_aux) -> _Node:
    """Flat array -> subtree structure (the inverse of the lowering's
    ``nops`` tiling; exact by the verifier's structure pass)."""
    aux = op_aux or tuple(None for _ in range(len(ops)))

    def node(pc: int) -> Tuple[_Node, int]:
        kind, a, b, col, nops, pad = (int(x) for x in ops[pc])
        nd = _Node(kind, a, b, col, pad, aux[pc])
        p, stop = pc + 1, pc + nops
        while p < stop:
            child, p = node(p)
            nd.children.append(child)
        if p != stop:
            raise ValueError(f"op {pc}: children end at {p}, nops "
                             f"claims {stop}")
        return nd, stop

    root, end = node(0)
    if end != len(ops):
        raise ValueError(f"root subtree ends at {end} of {len(ops)} ops")
    return root


def _flatten(root: _Node, drop_headers: bool = False):
    """Tree -> (ops int32[n,6], op_aux). ``drop_headers`` splices
    ``OP_FIXED_RUN`` members back into their parent and clears the pad
    flags — the raw-program inverse the equivalence oracle diffs."""
    rows: List[Optional[tuple]] = []
    auxes: List[Optional[tuple]] = []

    def emit(nd: _Node) -> None:
        if drop_headers and nd.kind == hp.OP_FIXED_RUN:
            for c in nd.children:
                emit(c)
            return
        i = len(rows)
        rows.append(None)
        auxes.append(nd.aux)
        for c in nd.children:
            emit(c)
        pad = 0 if drop_headers else nd.pad
        rows[i] = (nd.kind, nd.a, nd.b, nd.col, len(rows) - i, pad)

    emit(root)
    ops = np.ascontiguousarray(np.array(rows, np.int32))
    return ops, tuple(auxes)


# ---------------------------------------------------------------------------
# the three passes
# ---------------------------------------------------------------------------


def _fuse_fixed_runs(nd: _Node, stats: dict) -> None:
    """Wrap every maximal run of >= 2 consecutive fixed-layout leaf
    fields of a record in one ``OP_FIXED_RUN`` header. ``a=1`` (bulk-
    lane eligible) only when every member is exact-width; a run with
    varint members is grouped for dispatch but decoded per-member."""
    for c in nd.children:
        _fuse_fixed_runs(c, stats)
    if nd.kind != hp.OP_RECORD:
        return
    out: List[_Node] = []
    run: List[_Node] = []

    def close() -> None:
        if len(run) >= 2:
            width = sum(_FUSABLE_MIN_WIRE[m.kind] for m in run)
            exact = all(m.kind in _EXACT_WIDTH for m in run)
            out.append(_Node(hp.OP_FIXED_RUN, int(exact), width, -1, 0,
                             None, list(run)))
            stats["fused_runs"] += 1
            stats["fused_members"] += len(run)
        else:
            out.extend(run)
        run.clear()

    for c in nd.children:
        if c.kind in _FUSABLE_MIN_WIRE and not c.children and c.aux is None:
            run.append(c)
        else:
            close()
            out.append(c)
    close()
    nd.children = out


def _elide_dead_validity(nd: _Node, uncond: bool, stats: dict) -> None:
    """``FLAG_ALWAYS_PRESENT`` on fused headers whose every ancestor is
    a record (or another fused header): the walk can never reach them
    with ``present=false``, so the bulk lane may skip the test. The
    claim is re-proved by the oracle, not trusted."""
    if nd.kind == hp.OP_FIXED_RUN and uncond:
        nd.pad |= hp.FLAG_ALWAYS_PRESENT
        stats["always_present"] += 1
    inner = uncond and nd.kind in (hp.OP_RECORD, hp.OP_FIXED_RUN)
    for c in nd.children:
        _elide_dead_validity(c, inner, stats)


def _widen_string_blocks(nd: _Node, stats: dict) -> None:
    """``FLAG_STR_ITEMS`` on arrays/maps whose item subtree is exactly
    one string leaf: the engines' block loop takes the read-len /
    bulk-copy lane without re-deriving the shape per call."""
    for c in nd.children:
        _widen_string_blocks(c, stats)
    if nd.kind in (hp.OP_ARRAY, hp.OP_MAP) and len(nd.children) == 1:
        item = nd.children[0]
        if item.kind == hp.OP_STRING and not item.children:
            nd.pad |= hp.FLAG_STR_ITEMS
            stats["str_items"] += 1


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


@dataclass
class OptimizeStats:
    applied: bool = False
    fused_runs: int = 0
    fused_members: int = 0
    always_present: int = 0
    str_items: int = 0
    rejected: bool = False
    findings: tuple = ()


def _rebuild(prog, ops, op_aux):
    return hp.HostProgram(
        ir=prog.ir, ops=ops, cols=prog.cols, coltypes=prog.coltypes,
        regions=prog.regions, region_parents=prog.region_parents,
        op_aux=op_aux,
    )


def strip_optimizations(prog):
    """The optimized program with every rewrite undone: fused headers
    spliced out, pad flags cleared, ancestor ``nops`` restored. The
    equivalence oracle diffs this against the raw program byte-for-byte
    — a rewrite that cannot round-trip is by definition not
    effect-preserving."""
    root = _parse(prog.ops, prog.op_aux)
    ops, op_aux = _flatten(root, drop_headers=True)
    return _rebuild(prog, ops, op_aux)


# guard/consumer anchor scan for the oracle, once per process (the
# native sources don't change under a running interpreter)
_SCAN_CACHE: Optional[tuple] = None


def _scan_anchors():
    global _SCAN_CACHE
    if _SCAN_CACHE is None:
        from ..analysis import irverify
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        _SCAN_CACHE = (irverify.scan_native_guards(root),
                       irverify.scan_aux_consumers(root))
    return _SCAN_CACHE


def optimize_program(prog, verify: bool = True):
    """Apply the rewrite passes to ``prog``; returns
    ``(program, OptimizeStats)``. With ``verify`` (the default and the
    only mode any production caller uses) the optimized program is
    accepted ONLY when the irverify equivalence oracle reports zero
    findings — otherwise the ORIGINAL program is returned with
    ``stats.rejected`` set and ``optimize.rejected`` counted, so a
    buggy rewrite can cost performance but never correctness."""
    from ..runtime import metrics

    stats = OptimizeStats()
    counters = {"fused_runs": 0, "fused_members": 0, "always_present": 0,
                "str_items": 0}
    root = _parse(prog.ops, prog.op_aux)
    _fuse_fixed_runs(root, counters)
    _elide_dead_validity(root, True, counters)
    _widen_string_blocks(root, counters)
    stats.fused_runs = counters["fused_runs"]
    stats.fused_members = counters["fused_members"]
    stats.always_present = counters["always_present"]
    stats.str_items = counters["str_items"]
    if not (stats.fused_runs or stats.str_items):
        return prog, stats  # nothing to do; keep the raw array identity

    ops, op_aux = _flatten(root)
    opt = _rebuild(prog, ops, op_aux)
    if verify:
        from ..analysis import irverify

        guards, consumers = _scan_anchors()
        findings = irverify.verify_optimized(prog, opt, guards, consumers)
        if findings:
            stats.rejected = True
            stats.findings = tuple(f.to_dict() for f in findings)
            metrics.inc("optimize.rejected")
            return prog, stats
    stats.applied = True
    metrics.inc("optimize.applied")
    if stats.fused_runs:
        metrics.inc("optimize.fused_runs", float(stats.fused_runs))
    return opt, stats
