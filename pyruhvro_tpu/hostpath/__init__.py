"""Native host fast path (C++ bytecode VM).

The host-side counterpart of the device pipeline: the same schema IR is
lowered to a flat opcode program (:mod:`.program`) interpreted by the
C++ VM (``runtime/native/host_codec.cpp``), emitting the device blob's
named-column layout so :mod:`..ops.arrow_build` assembles both backends'
output identically. ≙ the reference's L2a fast path
(``ruhvro/src/fast_decode.rs``) in role; the architecture (linear
bytecode + columnar builders, no per-field decoder objects) is this
framework's own.
"""

from .codec import NativeHostCodec, native_available

__all__ = ["NativeHostCodec", "native_available"]
