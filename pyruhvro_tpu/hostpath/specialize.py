"""Schema-specialized native decoders: HostProgram → straight-line C++.

The bytecode VM (``runtime/native/host_codec.cpp``) serves any schema
with zero compile latency, but pays switch dispatch + tree recursion per
field per record. This module is the host tier's analogue of XLA's
compile-once-run-many model: when a schema gets hot (see
``codec.NativeHostCodec``'s row threshold), its opcode program is
unrolled into a dedicated C++ translation unit — every column index,
branch index, enum cardinality and fixed size a compile-time constant,
no dispatch, no recursion — compiled with the same flags as the VM and
cached on disk keyed by the generated source (so a schema compiles once
per machine, ever).

Correctness story: the generated code and the VM execute the SAME
per-field leaf helpers and the SAME shard/boundary machinery
(``host_vm_core.h``); only the walk between fields is specialized. The
generator mirrors ``Vm::exec`` case-for-case, and the differential
suite runs both engines against the Python oracle
(``tests/test_specialize.py``).

≙ the role of the reference's monomorphized generics: Rust gets its
per-schema specialization from the compiler at build time
(``fast_decode.rs``'s enum dispatch is the part it could NOT
specialize); this framework generates it per schema at runtime.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import weakref
from typing import Dict, List

import numpy as np

from ..runtime import schedtest
from .program import HostProgram

__all__ = ["generate_source", "load_specialized", "touch_engine",
           "bind_engine_user"]

# ops indices (kind, a, b, col, nops, pad) — see hostpath/program.py
from .program import (  # noqa: E402  (kept near use for readability)
    OP_RECORD, OP_INT, OP_LONG, OP_FLOAT, OP_DOUBLE, OP_BOOL,
    OP_STRING, OP_ENUM, OP_NULL, OP_NULLABLE, OP_UNION,
    OP_ARRAY, OP_MAP, OP_FIXED, OP_DEC_BYTES, OP_DEC_FIXED,
)


class _GenBase:
    """Shared emitter scaffolding for the two code generators.

    ``present`` threads through ``gen`` as the literal ``True`` (field
    is statically reached — the dominant case, which compiles to
    branchless straight-line reads), the literal ``False`` (field is
    statically ABSENT: emit pure default-appends / cursor-skips with no
    wire access — the branch-table arms), or the name of a C ``bool``
    local minted by an enclosing nullable/union.
    """

    # branch-table / two-version codegen is skipped for subtrees larger
    # than this many ops: each union arm (or nullable side) duplicates
    # the whole subtree body, so the cap bounds code-size blowup
    _BRANCH_TABLE_MAX_OPS = 48

    def __init__(self, ops: np.ndarray, indent: int):
        self.ops = ops
        self.lines: List[str] = []
        self.indent = indent
        self.uid = 0
        self.cols_used: set = set()
        # effect-event journal (ISSUE 15): every subtree the generator
        # emits records (mode, pc, kind, col) — "live" for the
        # present=True spine, "cond" for bodies guarded by a minted
        # bool, "default" for statically-absent bodies. The IR verifier
        # diffs this journal (and the EFFECTS-v1 trailer rendering it)
        # against its own abstract execution of the program, which
        # catches codegen drift the embedded-table diff cannot (a body
        # that pushes the wrong column still embeds the right table).
        self.effects: List[tuple] = []

    def note(self, mode: str, pc: int) -> None:
        kind, _a, _b, col = (int(x) for x in self.ops[pc][:4])
        self.effects.append((mode, pc, kind, col))

    def w(self, line: str) -> None:
        self.lines.append("  " * self.indent + line)

    def c(self, col: int) -> str:
        self.cols_used.add(col)
        return f"C{col}"

    def fresh(self) -> int:
        self.uid += 1
        return self.uid

    def subtree_branchy(self, pc: int) -> bool:
        """Does the subtree at ``pc`` contain nullable/union nodes?
        Two-version nullable codegen is limited to branch-free inners so
        nesting cannot double code size per level."""
        stop = pc + int(self.ops[pc][4])
        for q in range(pc, stop):
            if int(self.ops[q][0]) in (OP_NULLABLE, OP_UNION):
                return True
        return False


class _Gen(_GenBase):
    """Emit the decode body for one opcode subtree."""

    def __init__(self, ops: np.ndarray):
        super().__init__(ops, indent=1)

    def gen_default(self, pc: int) -> int:
        """The statically-ABSENT body: pure default appends, no wire
        reads — what ``Vm::exec(present=false)`` does per row, unrolled.
        The branch-table union arms and the null side of two-version
        nullables are built from this."""
        self.note("default", pc)
        kind, a, b, col, nops, _pad = (int(x) for x in self.ops[pc])
        if kind == OP_RECORD:
            q = pc + 1
            stop = pc + nops
            while q < stop:
                q = self.gen_default(q)
            return q
        if kind in (OP_INT, OP_ENUM):
            self.w(f"{self.c(col)}.i32.push_back(0);")
            return pc + 1
        if kind == OP_LONG:
            self.w(f"{self.c(col)}.i64.push_back(0);")
            return pc + 1
        if kind == OP_FLOAT:
            self.w(f"{self.c(col)}.f32.push_back(0.f);")
            return pc + 1
        if kind == OP_DOUBLE:
            self.w(f"{self.c(col)}.f64.push_back(0.0);")
            return pc + 1
        if kind == OP_BOOL:
            self.w(f"{self.c(col)}.u8.push_back(0);")
            return pc + 1
        if kind == OP_STRING:
            self.w(f"{self.c(col)}.i32.push_back(0);")
            return pc + 1
        if kind == OP_FIXED:
            self.w(f"{self.c(col)}.u8.append_fill({a}, 0);")
            return pc + 1
        if kind in (OP_DEC_BYTES, OP_DEC_FIXED):
            self.w(f"{self.c(col)}.u8.append_fill(16, 0);")
            return pc + 1
        if kind == OP_NULL:
            return pc + 1
        if kind == OP_NULLABLE:
            self.w(f"{self.c(col)}.u8.push_back(0);")
            return self.gen_default(pc + 1)
        if kind == OP_UNION:
            self.w(f"{self.c(col)}.i32.push_back(0);")
            q = pc + 1
            for _ in range(a):
                q = self.gen_default(q)
            return q
        if kind in (OP_ARRAY, OP_MAP):
            offs = self.c(col)
            self.w(f"{offs}.i32.push_back({offs}.running);")
            return pc + 1 + int(self.ops[pc + 1][4])
        raise AssertionError(f"unknown op kind {kind}")  # pragma: no cover

    def gen(self, pc: int, present) -> int:
        """Generate code for the subtree at ``pc``; return next pc.
        Mirrors ``Vm::exec`` (host_codec.cpp) case-for-case."""
        if present is False:
            return self.gen_default(pc)
        self.note("live" if present is True else "cond", pc)
        kind, a, b, col, nops, _pad = (int(x) for x in self.ops[pc])
        p = "true" if present is True else present

        if kind == OP_RECORD:
            q = pc + 1
            stop = pc + nops
            while q < stop:
                q = self.gen(q, present)
            return q

        if kind == OP_INT:
            v = "(int32_t)r.read_zigzag()"
            self.w(f"{self.c(col)}.i32.push_back("
                   + (v if present is True else f"{p} ? {v} : 0") + ");")
            return pc + 1
        if kind == OP_LONG:
            v = "r.read_zigzag()"
            self.w(f"{self.c(col)}.i64.push_back("
                   + (v if present is True else f"{p} ? {v} : 0") + ");")
            return pc + 1
        if kind in (OP_FLOAT, OP_DOUBLE):
            ty, nb, fld = (("float", 4, "f32") if kind == OP_FLOAT
                           else ("double", 8, "f64"))
            u = self.fresh()
            self.w(f"{ty} v{u} = 0;")
            rd = f"r.read_fixed(&v{u}, {nb});"
            self.w(rd if present is True else f"if ({p}) {rd}")
            self.w(f"{self.c(col)}.{fld}.push_back(v{u});")
            return pc + 1
        if kind == OP_BOOL:
            u = self.fresh()
            self.w(f"uint8_t v{u} = 0;")
            body = (f"if (r.cur >= r.end) r.err |= ERR_OVERRUN; "
                    f"else {{ v{u} = r.base[r.cur++]; "
                    f"if (v{u} > 1) r.err |= ERR_BAD_BOOL; }}")
            self.w(body if present is True else f"if ({p}) {{ {body} }}")
            self.w(f"{self.c(col)}.u8.push_back(v{u});")
            return pc + 1
        if kind == OP_STRING:
            self.w(f"rd_string({self.c(col)}, r, {p});")
            return pc + 1
        if kind == OP_FIXED:
            self.w(f"rd_fixed({self.c(col)}, r, {p}, {a});")
            return pc + 1
        if kind == OP_DEC_BYTES:
            self.w(f"rd_decimal({self.c(col)}, r, {p}, -1);")
            return pc + 1
        if kind == OP_DEC_FIXED:
            self.w(f"rd_decimal({self.c(col)}, r, {p}, {a});")
            return pc + 1
        if kind == OP_ENUM:
            u = self.fresh()
            self.w(f"int64_t v{u} = 0;")
            body = (f"v{u} = r.read_zigzag(); "
                    f"if (v{u} < 0 || v{u} >= {a}) "
                    f"{{ r.err |= ERR_BAD_ENUM; v{u} = 0; }}")
            self.w(body if present is True else f"if ({p}) {{ {body} }}")
            self.w(f"{self.c(col)}.i32.push_back((int32_t)v{u});")
            return pc + 1
        if kind == OP_NULL:
            return pc + 1

        if kind == OP_NULLABLE:
            u = self.fresh()
            two_version = (
                present is True
                and nops <= self._BRANCH_TABLE_MAX_OPS
                and not self.subtree_branchy(pc + 1)
            )
            self.w(f"uint8_t valid{u} = 0; bool p{u} = false;")
            body = (f"int64_t br{u} = r.read_zigzag(); "
                    f"if (br{u} == {1 - a}) "
                    f"{{ valid{u} = 1; p{u} = true; }} "
                    f"else if (br{u} != {a}) r.err |= ERR_BAD_BRANCH;")
            self.w("{ " + body + " }" if present is True
                   else f"if ({p}) {{ {body} }}")
            self.w(f"{self.c(col)}.u8.push_back(valid{u});")
            if not two_version:
                return self.gen(pc + 1, f"p{u}")
            # hoist the null-branch check out of the per-leaf path: the
            # live side compiles branchless, the null side is pure
            # default stores (ISSUE 2 fast lane; bounded by the op cap
            # and branch-free inners so nesting cannot blow up code size)
            self.w(f"(void)p{u};")
            self.w(f"if (valid{u}) {{")
            self.indent += 1
            end = self.gen(pc + 1, True)
            self.indent -= 1
            self.w("} else {")
            self.indent += 1
            self.gen_default(pc + 1)
            self.indent -= 1
            self.w("}")
            return end

        if kind == OP_UNION:
            u = self.fresh()
            self.w(f"int32_t tid{u} = 0;")
            body = (f"int64_t br{u} = r.read_zigzag(); "
                    f"if (br{u} < 0 || br{u} >= {a}) "
                    f"{{ r.err |= ERR_BAD_BRANCH; br{u} = 0; }} "
                    f"tid{u} = (int32_t)br{u};")
            self.w("{ " + body + " }" if present is True
                   else f"if ({p}) {{ {body} }}")
            self.w(f"{self.c(col)}.i32.push_back(tid{u});")
            if nops <= self._BRANCH_TABLE_MAX_OPS:
                # branch-table dispatch: one switch per row; the
                # selected arm decodes straight-line while the others
                # emit their default stores — replaces the per-arm
                # bool-flag chain that re-tested the branch at every leaf
                arm_pcs = []
                q = pc + 1
                for _ in range(a):
                    arm_pcs.append(q)
                    q += int(self.ops[q][4])
                self.w(f"switch (tid{u}) {{")
                for k, apc in enumerate(arm_pcs):
                    self.w(f"case {k}: {{")
                    self.indent += 1
                    for j, jpc in enumerate(arm_pcs):
                        if j == k:
                            self.gen(jpc, present)
                        else:
                            self.gen_default(jpc)
                    self.indent -= 1
                    self.w("} break;")
                # tids are range-checked upstream; the default arm keeps
                # the appends/cursors in sync regardless (the VM's
                # every-arm-absent behavior)
                self.w("default: {")
                self.indent += 1
                for jpc in arm_pcs:
                    self.gen_default(jpc)
                self.indent -= 1
                self.w("} break;")
                self.w("}")
                return q
            q = pc + 1
            for k in range(a):
                sel = (f"tid{u} == {k}" if present is True
                       else f"{p} && tid{u} == {k}")
                v = self.fresh()
                self.w(f"bool p{v} = {sel};")
                q = self.gen(q, f"p{v}")
            return q

        if kind in (OP_ARRAY, OP_MAP):
            u = self.fresh()
            offs = self.c(col)
            self.w("{")
            self.indent += 1
            opened = present is not True
            if opened:
                self.w(f"if ({p}) {{")
                self.indent += 1
            # ≙ Vm::decode_blocks — same checks in the same order
            self.w("for (;;) {")
            self.indent += 1
            self.w(f"if (r.err) goto blk{u}_done;")
            self.w(f"int64_t cnt{u} = r.read_zigzag();")
            self.w(f"if (r.err || cnt{u} == 0) goto blk{u}_done;")
            self.w(f"if (cnt{u} < 0) {{ cnt{u} = -cnt{u}; "
                   f"(void)r.read_raw_varint(); "
                   f"if (r.err) goto blk{u}_done; }}")
            self.w(f"for (int64_t i{u} = 0; i{u} < cnt{u}; i{u}++) {{")
            self.indent += 1
            self.w(f"if (r.err) goto blk{u}_done;")
            self.w(f"if (r.cur > r.end) "
                   f"{{ r.err |= ERR_OVERRUN; goto blk{u}_done; }}")
            # capture before the map key read (an entry with a key is
            # never zero-width) — same rule as Vm::decode_blocks
            self.w(f"int64_t c0_{u} = r.cur;")
            if kind == OP_MAP:
                self.w(f"rd_string({self.c(b)}, r, true);")
                self.w(f"if (r.err) goto blk{u}_done;")
            inner_end = self.gen(pc + 1, True)
            # zero-width item guard — same rule as Vm::decode_blocks:
            # a block of null/empty-record items charges its claimed
            # count against the per-record kMaxZeroWidthItems budget
            self.w(f"if (i{u} == 0 && r.cur == c0_{u}) {{")
            self.w(f"  r.zw += cnt{u};")
            self.w(f"  if (r.zw > kMaxZeroWidthItems) "
                   f"{{ r.err |= ERR_OVERRUN; goto blk{u}_done; }}")
            self.w("}")
            self.w(f"{offs}.running++;")
            self.w(f"if ({offs}.running < 0) "
                   f"{{ r.err |= ERR_OVERRUN; goto blk{u}_done; }}")
            self.indent -= 1
            self.w("}")
            self.indent -= 1
            self.w("}")
            self.w(f"blk{u}_done:;")
            if opened:
                self.indent -= 1
                self.w("}")
            self.indent -= 1
            self.w("}")
            self.w(f"{offs}.i32.push_back({offs}.running);")
            return inner_end

        raise AssertionError(f"unknown op kind {kind}")  # pragma: no cover


class _EncGen(_GenBase):
    """Emit the encode body for one opcode subtree — mirrors
    ``EncVm::exec`` (host_codec.cpp) case-for-case. Entry cursors always
    advance (absent subtrees consume their entries without emitting),
    exactly like the VM."""

    def __init__(self, ops: np.ndarray):
        super().__init__(ops, indent=2)

    def gen_default(self, pc: int) -> int:
        """The statically-ABSENT encode body: advance the entry cursors
        without emitting a byte — what ``EncVm::exec(present=false)``
        does, unrolled (non-selected union arms, null nullable sides)."""
        self.note("default", pc)
        kind, a, b, col, nops, _pad = (int(x) for x in self.ops[pc])
        if kind == OP_RECORD:
            q = pc + 1
            stop = pc + nops
            while q < stop:
                q = self.gen_default(q)
            return q
        if kind in (OP_INT, OP_ENUM, OP_LONG, OP_FLOAT, OP_DOUBLE, OP_BOOL):
            C = self.c(col)
            self.w(f"{C}.cur++;")
            return pc + 1
        if kind == OP_STRING:
            C = self.c(col)
            self.w(f"{C}.bcur += (size_t){C}.i32[{C}.cur++];")
            return pc + 1
        if kind == OP_FIXED:
            C = self.c(col)
            self.w(f"{C}.cur += {a};")
            return pc + 1
        if kind in (OP_DEC_BYTES, OP_DEC_FIXED):
            C = self.c(col)
            self.w(f"{C}.cur += 16;")
            return pc + 1
        if kind == OP_NULL:
            return pc + 1
        if kind == OP_NULLABLE:
            C = self.c(col)
            self.w(f"{C}.cur++;")
            return self.gen_default(pc + 1)
        if kind == OP_UNION:
            C = self.c(col)
            self.w(f"{C}.cur++;")
            q = pc + 1
            for _ in range(a):
                q = self.gen_default(q)
            return q
        if kind in (OP_ARRAY, OP_MAP):
            u = self.fresh()
            C = self.c(col)
            self.w(f"int32_t cnt{u} = {C}.i32[{C}.cur++];")
            self.w(f"for (int32_t i{u} = 0; i{u} < cnt{u}; i{u}++) {{")
            self.indent += 1
            if kind == OP_MAP:
                K = self.c(b)
                self.w(f"{K}.bcur += (size_t){K}.i32[{K}.cur++];")
            inner_end = self.gen_default(pc + 1)
            self.indent -= 1
            self.w("}")
            return inner_end
        raise AssertionError(f"unknown op kind {kind}")  # pragma: no cover

    def gen(self, pc: int, present) -> int:
        if present is False:
            return self.gen_default(pc)
        self.note("live" if present is True else "cond", pc)
        kind, a, b, col, nops, _pad = (int(x) for x in self.ops[pc])
        p = "true" if present is True else present

        if kind == OP_RECORD:
            q = pc + 1
            stop = pc + nops
            while q < stop:
                q = self.gen(q, present)
            return q

        if kind in (OP_INT, OP_ENUM):
            u = self.fresh()
            C = self.c(col)
            self.w(f"int32_t v{u} = {C}.i32[{C}.cur++];")
            wr = f"write_zigzag(out, (int64_t)v{u});"
            self.w(wr if present is True else f"if ({p}) {wr}")
            return pc + 1
        if kind == OP_LONG:
            u = self.fresh()
            C = self.c(col)
            self.w(f"int64_t v{u} = {C}.i64[{C}.cur++];")
            wr = f"write_zigzag(out, v{u});"
            self.w(wr if present is True else f"if ({p}) {wr}")
            return pc + 1
        if kind in (OP_FLOAT, OP_DOUBLE):
            ty, nb, fld = (("float", 4, "f32") if kind == OP_FLOAT
                           else ("double", 8, "f64"))
            u = self.fresh()
            C = self.c(col)
            self.w(f"{ty} v{u} = {C}.{fld}[{C}.cur++];")
            wr = (f"{{ uint8_t b{u}[{nb}]; std::memcpy(b{u}, &v{u}, {nb}); "
                  f"out.append(b{u}, {nb}); }}")
            self.w(wr if present is True else f"if ({p}) {wr}")
            return pc + 1
        if kind == OP_BOOL:
            u = self.fresh()
            C = self.c(col)
            self.w(f"uint8_t v{u} = {C}.u8[{C}.cur++];")
            wr = f"out.push(v{u} ? 1 : 0);"
            self.w(wr if present is True else f"if ({p}) {wr}")
            return pc + 1
        if kind == OP_STRING:
            self.w(f"wr_string(out, {self.c(col)}, {p});")
            return pc + 1
        if kind == OP_FIXED:
            C = self.c(col)
            wr = f"out.append({C}.u8 + {C}.cur, {a});"
            self.w(wr if present is True else f"if ({p}) {wr}")
            self.w(f"{C}.cur += {a};")
            return pc + 1
        if kind in (OP_DEC_BYTES, OP_DEC_FIXED):
            fs = -1 if kind == OP_DEC_BYTES else a
            self.w(f"if (!wr_decimal(out, {self.c(col)}, {p}, {fs})) "
                   f"return false;")
            return pc + 1
        if kind == OP_NULL:
            return pc + 1

        if kind == OP_NULLABLE:
            u = self.fresh()
            C = self.c(col)
            self.w(f"uint8_t valid{u} = {C}.u8[{C}.cur++];")
            wr = (f"write_zigzag(out, valid{u} ? (int64_t){1 - a} "
                  f": (int64_t){a});")
            self.w(wr if present is True else f"if ({p}) {wr}")
            if (present is True and nops <= self._BRANCH_TABLE_MAX_OPS
                    and not self.subtree_branchy(pc + 1)):
                # hoisted null check: live side writes branchless, null
                # side is pure cursor skips (mirrors the decode gen)
                self.w(f"if (valid{u}) {{")
                self.indent += 1
                end = self.gen(pc + 1, True)
                self.indent -= 1
                self.w("} else {")
                self.indent += 1
                self.gen_default(pc + 1)
                self.indent -= 1
                self.w("}")
                return end
            v = self.fresh()
            sel = (f"valid{u} != 0" if present is True
                   else f"{p} && valid{u}")
            self.w(f"bool p{v} = {sel};")
            return self.gen(pc + 1, f"p{v}")

        if kind == OP_UNION:
            u = self.fresh()
            C = self.c(col)
            self.w(f"int32_t tid{u} = {C}.i32[{C}.cur++];")
            wr = f"write_zigzag(out, (int64_t)tid{u});"
            self.w(wr if present is True else f"if ({p}) {wr}")
            if nops <= self._BRANCH_TABLE_MAX_OPS:
                # branch-table dispatch (mirrors the decode gen): the
                # selected arm encodes straight-line, the others skip
                # their cursors
                arm_pcs = []
                q = pc + 1
                for _ in range(a):
                    arm_pcs.append(q)
                    q += int(self.ops[q][4])
                self.w(f"switch (tid{u}) {{")
                for k, apc in enumerate(arm_pcs):
                    self.w(f"case {k}: {{")
                    self.indent += 1
                    for j, jpc in enumerate(arm_pcs):
                        if j == k:
                            self.gen(jpc, present)
                        else:
                            self.gen_default(jpc)
                    self.indent -= 1
                    self.w("} break;")
                # tids are range-checked upstream; the default arm keeps
                # the appends/cursors in sync regardless (the VM's
                # every-arm-absent behavior)
                self.w("default: {")
                self.indent += 1
                for jpc in arm_pcs:
                    self.gen_default(jpc)
                self.indent -= 1
                self.w("} break;")
                self.w("}")
                return q
            q = pc + 1
            for k in range(a):
                sel = (f"tid{u} == {k}" if present is True
                       else f"{p} && tid{u} == {k}")
                v = self.fresh()
                self.w(f"bool p{v} = {sel};")
                q = self.gen(q, f"p{v}")
            return q

        if kind in (OP_ARRAY, OP_MAP):
            u = self.fresh()
            C = self.c(col)
            self.w(f"int32_t cnt{u} = {C}.i32[{C}.cur++];")
            wr = f"if (cnt{u} > 0) write_zigzag(out, (int64_t)cnt{u});"
            self.w(wr if present is True
                   else f"if ({p}) {{ {wr} }}")
            self.w(f"for (int32_t i{u} = 0; i{u} < cnt{u}; i{u}++) {{")
            self.indent += 1
            if kind == OP_MAP:
                self.w(f"wr_string(out, {self.c(b)}, {p});")
            inner_end = self.gen(pc + 1, present)
            self.indent -= 1
            self.w("}")
            term = "out.push(0);  // block terminator"
            self.w(term if present is True else f"if ({p}) {term}")
            return inner_end

        raise AssertionError(f"unknown op kind {kind}")  # pragma: no cover


_TEMPLATE = """\
// AUTO-GENERATED by pyruhvro_tpu.hostpath.specialize — DO NOT EDIT.
// One schema's HostProgram unrolled into straight-line C++ over the
// shared decode/extract cores (host_vm_core.h, extract_core.h).
// Regenerated whenever the program or a core changes (content-hashed
// module name). The embedded opcode/aux tables feed the Arrow-native
// extraction pass, fused ahead of the generated encoder in
// encode_arrow — no VM dispatch anywhere between the Arrow buffers
// and the wire bytes.
#include "{core}"

namespace {{
using namespace pyr;

{static_tables}

inline void decode_record(Reader& r, std::vector<Col>& cols) {{
{col_refs}
{body}
}}

struct EncRec {{
  template <class W>
  inline bool operator()(W& out, std::vector<InCol>& cols) const {{
{enc_col_refs}
{enc_body}
    return true;
  }}
}};

PyObject* py_decode_spec(PyObject*, PyObject* args) {{
  PyObject *coltypes_obj, *list_obj;
  int nthreads = 0;
  if (!PyArg_ParseTuple(args, "OO|i", &coltypes_obj, &list_obj, &nthreads))
    return nullptr;
  return decode_boundary(
      [](Reader& r, std::vector<Col>& cols) {{ decode_record(r, cols); }},
      coltypes_obj, list_obj, nthreads);
}}

PyObject* py_decode_arrow_spec(PyObject*, PyObject* args) {{
  PyObject *coltypes_obj, *list_obj;
  int nthreads = 0;
  if (!PyArg_ParseTuple(args, "OO|i", &coltypes_obj, &list_obj, &nthreads))
    return nullptr;
  return decode_arrow_boundary(
      [](Reader& r, std::vector<Col>& cols) {{ decode_record(r, cols); }},
      kOps, kAux, coltypes_obj, list_obj, nthreads);
}}

PyObject* py_encode_spec(PyObject*, PyObject* args) {{
  PyObject *coltypes_obj, *bufs_obj;
  Py_ssize_t n;
  Py_ssize_t size_hint = 0;
  int checked = 0;
  if (!PyArg_ParseTuple(args, "OOn|ni", &coltypes_obj, &bufs_obj, &n,
                        &size_hint, &checked))
    return nullptr;
  return encode_boundary(EncRec{{}}, coltypes_obj, bufs_obj, n, size_hint,
                         checked);
}}

PyObject* py_encode_arrow_spec(PyObject*, PyObject* args) {{
  PyObject* coltypes_obj;
  unsigned long long addr_a, addr_s;
  Py_ssize_t n;
  int checked = 0, nshards = 1;
  if (!PyArg_ParseTuple(args, "OKKn|ii", &coltypes_obj, &addr_a, &addr_s,
                        &n, &checked, &nshards))
    return nullptr;
  return encode_arrow_boundary(EncRec{{}}, kOps, kAux, coltypes_obj,
                               (uintptr_t)addr_a, (uintptr_t)addr_s, n,
                               checked, nshards);
}}

PyObject* py_shard_stats_spec(PyObject*, PyObject*) {{
  return shard_stats_py();
}}

PyMethodDef methods[] = {{
    {{"decode", py_decode_spec, METH_VARARGS,
     "decode(coltypes, data, nthreads=0) -> (buffers, err_record, err_bits)"}},
    {{"decode_arrow", py_decode_arrow_spec, METH_VARARGS,
     "decode_arrow(coltypes, data, nthreads=0) -> "
     "((tag, payload), err_record, err_bits)"}},
    {{"encode", py_encode_spec, METH_VARARGS,
     "encode(coltypes, buffers, n, size_hint=0) -> (blob, offsets)"}},
    {{"encode_arrow", py_encode_arrow_spec, METH_VARARGS,
     "encode_arrow(coltypes, addr_array, addr_schema, n, checked=0, "
     "nshards=1) -> (blob, offsets, t_extract_s, t_encode_s) | status int"}},
    {{"shard_stats", py_shard_stats_spec, METH_NOARGS,
     "shard_stats() -> {{fanouts, shards, shard_s, wall_s, threads}}"}},
    {{nullptr, nullptr, 0, nullptr}},
}};

PyModuleDef moduledef = {{
    PyModuleDef_HEAD_INIT, "{mod}",
    "schema-specialized Avro decoder", -1, methods,
}};

}}  // namespace

extern "C" PyMODINIT_FUNC PyInit_{mod}(void) {{
  return PyModule_Create(&moduledef);
}}
"""


def _static_tables(prog: HostProgram) -> str:
    """The embedded opcode + aux tables the fused Arrow-native
    extraction walks (extract_core.h ArrowExtractor)."""
    lines = ["static const Op kOps[] = {"]
    for row in prog.ops:
        kind, a, b, col, nops, _pad = (int(x) for x in row)
        lines.append(f"    {{{kind}, {a}, {b}, {col}, {nops}, 0}},")
    lines.append("};")
    aux = prog.op_aux or tuple(None for _ in range(len(prog.ops)))
    entries = []
    for i, e in enumerate(aux):
        if e is None:
            entries.append("    {AUX_NONE, nullptr, nullptr, 0},")
        elif e[0] == "uuid":
            entries.append("    {AUX_UUID, nullptr, nullptr, 0},")
        elif e[0] == "binary":
            entries.append("    {AUX_BINARY, nullptr, nullptr, 0},")
        elif e[0] == "duration":
            entries.append("    {AUX_DURATION, nullptr, nullptr, 0},")
        elif e[0] == "decimal":  # ("decimal", precision)
            entries.append(
                f"    {{AUX_DECIMAL, nullptr, nullptr, {int(e[1])}}},"
            )
        else:  # ("enum", symbol_bytes, ...)
            syms = e[1:]
            for k, s in enumerate(syms):
                bs = ", ".join(str(x) for x in s) + ", 0" if s else "0"
                lines.append(f"static const char kSym_{i}_{k}[] = {{{bs}}};")
            ptrs = ", ".join(f"kSym_{i}_{k}" for k in range(len(syms)))
            lens = ", ".join(str(len(s)) for s in syms)
            lines.append(
                f"static const char* const kSyms_{i}[] = {{{ptrs}}};"
            )
            lines.append(
                f"static const int32_t kSymLens_{i}[] = {{{lens}}};"
            )
            entries.append(
                f"    {{AUX_ENUM, kSyms_{i}, kSymLens_{i}, {len(syms)}}},"
            )
    lines.append("static const OpAux kAux[] = {")
    lines.extend(entries)
    lines.append("};")
    return "\n".join(lines)


def generate_source(prog: HostProgram, mod_name: str,
                    core_include: str = "../arrow_decode_core.h",
                    with_effects: bool = False) -> str:
    """The C++ translation unit for one schema's decoder + encoder.

    ``with_effects=True`` appends the machine-readable ``EFFECTS-v1``
    trailer (the generators' effect-event journals as one JSON line) for
    the IR verifier's equivalence diff; production callers leave it off
    so cached sources stay byte-stable."""
    g = _Gen(prog.ops)
    g.gen(0, True)
    col_refs = "\n".join(
        f"  Col& C{c} = cols[{c}];" for c in sorted(g.cols_used)
    )
    eg = _EncGen(prog.ops)
    eg.gen(0, True)
    enc_col_refs = "\n".join(
        f"    InCol& C{c} = cols[{c}];" for c in sorted(eg.cols_used)
    )
    src = _TEMPLATE.format(
        core=core_include,
        mod=mod_name,
        static_tables=_static_tables(prog),
        col_refs=col_refs,
        body="\n".join(g.lines),
        enc_col_refs=enc_col_refs,
        enc_body="\n".join(eg.lines),
    )
    if with_effects:
        import json as _json

        trailer = _json.dumps(
            {"decode": [list(e) for e in g.effects],
             "encode": [list(e) for e in eg.effects]},
            separators=(",", ":"))
        src += f"\n// EFFECTS-v1 {trailer}\n"
    return src


def _native_dir() -> str:
    from ..runtime.native import build as nb

    return nb._HERE


# -- engine lifecycle / accounting (ISSUE 12) -------------------------------
#
# Every loaded specialized engine registers here: its on-disk .so size
# (the byte-accurate part of what dlopen mapped), an LRU clock, and
# weak references to the NativeHostCodec instances serving through it.
# Eviction drops the Python-side references (module memo + each codec's
# ``_spec``) so the next decode re-admits via ``load_specialized`` —
# a pure dlopen of the existing disk artifact, never a recompile. The
# mapped code itself stays resident (CPython never dlcloses extension
# modules); the registry accounts it either way so the footprint an
# operator sees matches what RSS holds.

_eng_lock = threading.Lock()
# mod_name -> {"bytes": so size, "last_used": monotonic, "codecs": WeakSet}
_engines: Dict[str, dict] = {}  # guarded-by: _eng_lock


def _note_engine(mod_name: str, so_path: str) -> dict:
    try:
        size = os.path.getsize(so_path)
    except OSError:
        size = 0
    schedtest.yp("engine.note")
    with _eng_lock:
        rec = _engines.get(mod_name)
        if rec is None:
            rec = _engines[mod_name] = {
                "bytes": float(size),
                "last_used": time.monotonic(),
                "codecs": weakref.WeakSet(),
            }
        else:
            rec["last_used"] = time.monotonic()
            if size:
                rec["bytes"] = float(size)
    return rec


def touch_engine(mod_name: str) -> None:
    """Stamp an engine's LRU clock (called per decode serving through
    it; a dict store under the GIL, no lock on the hot path)."""
    rec = _engines.get(mod_name)
    if rec is not None:
        rec["last_used"] = time.monotonic()


def bind_engine_user(mod_name: str, codec) -> None:
    """Attach a codec to the engine's user set so eviction can unhook
    its ``_spec`` reference."""
    with _eng_lock:
        rec = _engines.get(mod_name)
        if rec is not None:
            rec["codecs"].add(codec)


def _engine_entries():
    with _eng_lock:
        return [(name, rec["last_used"], rec["bytes"])
                for name, rec in _engines.items()]


def _evict_engine(mod_name: str) -> bool:
    from ..runtime import metrics
    from ..runtime.native import build as nb

    schedtest.yp("engine.evict")
    with _eng_lock:
        rec = _engines.pop(mod_name, None)
    if rec is None:
        return False
    nb._modules.pop(mod_name, None)
    for codec in list(rec["codecs"]):
        # leave _rows_seen and _spec_failed untouched: the schema is
        # still hot, so the NEXT decode re-admits through
        # load_specialized (a disk-cache dlopen, not a g++ run)
        codec._spec = None
        codec._spec_name = None
    metrics.inc("specialize.evictions")
    return True


def _register_lifecycle() -> None:
    from ..runtime import cachelife, knobs, memacct

    cachelife.register(
        "engines",
        entries=_engine_entries,
        evict=_evict_engine,
        capacity=lambda: knobs.get_int("PYRUHVRO_TPU_CACHE_MAX_ENGINES"),
    )

    def _probe():
        with _eng_lock:
            return {
                "bytes": float(sum(r["bytes"]
                                   for r in _engines.values())),
                "items": float(len(_engines)),
            }

    memacct.register_probe("cache.engines", _probe)


_register_lifecycle()


def load_specialized(prog: HostProgram):
    """Generate + compile + import this program's specialized decoder.

    Returns the extension module (its ``decode(coltypes, data,
    nthreads)`` matches the interpreter's minus the ops argument), or
    ``None`` when the toolchain is unavailable or the build fails —
    callers keep the interpreter. Disk-cached: the module name is a
    content hash of the generated source AND the shared core header, so
    any change to either regenerates, and repeat processes just dlopen.
    """
    from ..runtime.native import build as nb

    if nb._san_active() or nb._tsan_active():
        # the spec cache is keyed by source content only — a sanitized
        # build would be served to later uninstrumented runs. Sanitizer
        # sessions (ASan and TSan alike) pin the interpreter VM (whose
        # .san/.tsan flavors ARE keyed).
        return None
    spec_dir = os.path.join(_native_dir(), "_spec")
    try:
        core_text = ""
        for name in ("host_vm_core.h", "extract_core.h",
                     "arrow_decode_core.h", "shard_runner.h"):
            with open(os.path.join(_native_dir(), name)) as f:
                core_text += f.read() + "\x00"
        probe = generate_source(prog, "M")  # name-independent content
        h = hashlib.sha256(
            (probe + "\x00" + core_text).encode()
        ).hexdigest()[:12]
        mod_name = f"_pyruhvro_spec_{h}"
        so = os.path.join(spec_dir, mod_name + nb._ext_suffix())
        # memo hits read with .get: a concurrent lifecycle eviction may
        # pop the key between a membership check and the read, and a
        # swallowed KeyError here would read as "build failed" and pin
        # the interpreter for the codec's lifetime
        mod = nb._modules.get(mod_name)
        if mod is not None:
            _note_engine(mod_name, so)
            return mod
        schedtest.yp("engine.memo")
        with nb._lock:
            mod = nb._modules.get(mod_name)
            if mod is not None:
                _note_engine(mod_name, so)
                return mod
            os.makedirs(spec_dir, exist_ok=True)
            src = os.path.join(spec_dir, mod_name + ".cpp")
            if not os.path.exists(src):
                tmp = f"{src}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    f.write(generate_source(prog, mod_name))
                os.replace(tmp, src)
            if nb._needs_build(so, src):
                nb._compile(so, src)
            import importlib.util

            spec = importlib.util.spec_from_file_location(mod_name, so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            nb._modules[mod_name] = mod
        # lifecycle registration + admission OUTSIDE the build lock
        # (LRU eviction of another engine must not wait on a compile)
        _note_engine(mod_name, so)
        from ..runtime import cachelife

        cachelife.admit("engines")
        return mod
    except Exception:
        return None
