"""NativeHostCodec: decode Avro datums on the CPU through the C++ VM.

The fast host path the public API routes to when no device wins (and
the safety net behind it stays the pure-Python fallback decoder, which
doubles as the differential oracle). Output equality with both other
backends is guaranteed by construction: all three feed the same Arrow
assembly (``ops/arrow_build.py``) or are differentially tested against
it (``tests/test_hostpath.py``).

≙ the reference's fast path position in the stack
(``deserialize.rs:26-29`` gate → ``fast_decode.rs:806``), with the
bytecode-VM architecture documented in :mod:`.program`.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import pyarrow as pa

from ..fallback.io import malformed_record
from ..ops.varint import ERR_NAMES, ERR_SLUGS
from ..runtime.native.build import load_host_codec
from .program import HostProgram, lower_host

__all__ = ["NativeHostCodec", "native_available"]


def native_available() -> bool:
    """True when the C++ VM compiled/loaded (memoized by the builder)."""
    return load_host_codec() is not None


def _drain_native_prof(*mods, scale: float = 1.0) -> None:
    """Fold the native-tier profiler's per-opcode counters into the
    telemetry layer (``vm.op.*`` / ``vm.encop.*`` / ``extract.op.*``
    hit counts plus ``*_s`` self-time seconds). No-op on the default
    (unprofiled) builds — only the profiled variants export
    ``prof_drain``. ``scale`` is the adaptive sampler's weight
    correction: a deep-sampled call stands in for ~period calls, so its
    drained hits/seconds multiply by the period — the merged totals
    then ESTIMATE what an always-profiled run would have recorded."""
    from ..runtime import metrics

    for mod in mods:
        drain = getattr(mod, "prof_drain", None)
        if drain is None:
            continue
        for key, (hits, ns) in drain().items():
            if hits:
                metrics.inc(key, float(hits) * scale)
            if ns:
                metrics.inc(key + "_s", ns * 1e-9 * scale)


def _vm_threads(nthreads: int) -> int:
    """Resolve the VM shard-thread count: an explicit argument wins,
    else PYRUHVRO_TPU_VM_THREADS pins it (profiling runs set 1 so the
    per-opcode self-times decompose the wall-clock ``host.vm_s`` instead
    of summing CPU time across shards), else 0 = the VM's auto pick."""
    if nthreads:
        return nthreads
    from ..runtime import knobs

    return max(0, knobs.get_int("PYRUHVRO_TPU_VM_THREADS"))


class NativeHostCodec:
    """Schema-bound native decoder (per-schema program, compiled once).

    Raises :class:`RuntimeError` when the native module is unavailable
    and :class:`..ops.UnsupportedOnDevice` when the schema is outside
    the fast subset — callers fall back to the Python decoder for both.
    """

    # Cumulative decoded rows after which a schema is "hot" and earns a
    # SPECIALIZED decoder: its opcode program is unrolled to straight-
    # line C++ and compiled (hostpath/specialize.py) — a one-time ~1s
    # g++ run, disk-cached per machine, the same economics as an XLA
    # compile. Below the threshold the bytecode VM serves with zero
    # latency (tests, one-shot scripts). PYRUHVRO_TPU_SPECIALIZE_ROWS=0
    # forces immediate specialization; PYRUHVRO_TPU_NO_SPECIALIZE=1
    # pins the interpreter.
    _SPECIALIZE_ROWS = 20_000

    def __init__(self, ir, arrow_schema: pa.Schema):
        self.ir = ir
        self.arrow_schema = arrow_schema
        self.prog: HostProgram = lower_host(ir)  # raises UnsupportedOnDevice
        self._plan = self.prog.buffer_plan()
        self._mod = load_host_codec()
        if self._mod is None:
            raise RuntimeError("native host codec unavailable (no toolchain)")
        from ..runtime import knobs

        # the opcode superoptimizer (hostpath/optimize.py): fused runs /
        # elision flags, accepted ONLY when the irverify oracle proves
        # effect equality. The optimized program serves the GENERIC VM
        # call sites; the RAW program stays the source of truth for the
        # specializer, the encode plan and the assembler. A stale .so
        # (no ``shard_stats`` export ⇒ predates OP_FIXED_RUN) pins the
        # raw program — an old switch would silently skip fused members.
        self.oprog: HostProgram = self.prog
        self.opt_stats = None
        if (not knobs.get_bool("PYRUHVRO_TPU_NO_OPT")
                and hasattr(self._mod, "shard_stats")):
            from .optimize import optimize_program

            self.oprog, self.opt_stats = optimize_program(self.prog)

        self._spec = None            # the specialized module, once built
        self._spec_name = None       # its engine-registry key (ISSUE 12)
        # the per-opcode profiler lives in the generic VM's dispatch
        # points; the specialized engines are straight-line code with
        # nothing to attribute, so profiling pins the interpreter
        self._prof = knobs.get_bool("PYRUHVRO_TPU_NATIVE_PROF")
        self._spec_failed = (
            knobs.get_bool("PYRUHVRO_TPU_NO_SPECIALIZE") or self._prof
        )
        self._spec_rows = knobs.get_int("PYRUHVRO_TPU_SPECIALIZE_ROWS")
        self._rows_seen = 0
        # Arrow-native extraction (runtime/native/extract.cpp): probed
        # lazily; PYRUHVRO_TPU_NO_NATIVE_EXTRACT=1 pins the Python
        # extractor (the differential oracle for the native one).
        # Transient lane failures are no longer a permanent latch: the
        # process-wide ``native_extract`` circuit breaker decides when
        # the lane is withheld and when a half-open probe re-admits it.
        self._extract_mod = None
        self._extract_pinned = knobs.get_bool(
            "PYRUHVRO_TPU_NO_NATIVE_EXTRACT"
        )
        # the last Arrow schema the native extractor declined on SHAPE:
        # repeated encodes of that shape skip the doomed C++ probe (and
        # its duplicate struct build) instead of paying it per call
        self._extract_declined_schema = None

    def _maybe_specialize(self, n: int) -> None:
        if self._spec is not None or self._spec_failed:
            return
        self._rows_seen += n
        if self._rows_seen < self._spec_rows:
            return
        from .specialize import bind_engine_user, load_specialized

        mod = load_specialized(self.prog)
        if mod is None:
            self._spec_failed = True  # no toolchain / build error: probe once
        else:
            self._spec = mod
            # lifecycle hookup: the engine's LRU clock ticks per decode
            # and eviction can unhook this codec's reference
            self._spec_name = mod.__name__
            bind_engine_user(self._spec_name, self)

    def decode(self, data: Sequence[bytes],
               nthreads: int = 0, index_base: int = 0) -> pa.RecordBatch:
        """``index_base`` offsets error-message record indices so the
        per-chunk mode of :meth:`decode_threaded` still reports the
        GLOBAL position of a malformed datum.

        ``data`` is a sequence of bytes-likes or a
        :class:`..runtime.ingest.DatumView` (a pyarrow Binary/
        LargeBinaryArray): the latter ships its offsets+values buffers
        to the VM directly — zero per-datum Python objects on the
        ingest boundary."""
        from ..ops.arrow_build import (
            build_fused_record_batch,
            build_record_batch,
        )
        from ..runtime import metrics, telemetry

        n = len(data)
        # adaptive deep sampling (runtime/sampling.py): a sampled call
        # decodes through the per-opcode-profiled interpreter build —
        # even when a specialized engine is warm, because straight-line
        # code has nothing to attribute — and its drained self-times
        # merge weight-corrected (x period) into the live registry
        deep_mod = None
        if not self._prof:
            from ..runtime import sampling

            if sampling.deep_active():
                deep_mod = sampling.prof_codec_module()
        with telemetry.phase("host.decode_s", rows=n):
            self._maybe_specialize(n)
            # fault seam + cooperative deadline checkpoint before the
            # (uninterruptible) VM pass; index-aware like the VM's own
            # malformed-record reporting
            from ..runtime import deadline, faults

            deadline.check(index=index_base, site="host.vm")
            faults.fire("vm_decode")
            # records decode straight from the caller's bytes objects
            # (span collection in C++, ≙ extract_bytes_list
            # src/lib.rs:29-33) or straight from a pyarrow array's own
            # buffers — no concatenation pass exists on this path at all
            native_data = (
                data.native_parts() if hasattr(data, "native_parts")
                else data
            )
            # the serving engine: deep-sampled prof build > specialized
            # straight-line module > generic interpreter — each offers
            # the fused wire→Arrow entry unless the knob pins the
            # oracle (or a stale .so predates it)
            # bind the specialized engine ONCE: a concurrent lifecycle
            # eviction may null self._spec at any point (the engine
            # module itself stays valid — eviction only unlinks
            # references), so the check and the use must read the same
            # local, never re-read the attribute
            spec_eng = self._spec
            if deep_mod is not None:
                eng, generic = deep_mod, True
            elif spec_eng is not None:
                eng, generic = spec_eng, False
                if self._spec_name:
                    from .specialize import touch_engine

                    touch_engine(self._spec_name)
            else:
                eng, generic = self._mod, True
            from ..runtime import knobs

            fused = None
            if not knobs.get_bool("PYRUHVRO_TPU_NO_FUSED_DECODE"):
                fused = getattr(eng, "decode_arrow", None)
            with telemetry.phase("host.vm_s",
                                 specialized=(spec_eng is not None
                                              and deep_mod is None),
                                 fused=fused is not None):
                # generic engines run the OPTIMIZED program when the
                # loaded binary understands it (same stale-.so probe as
                # __init__ — the deep-sampled prof module is a separate
                # binary with its own staleness)
                gprog = (self.oprog if hasattr(eng, "shard_stats")
                         else self.prog)
                if fused is not None:
                    if generic:
                        payload, err_rec, err_bits = fused(
                            gprog.ops, gprog.coltypes,
                            gprog.op_aux, native_data,
                            _vm_threads(nthreads),
                        )
                    else:
                        payload, err_rec, err_bits = fused(
                            self.prog.coltypes, native_data, nthreads
                        )
                elif generic:
                    payload, err_rec, err_bits = eng.decode(
                        gprog.ops, gprog.coltypes, native_data,
                        _vm_threads(nthreads)
                    )
                else:
                    payload, err_rec, err_bits = eng.decode(
                        self.prog.coltypes, native_data, nthreads
                    )
            if self._prof:
                _drain_native_prof(self._mod)
            elif deep_mod is not None:
                from ..runtime import sampling

                sampling.note_deep_ran()
                _drain_native_prof(deep_mod,
                                   scale=sampling.deep_weight())
            if err_rec >= 0:
                bit = err_bits & -err_bits
                raise malformed_record(
                    err_rec + index_base,
                    ERR_NAMES.get(bit, f"error bit {bit:#x}"),
                    err_name=ERR_SLUGS.get(bit, f"bit_{bit:#x}"),
                    tier="native",
                )
            if fused is not None:
                tag, body = payload
                if tag == "arrow":
                    # the hot lane: every buffer already in Arrow
                    # layout — assembly is pure from_buffers composition
                    metrics.inc("decode.fused")
                    with telemetry.phase("host.build_s", fused=True):
                        return build_fused_record_batch(
                            self.ir, self.arrow_schema, body, n
                        )
                # the native pass declined (exotic value/shape — or a
                # data condition whose error the oracle words): the
                # plan buffers flow into the differential oracle below
                metrics.inc("decode.fused_fallback")
                bufs = body
            else:
                bufs = payload
            host = {}
            for (key, dt, _region), b in zip(self._plan, bufs):
                host[key] = np.frombuffer(b, dtype=dt)
            item_totals = {}
            for path in self.prog.regions[1:]:
                k = path + "#offsets"
                # the VM returns running totals; Arrow offsets lead with 0
                host[k] = np.concatenate([np.zeros(1, np.int32), host[k]])
                item_totals[path] = int(host[k][-1])
            # string values travel in-VM (#bytes); the assembler's flat-
            # buffer gather path is never taken on this backend
            meta = {"item_totals": item_totals, "flat": np.zeros(0, np.uint8)}
            with telemetry.phase("host.build_s", fused=False):
                return build_record_batch(
                    self.ir, self.arrow_schema, host, n, meta
                )

    # NOTE: the C++ VM's sampled-reserve prepass activates at
    # 4 * _PER_CHUNK_ROWS rows (host_codec.cpp py_decode) — keep the
    # two in sight of each other when retuning.
    # Above this many rows per chunk, each chunk decodes independently:
    # a chunk's whole working set (VM builders + assembly) then stays
    # cache-resident, which measures ~2x faster than decode-once+slice
    # at the 10M-row scale — and it is exactly the reference's execution
    # shape (one decode per chunk, ``deserialize.rs:90-121``). Small
    # batches keep the single pass + zero-copy slices.
    _PER_CHUNK_ROWS = 1 << 16

    def _drain_shard_stats(self) -> dict:
        """Snapshot-and-clear the native shard-runner counters from
        every loaded engine module (each extension .so has its own pool
        and stats singleton). Missing exports (stale binaries) read as
        zeros."""
        tot = {"fanouts": 0, "shards": 0, "shard_s": 0.0, "wall_s": 0.0,
               "threads": 0}
        for m in (self._mod, self._spec, self._extract_mod):
            drain = getattr(m, "shard_stats", None) if m else None
            if drain is None:
                continue
            d = drain()
            tot["fanouts"] += d["fanouts"]
            tot["shards"] += d["shards"]
            tot["shard_s"] += d["shard_s"]
            tot["wall_s"] += d["wall_s"]
            tot["threads"] = max(tot["threads"], d["threads"])
        return tot

    def _native_shards_usable(self) -> bool:
        """May the one-call native shard-runner path serve a chunked
        decode? Requires a binary that has the pool (``shard_stats``
        export) and an un-opened ``native_shards`` breaker; the knob
        pins the historic serial per-chunk loop."""
        from ..runtime import knobs

        if knobs.get_bool("PYRUHVRO_TPU_NO_NATIVE_SHARDS"):
            return False
        return hasattr(self._mod, "shard_stats")

    def _decode_native_shards(self, data: Sequence[bytes],
                              bounds) -> "List[pa.RecordBatch] | None":
        """One native call for the whole batch: the C++ shard runner is
        the fan-out (workers parked between calls), Python only slices
        the finished RecordBatch per chunk. Returns None to degrade to
        the retained serial per-chunk loop (breaker open, injected
        shard_worker fault, or a runtime lane fault)."""
        from ..ops.arrow_build import compact_union_slices
        from ..runtime import breaker, deadline, faults, metrics, telemetry
        from ..runtime.pool import fanout_stats

        br = breaker.get("native_shards")
        if not br.acquire():
            metrics.inc("shard.breaker_open")
            return None
        # per-chunk seam checkpoints BEFORE the (uninterruptible) native
        # call: an expired deadline still stops at a chunk boundary
        # naming the first row it never decoded, and the chaos harness's
        # shard_worker faults fire at the same per-chunk granularity the
        # serial loop had
        try:
            for a, _b in bounds:
                deadline.check(index=a, site="host.chunk")
                faults.fire("shard_worker")
        except faults.FaultInjected:
            br.record_failure()
            metrics.inc("shard.fallback")
            metrics.inc("shard.fallback_fault")
            return None
        except BaseException:
            br.release()  # deadline expiry: contract, not a lane verdict
            raise
        telemetry.annotate(chunk_mode="native_shard")
        self._drain_shard_stats()  # discard counters from other callers
        try:
            with fanout_stats(len(bounds), native=True) as stats:
                batch = self.decode(data)
                d = self._drain_shard_stats()
                if d["fanouts"]:
                    stats.native_fanout(d["shard_s"], d["wall_s"],
                                        d["threads"])
        except Exception as e:
            if faults.degradable(e):
                # lane fault (VM module bug, injected vm_decode error):
                # the serial per-chunk loop still serves the call
                br.record_failure()
                metrics.inc("shard.fallback")
                return None
            br.record_success()  # data/contract condition, lane worked
            raise
        br.record_success()
        metrics.inc("shard.native")
        return [
            compact_union_slices(batch.slice(a, b - a)) for a, b in bounds
        ]

    def decode_threaded(self, data: Sequence[bytes], num_chunks: int,
                        pool: "str | None" = None
                        ) -> List[pa.RecordBatch]:
        """Chunked decode → one RecordBatch per chunk (reference chunk
        slicing, ``deserialize.rs:57-68``).

        ``pool`` is the router's placement hint: ``"shard"`` (or None
        with a shard-capable binary) sends the large-batch mode through
        ONE native call — the C++ shard runner fans rows out over its
        persistent worker pool and Python slices the result — while
        ``"thread"`` keeps the historic serial per-chunk loop (also the
        degradation target when the ``native_shards`` breaker is open).
        Every shape reports what the fan-out bought: the native path
        feeds ``pool.chunk_efficiency`` from the runner's own busy/wall
        counters, the serial loop from per-chunk timings, and the
        small-batch path annotates ``chunk_mode=slice`` (one decode,
        zero fan-out, flat by design)."""
        import time as _time

        from ..ops.arrow_build import compact_union_slices
        from ..runtime import telemetry
        from ..runtime.chunking import chunk_bounds
        from ..runtime.pool import fanout_stats

        bounds = chunk_bounds(len(data), num_chunks)
        if len(data) >= self._PER_CHUNK_ROWS * max(len(bounds), 1):
            from ..runtime import deadline

            if pool != "thread" and self._native_shards_usable():
                out = self._decode_native_shards(data, bounds)
                if out is not None:
                    return out
            with fanout_stats(len(bounds), serial=True) as stats:
                out = []
                for a, b in bounds:
                    # per-chunk deadline checkpoint: an expired budget
                    # stops the serial chunk walk at a chunk boundary,
                    # naming the first row it never decoded
                    deadline.check(index=a, site="host.chunk")
                    t0 = _time.perf_counter()
                    out.append(self.decode(data[a:b], index_base=a))
                    stats.chunk(_time.perf_counter() - t0)
            return out
        telemetry.annotate(chunk_mode="slice")
        batch = self.decode(data)
        return [
            compact_union_slices(batch.slice(a, b - a)) for a, b in bounds
        ]

    # -- encode -----------------------------------------------------------

    def _native_extract_mod(self):
        """The generic Arrow-native extractor module, or None (toolchain
        missing, stale binary, or disabled by env). The module memo is
        per-codec; a load failure feeds the ``native_extract`` breaker
        (the builder's own memo makes re-probes cheap)."""
        if self._extract_pinned:
            return None
        if self._extract_mod is None:
            from ..runtime.native.build import load_extract

            mod = load_extract()
            if mod is None or not hasattr(mod, "encode"):
                from ..runtime import breaker

                breaker.get("native_extract").record_failure()
                return None
            self._extract_mod = mod
        return self._extract_mod

    @staticmethod
    def _wrap_blob(blob, offs, n: int) -> pa.Array:
        """Wrap the native encode's return — ``offs`` now arrives as
        the finished Arrow offsets buffer (n+1 int32, leading 0, built
        inside the encode loop itself: ISSUE 9 satellite), so this is
        two zero-copy ``py_buffer`` wraps. A stale pre-offsets ``.so``
        still ships n sizes; its prefix sum runs here (counted by
        length, never guessed)."""
        if len(offs) != (n + 1) * 4:
            from ..ops.arrow_build import cumsum0

            offs = cumsum0(np.frombuffer(offs, np.int32))
        return pa.Array.from_buffers(
            pa.binary(), n,
            [None, pa.py_buffer(offs),
             pa.py_buffer(np.frombuffer(blob, np.uint8))],
        )

    def _encode_native(self, batch: pa.RecordBatch, n: int,
                       checked: int, nshards: int = 1) -> pa.Array:
        """The fused Arrow-native encode: export the column-matched
        struct through the Arrow C data interface and run extraction +
        wire encode in ONE GIL-released C++ call — no Python/numpy
        per-path arrays exist on this lane at all. Returns None when the
        native lane declines (unsupported arrow shape, data error the
        Python extractor words precisely, stale/missing module) — the
        caller falls back to ``run_extractor`` and counts it."""
        from ..ops.decode import BatchTooLarge
        from ..runtime import breaker, faults, metrics

        if self._extract_pinned:  # PYRUHVRO_TPU_NO_NATIVE_EXTRACT
            return None
        br = breaker.get("native_extract")
        if not br.acquire():
            # lane withheld while its breaker is open; half-open admits
            # one probe encode, whose success below re-closes it
            metrics.inc("extract.fallback")
            metrics.inc("extract.breaker_open")
            return None
        if (self._extract_declined_schema is not None
                and batch.schema.equals(self._extract_declined_schema)):
            metrics.inc("extract.fallback")
            metrics.inc("extract.fallback_shape")
            # a memo-served shape decline runs NO native code: it must
            # not read as probe success (that would close a half-open
            # breaker — and reset its backoff exponent — with zero
            # evidence the lane works); release the slot verdict-free
            br.release()
            return None
        spec_eng = self._spec  # single read: eviction may null it
        spec = spec_eng if (
            spec_eng is not None and hasattr(spec_eng, "encode_arrow")
        ) else None
        if spec is not None and self._spec_name:
            from .specialize import touch_engine

            touch_engine(self._spec_name)
        mod = None if spec is not None else self._native_extract_mod()
        if spec is None and mod is None:
            return None  # _native_extract_mod already fed the breaker
        try:
            faults.fire("native_extract")
        except faults.FaultInjected:
            br.record_failure()
            metrics.inc("extract.fallback")
            metrics.inc("extract.fallback_fault")
            return None
        try:
            return self._encode_native_admitted(
                batch, n, checked, br, spec, mod, nshards)
        except (BatchTooLarge, OverflowError):
            # contract/data conditions raised THROUGH the lane: the
            # native call itself executed correctly, so a half-open
            # probe reads success — without a verdict here, a raising
            # exit would wedge the probe slot for the TTL and withhold
            # a healthy lane
            br.record_success()
            raise
        except BaseException:
            br.release()  # no verdict — but never wedge the probe slot
            raise

    def _encode_native_admitted(self, batch: pa.RecordBatch, n: int,
                                checked: int, br, spec, mod,
                                nshards: int = 1):
        """The admitted half of :meth:`_encode_native` — every return
        path below delivers its own breaker verdict; raising paths are
        resolved by the caller's except clauses."""
        from ..ops.decode import BatchTooLarge
        from ..ops.encode import batch_to_struct
        from ..runtime import metrics, telemetry

        struct = batch_to_struct(self.ir, batch)
        # ArrowArray is 80 ABI bytes, ArrowSchema 72; the C++ side moves
        # both structs out and releases them before returning
        holder_a = np.zeros(10, np.uint64)
        holder_s = np.zeros(9, np.uint64)
        struct._export_to_c(
            int(holder_a.ctypes.data), int(holder_s.ctypes.data)
        )
        try:
            if spec is not None:
                args = (self.prog.coltypes, int(holder_a.ctypes.data),
                        int(holder_s.ctypes.data), n, checked)
                res = spec.encode_arrow(*(args + (nshards,) if nshards > 1
                                          else args))
            else:
                args = (self.prog.ops, self.prog.coltypes,
                        self.prog.op_aux, int(holder_a.ctypes.data),
                        int(holder_s.ctypes.data), n, checked)
                res = mod.encode(*(args + (nshards,) if nshards > 1
                                   else args))
        except OverflowError as e:
            if "decimal" in str(e):
                raise  # oracle parity — a batch split cannot help
            raise BatchTooLarge(n, -1)
        except TypeError:
            # a stale pinned .so with a pre-fused signature (build.py
            # keeps a usable old binary when rebuild fails): the lane
            # declines through the breaker instead of crashing every
            # call — a stale binary never heals in-process, so probes
            # keep failing and the breaker keeps it open at backoff cost
            br.record_failure()
            metrics.inc("extract.fallback")
            metrics.inc("extract.fallback_stale")
            return None
        if self._prof and mod is not None:
            _drain_native_prof(mod)
        if isinstance(res, int):
            # 1 = arrow shape outside the native surface; 2 = a data
            # error the Python extractor reports with its exact message
            # — neither is a LANE fault, so the breaker reads success
            metrics.inc("extract.fallback")
            metrics.inc("extract.fallback_data" if res == 2
                        else "extract.fallback_shape")
            if res == 1:
                self._extract_declined_schema = batch.schema
            br.record_success()
            return None
        blob, offs, t_ex, t_enc = res
        br.record_success()
        telemetry.observe("host.extract_s", t_ex, rows=n, native=True)
        telemetry.observe("host.extract_native_s", t_ex, rows=n)
        telemetry.observe("host.encode_vm_s", t_enc, fused=True,
                          specialized=spec is not None)
        metrics.inc("extract.native")
        return self._wrap_blob(blob, offs, n)

    def _encode_buffers(self, ex) -> List[np.ndarray]:
        """Map the shared Arrow extractor's per-path arrays
        (``ops.encode.run_extractor``) onto the VM's plan buffer order."""
        from .program import COL_F64, COL_I64, COL_OFFS, COL_STR

        empty_u8 = np.zeros(0, np.uint8)
        bufs: List[np.ndarray] = []
        for c in self.prog.cols:
            key, ctype = c.key, c.ctype
            if ctype == COL_STR:
                bufs.append(ex.byte_bufs.get(key + "#bytes", empty_u8))
                bufs.append(ex.arrays[key + "#len"][0])
            elif ctype == COL_OFFS:
                path = key[: -len("#offsets")]
                bufs.append(ex.arrays[path + "#count"][0])
            elif ctype in (COL_I64, COL_F64):
                # host_mode extraction emits whole #v64 arrays (no u32
                # lane split); a KeyError here means a device-mode
                # extract was passed in — encode() always uses host_mode
                bufs.append(ex.arrays[key][0])
            else:  # #v / #valid / #tid — same keys both sides
                bufs.append(ex.arrays[key][0])
        return bufs

    def encode(self, batch: pa.RecordBatch) -> pa.Array:
        """Encode every row as one Avro datum → BinaryArray
        (≙ ``serialize_chunk``, ``fast_encode.rs:27-52``). Raises
        :class:`..ops.decode.BatchTooLarge` when the wire total blows
        int32 binary offsets (callers split the batch).

        Large batches encode in ~128k-row sub-slices and concatenate
        the BinaryArrays (a plain offsets-rebase + values memcpy): the
        sub-slice working set stays cache-resident, measured ~4x faster
        than one giant pass at the 10M-row scale — the same locality
        economics as ``decode_threaded``'s per-chunk mode."""
        from ..ops.decode import BatchTooLarge
        from ..ops.encode import run_extractor
        from ..runtime import telemetry

        n = batch.num_rows
        if n == 0:
            return pa.array([], pa.binary())
        step = self._PER_CHUNK_ROWS * 2
        if n > step:  # strict: a recursing sub-slice is exactly `step`
            try:
                return pa.concat_arrays([
                    self.encode(batch.slice(a, min(step, n - a)))
                    for a in range(0, n, step)
                ])
            except pa.lib.ArrowInvalid:
                # each sub-slice fit, but the CONCATENATED offsets blow
                # int32 — the same capacity condition the single-pass VM
                # reports, surfaced through the library's contract
                raise BatchTooLarge(n, -1)
        self._maybe_specialize(n)
        # PYRUHVRO_DEBUG_BOUNDS=1: the writer verifies every store
        # against the extractor's bound instead of trusting it — a bound
        # under-estimate becomes RuntimeError, not heap corruption. Read
        # per call (it is a debug switch, toggled in tests/soaks).
        from ..runtime import knobs

        checked = 1 if knobs.get_bool("PYRUHVRO_DEBUG_BOUNDS") else 0
        # fast lane: Arrow-native fused extract+encode (one GIL-released
        # C++ call straight off the Arrow buffers); None → the Python
        # extractor below serves the call (counted as extract.fallback)
        out = self._encode_native(batch, n, checked)
        if out is not None:
            return out
        with telemetry.phase("host.extract_s", rows=n, native=False):
            ex = run_extractor(self.ir, batch, host_mode=True)
            bufs = self._encode_buffers(ex)
        # the extractor's bound is a STRICT upper bound on the wire
        # total (loose: 10 B/long regardless of varint width), which
        # lets the VM write unchecked into a single allocation of that
        # size; past 1 GiB of bound, hint=0 selects the VM's
        # capacity-checked growth path instead of a giant eager alloc
        hint = ex.bound if ex.bound <= (1 << 30) else 0
        spec_eng = self._spec  # single read: eviction may null it
        if spec_eng is not None and self._spec_name:
            # encode-only traffic through this lane must stamp the
            # engine's LRU clock too, or TTL/LRU evicts the hot engine
            from .specialize import touch_engine

            touch_engine(self._spec_name)
        try:
            with telemetry.phase("host.encode_vm_s",
                                 specialized=spec_eng is not None):
                if spec_eng is not None:
                    blob, offs = spec_eng.encode(
                        self.prog.coltypes, bufs, n, hint, checked
                    )
                else:
                    try:
                        blob, offs = self._mod.encode(
                            self.prog.ops, self.prog.coltypes, bufs, n,
                            hint, checked
                        )
                    except TypeError:
                        if checked:
                            # a stale pre-checked .so cannot honor the
                            # bounds-verified mode — failing silently
                            # would report a clean soak while unchecked
                            # writes still run
                            raise RuntimeError(
                                "PYRUHVRO_DEBUG_BOUNDS=1 requested but "
                                "the loaded native module predates the "
                                "checked writer; rebuild the extension"
                            ) from None
                        # stale pre-hint .so (build.py keeps a usable old
                        # binary when rebuild fails): 4-arg form
                        blob, offs = self._mod.encode(
                            self.prog.ops, self.prog.coltypes, bufs, n
                        )
        except OverflowError as ex:
            if "decimal" in str(ex):
                raise  # oracle parity (int.to_bytes overflow) — a
                # batch split cannot make the value fit
            raise BatchTooLarge(n, -1)
        if self._prof:
            _drain_native_prof(self._mod)
        return self._wrap_blob(blob, offs, n)

    def _encode_native_shards(self, batch: pa.RecordBatch,
                              bounds) -> "List[pa.Array] | None":
        """One native call for the whole chunked encode: the fused
        extract+encode boundary shards rows over the persistent C++
        pool (extract_core.h encode_arrow_sharded) and Python slices
        the finished BinaryArray per chunk. None degrades to the
        retained per-chunk process-pool fan-out."""
        from ..ops.decode import BatchTooLarge
        from ..runtime import breaker, faults, knobs, metrics, telemetry
        from ..runtime.pool import fanout_stats

        br = breaker.get("native_shards")
        if not br.acquire():
            metrics.inc("shard.breaker_open")
            return None
        try:
            for _a, _b in bounds:
                faults.fire("shard_worker")
        except faults.FaultInjected:
            br.record_failure()
            metrics.inc("shard.fallback")
            metrics.inc("shard.fallback_fault")
            return None
        except BaseException:
            br.release()
            raise
        telemetry.annotate(chunk_mode="native_shard")
        n = batch.num_rows
        checked = 1 if knobs.get_bool("PYRUHVRO_DEBUG_BOUNDS") else 0
        self._drain_shard_stats()  # discard counters from other callers
        try:
            with fanout_stats(len(bounds), native=True,
                              op="encode") as stats:
                arr = self._encode_native(batch, n, checked,
                                          nshards=len(bounds))
                d = self._drain_shard_stats()
                if d["fanouts"]:
                    stats.native_fanout(d["shard_s"], d["wall_s"],
                                        d["threads"])
        except BatchTooLarge:
            # capacity contract (int32 wire total): the retained path's
            # recursive splitter serves the call — the lane itself worked
            br.record_success()
            metrics.inc("shard.fallback")
            return None
        except Exception as e:
            if faults.degradable(e):
                br.record_failure()
                metrics.inc("shard.fallback")
                return None
            br.record_success()
            raise
        if arr is None:
            # the Arrow-native extract lane declined (shape/data) — not
            # a shard-runner fault; the retained path words the error
            br.record_success()
            metrics.inc("shard.fallback")
            return None
        br.record_success()
        metrics.inc("shard.native")
        return [arr.slice(a, b - a) for a, b in bounds]

    def encode_threaded(self, batch: pa.RecordBatch, num_chunks: int,
                        pool: "str | None" = None) -> List[pa.Array]:
        """Encode ONCE, slice per chunk (one VM pass regardless of the
        chunk count — the chunked return shape is an API contract, not a
        unit of work). An oversized batch is split recursively, still
        through the VM. Large batches prefer ONE native shard-runner
        call (``pool="shard"`` hint or default); ``pool="thread"`` or a
        degradation keeps the per-chunk process-pool fan-out."""
        from ..ops.decode import BatchTooLarge
        from ..runtime.chunking import chunk_bounds

        bounds = chunk_bounds(batch.num_rows, num_chunks)
        if batch.num_rows >= self._PER_CHUNK_ROWS * max(len(bounds), 1):
            if pool != "thread" and self._native_shards_usable():
                out = self._encode_native_shards(batch, bounds)
                if out is not None:
                    return out
            # large chunks: one encode per chunk (cache-resident working
            # set, ≙ the reference's per-chunk serialize fan-out), run
            # on the process pool — the fused Arrow-native encode
            # releases the GIL for essentially the whole call, so chunk
            # encodes genuinely overlap on multi-core hosts (the encode
            # analogue of the decode VM's internal row sharding)
            from ..runtime.chunking import bounds_rows
            from ..runtime.pool import map_chunks

            return map_chunks(
                lambda ab: self._encode_split(
                    batch.slice(ab[0], ab[1] - ab[0])
                ),
                bounds,
                rows=bounds_rows,
            )
        arr = self._encode_split(batch)
        return [arr.slice(a, b - a) for a, b in bounds]

    def _encode_split(self, batch: pa.RecordBatch) -> pa.Array:
        from ..ops.decode import BatchTooLarge

        try:
            return self.encode(batch)
        except BatchTooLarge:
            if batch.num_rows < 2:
                raise
            mid = batch.num_rows // 2
            try:
                return pa.concat_arrays(
                    [self._encode_split(batch.slice(0, mid)),
                     self._encode_split(batch.slice(mid))]
                )
            except pa.lib.ArrowInvalid:
                # the halves fit individually but their concatenation
                # blows int32 offsets: no split can make this batch one
                # BinaryArray — the caller must use more chunks
                raise BatchTooLarge(batch.num_rows, -1)
