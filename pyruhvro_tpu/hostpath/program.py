"""Schema IR → host bytecode program (the C++ VM's input).

Mirrors the device lowering (:mod:`..ops.fieldprog`) in walk order and
column naming — ``path#v`` / ``path#v64`` / ``path#valid`` /
``path#tid`` / ``path#bytes``+``#len`` / ``path#offsets`` — so the
VM's output dict drops straight into ``ops.arrow_build``. Op kinds and
column-type codes are the C++ side's contract
(``runtime/native/host_codec.cpp`` enums; keep in sync).

≙ the role of ``make_decoder`` (``ruhvro/src/fast_decode.rs:176-420``):
where the reference builds a tree of boxed decoder objects at runtime,
this framework compiles the schema once into a flat program — the same
"static field program" idea the device path uses, executed by switch
dispatch instead of XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from ..ops import UnsupportedOnDevice
from ..gate import host_supported
from ..schema.model import (
    Array,
    AvroType,
    Enum,
    Fixed,
    Map,
    Primitive,
    Record,
    Union,
)

__all__ = ["HostProgram", "lower_host", "COL_NBUF", "OP_NAMES",
           "OP_EFFECTS"]

# op kinds (≙ host_codec.cpp OpKind)
OP_RECORD, OP_INT, OP_LONG, OP_FLOAT, OP_DOUBLE, OP_BOOL = 0, 1, 2, 3, 4, 5
OP_STRING, OP_ENUM, OP_NULL, OP_NULLABLE, OP_UNION = 6, 7, 8, 9, 10
OP_ARRAY, OP_MAP, OP_FIXED, OP_DEC_BYTES, OP_DEC_FIXED = 11, 12, 13, 14, 15
# superoptimizer-only op (hostpath/optimize.py): a fused header over a
# run of ≥2 consecutive fixed-layout leaf fields of one record. Never
# emitted by lower_host — only the verified rewrite pass inserts it.
#   a    — 1 when every member is exact-width (float/double/bool): the
#          engines may take the bulk lane (one span pre-check, then
#          unchecked member reads); 0 = dispatch-only grouping
#   b    — total minimum wire bytes of the member run (the bulk lane's
#          span pre-check amount — for all-fixed runs it is exact)
#   nops — 1 + member count (members stay in-stream, unchanged)
OP_FIXED_RUN = 16

# ``pad`` flag bits (optimizer-set; 0 on every lower_host program).
# FLAG_ALWAYS_PRESENT on an OP_FIXED_RUN header asserts the header's
# ancestor chain is unconditional (records only): the engines may skip
# the runtime ``present`` test on the bulk lane. FLAG_STR_ITEMS on an
# OP_ARRAY/OP_MAP asserts the item subtree is exactly one plain
# string/bytes leaf, pre-deciding decode_blocks' string fast lane at
# compile time. Both are PROOF-CARRYING: analysis/irverify.py
# verify_optimized re-derives each claim and rejects the program when
# the flag overclaims (a wrong flag would mean wire reads for absent
# subtrees / string reads over non-string items).
FLAG_ALWAYS_PRESENT = 1
FLAG_STR_ITEMS = 2

# column types (≙ host_codec.cpp ColType)
COL_I32, COL_I64, COL_F32, COL_F64, COL_U8, COL_STR, COL_OFFS = range(7)

# buffers each column type contributes (COL_STR: value bytes + len i32)
COL_NBUF = {COL_STR: 2}

OP_NAMES = {
    OP_RECORD: "record", OP_INT: "int", OP_LONG: "long",
    OP_FLOAT: "float", OP_DOUBLE: "double", OP_BOOL: "bool",
    OP_STRING: "string", OP_ENUM: "enum", OP_NULL: "null",
    OP_NULLABLE: "nullable", OP_UNION: "union", OP_ARRAY: "array",
    OP_MAP: "map", OP_FIXED: "fixed", OP_DEC_BYTES: "dec_bytes",
    OP_DEC_FIXED: "dec_fixed", OP_FIXED_RUN: "fixed_run",
}

# Per-opcode effect contract, the machine-readable half of what the two
# native engines implement (ISSUE 15: the IR verifier abstract-interprets
# programs against THIS table, and anchors every declared guard to the
# C++ source it names). Fields:
#   ctype      — required ColType of the op's primary column (None = no
#                column); the map KEY column (op.b) is always COL_STR.
#   min_wire   — minimum wire bytes one present execution consumes
#                ("a" = the op's size operand). Array/map items whose
#                subtree floor is 0 are legal ONLY because both engines
#                charge zero-width items against kMaxZeroWidthItems.
#   pushes     — buffer lanes appended per present execution of the op
#                itself (items repeat per item, handled by the walker).
#   sinks      — int32-narrowing lanes this op writes, as
#                (lane, (guard, ...)): every guard names an anchor the
#                verifier greps out of the native sources, so deleting a
#                C++ range check (or this declaration) fails the gate.
#   aux        — aux tags permitted on the op (None = no aux legal);
#                "!tag" marks a REQUIRED tag.
OP_EFFECTS = {
    OP_RECORD: dict(ctype=None, min_wire=0, pushes=(), sinks=(),
                    aux=(None,)),
    OP_INT: dict(ctype=COL_I32, min_wire=1, pushes=("i32",),
                 # the 64-bit zigzag is truncated to its low 32 bits by
                 # contract (matches the device walk)
                 sinks=(("int_value", ("int_low32_by_design",)),),
                 aux=(None,)),
    OP_LONG: dict(ctype=COL_I64, min_wire=1, pushes=("i64",), sinks=(),
                  aux=(None,)),
    OP_FLOAT: dict(ctype=COL_F32, min_wire=4, pushes=("f32",), sinks=(),
                   aux=(None,)),
    OP_DOUBLE: dict(ctype=COL_F64, min_wire=8, pushes=("f64",), sinks=(),
                    aux=(None,)),
    OP_BOOL: dict(ctype=COL_U8, min_wire=1, pushes=("u8",), sinks=(),
                  aux=(None,)),
    OP_STRING: dict(ctype=COL_STR, min_wire=1, pushes=("u8", "i32"),
                    # the wire length lands in the int32 lens lane: it
                    # must be bounded by the remaining span AND by
                    # int32 (a >2GiB datum could otherwise wrap it)
                    sinks=(("string_len",
                            ("string_len_span", "string_len_i32")),),
                    aux=(None, "uuid", "binary")),
    OP_ENUM: dict(ctype=COL_I32, min_wire=1, pushes=("i32",),
                  sinks=(("enum_index", ("enum_range",)),),
                  aux=("!enum",)),
    OP_NULL: dict(ctype=None, min_wire=0, pushes=(), sinks=(),
                  aux=(None,)),
    OP_NULLABLE: dict(ctype=COL_U8, min_wire=1, pushes=("u8",), sinks=(),
                      aux=(None,)),
    OP_UNION: dict(ctype=COL_I32, min_wire=1, pushes=("i32",),
                   sinks=(("union_tid", ("union_branch_range",)),),
                   aux=(None,)),
    OP_ARRAY: dict(ctype=COL_OFFS, min_wire=1, pushes=("i32",),
                   sinks=(("offs_running", ("offs_running_i32",)),
                          ("merge_rebase", ("merge_offsets_i32",))),
                   aux=(None,)),
    OP_MAP: dict(ctype=COL_OFFS, min_wire=1, pushes=("i32",),
                 sinks=(("offs_running", ("offs_running_i32",)),
                        ("merge_rebase", ("merge_offsets_i32",))),
                 aux=(None,)),
    OP_FIXED: dict(ctype=COL_U8, min_wire="a", pushes=("u8",), sinks=(),
                   aux=(None, "duration")),
    OP_DEC_BYTES: dict(ctype=COL_U8, min_wire=1, pushes=("u8",), sinks=(),
                       aux=("!decimal",)),
    OP_DEC_FIXED: dict(ctype=COL_U8, min_wire="a", pushes=("u8",),
                       sinks=(), aux=("!decimal",)),
    # fused header: consumes no wire bytes itself (its b operand only
    # SUMMARIZES the members' floors for the bulk lane's span
    # pre-check — the members still account their own min_wire), pushes
    # nothing, owns no column. The bulk lane reads members unchecked,
    # which is sound only behind the span pre-check the sink names.
    OP_FIXED_RUN: dict(ctype=None, min_wire=0, pushes=(),
                       sinks=(("bulk_span", ("fixed_run_span",)),),
                       aux=(None,)),
}

# numpy dtypes per buffer, in buffer order
_COL_DTYPES = {
    COL_I32: (np.int32,),
    COL_I64: (np.int64,),
    COL_F32: (np.float32,),
    COL_F64: (np.float64,),
    COL_U8: (np.uint8,),
    COL_STR: (np.uint8, np.int32),
    COL_OFFS: (np.int32,),
}


@dataclass
class ColSpec:
    key: str       # assembler dict key ("" + suffix handled by builder)
    ctype: int
    region: int    # region id (0 = rows), for entry-count bookkeeping


@dataclass
class HostProgram:
    ir: Record
    ops: np.ndarray            # int32 [n_ops, 6]
    cols: List[ColSpec]
    coltypes: np.ndarray       # int32 [n_cols]
    regions: List[str]         # region id -> repeated-field path
    region_parents: List[int]
    # per-op logical facts the flat opcode table cannot carry, shaped
    # for the Arrow-native extractor AND the fused Arrow decoder
    # (runtime/native/extract_core.h / arrow_decode_core.h): one entry
    # per op — None, ("uuid",), ("binary",), ("duration",),
    # ("decimal", precision) or ("enum", symbol_bytes, ...)
    op_aux: tuple = ()

    def op_effects(self) -> List[dict]:
        """Per-op resolved effect rows for the IR verifier (ISSUE 15):
        the :data:`OP_EFFECTS` contract with the op's operands folded in
        (``min_wire="a"`` resolves to the size operand; required aux
        tags are checked by the verifier, not here)."""
        out = []
        aux = self.op_aux or tuple(None for _ in range(len(self.ops)))
        for pc, row in enumerate(self.ops):
            kind, a, b, col, nops, _pad = (int(x) for x in row)
            eff = OP_EFFECTS[kind]
            mw = eff["min_wire"]
            out.append({
                "pc": pc, "kind": kind, "name": OP_NAMES[kind],
                "a": a, "b": b, "col": col, "nops": nops,
                "ctype": eff["ctype"],
                "min_wire": a if mw == "a" else mw,
                "pushes": eff["pushes"], "sinks": eff["sinks"],
                "aux_allowed": eff["aux"], "aux": aux[pc],
            })
        return out

    def buffer_plan(self) -> List[Tuple[str, object, int]]:
        """Flat (host_key, dtype, region) per returned buffer, in the
        VM's buffer order. Host keys: ``#start``/``#len`` suffixes for
        strings, the col key otherwise."""
        plan = []
        for c in self.cols:
            dts = _COL_DTYPES[c.ctype]
            if c.ctype == COL_STR:
                plan.append((c.key + "#bytes", dts[0], c.region))
                plan.append((c.key + "#len", dts[1], c.region))
            else:
                plan.append((c.key, dts[0], c.region))
        return plan


class _HostLowering:
    def __init__(self) -> None:
        self.ops: List[Tuple[int, int, int, int]] = []  # kind,a,b,col
        self.cols: List[ColSpec] = []
        self.subtree: Dict[int, int] = {}  # op index -> nops
        self.regions: List[str] = [""]
        self.region_parents: List[int] = [-1]
        self.aux: Dict[int, tuple] = {}    # op index -> extractor aux

    def col(self, key: str, ctype: int, region: int) -> int:
        self.cols.append(ColSpec(key, ctype, region))
        return len(self.cols) - 1

    def emit(self, kind: int, a: int = 0, b: int = 0, col: int = -1) -> int:
        self.ops.append((kind, a, b, col))
        i = len(self.ops) - 1
        self.subtree[i] = 1
        return i

    def close(self, i: int) -> None:
        self.subtree[i] = len(self.ops) - i

    def lower_type(self, t: AvroType, path: str, region: int) -> None:
        if isinstance(t, Primitive):
            name = t.name
            if name == "null":
                self.emit(OP_NULL)
            elif name == "int":
                self.emit(OP_INT, col=self.col(path + "#v", COL_I32, region))
            elif name == "long":
                self.emit(OP_LONG, col=self.col(path + "#v64", COL_I64, region))
            elif name == "float":
                self.emit(OP_FLOAT, col=self.col(path + "#v", COL_F32, region))
            elif name == "double":
                self.emit(OP_DOUBLE,
                          col=self.col(path + "#v64", COL_F64, region))
            elif name == "boolean":
                self.emit(OP_BOOL, col=self.col(path + "#v", COL_U8, region))
            elif name == "string":
                # incl. uuid: the wire form is a plain string; the
                # text→16-byte conversion is the assembler's job (the
                # aux tag tells the Arrow-native extractor the column
                # arrives as FixedSizeBinary(16), not text)
                i = self.emit(OP_STRING, col=self.col(path, COL_STR, region))
                if t.logical == "uuid":
                    self.aux[i] = ("uuid",)
            elif name == "bytes":
                if t.logical == "decimal":
                    # wire: length-prefixed big-endian two's complement;
                    # column: 16-byte LE decimal128 words (the aux tag
                    # carries the declared precision for the fused
                    # decoder's native range check)
                    i = self.emit(OP_DEC_BYTES,
                                  col=self.col(path + "#dec", COL_U8,
                                               region))
                    self.aux[i] = ("decimal", t.precision)
                else:
                    # same wire form and builder as string; only the
                    # Arrow assembly differs (Binary, no UTF-8 check —
                    # the aux tag tells the fused decoder to skip it)
                    i = self.emit(OP_STRING,
                                  col=self.col(path, COL_STR, region))
                    self.aux[i] = ("binary",)
            else:  # pragma: no cover — gated by host_supported
                raise UnsupportedOnDevice(f"primitive {name!r} at {path!r}")
        elif isinstance(t, Fixed):
            if t.logical == "decimal":
                i = self.emit(OP_DEC_FIXED, a=t.size,
                              col=self.col(path + "#dec", COL_U8, region))
                self.aux[i] = ("decimal", t.precision)
            else:
                i = self.emit(OP_FIXED, a=t.size,
                              col=self.col(path + "#fix", COL_U8, region))
                if t.logical == "duration":
                    self.aux[i] = ("duration",)
        elif isinstance(t, Enum):
            i = self.emit(OP_ENUM, a=len(t.symbols),
                          col=self.col(path + "#v", COL_I32, region))
            self.aux[i] = ("enum",) + tuple(
                s.encode("utf-8") for s in t.symbols
            )
        elif isinstance(t, Record):
            i = self.emit(OP_RECORD)
            prefix = path + "/" if path else ""
            for f in t.fields:
                self.lower_type(f.type, prefix + f.name, region)
            self.close(i)
        elif isinstance(t, Union):
            if t.is_nullable_pair:
                i = self.emit(
                    OP_NULLABLE, a=t.null_index,
                    col=self.col(path + "#valid", COL_U8, region),
                )
                self.lower_type(t.non_null_variant, path, region)
                self.close(i)
            else:
                i = self.emit(
                    OP_UNION, a=len(t.variants),
                    col=self.col(path + "#tid", COL_I32, region),
                )
                for k, v in enumerate(t.variants):
                    if v.is_null():
                        self.emit(OP_NULL)
                    else:
                        self.lower_type(v, f"{path}/{k}", region)
                self.close(i)
        elif isinstance(t, (Array, Map)):
            rid = len(self.regions)
            self.regions.append(path)
            self.region_parents.append(region)
            offs = self.col(path + "#offsets", COL_OFFS, region)
            if isinstance(t, Array):
                i = self.emit(OP_ARRAY, col=offs)
                self.lower_type(t.items, path + "/@item", rid)
            else:
                key_col = self.col(path + "/@key", COL_STR, rid)
                i = self.emit(OP_MAP, b=key_col, col=offs)
                self.lower_type(t.values, path + "/@val", rid)
            self.close(i)
        else:  # pragma: no cover — gated by is_supported
            raise UnsupportedOnDevice(f"type {type(t).__name__} at {path!r}")


def lower_host(ir: AvroType) -> HostProgram:
    """Lower a top-level record schema to its host bytecode program
    (gate: :func:`..gate.host_supported` — the fast subset plus
    bytes/fixed/duration/time-*/local-timestamp-*)."""
    if not host_supported(ir):
        raise UnsupportedOnDevice("schema is outside the host VM subset")
    lo = _HostLowering()
    lo.lower_type(ir, "", 0)
    n = len(lo.ops)
    ops = np.zeros((n, 6), np.int32)
    for i, (kind, a, b, col) in enumerate(lo.ops):
        ops[i] = (kind, a, b, col, lo.subtree[i], 0)
    return HostProgram(
        ir=ir,
        ops=np.ascontiguousarray(ops),
        cols=lo.cols,
        coltypes=np.ascontiguousarray(
            np.array([c.ctype for c in lo.cols], np.int32)
        ),
        regions=lo.regions,
        region_parents=lo.region_parents,
        op_aux=tuple(lo.aux.get(i) for i in range(n)),
    )
